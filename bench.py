"""Headline benchmark: Llama training-step throughput + MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
honesty fields — "mfu", "assumed_peak_tflops", "device_kind",
"flops_per_token", and a long-sequence leg ("s4096_*").

The reference publishes no performance numbers (BASELINE.json
"published": {} — see BASELINE.md), so "vs_baseline" compares against the
same training step with the hand-tuned paths disabled (XLA-naive attention
instead of the pallas flash kernel; materialized full-vocab logits instead
of the fused chunked cross-entropy): > 1 means the TPU-native design beats
a straightforward XLA translation of the reference capability. MFU is the
absolute check the ratio can't game: model FLOPs (6·N_matmul + causal
attention, no remat recompute credit) / chip peak bf16 FLOPs.

"kernels_verified"/"kernel_errors" report on-chip numerical parity of the
pallas flash kernel (fwd + bwd) and the fused chunked CE against their
XLA reference paths — correctness proven where the kernels actually run,
not only in CPU interpret mode.

Exit contract: 0 = JSON result line on stdout. 3 = structured failure —
still ONE JSON line, with an "error" field (emitted by the hang watchdog,
or by the catch-all around the run: backend-unavailable after bounded
retries, OOM, any exception). When the backend never came up the line
additionally carries {"skipped": "backend unavailable"} so the recorder
can tell an environmental skip from a failure on merit; the retry loop's
total wall-clock is capped by RLT_BENCH_MAX_WAIT (default 300s) so it
can never outlive the harness timeout (BENCH_r05 rc=124). A raw
traceback with no JSON is a bug.
"""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import numpy as np

# peak table + probe shared with the doctor CLI (utils/probe.py)
from ray_lightning_tpu.utils.probe import (  # noqa: E402
    DEFAULT_PEAK as _DEFAULT_PEAK,
    PEAK_TFLOPS as _PEAK_TFLOPS,
    matmul_tflops as _probe_matmul_tflops,
)


def _bench_cfg(use_flash: bool, fused_ce: bool, seq: int,
               vocab: int = 32768, remat: bool = True, scan: bool = True,
               remat_policy: str = "nothing", ce_chunk_tokens: int = 2048,
               ce_inline: bool = False):
    from ray_lightning_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=vocab,
        dim=2048,
        n_layers=8,
        n_heads=16,
        n_kv_heads=8,
        hidden_dim=5632,
        max_seq_len=seq,
        use_flash=use_flash,
        fused_ce=fused_ce,
        ce_chunk_tokens=ce_chunk_tokens,
        ce_inline_bwd=ce_inline,
        remat=remat,
        remat_policy=remat_policy,
        scan_layers=scan,
    )


def _flops_per_token(cfg, seq: int) -> float:
    """Model FLOPs per trained token: 6×(matmul params) + causal attention
    (QK^T + AV, average context S/2), fwd×2 + bwd×4. Remat recompute is
    real work but not counted — MFU measures useful FLOPs."""
    hd = cfg.head_dim
    per_layer = (
        cfg.dim * (cfg.n_heads + 2 * cfg.n_kv_heads) * hd  # wqkv
        + cfg.n_heads * hd * cfg.dim                       # wo
        + 3 * cfg.dim * cfg.hidden_dim                     # gate_up + down
    )
    n_matmul = cfg.n_layers * per_layer + cfg.dim * cfg.vocab_size  # lm_head
    attn = 6 * cfg.n_layers * cfg.n_heads * hd * seq  # 3×(2·2·(S/2)·nq·hd)
    return 6.0 * n_matmul + attn


def _make_step(use_flash: bool, fused_ce: bool, batch: int, seq: int,
               vocab: int = 32768, remat: bool = True, scan: bool = True,
               remat_policy: str = "nothing", ce_chunk_tokens: int = 2048,
               ce_inline: bool = False, mu_dtype=None):
    import jax
    import optax

    from ray_lightning_tpu.models.llama import Llama, LlamaModule

    cfg = _bench_cfg(use_flash, fused_ce, seq, vocab, remat, scan,
                     remat_policy, ce_chunk_tokens, ce_inline)
    model = Llama(cfg)
    module = LlamaModule(cfg)
    module.model = model
    tokens = jax.random.randint(
        jax.random.key(0), (batch, seq + 1), 0, cfg.vocab_size, dtype=np.int32
    )
    params = jax.jit(model.init)(jax.random.key(0), tokens[:, :-1])["params"]
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1,
                     mu_dtype=mu_dtype)
    opt_state = jax.jit(tx.init)(params)

    def loss_fn(params, tokens):
        # the trainer's actual loss path (fused or materialized, per cfg)
        return module._loss(params, tokens[:, :-1], tokens[:, 1:], None)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, params, opt_state, tokens, batch * seq, cfg


def _time_step(step, params, opt_state, tokens, warmup=3, iters=5,
               windows=3, timing: dict | None = None):
    """Best-of-``windows`` timing: the chip may be shared/tunneled, and a
    contention burst in one window must not masquerade as model speed —
    the minimum window is the closest observable to the true step time.

    ``timing`` (optional, filled in place) carries the goodput view of
    the same measurement: ``wall_s`` (entry to exit, INCLUDING the
    warmup/compile the best-of window deliberately excludes) and
    ``productive_s`` (the timed windows' elapsed sum) — compile/warmup
    is lost time under goodput semantics, exactly as in a real run."""
    import jax

    t_start = time.perf_counter()
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, tokens)
    # device_get, not block_until_ready: the latter can be a no-op through
    # remote-device tunnels; fetching the loss value forces execution of
    # the whole dependency chain.
    float(jax.device_get(loss))
    best = float("inf")
    productive = 0.0
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(jax.device_get(loss))
        elapsed = time.perf_counter() - t0
        productive += elapsed
        best = min(best, elapsed / iters)
    if timing is not None:
        timing.update({"wall_s": time.perf_counter() - t_start,
                       "productive_s": productive})
    return best


def _telemetry_overhead_fraction(step_dt: float,
                                 spans_per_step: int = 4,
                                 n: int = 4000) -> float:
    """Measured recorder cost relative to the measured step time: the
    per-span price of the ring recorder (two clock reads + a dict + a
    deque append) times the spans the trainer emits per batch
    (dispatch + step + data_wait + H2D), over the headline step time.
    The bench_gate upper-bounds this below 1%."""
    from ray_lightning_tpu.telemetry.spans import TelemetryRecorder

    rec = TelemetryRecorder()  # memory-only: no file I/O in the ring path
    t0 = time.perf_counter()
    for i in range(n):
        with rec.span("dispatch", step=i):
            pass
    per_span = (time.perf_counter() - t0) / n
    return (per_span * spans_per_step) / max(step_dt, 1e-9)


def _measure(use_flash: bool, fused_ce: bool, batch: int, seq: int,
             vocab: int = 32768, remat: bool = True, scan: bool = True,
             remat_policy: str = "nothing", ce_chunk_tokens: int = 2048,
             ce_inline: bool = False, mu_dtype=None,
             timing: dict | None = None):
    step, params, opt_state, tokens, tps, cfg = _make_step(
        use_flash, fused_ce, batch, seq, vocab, remat, scan,
        remat_policy, ce_chunk_tokens, ce_inline, mu_dtype
    )
    dt = _time_step(step, params, opt_state, tokens, timing=timing)
    if timing is not None:
        timing["step_dt_s"] = dt
    del step, params, opt_state, tokens
    return tps / dt, cfg


def _flagship_leg(measure, shared: dict, mfu_of, shape_desc: str):
    """The flagship leg's measurement policy, extracted for unit tests
    (tests/test_bench.py): try the inline-CE config; on a compile
    rejection reuse the rematce leg's measurement from ``shared``
    (identical configuration, already timed — never compile it twice),
    preserving the inline failure cause; with nothing to reuse,
    re-raise so leg() degrades the row with the REAL error.

    ``measure(ce_inline=...)`` -> (tokens_per_sec, cfg); ``mfu_of(t, c)``
    -> useful-FLOP MFU; ``shape_desc`` describes the measured shape and
    lives WITH the measure closure so the artifact's config string
    cannot drift from the actual parameters. Returns ``(row, mfu)``.
    """
    try:
        t, c = measure(ce_inline=True)
        config = f"remat(nothing)+scan+fusedCE(inline) {shape_desc}"
        note = {}
        m = mfu_of(t, c)
    except Exception as exc:  # noqa: BLE001 — fall back, keep cause
        note = {"flagship_inline_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}
        if "rematce" not in shared:
            raise  # no reusable measurement — surface the real error
        t, m = shared["rematce"]
        config = (f"remat(nothing)+scan+fusedCE(remat) {shape_desc} "
                  "[inline fallback: rematce leg's measurement]")
    return ({"flagship_tokens_per_sec": round(t, 1),
             "flagship_mfu": round(m, 4),
             "flagship_config": config, **note}, m)


def _attnout_leg(measure, mfu_of):
    """The attn_out flagship leg's measurement policy, extracted for unit
    tests (tests/test_bench.py): try the inline-CE config; on a compile
    rejection fall back to the measurable non-inline attn_out config,
    keeping the inline cause in the row. If the FALLBACK also fails, the
    inline root cause must not be discarded (ADVICE r5): both causes are
    folded into the raised error, with the inline failure chained as
    __cause__, so leg() records the full story."""
    note = {}
    try:
        t, c = measure(ce_inline=True)
    except Exception as exc:  # noqa: BLE001 — fall back, keep cause
        note = {"flagship_attnout_inline_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}
        try:
            t, c = measure(ce_inline=False)
        except Exception as exc2:  # noqa: BLE001 — chain BOTH causes
            raise RuntimeError(
                "attn_out leg failed on both paths — inline "
                f"[{type(exc).__name__}: {str(exc)[:200]}]; non-inline "
                f"[{type(exc2).__name__}: {str(exc2)[:200]}]"
            ) from exc
    m = mfu_of(t, c)
    return ({"flagship_attnout_tokens_per_sec": round(t, 1),
             "flagship_attnout_mfu": round(m, 4), **note}, m)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


#: tracecheck + trainguard summaries computed ONCE at startup (CPU-only
#: traces, no backend touch) and attached to EVERY JSON line this
#: process emits — success, skip, error, watchdog, or signal kill — so
#: even a round with no chip still carries analysis data (ISSUE 2/5
#: satellites).
_ANALYSIS: dict = {}

#: Measurement-leg execution order, flagship-first: a watchdog timeout
#: or driver kill mid-run flushes the partial sink, and the legs that
#: must survive such a death are the driver-verified flagship numbers —
#: so they run before the comparison legs. Constraint encoded here and
#: pinned by tests/test_bench_script.py: `flagship_rematce` stays
#: immediately before `flagship` (the inline leg's compile-rejection
#: fallback reuses the rematce measurement via `shared`).
LEG_ORDER: tuple = (
    "flagship_rematce", "flagship", "flagship_attnout",
    "vs_baseline", "s4096", "v128k", "overlap", "serving", "reshard",
)


def _concurrency_summary() -> dict:
    """threadcheck static audit of the package (analysis/concurrency.py):
    finding counts by RLT7xx rule. Pure host-side AST work — carried on
    every JSON line even when the backend is down, like the tracecheck
    block."""
    try:
        from ray_lightning_tpu.analysis.concurrency import (
            check_concurrency_paths, summarize,
        )

        pkg = os.path.dirname(os.path.abspath(
            __import__("ray_lightning_tpu").__file__))
        return {"concurrency": summarize(check_concurrency_paths([pkg]))}
    except Exception as exc:  # noqa: BLE001 — analysis is bonus data
        return {"concurrency": {
            "error": f"{type(exc).__name__}: {str(exc)[:200]}"}}


def _guard_summary() -> dict:
    """Structural audit of the trainguard (resilience/guard.py, ISSUE 5):
    jaxpr-trace the guarded update with abstract inputs (make_jaxpr over
    ShapeDtypeStructs — no backend is ever initialized, so this works
    with the TPU tunnel dead) and report the guard counters that ride
    the step's metric outputs plus the effect count, proving the guard
    adds detection WITHOUT host callbacks/transfers. The counter VALUES
    are the zero-state (this process measures throughput with a raw
    step, not the Trainer); the schema and the no-new-transfers claim
    are what the recorder consumes."""
    try:
        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.resilience.guard import (
            GuardConfig,
            abstract_guard_state,
            apply_guard,
        )

        cfg = GuardConfig()

        def guarded(guard, step, loss, gn, params):
            new_params = jax.tree.map(lambda x: x - 1.0, params)
            return apply_guard(cfg, guard, step, loss, gn,
                               new_params, params, (), ())

        s = jax.ShapeDtypeStruct
        jaxpr = jax.make_jaxpr(guarded)(
            abstract_guard_state(), s((), jnp.int32), s((), jnp.float32),
            s((), jnp.float32), {"w": s((16,), jnp.float32)})
        _, _, _, _, metrics = jax.eval_shape(
            guarded, abstract_guard_state(), s((), jnp.int32),
            s((), jnp.float32), s((), jnp.float32),
            {"w": s((16,), jnp.float32)})
        return {"guard": {
            "counters": sorted(metrics),
            "in_jit": True,
            "effects": len(jaxpr.effects),       # 0 = no callbacks
            "extra_host_transfers": 0,           # flags ride the metrics
            "skipped_steps": 0,
            "rollbacks": 0,
            "sdc_probes": 0,
            "last_anomaly": -1,
            "source": "static-trace",
        }}
    except Exception as exc:  # noqa: BLE001 — advisory data only; a
        # guard-audit bug must never cost the bench its perf evidence
        return {"guard_error": f"{type(exc).__name__}: {str(exc)[:200]}"}


def _telemetry_summary() -> dict:
    """Telemetry/goodput SCHEMA for every JSON line this process emits
    (ISSUE 7): pure imports, no backend touch, so a backend-down skip
    line still tells the recorder what shape measured goodput data will
    take when the chip returns. The measured values
    (``goodput_fraction``, ``telemetry_overhead_fraction``) land only on
    success lines, next to this schema."""
    try:
        from ray_lightning_tpu.telemetry import GOODPUT_SCHEMA
        from ray_lightning_tpu.telemetry.spans import PHASES

        return {"goodput": {"schema": GOODPUT_SCHEMA,
                            "source": "static-schema"},
                "telemetry": {"span_phases": list(PHASES),
                              "recorder": "bounded-ring+jsonl"}}
    except Exception as exc:  # noqa: BLE001 — advisory data only
        return {"telemetry_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _trace_summary() -> dict:
    """Zero-hardware tracecheck (analysis/tracecheck.py) of the
    flagship bench config: ICI bytes/step (0 on one chip — honest) and
    the estimated peak HBM, against a conservative single-chip budget.
    jax.eval_shape/make_jaxpr never initialize a backend, so this works
    even when the TPU tunnel is dead."""
    try:
        from ray_lightning_tpu.analysis.costmodel import topology_for_kind
        from ray_lightning_tpu.analysis.tracecheck import audit_step
        from ray_lightning_tpu.models.llama import LlamaModule
        from ray_lightning_tpu.parallel.strategy import SingleDevice

        cfg = _bench_cfg(use_flash=True, fused_ce=True, seq=2048,
                         vocab=128256, remat=True, scan=True,
                         ce_chunk_tokens=4096)
        # 16-GiB class (v5e) is the conservative assumption: the real
        # chip is unknown exactly when this data matters (backend down)
        topo = topology_for_kind("TPU v5e", 1)
        report = audit_step(
            LlamaModule(cfg), SingleDevice(),
            {"tokens": np.zeros((8, 2049), np.int32)},
            topology=topo, label="bench flagship")
        return {"tracecheck": {
            "ici_bytes_per_step": report.ici_bytes_per_step,
            "est_peak_hbm_bytes": report.peak_hbm_bytes,
            "hbm_budget_bytes": report.hbm_budget_bytes,
            "assumed_device_kind": topo.device_kind,
            "findings": len(report.findings),
        }, **_overlap_summary(cfg, topology_for_kind)}
    except Exception as exc:  # noqa: BLE001 — advisory data only; an
        # analysis bug must never cost the bench its perf evidence
        return {"tracecheck_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _numerics_summary() -> dict:
    """numcheck static audit (analysis/numcheck.py) of the flagship
    bench config's traced step: RLT801-805 counts by rule, the
    precision ledger, and the headline ``low_precision_reductions``
    (RLT801 narrow accumulations + RLT804 narrow gradient collectives)
    duplicated at top level for the bench_gate ceiling ratchet — 0
    since the f32-accumulation fixes, and it may only stay 0. Pure
    jaxpr work like `_trace_summary`, carried on every JSON line even
    when the backend is down; a numerics bug emits ``numerics_error``
    instead, which waives ABSENCE at the gate, never a grown value."""
    try:
        from ray_lightning_tpu.analysis.costmodel import topology_for_kind
        from ray_lightning_tpu.analysis.numcheck import summarize
        from ray_lightning_tpu.analysis.tracecheck import audit_step
        from ray_lightning_tpu.models.llama import LlamaModule
        from ray_lightning_tpu.parallel.strategy import SingleDevice

        # seq can stay small: the accumulation extents RLT801/804
        # judge are the model's contraction dims, not the sequence
        cfg = _bench_cfg(use_flash=True, fused_ce=True, seq=512,
                         vocab=128256, remat=True, scan=True,
                         ce_chunk_tokens=1024)
        report = audit_step(
            LlamaModule(cfg), SingleDevice(),
            {"tokens": np.zeros((2, 513), np.int32)},
            topology=topology_for_kind("TPU v5e", 1),
            label="bench flagship numerics")
        nc = [f for f in report.findings if f.rule.startswith("RLT8")]
        s = summarize(nc)
        lpr = sum(n for rule, n in s["by_rule"].items()
                  if rule in ("RLT801", "RLT804"))
        prec = report.precision or {}
        return {
            "numerics": {
                "findings": s["total"],
                "by_rule": s["by_rule"],
                "loss_widest_dtype": prec.get("loss_widest_dtype"),
                "ledger": {k: prec.get(k) for k in
                           ("params", "opt_state", "activations",
                            "kv_pool")},
                "source": "static-trace",
            },
            "low_precision_reductions": lpr,
        }
    except Exception as exc:  # noqa: BLE001 — advisory data only; a
        # numerics-audit bug must never cost the bench its perf evidence
        return {"numerics_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _multislice_summary() -> dict:
    """Static multi-slice (DCN) trace summary for the bench JSON
    (ISSUE 9): the bench model's HSDP step on a 2xv5p-64 deployment —
    `data` across the two slices (hierarchical gradient reduction on
    DCN), fsdp inside each slice on ICI — itemized by network tier.
    Pure jaxpr work like `_trace_summary`, carried on every line
    (success or backend-down skip), with the headline
    `dcn_bytes_per_step` duplicated at top level for the bench_gate
    ceiling ratchet (DCN bytes may only shrink)."""
    try:
        from ray_lightning_tpu.analysis.costmodel import parse_topology
        from ray_lightning_tpu.analysis.tracecheck import audit_step
        from ray_lightning_tpu.models.llama import LlamaModule
        from ray_lightning_tpu.parallel.strategy import ShardedMesh

        topo = parse_topology("2xv5p-64")
        cfg = _bench_cfg(use_flash=True, fused_ce=True, seq=2048,
                         remat=True, scan=True)
        per_slice = topo.devices_per_slice
        report = audit_step(
            LlamaModule(cfg),
            ShardedMesh(data=topo.n_slices, fsdp=per_slice),
            {"tokens": np.zeros((topo.n_devices, 2049), np.int32)},
            topology=topo, label="bench 2xv5p-64 HSDP")
        from ray_lightning_tpu.parallel.plan import dcn_crossing_axes

        # the mesh axes (other than `data`, whose crossing is the
        # designed HSDP placement) that span slices — empty when the
        # placement is sound; non-empty mirrors an RLT306 flag
        crossing = sorted(ax for ax in dcn_crossing_axes(
            report.mesh_axes, topo.n_slices) if ax != "data")
        return {
            "dcn_bytes_per_step": report.dcn_bytes_per_step,
            "multislice": {
                "topology": topo.name,
                "n_slices": topo.n_slices,
                "mesh": report.mesh_axes,
                "ici_bytes_per_step": report.ici_bytes_per_step,
                "dcn_bytes_per_step": report.dcn_bytes_per_step,
                "dcn_gbps_per_chip": topo.dcn_gbps,
                "dcn_crossing_flags": crossing,
                "findings": len(report.findings),
            },
        }
    except Exception as exc:  # noqa: BLE001 — advisory data only
        return {"multislice_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _overlap_summary(cfg, topology_for_kind) -> dict:
    """Static overlap audit for the bench JSON (ISSUE 6): the bench
    model's ZeRO step on an 8-chip FSDP slice with the double-buffered
    prefetch schedule on, classified hidden-vs-exposed by tracecheck's
    roofline model. Like `_trace_summary`, pure jaxpr work — carried on
    every line (success or backend-down) so the overlap evidence never
    depends on a live TPU. The headline `overlap_hidden_fraction` is
    duplicated at top level for the bench_gate ratchet."""
    try:
        from ray_lightning_tpu.analysis.tracecheck import audit_step
        from ray_lightning_tpu.models.llama import LlamaModule
        from ray_lightning_tpu.parallel.strategy import ShardedMesh

        topo = topology_for_kind("TPU v5e", 8)
        seq = min(2048, cfg.max_seq_len)
        report = audit_step(
            LlamaModule(cfg), ShardedMesh(fsdp=8, overlap="on"),
            {"tokens": np.zeros((8, seq + 1), np.int32)},
            topology=topo, label="bench flagship overlap=on")
        ov = report.overlap or {}
        return {
            "overlap_hidden_fraction": round(
                report.overlap_hidden_fraction, 4),
            "overlap": {
                "scheduled": bool(ov.get("scheduled")),
                "ici_hidden_us": round(report.ici_hidden_us, 1),
                "ici_exposed_us": round(report.ici_exposed_us, 1),
                "ici_bytes_per_step": report.ici_bytes_per_step,
                "assumed_topology": topo.name,
                "findings": len(report.findings),
            },
        }
    except Exception as exc:  # noqa: BLE001 — advisory data only
        return {"overlap_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _serve_summary() -> dict:
    """Serving SCHEMA + the flagship serve plan for every JSON line
    this process emits (ISSUE 8): byte math + one eval_shape, no
    backend touch, so a backend-down skip line still carries the
    serving memory story and tells the recorder what shape the
    measured serving metrics (`decode_tokens_per_s`, `ttft_cold_s`,
    `ttft_warm_s`, `slot_occupancy` — success lines only) will take.

    ``serve_hbm_bytes_per_replica`` (top-level, EVERY line — ISSUE 11)
    is the flagship replica's static per-device HBM on the attention
    paths the deployment would actually run (the fused paged decode
    AND prefill kernels when they tile the shape — they retire the
    reference lanes' dense gathered views). bench_gate
    CEILING-ratchets it: per-replica serving HBM may only shrink; a
    ``serving_error`` line waives (an analysis bug is not a
    regression). ``serve_prefill_gather_bytes`` (top-level, EVERY
    line — ISSUE 15) is the prefill lane's surviving per-group dense
    gather on the same plan — 0 once the fused prefill kernel covers
    the shape; bench_gate CEILING-ratchets it the same way (it may
    only shrink, anchoring the retirement).

    ``serve_tp`` (EVERY line — ISSUE 18) prices ONE RANK of the
    flagship TP=2 sharded replica (docs/SERVING.md "sharded
    replicas"): per-shard params/pool/total HBM plus the decode step's
    collective schedule over the replica's own tensor mesh — all from
    `serve/audit.py` tracing, no backend touch.
    ``serve_decode_ici_bytes_per_tick`` (top-level, EVERY line) is
    that schedule's total wire bytes per decode tick; bench_gate
    CEILING-ratchets it (decode collectives ride the latency-critical
    path, so their per-tick traffic may only shrink).

    ``prefix_plan`` / ``speculative_plan`` (ISSUE 19, inside
    ``serving`` — EVERY line) statically price the scheduler's two
    decode accelerators at the flagship shape: the pool bytes + prefill
    tokens a shared prefix saves across the fleet, and the verify-step
    FLOPs vs k plain decode ticks with the expected tokens/tick. The
    MEASURED twins — ``shared_block_fraction`` and
    ``accepted_tokens_per_step`` from the steady-state leg — ride
    success lines and bench_gate RATCHETS both (higher is better;
    waived on skip)."""
    try:
        import jax.numpy as jnp

        from ray_lightning_tpu.models.llama import LlamaConfig
        from ray_lightning_tpu.serve.audit import serve_memory_summary
        from ray_lightning_tpu.serve.engine import EngineConfig

        cfg = LlamaConfig.llama3_8b(max_seq_len=4096, dtype=jnp.bfloat16)
        ecfg = EngineConfig(capacity=8, block_size=16,
                            blocks_per_slot=256, prefill_chunk=256)
        plan = serve_memory_summary(cfg, ecfg)
        reference = serve_memory_summary(cfg, ecfg, fused=False)
        from ray_lightning_tpu.serve.audit import audit_decode_step

        tp = 2
        plan_tp = serve_memory_summary(cfg, ecfg, tp=tp)
        report_tp = audit_decode_step(cfg, ecfg, tp=tp)
        ici_tick = sum(e.wire_bytes for e in report_tp.collectives)
        serve_tp = {
            "tp": tp,
            "hbm_bytes_per_shard": plan_tp["per_device_bytes"],
            "params_bytes_per_shard": plan_tp["params_bytes"],
            "pool_bytes_per_shard": plan_tp["pool_bytes"],
            "decode_ici_bytes_per_tick": ici_tick,
            "collectives": [
                {"kind": e.kind, "axes": list(e.axes),
                 "payload_bytes": e.payload_bytes, "count": e.count,
                 "wire_bytes": e.wire_bytes, "source": e.source}
                for e in report_tp.collectives],
        }
        # static pricing for the scheduler's two decode accelerators
        # (ISSUE 19): prefix sharing across a full fleet of slots and
        # speculative decoding vs a quarter-depth draft — byte/FLOP
        # math from serve/audit.py, carried on EVERY line like the
        # rest of the serve plan
        import dataclasses as _dc

        from ray_lightning_tpu.serve.audit import (
            shared_prefix_plan, speculative_plan,
        )

        draft_cfg = _dc.replace(cfg, n_layers=max(1, cfg.n_layers // 4))
        prefix_plan = shared_prefix_plan(cfg, ecfg,
                                         n_streams=ecfg.capacity)
        spec_plan = speculative_plan(cfg, draft_cfg, ecfg)
        return {"serve_tp": serve_tp,
                "serve_decode_ici_bytes_per_tick": ici_tick,
                "serving": {
            "schema": ["decode_tokens_per_s", "prefill_tokens_per_s",
                       "ttft_cold_s", "ttft_warm_s", "ttft_p99_s",
                       "slot_occupancy", "shared_block_fraction",
                       "accepted_tokens_per_step",
                       "serving_attention_path",
                       "serving_prefill_path", "serve_metrics",
                       "scale_up_s", "autoscale", "slo_attainment",
                       "slo_attainment_latency_critical",
                       "shed_fraction"],
            # ISSUE 20: the traffic-class leg's measured fields
            # (success lines only; bench_gate ratchets the
            # latency-critical attainment and waives skips)
            "traffic_schema": {
                "slo_attainment": "per class {ttft_p95_s, target_s, "
                                  "attainment} from a mixed-class "
                                  "burst with the SLO machinery "
                                  "armed (docs/SERVING.md 'traffic "
                                  "& SLO classes')",
                "slo_attainment_latency_critical":
                    "fraction of latency-critical completions whose "
                    "TTFT met the class target — bench_gate ratchets "
                    "it (may only grow)",
                "shed_fraction": "typed best-effort sheds / submitted "
                                 "requests in that burst — explicit "
                                 "degradation, never silence",
            },
            "prefix_plan": prefix_plan,
            "speculative_plan": spec_plan,
            "autoscale_schema": {
                "scale_up_s": "wall seconds one controller-driven "
                              "add_replica pays (spawn + weights + "
                              "step warm; bench_gate bounds it via "
                              "RLT_BENCH_SCALE_UP_MAX)",
                "decisions": "controller polls in the drill",
                "final_replicas": "replica count after the drill",
            },
            "engine": "paged-kv continuous-batching (serve/)",
            "source": "static-schema",
            "flagship_plan": plan,
            "attention_path": plan["attention_path"],
            "prefill_attention_path": plan["prefill_attention_path"],
            "gathered_view_retired_bytes":
                plan["gathered_view_retired_bytes"],
            "prefill_kv_traffic_bytes_per_chunk":
                plan["prefill_kv_traffic_bytes_per_chunk"],
            "reference_hbm_bytes_per_replica":
                reference["per_device_bytes"],
        }, "serve_hbm_bytes_per_replica": plan["per_device_bytes"],
           "serve_prefill_gather_bytes": plan["prefill_gather_bytes"]}
    except Exception as exc:  # noqa: BLE001 — advisory data only
        return {"serving_error": f"{type(exc).__name__}: {str(exc)[:200]}"}


def _measure_serving(tiny: bool | None = None,
                     autoscale: bool = True) -> dict:
    """Measured serving leg (bench success lines + unit tests).

    ``tiny=None`` auto-sizes: the 0.5B-class bench model on an
    accelerator, the laptop-sized tiny config on CPU (unit tests /
    RLT_BENCH_SERVE_TINY=1) — same engine code path either way.
    ``autoscale=False`` skips the scale-up/down drill (unit tests of
    the throughput/TTFT fields alone — the drill pays two extra engine
    compiles; real bench lines always run it).
    """
    import time as _time

    import jax

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
    from ray_lightning_tpu.serve.scheduler import Request, Scheduler

    if tiny is None:
        tiny = (jax.default_backend() == "cpu"
                or os.environ.get("RLT_BENCH_SERVE_TINY") == "1")
    if tiny:
        import jax.numpy as jnp

        cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
        ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                            prefill_chunk=4)
        prompt_len, max_new, n_requests = 6, 8, 8
    else:
        cfg = _bench_cfg(use_flash=True, fused_ce=False, seq=1024,
                         remat=False, scan=False)
        ecfg = EngineConfig(capacity=8, block_size=16,
                            blocks_per_slot=64, prefill_chunk=128)
        prompt_len, max_new, n_requests = 128, 64, 16
    from ray_lightning_tpu.telemetry.metrics import MetricsRegistry

    model = Llama(cfg)
    prompt = np.asarray(jax.random.randint(
        jax.random.key(0), (1, prompt_len), 0, cfg.vocab_size),
        dtype=np.int32)
    params = jax.jit(model.init)(jax.random.key(1), prompt)["params"]

    def first_token_wall(engine, metrics=None) -> float:
        sched = Scheduler(engine, metrics=metrics)
        sched.submit(Request(rid="ttft", prompt=prompt[0],
                             max_new_tokens=1))
        t0 = _time.perf_counter()
        while sched.busy():
            sched.tick()
        return _time.perf_counter() - t0

    # in-memory live-metrics registry (telemetry/metrics.py) for the
    # WARM legs only — the cold probe's compile must not pollute the
    # steady-state SLO histogram the ttft_p99_s bound gates
    reg = MetricsRegistry()
    # TTFT cold: fresh engine, no warmup — the compile is the latency
    engine = DecodeEngine(model, params, ecfg)
    ttft_cold = first_token_wall(engine)
    # TTFT warm: the same compiled engine, a fresh request
    ttft_warm = first_token_wall(engine, metrics=reg)
    # steady-state decode throughput, slots saturated. The requests
    # share ONE prompt, so the prefix cache measures its real effect:
    # the common blocks prefill once and map into every slot's table
    # (shared_block_fraction below; decode streams stay bitwise)
    engine.metrics = reg
    sched = Scheduler(engine, metrics=reg, prefix_cache=True)
    for i in range(n_requests):
        sched.submit(Request(rid=f"r{i}", prompt=prompt[0],
                             max_new_tokens=max_new, seed=i))
    t0 = _time.perf_counter()
    n_tokens = 0
    while sched.busy():
        sched.tick()
        n_tokens += len(sched.last_emissions)
    wall = _time.perf_counter() - t0
    # prefill throughput (ISSUE 15): a prefill-DOMINATED drain on the
    # same warm engine — every request generates one token, so the
    # wall is the prompt chewing. Tokens counted from the engine's own
    # prefill_tokens metric (chunk positions actually advanced, incl.
    # pad columns on the batched lane — the work the kernel did).
    pf_reg = MetricsRegistry()
    engine.metrics = pf_reg
    pf_sched = Scheduler(engine, metrics=pf_reg)
    for i in range(n_requests):
        pf_sched.submit(Request(rid=f"p{i}", prompt=prompt[0],
                                max_new_tokens=1, seed=100 + i))
    t0 = _time.perf_counter()
    while pf_sched.busy():
        pf_sched.tick()
    pf_wall = _time.perf_counter() - t0
    pf_tokens = pf_reg.counters().get("prefill_tokens", 0)
    # per-class SLO leg (ISSUE 20): a mixed-class burst on the SAME
    # warm engine with the SLO machinery armed — the best-effort
    # admission budget forces typed shed records while the paying
    # classes complete; the latency-critical attainment fraction is
    # the number bench_gate ratchets (may only grow toward 1.0)
    from ray_lightning_tpu.serve.scheduler import ClassSLO, SLOConfig

    slo = SLOConfig(classes={
        "latency_critical": ClassSLO(ttft_p95_s=10.0, tpot_p95_s=5.0),
        "standard": ClassSLO(ttft_p95_s=30.0, tpot_p95_s=10.0),
        "best_effort": ClassSLO(ttft_p95_s=60.0, tpot_p95_s=20.0,
                                queue_budget=1),
    })
    slo_reg = MetricsRegistry()
    engine.metrics = slo_reg
    slo_sched = Scheduler(engine, metrics=slo_reg, slo=slo)
    slo_classes = ("latency_critical", "standard", "best_effort")
    for i in range(n_requests):
        slo_sched.submit(Request(rid=f"s{i}", prompt=prompt[0],
                                 max_new_tokens=max_new, seed=200 + i,
                                 priority=slo_classes[i % 3]))
    shed_recs = slo_sched.take_sheds()   # enqueue-budget sheds
    while slo_sched.busy():
        slo_sched.tick()
        shed_recs.extend(slo_sched.take_sheds())
    attain = {}
    lc_frac = None
    for cls in slo_classes:
        spec = slo.classes[cls]
        ttfts = sorted(c.ttft_s for c in slo_sched.completions
                       if c.priority == cls)
        if not ttfts:
            continue
        frac = sum(1 for t in ttfts if t <= spec.ttft_p95_s) \
            / len(ttfts)
        attain[cls] = {
            "ttft_p95_s": round(ttfts[min(
                len(ttfts) - 1,
                max(0, -(-95 * len(ttfts) // 100) - 1))], 4),
            "target_s": spec.ttft_p95_s,
            "attainment": round(frac, 4),
        }
        if cls == "latency_critical":
            lc_frac = round(frac, 4)
    engine.metrics = reg
    # the serve_metrics rollup: queue-depth stats from the per-tick
    # ring, event counters, and the warm TTFT p99 from the mergeable
    # histogram buckets (the SLO number bench_gate upper-bounds;
    # env-overridable, waived on skip/null like ttft_warm_s)
    counters = reg.counters()
    qd = sorted(float((s.get("g") or {}).get("queue_depth", 0.0))
                for s in reg.ring())
    ttft_hist = reg.histogram("ttft_s")
    ttft_p99 = ttft_hist.quantile(0.99) if ttft_hist else None
    autoscale_fields = (_measure_autoscale(cfg, ecfg, params)
                        if autoscale else {})
    return {
        **autoscale_fields,
        "decode_tokens_per_s": round(n_tokens / max(wall, 1e-9), 2),
        "prefill_tokens_per_s": round(
            pf_tokens / max(pf_wall, 1e-9), 2),
        "ttft_cold_s": round(ttft_cold, 4),
        "ttft_warm_s": round(ttft_warm, 4),
        "ttft_p99_s": round(ttft_p99, 4) if ttft_p99 else None,
        "slot_occupancy": round(sched.slot_occupancy, 4),
        # measured prefix-sharing / speculative twins of the static
        # plans (ISSUE 19): fraction of mapped blocks that were shared
        # in the steady-state leg, and tokens emitted per decoding
        # slot-step (exactly 1.0 without a draft — the spec ratchet's
        # honest baseline)
        "shared_block_fraction": round(sched.shared_block_fraction, 4),
        "accepted_tokens_per_step": round(
            sched.accepted_tokens_per_step, 4),
        # traffic-class leg (ISSUE 20): per-class attainment + the
        # typed-shed fraction from the mixed-class burst above
        "slo_attainment": attain,
        "slo_attainment_latency_critical": lc_frac,
        "shed_fraction": round(len(shed_recs) / n_requests, 4),
        "serving_compile_count": engine.compile_count,
        # which attention each lane actually exercised — a
        # decode/prefill tok/s number is only comparable to priors on
        # the same path (ISSUES 11 + 15)
        "serving_attention_path": engine.attention_path,
        "serving_prefill_path": engine.prefill_path,
        "serve_metrics": {
            "queue_depth_p50": qd[len(qd) // 2] if qd else None,
            "queue_depth_max": qd[-1] if qd else None,
            "preemptions": counters.get("preemptions", 0),
            "growth_stalls": counters.get("growth_stalls", 0),
            "admissions": counters.get("admissions", 0),
            "completions": counters.get("completions", 0),
            "ttft_p99_s": round(ttft_p99, 4) if ttft_p99 else None,
            "ticks": reg.ticks,
        },
    }


def _measure_autoscale(cfg, ecfg, params) -> dict:
    """Autoscale actuation drill (autoscale/, docs/AUTOSCALE.md,
    ISSUE 13): one controller-driven scale-up then scale-down on the
    SAME model/engine shape as the serving leg. ``scale_up_s`` is the
    wall one `add_replica` pays through the controller seam — the
    respawn path: weights + step compile (or persistent-cache
    deserialize) + warmup — the latency a pressure spike waits before
    capacity actually arrives. bench_gate upper-bounds it
    (RLT_BENCH_SCALE_UP_MAX). A drill failure degrades to
    ``autoscale_error`` — the serving measurements must never die with
    it."""
    import shutil
    import tempfile

    try:
        from ray_lightning_tpu.autoscale import (
            AutoscaleController, ControllerConfig, PolicyConfig,
        )
        from ray_lightning_tpu.serve.driver import (
            ReplicaGroupConfig, ServeDriver,
        )

        as_dir = tempfile.mkdtemp(prefix="rlt_bench_autoscale_")
        try:
            drv = ServeDriver(cfg, params, ReplicaGroupConfig(
                n_replicas=1, engine=ecfg, run_dir=as_dir,
                metrics_flush_every_n_ticks=2))
            drv.start()
            # fabricated signals isolate the drill to ACTUATION cost —
            # the signal path itself is the smoke/tests' business
            high = {"available": True, "pressure": 2.0,
                    "queue_depth_now": float(2 * ecfg.capacity),
                    "occupancy": 1.0,
                    "total_slots": float(ecfg.capacity)}
            low = {"available": True, "pressure": 0.0,
                   "queue_depth_now": 0.0, "occupancy": 0.0,
                   "total_slots": float(2 * ecfg.capacity)}
            sigs = [dict(high), dict(low)]
            ctl = AutoscaleController(
                drv,
                ControllerConfig(policy=PolicyConfig(
                    min_replicas=1, max_replicas=2, sustain_polls=1,
                    up_cooldown_s=0.0, down_cooldown_s=0.0)),
                signal_fn=lambda: (sigs.pop(0) if len(sigs) > 1
                                   else dict(sigs[0])))
            ctl.step(now=0.0)     # scale up: the measured spawn
            ctl.step(now=100.0)   # scale down: graceful drain
            result = drv.stop()
            # SLO watch over the drill's own run dir (telemetry/
            # watch.py, ISSUE 14): evaluate the built-in rules against
            # the evidence the drill just persisted. A healthy bench
            # fires ZERO incidents — bench_gate fails the round on
            # incidents > 0 (a breach in the bench's own serving drill
            # is a regression, not noise); skip/null lines waive.
            from ray_lightning_tpu.telemetry.watch import (
                WatchConfig, WatchEngine,
            )

            watch = WatchEngine(as_dir, WatchConfig(capture=False))
            watch.poll()
            return {
                "incidents": len(watch.incidents),
                "scale_up_s": (round(ctl.scale_up_s[0], 4)
                               if ctl.scale_up_s else None),
                "autoscale": {
                    "scale_up_s": (round(ctl.scale_up_s[0], 4)
                                   if ctl.scale_up_s else None),
                    "decisions": ctl.decisions,
                    "scale_ups": ctl.scale_ups,
                    "scale_downs": ctl.scale_downs,
                    "final_replicas":
                        result.stats["final_replicas"],
                },
            }
        finally:
            shutil.rmtree(as_dir, ignore_errors=True)
    except Exception as exc:  # noqa: BLE001 — advisory drill only
        return {"autoscale_error":
                f"{type(exc).__name__}: {str(exc)[:200]}"}


def _watch_summary() -> dict:
    """Watch/incident SCHEMA for every JSON line this process emits
    (ISSUE 14): the rule vocabulary and the shape the measured
    ``incidents`` count (success lines only — the serving drill's run
    dir is the subject) will take. Static, no backend touch: a
    backend-down skip line still tells the recorder what the field
    means, and bench_gate waives the absent count there."""
    try:
        from ray_lightning_tpu.telemetry.watch import BUILTIN_RULES

        return {"watch": {
            "schema": {
                "incidents": "watch-rule breaches fired against the "
                             "bench's own autoscale-drill run dir "
                             "(success lines; absent/null waived)",
            },
            "rules": [r.name for r in BUILTIN_RULES],
            "source": "static-schema",
        }}
    except Exception as exc:  # noqa: BLE001 — advisory data only
        return {"watch_error": f"{type(exc).__name__}: {str(exc)[:200]}"}


def _kill_line(signame: str) -> str:
    """The structured line a driver kill flushes before death: same
    schema as the watchdog/skip lines — ONE parseable JSON object, with
    a "skipped" field (environmental, not on merit) and the tracecheck
    summary. BENCH_r05 regression class: rc=124 with no JSON at all."""
    return json.dumps({
        "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "skipped": f"killed: {signame}",
        "error": (f"driver sent {signame} before the benchmark "
                  "completed; partial run discarded"),
        **_ANALYSIS,
    })


def _install_kill_handlers() -> None:
    """SIGTERM/SIGALRM -> flush the structured JSON line, exit 3. A
    harness timeout must land as a parseable skip, never as silent
    death (the BENCH_r05 `parsed: null` failure mode)."""
    import signal

    def _die(signum, frame):  # noqa: ARG001 — signal handler shape
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = f"signal {signum}"
        # os.write to fd 1, not print(): the handler may interrupt an
        # in-progress print of another JSON line, and a buffered print
        # here could interleave into it (recreating the unparseable
        # line this handler exists to prevent) or deadlock on the
        # buffer lock. The leading newline closes any half-written
        # line so the LAST stdout line is always this parseable one.
        os.write(1, b"\n" + _kill_line(name).encode() + b"\n")
        os._exit(3)

    for sig in (signal.SIGTERM, signal.SIGALRM):
        try:
            signal.signal(sig, _die)
        except (ValueError, OSError):  # non-main thread / exotic host
            pass


class BackendUnavailable(RuntimeError):
    """The jax backend never came up within the retry budget — the bench
    SKIPPED for environmental reasons, it did not fail on merit. main()
    turns this into a ``{"skipped": "backend unavailable", ...}`` JSON
    line (exit 3) the recorder can tell apart from a model/compile
    failure."""


def _backend_with_retry(tries: int | None = None,
                        base_backoff: float | None = None,
                        max_wait_s: float | None = None):
    """First backend touch, survivable: ``jax.devices()`` initializes the
    backend, and on a wedged/flaky device tunnel that RAISES (observed:
    ``jax.errors.JaxRuntimeError: UNAVAILABLE`` — the rc=1 raw-traceback
    failure that cost round 4 its perf evidence) rather than hanging
    (which the watchdog handles). Bounded retry with exponential backoff
    AND a total wall-clock cap (``RLT_BENCH_MAX_WAIT`` seconds, default
    300): the round-5 postmortem (BENCH_r05) showed the 6x20s exponential
    ladder alone (20+40+...+320s ≈ 10 min of sleeping) outliving the
    harness timeout — rc=124, no JSON at all, which is the exact
    unparseable outcome this function exists to prevent. The final
    failure raises BackendUnavailable, never a raw traceback."""
    import jax

    if tries is None:
        tries = max(1, int(_env_float("RLT_BENCH_INIT_RETRIES", 6)))
    if base_backoff is None:
        base_backoff = _env_float("RLT_BENCH_INIT_BACKOFF_S", 20.0)
    if max_wait_s is None:
        max_wait_s = _env_float("RLT_BENCH_MAX_WAIT", 300.0)
    start = time.monotonic()
    last: Exception | None = None
    for i in range(tries):
        try:
            return jax.devices()[0]
        except Exception as exc:  # noqa: BLE001 — backend init failures
            last = exc
            if i >= tries - 1:
                break
            delay = base_backoff * (2 ** i)
            elapsed = time.monotonic() - start
            if elapsed + delay > max_wait_s:
                # sleeping further would outlive the budget — stop NOW
                # with a parseable verdict instead of eating the
                # harness timeout (BENCH_r05 rc=124)
                raise BackendUnavailable(
                    f"jax backend unavailable after {i + 1} attempts; "
                    f"retry budget RLT_BENCH_MAX_WAIT={max_wait_s:.0f}s "
                    f"exhausted ({elapsed:.0f}s elapsed): {last}"
                )
            print(f"# backend unavailable (attempt {i + 1}/{tries}): "
                  f"{exc}; retrying in {delay:.0f}s",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
    raise BackendUnavailable(
        f"jax backend unavailable after {tries} attempts: {last}"
    )


def _verify_kernels() -> dict:
    """Numerical parity of the hand-tuned kernels against the XLA
    reference paths IN THE REAL EXECUTION ENVIRONMENT (on the chip the
    bench runs on) — throughput legs alone would not catch a
    wrong-but-fast kernel. The analog of the reference's behavioral
    asserts inside the remote workers
    (/root/reference/ray_lightning/tests/test_ddp_gpu.py:63-99).

    Small shapes: this is a correctness gate, not a perf leg. Tolerances
    are scale-relative and sized for two f32-accumulated MXU paths that
    differ only in tiling/reduction order."""
    import jax
    import jax.numpy as jnp

    from ray_lightning_tpu.ops import dispatch
    from ray_lightning_tpu.ops.attention import dot_product_attention
    from ray_lightning_tpu.ops.fused_ce import fused_cross_entropy
    from ray_lightning_tpu.ops.pallas.flash import flash_attention_pallas

    rng = np.random.default_rng(7)
    if dispatch.on_tpu():
        # on the real chip: the PRODUCTION tile path — flagship head_dim,
        # tuned default blocks, and the production S=2048 so there are
        # >= 2 KV tiles (the cross-tile online-softmax rescaling only
        # runs with multiple KV blocks — a single-tile shape would pass
        # the gate even with that path broken). Cheap on the MXU.
        B, S, H, Hk, D = 2, 2048, 4, 2, 128
        block_q, block_k = None, None  # tuned defaults (512/1024)
    else:
        # CPU interpret mode: same kernel code, sized to stay fast
        B, S, H, Hk, D = 2, 256, 4, 2, 64
        block_q, block_k = 128, 128
    q = jnp.asarray(rng.standard_normal((B, S, H, D), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((B, S, Hk, D), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((B, S, Hk, D), dtype=np.float32))

    errors: dict[str, float] = {}

    def _rel_err(got, want) -> float:
        scale = max(float(jnp.abs(want).max()), 1.0)
        return float(jnp.abs(got - want).max()) / scale

    # flash forward (GQA shape, causal — the model's configuration)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention_pallas(q, k, v, causal=True,
                                 block_q=block_q, block_k=block_k)
    errors["flash_fwd"] = _rel_err(out, ref)

    # flash backward: grads of the same scalar through both paths
    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention_pallas(
            q, k, v, causal=True, block_q=block_q,
            block_k=block_k) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    errors["flash_bwd"] = max(_rel_err(b, a) for a, b in zip(gr, gf))

    # fused chunked CE vs materialized logits (loss AND grads)
    Dm, V, T = 128, 1024, B * S
    hidden = jnp.asarray(
        rng.standard_normal((B, S, Dm), dtype=np.float32))
    w = jnp.asarray(
        (rng.standard_normal((Dm, V)) * Dm ** -0.5).astype(np.float32))
    targets = jnp.asarray(rng.integers(0, V, (B, S)).astype(np.int32))

    def ce_ref(hidden, w):
        x = hidden.reshape(T, Dm).astype(jnp.bfloat16)
        logits = jnp.dot(x, w.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, targets.reshape(T)[:, None], axis=-1)[:, 0]
        return (lse - tgt).mean()

    def ce_fused(hidden, w):
        return fused_cross_entropy(hidden, w, targets, chunk_tokens=128)

    def ce_inline(hidden, w):
        return fused_cross_entropy(hidden, w, targets, chunk_tokens=128,
                                   inline_backward=True)

    (l_ref, g_ref) = jax.value_and_grad(ce_ref, argnums=(0, 1))(hidden, w)
    (l_fus, g_fus) = jax.value_and_grad(ce_fused, argnums=(0, 1))(hidden, w)
    (l_inl, g_inl) = jax.value_and_grad(ce_inline, argnums=(0, 1))(hidden, w)
    errors["fused_ce_loss"] = abs(float(l_fus) - float(l_ref))
    errors["fused_ce_grad"] = max(
        _rel_err(b, a) for a, b in zip(g_ref, g_fus))
    errors["inline_ce_loss"] = abs(float(l_inl) - float(l_ref))
    errors["inline_ce_grad"] = max(
        _rel_err(b, a) for a, b in zip(g_ref, g_inl))

    tolerances = {"flash_fwd": 2e-2, "flash_bwd": 2e-2,
                  "fused_ce_loss": 2e-2, "fused_ce_grad": 2e-2,
                  "inline_ce_loss": 2e-2, "inline_ce_grad": 2e-2}
    return {
        "kernels_verified": all(
            errors[kk] <= tolerances[kk] for kk in tolerances),
        "kernel_errors": {kk: round(vv, 6) for kk, vv in errors.items()},
    }


def main() -> None:
    import threading

    # FIRST: a driver kill arriving at any later point must still flush
    # a structured line; THEN the CPU-only tracecheck summary, before
    # any backend touch, so skip/error lines carry analysis data too
    _install_kill_handlers()
    _ANALYSIS.update(_concurrency_summary())
    _ANALYSIS.update(_trace_summary())
    _ANALYSIS.update(_numerics_summary())
    _ANALYSIS.update(_multislice_summary())
    _ANALYSIS.update(_guard_summary())
    _ANALYSIS.update(_telemetry_summary())
    _ANALYSIS.update(_serve_summary())
    _ANALYSIS.update(_watch_summary())

    # Watchdog: a wedged device tunnel (observed on shared-chip setups:
    # every op, even jax.devices(), blocks forever) must surface as an
    # honest JSON error line for the bench recorder, not a silent hang.
    # <= 0 disables.
    # a malformed value must not reproduce the silent-failure mode the
    # watchdog exists to prevent — parse-or-default (_env_float)
    watchdog_s = _env_float("RLT_BENCH_WATCHDOG_S", 2700.0)
    finished = threading.Event()

    def _watchdog():
        if not finished.wait(watchdog_s):
            print(json.dumps({
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
                "error": (f"benchmark did not complete within "
                          f"{watchdog_s:.0f}s — device unreachable or "
                          "compile hang; rerun when the chip is healthy"),
                **_ANALYSIS,
            }), flush=True)
            os._exit(3)

    if watchdog_s > 0:
        threading.Thread(target=_watchdog, daemon=True).start()

    # Supervised legs (resilience/policy.py, ISSUE 3 satellite): a
    # MID-RUN backend loss — the tunnel dropping between legs, a
    # transient UNAVAILABLE after the headline already measured — gets a
    # bounded restart instead of voiding the round, and the final line
    # carries the PARTIAL results + restart count either way. FATAL
    # classifications (OOM, compile error) never retry: deterministic
    # failures would just replay.
    from ray_lightning_tpu.resilience.policy import (
        FailureKind,
        classify_failure,
    )

    partial: dict = {}
    restarts = 0
    max_restarts = max(0, int(_env_float("RLT_BENCH_RESTARTS", 1)))
    while True:
        try:
            payload = _run(partial)
            break
        except BackendUnavailable as exc:
            # _backend_with_retry already spent its bounded init budget
            # (RLT_BENCH_MAX_WAIT) — re-retrying here would double the
            # wait and risk rc=124. With nothing measured this is the
            # environmental skip; with partial legs in hand it is a
            # partial result, not a skip.
            line = {
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
                **partial,
                "restarts": restarts,
                "error": str(exc),
                **_ANALYSIS,
            }
            if partial.get("value"):
                line["partial"] = True
            else:
                line["skipped"] = "backend unavailable"
            print(json.dumps(line), flush=True)
            finished.set()
            raise SystemExit(3) from None
        except Exception as exc:  # noqa: BLE001 — every failure mode
            # must surface as the same structured JSON line the watchdog
            # emits (VERDICT r4 weak #1). Exit 3 = structured failure.
            fc = classify_failure(exc)
            if fc.kind == FailureKind.RETRYABLE and restarts < max_restarts:
                restarts += 1
                print(f"# mid-run failure [{fc.cause}]: {fc.detail}; "
                      f"supervised restart {restarts}/{max_restarts}",
                      file=sys.stderr, flush=True)
                time.sleep(_env_float("RLT_BENCH_RESTART_BACKOFF_S", 5.0))
                continue
            line = {
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
                **partial,
                "restarts": restarts,
                "error": f"{type(exc).__name__}: {exc}",
                "failure_class": f"{fc.kind}/{fc.cause}",
                **_ANALYSIS,
            }
            if partial.get("value"):
                line["partial"] = True
            print(json.dumps(line), flush=True)
            finished.set()
            raise SystemExit(3) from None
    payload = {**payload, "restarts": restarts, **_ANALYSIS}
    print(json.dumps(payload), flush=True)
    finished.set()


def _run(sink: dict | None = None) -> dict:
    """One full measurement pass. ``sink`` (the supervisor's partial-
    result carrier) is updated IN PLACE as legs land, so a mid-run
    failure leaves everything already measured available to the final
    JSON line instead of losing the round."""
    device = _backend_with_retry()
    kind = device.device_kind
    peak_tflops = _PEAK_TFLOPS.get(kind, _DEFAULT_PEAK)
    # device-aware sizing inside the probe: full ~280-TFLOP chain on
    # known accelerators (seconds on a TPU; amortizes tunnel dispatch
    # latency — the old per-call probe read 34.5 "TFLOP/s" on a chip
    # simultaneously delivering 117 to the model step), tiny on unknown
    # kinds so CPU smoke runs don't stall for minutes
    probe = _probe_matmul_tflops()

    # on-chip kernel correctness gate (cheap; before the throughput legs
    # so a wrong kernel is flagged even if a later leg OOMs). A CRASHING
    # kernel (raises, not just wrong numbers) must report as a failed
    # gate, not void the throughput legs that don't use it.
    try:
        kernels = _verify_kernels()
    except Exception as exc:  # noqa: BLE001 — the gate result is data
        kernels = {"kernels_verified": False,
                   "kernel_verify_error": f"{type(exc).__name__}: "
                                          f"{str(exc)[:300]}"}

    # Tuned configs per leg, from the v5e sweeps (batch 2..16; chunk
    # 1k..24k; remat on/off x nothing/dots; scan on/off):
    #   * remat=False + unrolled layers wins when the 0.5B model's
    #     activations fit (16 GB chip): no backward recompute, and the
    #     unrolled program lets XLA schedule layers without the scan's
    #     worst-case buffer allocation (remat=False + scan OOMs where
    #     remat=False + unrolled compiles and is fastest);
    #   * at V=32768 materialized logits fit and beat the fused-CE
    #     recompute by ~3%, so the S=2048/S=4096 legs run fused_ce=False;
    #   * the V=128256 leg is where fused CE pays: the materialized
    #     [B, S, V] logits do not even compile there (verified OOM), so
    #     fused is the ONLY path and is reported with its own MFU.
    # headline leg — fatal on failure (the driver schema requires it)
    headline_timing: dict = {}
    tps, cfg = _measure(use_flash=True, fused_ce=False, batch=9, seq=2048,
                        remat=False, scan=False, timing=headline_timing)
    fpt = _flops_per_token(cfg, 2048)
    mfu = tps * fpt / (peak_tflops * 1e12)
    # goodput view of the headline measurement window: timed productive
    # seconds over total wall including the warmup/compile the best-of
    # timing excludes (compile is lost time under goodput semantics);
    # plus the measured recorder cost relative to the step time (the
    # bench_gate bounds it < 1%)
    goodput_fraction = (headline_timing["productive_s"]
                        / headline_timing["wall_s"]
                        if headline_timing.get("wall_s") else 0.0)
    try:
        overhead = _telemetry_overhead_fraction(
            headline_timing.get("step_dt_s") or 1.0)
    except Exception:  # noqa: BLE001 — advisory measurement
        overhead = None

    results = sink if sink is not None else {}
    results.update({
        "goodput_fraction": round(goodput_fraction, 4),
        "telemetry_overhead_fraction": (
            round(overhead, 6) if overhead is not None else None),
        "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        # overwritten by the baseline leg; on baseline failure it stays
        # 0.0 NEXT TO a vs_baseline_error field — the same "0.0 means
        # not-measured" convention as the watchdog/error JSON lines (the
        # field is required by the driver schema, so it is never dropped)
        "vs_baseline": 0.0,
        "mfu": round(mfu, 4),
        "assumed_peak_tflops": peak_tflops,
        "device_kind": kind,
        "flops_per_token": round(fpt / 1e9, 3),  # GFLOP
        "probe_matmul_tflops": round(probe, 1),
        **kernels,
    })
    mfus = [mfu]

    def leg(name, fn):
        """Secondary legs degrade to a ``<name>_error`` field instead of
        voiding the whole artifact (one OOMing config must not cost the
        round every other number, the round-4 lesson at bench level)."""
        try:
            results.update(fn())
        except Exception as exc:  # noqa: BLE001 — leg failures are data
            results[f"{name}_error"] = f"{type(exc).__name__}: {str(exc)[:300]}"

    def _baseline():
        # every hand-tuned path off — XLA-naive attention, default
        # remat/scan, at ITS swept-best batch (6; larger batches OOM the
        # S^2 score matrices)
        base_tps, _ = _measure(use_flash=False, fused_ce=False, batch=6,
                               seq=2048)
        return {"vs_baseline": round(tps / base_tps, 4)}

    def _s4k():
        # long-sequence leg (2× context)
        t, c = _measure(use_flash=True, fused_ce=False, batch=3, seq=4096,
                        remat=False, scan=False)
        m = t * _flops_per_token(c, 4096) / (peak_tflops * 1e12)
        mfus.append(m)
        return {"s4096_tokens_per_sec": round(t, 1), "s4096_mfu": round(m, 4)}

    def _v128k():
        # Llama-3-vocab leg (V=128256): fused chunked CE (ops/fused_ce.py)
        t, c = _measure(use_flash=True, fused_ce=True, batch=4, seq=2048,
                        vocab=128256, remat=False, scan=False)
        m = t * _flops_per_token(c, 2048) / (peak_tflops * 1e12)
        mfus.append(m)
        return {"v128k_tokens_per_sec": round(t, 1), "v128k_mfu": round(m, 4),
                "v128k_materialized_logits": "OOM (does not compile)"}

    def _flagship():
        # FLAGSHIP leg: remat + scan_layers + fused CE at the Llama-3
        # vocab — the only configuration class that holds at the
        # north-star Llama-3-8B (BASELINE.md config 4: remat+scan+FSDP
        # are mandatory at 8B on real chips), benched at its swept
        # optimum (scripts/sweep_flagship.py) with the inline-backward
        # CE (ops/fused_ce.py _ce_inline — no logits-tile recompute).
        # MFU counts useful FLOPs only: the backward recompute remat
        # performs is real work the flagship deliberately trades for
        # memory, so its MFU reads lower than the unrolled legs.
        # The inline compile has a fallback: this leg's job is a
        # driver-verified flagship number, and an inline-path compile
        # failure (the TPU compile helper has rejected some large inline
        # programs — sweep JSONL) must degrade to the proven non-inline
        # optimum rather than void the row (_flagship_leg).
        def measure(ce_inline):
            return _measure(use_flash=True, fused_ce=True, batch=8,
                            seq=2048, vocab=128256, remat=True, scan=True,
                            remat_policy="nothing", ce_chunk_tokens=4096,
                            ce_inline=ce_inline)

        row, m = _flagship_leg(
            measure, shared,
            lambda t, c: t * _flops_per_token(c, 2048) / (peak_tflops * 1e12),
            shape_desc="B=8 S=2048 V=128256 chunk=4096")
        mfus.append(m)
        return row

    shared: dict = {}

    def _flagship_remat_ce():
        # the pre-inline flagship config, kept as its own leg so the
        # inline win is visible in one artifact; runs BEFORE the inline
        # leg so the latter's fallback can reuse this measurement
        t, c = _measure(use_flash=True, fused_ce=True, batch=8, seq=2048,
                        vocab=128256, remat=True, scan=True,
                        remat_policy="nothing", ce_chunk_tokens=4096)
        m = t * _flops_per_token(c, 2048) / (peak_tflops * 1e12)
        mfus.append(m)
        shared["rematce"] = (t, m)
        return {"flagship_rematce_tokens_per_sec": round(t, 1),
                "flagship_rematce_mfu": round(m, 4)}

    def _flagship_attnout():
        # the round-5 remat policy (save flash VJP residuals — no
        # attention recompute in backward) on top of the inline CE, so
        # the driver artifact carries the comparison against the
        # "nothing" flagship leg in one capture. Same degradation policy
        # as the flagship leg: an inline compile rejection (documented
        # at this shape class) falls back to the measurable non-inline
        # attn_out config instead of voiding the row.
        def measure(ce_inline):
            return _measure(use_flash=True, fused_ce=True, batch=8,
                            seq=2048, vocab=128256, remat=True, scan=True,
                            remat_policy="attn_out", ce_chunk_tokens=4096,
                            ce_inline=ce_inline)

        row, m = _attnout_leg(
            measure,
            lambda t, c: t * _flops_per_token(c, 2048) / (peak_tflops * 1e12))
        mfus.append(m)
        return row

    def _overlap():
        # hot-loop overlap leg (pipeline/overlap.py, docs/PERFORMANCE.md):
        # device-prefetch speedup against a calibrated synthetic slow
        # loader + the AOT warm-start compile metrics (cold vs
        # persistent-cache hit). Runs on whatever backend this bench got
        # — the same numbers are CPU-measurable when the chip is down.
        from ray_lightning_tpu.pipeline.overlap import (
            measure_prefetch_overlap,
        )

        r = measure_prefetch_overlap(steps=30)
        return {"prefetch_speedup": r["value"],
                "prefetch_occupancy": r["pipeline_occupancy"],
                "compile_cold_s": r["compile_cold_s"],
                "compile_warm_s": r["compile_warm_s"],
                "overlap": r}

    def _serving():
        # serving leg (serve/, docs/SERVING.md, ISSUE 8): the real
        # continuous-batching engine on THIS backend. TTFT cold = first
        # request through a FRESH engine including the step compile
        # (the P99 story a persistent compile cache improves); TTFT
        # warm = a later request on the compiled engine (pure
        # queue+prefill); decode throughput at steady state with every
        # slot occupied. Random weights: serving throughput is
        # content-independent.
        return _measure_serving()

    def _reshard():
        # elastic leg (elastic/, docs/ELASTIC.md, ISSUE 9): time a
        # cross-topology checkpoint restore on THIS backend — save a
        # provenance-stamped state on the full local mesh, restore it
        # onto a half-size mesh (or same-size on one device), report
        # wall seconds. The number the elastic supervisor pays per
        # shrink/grow; bench_gate bounds it.
        import shutil
        import tempfile

        import jax
        import jax.numpy as jnp

        from ray_lightning_tpu.checkpoint.io import (
            save_checkpoint,
            sharding_provenance,
            wait_for_checkpoints,
        )
        from ray_lightning_tpu.elastic.reshard import reshard_restore
        from ray_lightning_tpu.parallel.strategy import FSDP

        n = len(jax.devices())
        src = FSDP(min_shard_size=8)
        src.setup()
        # ~32 MiB of params: big enough that the restore is I/O, small
        # enough to never disturb the throughput legs
        params = {"w": jnp.arange(8 * 1024 * 1024,
                                  dtype=jnp.float32).reshape(2048, -1)}
        params = src.shard_params(params)
        state = {"params": params,
                 "step": jax.device_put(jnp.zeros((), jnp.int32),
                                        src.replicated())}
        d = tempfile.mkdtemp(prefix="rlt_bench_reshard_")
        try:
            ck = os.path.join(d, "ck")
            save_checkpoint(ck, state,
                            {"global_step": 0,
                             **sharding_provenance(src.mesh, state)})
            wait_for_checkpoints()
            dst = FSDP(num_workers=max(1, n // 2), min_shard_size=8)
            dst.setup()
            tgt = {"params": dst.shard_params(
                       jax.tree.map(jnp.zeros_like,
                                    jax.device_get(params))),
                   "step": jax.device_put(jnp.zeros((), jnp.int32),
                                          dst.replicated())}
            t0 = time.perf_counter()
            restored = reshard_restore(ck, tgt)
            jax.block_until_ready(restored)
            dt = time.perf_counter() - t0
        finally:
            shutil.rmtree(d, ignore_errors=True)
        return {"reshard_restore_s": round(dt, 4),
                "reshard": {"from_world": n,
                            "to_world": max(1, n // 2),
                            "bytes": int(8 * 1024 * 1024 * 4)}}

    legs = {
        "flagship_rematce": _flagship_remat_ce,
        "flagship": _flagship,
        "flagship_attnout": _flagship_attnout,
        "vs_baseline": _baseline,
        "s4096": _s4k,
        "v128k": _v128k,
        "overlap": _overlap,
        "serving": _serving,
        "reshard": _reshard,
    }
    assert set(legs) == set(LEG_ORDER), "LEG_ORDER out of sync with legs"
    for name in LEG_ORDER:
        leg(name, legs[name])

    # Self-consistency (VERDICT r3 weak #1): the probe is a THROUGHPUT
    # ceiling; any model leg reading more effective FLOP/s than the bare
    # matmul chain means one of the two mismeasured. Flag it in-line
    # rather than shipping arithmetic that cannot all be true.
    results["probe_consistent"] = probe >= 0.95 * max(mfus) * peak_tflops
    return results


if __name__ == "__main__":
    main()
