"""Headline benchmark: Llama training-step throughput + MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
honesty fields — "mfu", "assumed_peak_tflops", "device_kind",
"flops_per_token", and a long-sequence leg ("s4096_*").

The reference publishes no performance numbers (BASELINE.json
"published": {} — see BASELINE.md), so "vs_baseline" compares against the
same training step with the hand-tuned paths disabled (XLA-naive attention
instead of the pallas flash kernel; materialized full-vocab logits instead
of the fused chunked cross-entropy): > 1 means the TPU-native design beats
a straightforward XLA translation of the reference capability. MFU is the
absolute check the ratio can't game: model FLOPs (6·N_matmul + causal
attention, no remat recompute credit) / chip peak bf16 FLOPs.
"""
from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

# peak table + probe shared with the doctor CLI (utils/probe.py)
from ray_lightning_tpu.utils.probe import (  # noqa: E402
    DEFAULT_PEAK as _DEFAULT_PEAK,
    PEAK_TFLOPS as _PEAK_TFLOPS,
    matmul_tflops as _probe_matmul_tflops,
)


def _bench_cfg(use_flash: bool, fused_ce: bool, seq: int,
               vocab: int = 32768, remat: bool = True, scan: bool = True,
               remat_policy: str = "nothing", ce_chunk_tokens: int = 2048):
    from ray_lightning_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=vocab,
        dim=2048,
        n_layers=8,
        n_heads=16,
        n_kv_heads=8,
        hidden_dim=5632,
        max_seq_len=seq,
        use_flash=use_flash,
        fused_ce=fused_ce,
        ce_chunk_tokens=ce_chunk_tokens,
        remat=remat,
        remat_policy=remat_policy,
        scan_layers=scan,
    )


def _flops_per_token(cfg, seq: int) -> float:
    """Model FLOPs per trained token: 6×(matmul params) + causal attention
    (QK^T + AV, average context S/2), fwd×2 + bwd×4. Remat recompute is
    real work but not counted — MFU measures useful FLOPs."""
    hd = cfg.head_dim
    per_layer = (
        cfg.dim * (cfg.n_heads + 2 * cfg.n_kv_heads) * hd  # wqkv
        + cfg.n_heads * hd * cfg.dim                       # wo
        + 3 * cfg.dim * cfg.hidden_dim                     # gate_up + down
    )
    n_matmul = cfg.n_layers * per_layer + cfg.dim * cfg.vocab_size  # lm_head
    attn = 6 * cfg.n_layers * cfg.n_heads * hd * seq  # 3×(2·2·(S/2)·nq·hd)
    return 6.0 * n_matmul + attn


def _make_step(use_flash: bool, fused_ce: bool, batch: int, seq: int,
               vocab: int = 32768, remat: bool = True, scan: bool = True,
               remat_policy: str = "nothing", ce_chunk_tokens: int = 2048):
    import jax
    import optax

    from ray_lightning_tpu.models.llama import Llama, LlamaModule

    cfg = _bench_cfg(use_flash, fused_ce, seq, vocab, remat, scan,
                     remat_policy, ce_chunk_tokens)
    model = Llama(cfg)
    module = LlamaModule(cfg)
    module.model = model
    tokens = jax.random.randint(
        jax.random.key(0), (batch, seq + 1), 0, cfg.vocab_size, dtype=np.int32
    )
    params = jax.jit(model.init)(jax.random.key(0), tokens[:, :-1])["params"]
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = jax.jit(tx.init)(params)

    def loss_fn(params, tokens):
        # the trainer's actual loss path (fused or materialized, per cfg)
        return module._loss(params, tokens[:, :-1], tokens[:, 1:], None)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, params, opt_state, tokens, batch * seq, cfg


def _time_step(step, params, opt_state, tokens, warmup=3, iters=5,
               windows=3):
    """Best-of-``windows`` timing: the chip may be shared/tunneled, and a
    contention burst in one window must not masquerade as model speed —
    the minimum window is the closest observable to the true step time."""
    import jax

    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, tokens)
    # device_get, not block_until_ready: the latter can be a no-op through
    # remote-device tunnels; fetching the loss value forces execution of
    # the whole dependency chain.
    float(jax.device_get(loss))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(jax.device_get(loss))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _measure(use_flash: bool, fused_ce: bool, batch: int, seq: int,
             vocab: int = 32768, remat: bool = True, scan: bool = True,
             remat_policy: str = "nothing", ce_chunk_tokens: int = 2048):
    step, params, opt_state, tokens, tps, cfg = _make_step(
        use_flash, fused_ce, batch, seq, vocab, remat, scan,
        remat_policy, ce_chunk_tokens
    )
    dt = _time_step(step, params, opt_state, tokens)
    del step, params, opt_state, tokens
    return tps / dt, cfg


def main() -> None:
    import os
    import threading

    # Watchdog: a wedged device tunnel (observed on shared-chip setups:
    # every op, even jax.devices(), blocks forever) must surface as an
    # honest JSON error line for the bench recorder, not a silent hang.
    # <= 0 disables.
    try:
        watchdog_s = float(os.environ.get("RLT_BENCH_WATCHDOG_S", "2700"))
    except ValueError:
        # a malformed value must not reproduce the silent-failure mode
        # the watchdog exists to prevent
        watchdog_s = 2700.0
    finished = threading.Event()

    def _watchdog():
        if not finished.wait(watchdog_s):
            print(json.dumps({
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
                "error": (f"benchmark did not complete within "
                          f"{watchdog_s:.0f}s — device unreachable or "
                          "compile hang; rerun when the chip is healthy"),
            }), flush=True)
            os._exit(3)

    if watchdog_s > 0:
        threading.Thread(target=_watchdog, daemon=True).start()

    import jax

    device = jax.devices()[0]
    kind = device.device_kind
    peak_tflops = _PEAK_TFLOPS.get(kind, _DEFAULT_PEAK)
    # device-aware sizing inside the probe: full ~280-TFLOP chain on
    # known accelerators (seconds on a TPU; amortizes tunnel dispatch
    # latency — the old per-call probe read 34.5 "TFLOP/s" on a chip
    # simultaneously delivering 117 to the model step), tiny on unknown
    # kinds so CPU smoke runs don't stall for minutes
    probe = _probe_matmul_tflops()

    # Tuned configs per leg, from the v5e sweeps (batch 2..16; chunk
    # 1k..24k; remat on/off x nothing/dots; scan on/off):
    #   * remat=False + unrolled layers wins when the 0.5B model's
    #     activations fit (16 GB chip): no backward recompute, and the
    #     unrolled program lets XLA schedule layers without the scan's
    #     worst-case buffer allocation (remat=False + scan OOMs where
    #     remat=False + unrolled compiles and is fastest);
    #   * at V=32768 materialized logits fit and beat the fused-CE
    #     recompute by ~3%, so the S=2048/S=4096 legs run fused_ce=False;
    #   * the V=128256 leg is where fused CE pays: the materialized
    #     [B, S, V] logits do not even compile there (verified OOM), so
    #     fused is the ONLY path and is reported with its own MFU.
    tps, cfg = _measure(use_flash=True, fused_ce=False, batch=9, seq=2048,
                        remat=False, scan=False)
    fpt = _flops_per_token(cfg, 2048)
    mfu = tps * fpt / (peak_tflops * 1e12)

    # baseline: every hand-tuned path off — XLA-naive attention, default
    # remat/scan, at ITS swept-best batch (6; larger batches OOM the S^2
    # score matrices)
    base_tps, _ = _measure(use_flash=False, fused_ce=False, batch=6, seq=2048)

    # long-sequence leg (2× context)
    s4k_tps, s4k_cfg = _measure(use_flash=True, fused_ce=False,
                                batch=3, seq=4096, remat=False, scan=False)
    s4k_mfu = s4k_tps * _flops_per_token(s4k_cfg, 4096) / (peak_tflops * 1e12)

    # Llama-3-vocab leg (V=128256): fused chunked CE (ops/fused_ce.py)
    v128k_tps, v128k_cfg = _measure(use_flash=True, fused_ce=True,
                                    batch=4, seq=2048, vocab=128256,
                                    remat=False, scan=False)
    v128k_mfu = (v128k_tps * _flops_per_token(v128k_cfg, 2048)
                 / (peak_tflops * 1e12))

    # FLAGSHIP leg: remat + scan_layers + fused CE at the Llama-3 vocab —
    # the only configuration class that holds at the north-star
    # Llama-3-8B (BASELINE.md config 4: remat+scan+FSDP are mandatory at
    # 8B on real chips), benched first-class at its swept optimum
    # (scripts/sweep_flagship.py: remat_policy x batch x ce_chunk x flash
    # blocks under remat). MFU counts useful FLOPs only — the backward
    # recompute remat performs is real work the flagship deliberately
    # trades for memory, so its MFU reads lower than the unrolled legs.
    flag_tps, flag_cfg = _measure(
        use_flash=True, fused_ce=True, batch=8, seq=2048, vocab=128256,
        remat=True, scan=True, remat_policy="nothing",
        ce_chunk_tokens=4096,
    )
    flag_mfu = (flag_tps * _flops_per_token(flag_cfg, 2048)
                / (peak_tflops * 1e12))

    # Self-consistency (VERDICT r3 weak #1): the probe is a THROUGHPUT
    # ceiling; any model leg reading more effective FLOP/s than the bare
    # matmul chain means one of the two mismeasured. Flag it in-line
    # rather than shipping arithmetic that cannot all be true.
    best_model_tflops = max(
        mfu, s4k_mfu, v128k_mfu, flag_mfu) * peak_tflops
    probe_consistent = probe >= 0.95 * best_model_tflops

    print(
        json.dumps(
            {
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": round(tps, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(tps / base_tps, 4),
                "mfu": round(mfu, 4),
                "assumed_peak_tflops": peak_tflops,
                "device_kind": kind,
                "flops_per_token": round(fpt / 1e9, 3),  # GFLOP
                "probe_matmul_tflops": round(probe, 1),
                "probe_consistent": probe_consistent,
                "s4096_tokens_per_sec": round(s4k_tps, 1),
                "s4096_mfu": round(s4k_mfu, 4),
                "v128k_tokens_per_sec": round(v128k_tps, 1),
                "v128k_mfu": round(v128k_mfu, 4),
                "v128k_materialized_logits": "OOM (does not compile)",
                "flagship_tokens_per_sec": round(flag_tps, 1),
                "flagship_mfu": round(flag_mfu, 4),
                "flagship_config": "remat(nothing)+scan+fusedCE "
                                   "B=8 S=2048 V=128256 chunk=4096",
            }
        )
    )
    finished.set()


if __name__ == "__main__":
    main()
