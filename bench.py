"""Headline benchmark: Llama training-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no performance numbers (BASELINE.json
"published": {} — see BASELINE.md), so the baseline here is the same
training step with the framework's hand-tuned paths disabled (XLA-naive
attention instead of the pallas flash kernel): vs_baseline > 1 means the
TPU-native design beats the straightforward XLA translation of the
reference capability.
"""
from __future__ import annotations

import json
import time
from functools import partial

import numpy as np


def _make_step(use_flash: bool):
    import jax
    import optax

    from ray_lightning_tpu.models.llama import (
        LlamaConfig,
        cross_entropy_loss,
        Llama,
    )

    cfg = LlamaConfig(
        vocab_size=32768,
        dim=2048,
        n_layers=8,
        n_heads=16,
        n_kv_heads=8,
        hidden_dim=5632,
        max_seq_len=2048,
        use_flash=use_flash,
    )
    model = Llama(cfg)
    # batch swept on v5e (4/6/8): 6 keeps activations within HBM while
    # maximizing MXU occupancy for this 0.5B config
    batch, seq = 6, 2048
    tokens = jax.random.randint(
        jax.random.key(0), (batch, seq + 1), 0, cfg.vocab_size, dtype=np.int32
    )
    params = jax.jit(model.init)(jax.random.key(0), tokens[:, :-1])["params"]
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = jax.jit(tx.init)(params)

    def loss_fn(params, tokens):
        logits = model.apply({"params": params}, tokens[:, :-1])
        return cross_entropy_loss(logits, tokens[:, 1:])

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, params, opt_state, tokens, batch * seq


def _time_step(step, params, opt_state, tokens, warmup=3, iters=10):
    import jax

    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, tokens)
    # device_get, not block_until_ready: the latter can be a no-op through
    # remote-device tunnels; fetching the loss value forces execution of
    # the whole dependency chain.
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(jax.device_get(loss))
    return (time.perf_counter() - t0) / iters


def main() -> None:
    step, params, opt_state, tokens, tokens_per_step = _make_step(
        use_flash=True
    )
    dt = _time_step(step, params, opt_state, tokens)
    tokens_per_sec = tokens_per_step / dt

    del step, params, opt_state
    step_b, params_b, opt_b, tokens_b, _ = _make_step(use_flash=False)
    dt_base = _time_step(step_b, params_b, opt_b, tokens_b)
    baseline_tps = tokens_per_step / dt_base

    print(
        json.dumps(
            {
                "metric": "llama_0.5b_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(tokens_per_sec / baseline_tps, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
