"""trainguard (ISSUE 5): in-step numerics guard, SDC detection, and
rollback-to-last-good (docs/RESILIENCE.md "trainguard").

Fast tests prove the acceptance matrix at Trainer level on the virtual
CPU mesh: an injected NaN batch is skipped IN-JIT and the final params
are bitwise-identical to a clean run trained without that batch; the
guard adds no per-step host syncs (the guarded step's jaxpr carries no
effects and the anomaly flag rides the metrics outputs; RLT201/RLT304
lint the trainer+guard clean); K anomalies escalate with a rollback
marker; the SDC fingerprint probe catches a one-bit parameter flip and
attributes the divergent device; blessed-checkpoint retention and
selection. The @slow tests drive REAL 2-process SPMD groups through
supervise(): nan-skip with zero restarts, corruption rollback resuming
from the blessed checkpoint with the data order advanced past the
poisoned window, and bit-flip -> quarantine.
"""
import json
import os

import numpy as np
import pytest

from ray_lightning_tpu.resilience.guard import (
    GuardConfig,
    SDCDetectedError,
    TrainingAnomalyError,
    diagnose_digests,
    read_rollback_marker,
)
from ray_lightning_tpu.resilience.policy import (
    FailureKind,
    RetryPolicy,
    classify_failure,
)

# ------------------------------------------------------------- helpers


class SkipLoader:
    """Deterministic loader wrapper that drops selected (epoch, batch)
    pairs — the "clean run trained without that batch" reference."""

    def __init__(self, loader, skip):
        self.loader = loader
        self.skip = set(skip)
        self._epoch = 0

    def set_epoch(self, epoch):
        self._epoch = epoch
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self):
        for i, b in enumerate(iter(self.loader)):
            if (self._epoch, i) in self.skip:
                continue
            yield b


def _loader(batch_size=32, seed=5):
    from ray_lightning_tpu import DataLoader
    from tests.utils import random_dataset

    return DataLoader(random_dataset(), batch_size=batch_size,
                      shuffle=True, seed=seed)


def _trainer(tmp_path, guard=None, strategy=None, callbacks=None, **kw):
    from ray_lightning_tpu import SingleDevice, Trainer

    return Trainer(strategy=strategy or SingleDevice(), max_epochs=2,
                   enable_checkpointing=False, enable_progress_bar=False,
                   seed=7, log_every_n_steps=1,
                   default_root_dir=str(tmp_path), guard=guard,
                   callbacks=callbacks, **kw)


# --------------------------------------------------- tier 1: in-jit skip


def test_nan_skip_bitwise_equals_clean_minus_batch(tmp_path):
    """The acceptance bar: nan_loss injected at step 3 is skipped in-jit
    and the final params are BITWISE identical to a clean run trained
    without that batch — the discarded update also leaves the step index
    (per-step RNG fold, optimizer schedule) untouched."""
    import jax

    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    clean = BoringModel()
    # batch idx 2 of epoch 0 is the one that would have become step 3
    _trainer(tmp_path / "a").fit(clean, SkipLoader(_loader(), {(0, 2)}))

    hurt = BoringModel()
    t = _trainer(tmp_path / "b", guard=GuardConfig(warmup_steps=2))
    t.callbacks.append(
        FaultInjector([Fault("nan_loss", None, 3, {}, index=0)]))
    t.fit(hurt, _loader())

    assert t.callback_metrics["guard_skipped_steps"] == 1
    assert t.callback_metrics["guard_last_anomaly"] == 2  # update index
    assert int(jax.device_get(t.state.step)) == 15  # 16 batches, 1 skip
    for a, b in zip(jax.tree.leaves(jax.device_get(clean.params)),
                    jax.tree.leaves(jax.device_get(hurt.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grad_blowup_is_skipped_and_training_recovers(tmp_path):
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    t = _trainer(tmp_path, guard=GuardConfig(warmup_steps=2))
    t.callbacks.append(FaultInjector(
        [Fault("grad_blowup", None, 4, {"scale": "1e18"}, index=0)]))
    t.fit(BoringModel(), _loader())
    assert t.callback_metrics["guard_skipped_steps"] >= 1
    # the skipped update left the params usable: the run kept training
    assert np.isfinite(t.callback_metrics["loss"])
    assert t.callback_metrics["guard_streak"] == 0


def test_guard_disabled_changes_nothing(tmp_path):
    """guard=None trains bitwise-identically to the pre-guard trainer
    (the empty-tuple guard slot contributes no pytree leaves)."""
    import jax

    from tests.utils import BoringModel

    a, b = BoringModel(), BoringModel()
    _trainer(tmp_path / "a").fit(a, _loader())
    t = _trainer(tmp_path / "b", guard=GuardConfig())
    t.fit(b, _loader())
    assert "guard_skipped_steps" in t.callback_metrics
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_guard_step_adds_no_host_syncs(tmp_path):
    """The RLT304 acceptance criterion, pinned two ways: (1) the guarded
    train step's jaxpr carries NO effects (no callbacks, no transfers —
    the anomaly flag rides the metrics outputs the trainer already
    fetches lazily); (2) the trainer + guard source lint clean under the
    host-sync rules."""
    import jax

    from ray_lightning_tpu.analysis import lint_paths
    from tests.utils import BoringModel

    t = _trainer(tmp_path, guard=GuardConfig(), max_steps=1,
                 limit_train_batches=1)
    t.fit(BoringModel(), _loader())
    batch = t._cast(next(iter(_loader())))
    device_batch = t._shard_train_batch(batch)
    jaxpr = jax.make_jaxpr(
        lambda s, b, r: t._train_step._jitted(s, b, r))(
            t.state, device_batch, t._base_rng)
    assert not jaxpr.effects, f"guarded step has effects: {jaxpr.effects}"
    _, metrics = jax.eval_shape(
        lambda s, b, r: t._train_step._jitted(s, b, r),
        t.state, device_batch, t._base_rng)
    for counter in ("guard_anomaly", "guard_skipped_steps",
                    "guard_streak", "guard_last_anomaly"):
        assert counter in metrics  # the flag RIDES the metrics outputs

    import ray_lightning_tpu.core.trainer as trainer_mod
    import ray_lightning_tpu.resilience.guard as guard_mod

    findings = lint_paths([trainer_mod.__file__, guard_mod.__file__])
    host_sync = [f for f in findings if f.rule in ("RLT201", "RLT304")]
    assert not host_sync, [f.format() for f in host_sync]


# ------------------------------------------- tier 2: escalation/rollback


def _sticky_nan_fit(tmp_path, callbacks=None, **guard_kw):
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    guard_kw.setdefault("warmup_steps", 1)
    guard_kw.setdefault("escalate_after", 3)
    guard_kw.setdefault("escalate_window", 8)
    t = _trainer(tmp_path, guard=GuardConfig(**guard_kw),
                 callbacks=list(callbacks or []))
    t.callbacks.append(FaultInjector(
        [Fault("nan_loss", None, 4, {"count": "10"}, index=0)]))
    with pytest.raises(TrainingAnomalyError) as exc_info:
        t.fit(BoringModel(), _loader())
    return t, exc_info.value


def test_escalation_raises_and_writes_marker(tmp_path):
    t, err = _sticky_nan_fit(tmp_path)
    assert err.detected_step == 6          # anomalies at steps 4, 5, 6
    assert err.last_good_step == 3
    marker = read_rollback_marker(str(tmp_path))
    assert marker["kind"] == "anomaly-streak"
    assert marker["last_good_step"] == 3
    assert marker["epoch"] == 0 and marker["epoch_batch"] == 6
    fc = classify_failure(err)
    assert fc.kind == FailureKind.CORRUPTION
    assert fc.cause == "anomaly-streak" and fc.restartable


def test_classify_corruption_from_worker_traceback():
    """The exception NAME travels inside the worker traceback — the
    driver-side classification keys on it (CORRUPTION, never FATAL)."""
    from ray_lightning_tpu.runtime.group import WorkerError

    err = WorkerError(1, "Traceback ...\nray_lightning_tpu.resilience."
                         "guard.TrainingAnomalyError: training anomaly "
                         "escalation: 3 anomalous step(s) ...")
    fc = classify_failure(err)
    assert fc.kind == FailureKind.CORRUPTION
    assert fc.cause == "anomaly-streak" and fc.rank == 1
    sdc = WorkerError(0, "Traceback ...\nray_lightning_tpu.resilience."
                         "guard.SDCDetectedError: silent data corruption "
                         "detected at step 4 ...")
    assert classify_failure(sdc).cause == "sdc"


def test_retry_policy_rollback_budget():
    p = RetryPolicy(max_restarts=3, max_rollbacks=1)
    corruption = classify_failure(TrainingAnomalyError(6, 3, 8, 3))
    assert p.allows(0, 0, corruption, rollbacks=0)
    assert not p.allows(0, 0, corruption, rollbacks=1)  # own budget,
    #                                 independent of max_restarts=3
    retry = classify_failure(TimeoutError("x"))
    assert p.allows(2, 0, retry, rollbacks=1)  # and vice versa


def test_blessed_stamp_and_good_only_selection(tmp_path):
    """Checkpoints saved inside the anomaly window are stamped
    blessed=False; latest_checkpoint(good_only=True, max_step=...)
    skips them AND anything past the rollback horizon."""
    from ray_lightning_tpu.checkpoint import latest_checkpoint
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint

    ck = tmp_path / "ck"
    mc = ModelCheckpoint(dirpath=str(ck), monitor=None,
                         every_n_train_steps=1, save_top_k=-1)
    _sticky_nan_fit(tmp_path, callbacks=[mc])
    metas = {}
    for d in os.listdir(ck):
        with open(ck / d / "meta.json") as f:
            metas[d] = json.load(f)
    assert metas["step=3"]["blessed"] is True
    assert metas["step=4"]["blessed"] is False   # streak active
    assert metas["step=4"]["guard"]["streak"] >= 1
    # newest first is step=6 (unblessed): good_only must land on step=3
    assert latest_checkpoint(str(ck)).endswith("step=6")
    marker = read_rollback_marker(str(tmp_path))
    good = latest_checkpoint(str(ck), good_only=True,
                             max_step=marker["last_good_step"])
    assert good.endswith("step=3")


def test_retention_never_deletes_last_blessed(tmp_path):
    """ISSUE 5 satellite: save_top_k pruning inside a long anomaly
    streak must keep the newest blessed checkpoint even when it falls
    outside the newest-N window."""
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint

    ck = tmp_path / "ck"
    mc = ModelCheckpoint(dirpath=str(ck), monitor=None,
                         every_n_train_steps=1, save_top_k=1)
    _sticky_nan_fit(tmp_path, callbacks=[mc])
    dirs = sorted(d for d in os.listdir(ck) if d.startswith("step="))
    # newest-1 window = the unblessed step=6; the blessed step=3
    # survives as the protected rollback target
    assert "step=3" in dirs, dirs
    assert "step=6" in dirs, dirs
    assert "step=4" not in dirs and "step=5" not in dirs, dirs


def test_sweep_keep_last_n_protects_blessed(tmp_path):
    """The sweep-side retention (TuneReportCheckpointCallback
    keep_last_n) honors the same floor."""
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import save_checkpoint
    from ray_lightning_tpu.sweep.callbacks import (
        TuneReportCheckpointCallback,
    )

    cb = TuneReportCheckpointCallback(keep_last_n=2)
    for step, blessed in ((1, True), (2, True), (3, False), (4, False),
                          (5, False)):
        path = str(tmp_path / f"checkpoint_{step:08d}")
        save_checkpoint(path, {"w": jnp.full((8,), float(step))},
                        {"global_step": step, "blessed": blessed})
        cb._written.append(path)
    cb._prune()
    left = sorted(os.listdir(tmp_path))
    # window = {4, 5} (both unblessed): the newest blessed (2) survives
    assert "checkpoint_00000002" in left, left
    assert "checkpoint_00000004" in left and "checkpoint_00000005" in left
    assert "checkpoint_00000001" not in left and \
        "checkpoint_00000003" not in left


def test_rollback_resume_advances_past_poisoned_window(tmp_path):
    """Tier-2 resume semantics at Trainer level: restore from the
    blessed checkpoint + the rollback marker => the poisoned window's
    batches are SKIPPED, not replayed."""
    from ray_lightning_tpu.checkpoint import latest_checkpoint
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from tests.utils import BoringModel

    ck = tmp_path / "ck"
    mc = ModelCheckpoint(dirpath=str(ck), monitor=None,
                         every_n_train_steps=1, save_top_k=-1)
    _sticky_nan_fit(tmp_path, callbacks=[mc])
    marker = read_rollback_marker(str(tmp_path))
    resume_from = latest_checkpoint(str(ck), good_only=True,
                                    max_step=marker["last_good_step"])
    assert resume_from.endswith("step=3")

    t2 = _trainer(tmp_path / "resume")
    t2.resume_skip_past = marker
    t2.fit(BoringModel(), _loader(), ckpt_path=resume_from)
    # epoch 0 restored at batch 3, window skipped through batch 6:
    # 2 batches left of epoch 0 + 8 of epoch 1 on top of the 3 restored
    assert t2.global_step == 3 + 2 + 8


def test_scratch_rollback_still_advances_past_window(tmp_path):
    """A rollback that found NO blessed checkpoint resumes from scratch
    — the poisoned window must still be skipped, not replayed."""
    from tests.utils import BoringModel

    t = _trainer(tmp_path)
    t.resume_skip_past = {"detected_step": 6, "last_good_step": 3,
                          "epoch": 0, "epoch_batch": 6}
    t.fit(BoringModel(), _loader())  # no ckpt_path: scratch
    # epoch 0 loses its first 6 batches (clean prefix sacrificed with
    # the window — suspect data is never retrained): 2 + 8 steps
    assert t.global_step == 10


def test_escalation_respects_window_at_sparse_cadence(tmp_path):
    """K anomalies spread over a gap LONGER than the window must not
    escalate (the windowed contract), while K consecutive ones must
    (the in-jit streak counter is cadence-independent)."""
    from ray_lightning_tpu.resilience.guard import GuardCallback

    class _T:
        current_epoch = 0
        _epoch_batches_done = 0
        global_step = 0
        default_root_dir = str(tmp_path)

    cb = GuardCallback(GuardConfig(escalate_after=4, escalate_window=16),
                       marker_dir=str(tmp_path))
    t = _T()
    # 4 anomalies spread across a 50-step observation gap: NOT 4-in-16
    cb._note(t, 50, 0.0, streak=0.0)
    cb._note(t, 100, 4.0, streak=1.0)  # no escalation
    # but a 4-step STREAK escalates regardless of the fetch cadence
    with pytest.raises(TrainingAnomalyError):
        cb._note(t, 150, 8.0, streak=4.0)


def test_rollback_quarantines_poisoned_checkpoints(tmp_path):
    """After a rollback, checkpoints newer than the last-good step are
    moved out of the candidate set (quarantined.ckpts/) — a LATER
    retryable restart must never resurrect a poisoned one."""
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint,
    )
    from ray_lightning_tpu.resilience.supervisor import (
        _quarantine_newer_checkpoints,
    )

    for step in (1, 2, 3, 4):
        save_checkpoint(str(tmp_path / f"step={step}"),
                        {"w": jnp.full((8,), float(step))},
                        {"global_step": step, "blessed": True})
    _quarantine_newer_checkpoints(str(tmp_path), 2)
    assert sorted(d for d in os.listdir(tmp_path)
                  if d.startswith("step=")) == ["step=1", "step=2"]
    moved = os.listdir(tmp_path / "quarantined.ckpts")
    assert sorted(m.split(".")[0] for m in moved) == ["step=3", "step=4"]
    # the plain (non-good_only) selection now lands on the clean one
    assert latest_checkpoint(str(tmp_path)).endswith("step=2")


def test_leaf_digest_sees_every_bit_of_wide_dtypes():
    """A flip in the LOW 32 bits of a 64-bit word must change the
    fingerprint (a lossy f32 image would round it away)."""
    import jax.numpy as jnp

    from ray_lightning_tpu.resilience.guard import _leaf_digest

    base = np.array([3, 5, 7], dtype=np.int64)
    flipped = base.copy()
    flipped[1] ^= 1 << 4  # low bits of an int64 word
    a = _leaf_digest(jnp.asarray(base))
    b = _leaf_digest(jnp.asarray(flipped))
    assert int(a) != int(b)
    # bf16 exactness too: one mantissa bit
    h = np.zeros(4, np.uint16)
    h[2] = 0x3C00
    h2 = h.copy()
    h2[2] ^= 1 << 3
    ha = _leaf_digest(jnp.asarray(h).view(jnp.bfloat16))
    hb = _leaf_digest(jnp.asarray(h2).view(jnp.bfloat16))
    assert int(ha) != int(hb)


def test_stale_rollback_marker_is_ignored(tmp_path):
    """A marker whose detection step is behind the restore point must
    no-op (it describes an older incident)."""
    from ray_lightning_tpu.checkpoint import latest_checkpoint
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from tests.utils import BoringModel

    ck = tmp_path / "ck"
    mc = ModelCheckpoint(dirpath=str(ck), monitor=None,
                         every_n_train_steps=1, save_top_k=-1)
    t = _trainer(tmp_path, callbacks=[mc])
    t.fit(BoringModel(), _loader())
    resume_from = latest_checkpoint(str(ck))  # step=16, end of run
    t2 = _trainer(tmp_path / "resume")
    t2.resume_skip_past = {"detected_step": 6, "last_good_step": 3,
                           "epoch": 0, "epoch_batch": 6}
    t2.fit(BoringModel(), _loader(), ckpt_path=resume_from)
    assert t2.global_step == 16  # nothing skipped, nothing replayed


# ------------------------------------------------------ tier 3: the SDC


def test_diagnose_digests_majority_tie_and_singletons():
    # 3:1 majority -> the minority device is the suspect
    assert diagnose_digests([7, 7, 5, 7], [[0, 1, 2, 3]]) == ([2], True)
    # 1:1 tie -> both suspect (attribution indeterminate)
    assert diagnose_digests([7, 5], [[0, 1]]) == ([0, 1], True)
    # agreement -> clean
    assert diagnose_digests([7, 7], [[0, 1]]) == ([], True)
    # no redundancy -> not comparable
    assert diagnose_digests([7, 5], []) == ([], False)


def test_replica_groups_dp_vs_fsdp(devices8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_tpu.parallel.mesh import MeshSpec
    from ray_lightning_tpu.resilience.guard import replica_groups

    mesh = MeshSpec(data=8).build(devices8)
    rep = jax.device_put(jnp.zeros((16, 16)), NamedSharding(mesh, P()))
    groups = replica_groups({"w": rep}, mesh)
    assert len(groups) == 1 and len(groups[0]) == 8  # one replica group

    mesh_f = MeshSpec(fsdp=8).build(devices8)
    sh = jax.device_put(jnp.zeros((16, 16)),
                        NamedSharding(mesh_f, P("fsdp")))
    assert replica_groups({"w": sh}, mesh_f) == []  # no redundancy


def test_bitflip_detected_within_one_probe_cadence(tmp_path):
    """A one-bit mantissa flip on device 3's replica: the fingerprint
    probe catches it at the next cadence and the marker names the
    divergent device's process."""
    from ray_lightning_tpu import DataParallel
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    t = _trainer(tmp_path, strategy=DataParallel(),
                 guard=GuardConfig(sdc_every_n_steps=2))
    t.callbacks.append(FaultInjector(
        [Fault("bitflip_param", None, 3, {"device": "3"}, index=0)]))
    with pytest.raises(SDCDetectedError) as exc_info:
        t.fit(BoringModel(), _loader())
    err = exc_info.value
    assert err.detected_step == 4          # flip lands at step 3
    assert err.suspect_ranks == [0]        # single-process: rank 0
    marker = read_rollback_marker(str(tmp_path))
    assert marker["kind"] == "sdc" and marker["quarantine"] == [0]
    assert marker["last_good_step"] == 2   # the step-2 probe passed
    digests = marker["digests"]
    assert len(digests) == 8
    # exactly one device disagrees — and it is the one we flipped
    counts = {d: digests.count(d) for d in set(digests)}
    minority = [i for i, d in enumerate(digests) if counts[d] == 1]
    assert minority == [3]
    assert classify_failure(err).kind == FailureKind.CORRUPTION
    assert t.callback_metrics["guard_sdc_probes"] >= 1


def test_bitflip_is_invisible_to_tier1(tmp_path):
    """The whole point of tier 3: a bit-flip corrupts a replica without
    ever producing a NaN or a spike — with the probe disabled the run
    finishes 'successfully' with zero skipped steps."""
    from ray_lightning_tpu import DataParallel
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    t = _trainer(tmp_path, strategy=DataParallel(),
                 guard=GuardConfig(sdc_every_n_steps=0))
    t.callbacks.append(FaultInjector(
        [Fault("bitflip_param", None, 3, {"device": "3"}, index=0)]))
    t.fit(BoringModel(), _loader())
    assert t.callback_metrics["guard_skipped_steps"] == 0


# ------------------------------------------------ faults grammar + bench


def test_parse_new_fault_kinds():
    from ray_lightning_tpu.resilience.faults import parse_faults

    faults = parse_faults(
        "nan_loss:rank=0,step=3,count=5; grad_blowup:rank=*,step=2;"
        "bitflip_param:rank=1,step=4,bit=7,device=1,element=3")
    assert [f.kind for f in faults] == ["nan_loss", "grad_blowup",
                                       "bitflip_param"]
    assert faults[0].args["count"] == "5"
    assert faults[2].args == {"bit": "7", "device": "1", "element": "3"}


def test_nan_loss_fires_once_across_restarts(tmp_path):
    """The once-per-rank marker spans restarts: a resumed run sails past
    the step whose batch poisoned its predecessor."""
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import BoringModel

    state = str(tmp_path / "fault_state")
    t = _trainer(tmp_path / "a", guard=GuardConfig(warmup_steps=2))
    t.callbacks.append(FaultInjector(
        [Fault("nan_loss", None, 3, {}, index=0)], state))
    t.fit(BoringModel(), _loader())
    assert t.callback_metrics["guard_skipped_steps"] == 1
    t2 = _trainer(tmp_path / "b", guard=GuardConfig(warmup_steps=2))
    t2.callbacks.append(FaultInjector(
        [Fault("nan_loss", None, 3, {}, index=0)], state))
    t2.fit(BoringModel(), _loader())
    assert t2.callback_metrics["guard_skipped_steps"] == 0  # marker held


def test_bench_guard_summary_is_backend_free():
    """Every bench JSON line carries the guard counters, even with the
    backend down: the summary is a pure jaxpr-level audit."""
    import bench

    g = bench._guard_summary()
    assert "guard" in g, g
    guard = g["guard"]
    assert guard["effects"] == 0 and guard["extra_host_transfers"] == 0
    assert {"guard_anomaly", "guard_skipped_steps",
            "guard_streak", "guard_last_anomaly"} <= set(guard["counters"])
    for counter in ("skipped_steps", "rollbacks", "sdc_probes",
                    "last_anomaly"):
        assert counter in guard


# ----------------------------------------- supervised SPMD runs (slow)


def _sup_module():
    from tests.utils import IdSumModel

    return IdSumModel(lr=1e-2)


def _sup_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(strategy=DataParallel(), max_epochs=2,
                   enable_progress_bar=False, enable_checkpointing=False,
                   seed=0, log_every_n_steps=1)


def _sup_data():
    import jax

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    x = np.zeros((64, 8), np.float32)
    x[:, 0] = np.arange(64)
    y = rng.integers(0, 2, 64).astype(np.int32)
    return DataLoader({"x": x, "y": y}, batch_size=8,
                      num_shards=jax.process_count(),
                      shard_index=jax.process_index())


def _guard_resilience(tmp_path, name, guard, faults):
    from ray_lightning_tpu import ResilienceConfig

    return ResilienceConfig(
        checkpoint_dir=str(tmp_path / name),
        policy=RetryPolicy(max_restarts=2, backoff_base_s=0.2,
                           jitter=0.0),
        save_every_n_steps=1,
        heartbeat_interval_s=1.0,
        stall_timeout_s=0.0,
        guard=guard,
        faults=faults,
    )


def _run_supervised(tmp_path, name, guard, faults, devices=1):
    from ray_lightning_tpu import fit_supervised

    return fit_supervised(
        _sup_module, _sup_trainer, _sup_data, 2,
        resilience=_guard_resilience(tmp_path, name, guard, faults),
        log_dir=str(tmp_path / f"logs_{name}"), platform="cpu",
        num_cpu_devices_per_process=devices, timeout=420,
        return_weights=False)


@pytest.mark.slow
def test_supervise_nan_skip_no_restart(tmp_path):
    """Tier 1 under real 2-proc SPMD: the poisoned batch is skipped
    inside the compiled step — the processes never die, the supervisor
    never restarts, and the run converges."""
    sup = _run_supervised(tmp_path, "nan",
                          GuardConfig(warmup_steps=2),
                          "nan_loss:rank=0,step=3")
    assert sup.total_attempts == 1 and sup.rollbacks == 0
    assert sup.result.metrics["guard_skipped_steps"] >= 1
    assert np.isfinite(sup.result.metrics["loss"])


@pytest.mark.slow
def test_supervise_corruption_rollback_from_blessed(tmp_path):
    """Tier 2 end to end: a sustained NaN streak escalates, the
    supervisor rolls back to the blessed checkpoint at/below the
    marker's last-good step, the data order advances past the poisoned
    window, and the resumed run completes."""
    sup = _run_supervised(tmp_path, "streak",
                          GuardConfig(warmup_steps=1, escalate_after=3,
                                      escalate_window=8),
                          "nan_loss:rank=0,step=4,count=6")
    assert sup.rollbacks == 1 and sup.restarts == 0
    [failure] = sup.failures
    assert failure["kind"] == "corruption"
    assert failure["cause"] == "anomaly-streak"
    marker = read_rollback_marker(str(tmp_path / "streak"))
    assert marker["last_good_step"] == 3
    assert marker["rollbacks_performed"] == 1
    # the blessed rollback target survived retention and still exists
    assert os.path.isdir(tmp_path / "streak" / "step=3")
    assert sup.result.metrics["guard_rollbacks"] == 1.0
    assert np.isfinite(sup.result.metrics["loss"])


@pytest.mark.slow
def test_supervise_bitflip_quarantines_rank1(tmp_path):
    """Tier 3 end to end (2 proc x 2 devices = 4 replicas): the flip on
    rank 1's device is outvoted 3:1 within one probe cadence, rank 1 is
    quarantined in the ledger AND on disk, and the run resumes from a
    probe-verified checkpoint."""
    from ray_lightning_tpu.resilience.guard import QUARANTINE_FILE

    sup = _run_supervised(tmp_path, "sdc",
                          GuardConfig(sdc_every_n_steps=2),
                          "bitflip_param:rank=1,step=3,device=0",
                          devices=2)
    assert sup.rollbacks == 1 and sup.quarantined == [1]
    [failure] = sup.failures
    assert failure["kind"] == "corruption" and failure["cause"] == "sdc"
    with open(tmp_path / "sdc" / QUARANTINE_FILE) as f:
        assert json.load(f)["excluded"] == [1]
    marker = read_rollback_marker(str(tmp_path / "sdc"))
    assert marker["kind"] == "sdc" and marker["quarantine"] == [1]
    assert np.isfinite(sup.result.metrics["loss"])
