"""Runtime substrate tests: worker launch, env injection, side channel,
failure propagation, and TRUE multi-process SPMD over gloo.

Reference test analog: tests/test_ddp.py:29-41 (actor lifecycle/teardown)
plus the process_results behavior implicit in every fit test. The
multi-process SPMD test is the rebuild's version of "real distributed
training on a laptop" (reference fixtures ray.init(num_cpus=2),
tests/test_ddp.py:16-21).
"""
import os

import pytest

from ray_lightning_tpu.runtime import (
    LoopbackTransport,
    WorkerError,
    WorkerGroup,
    launch,
    launch_cpu_spmd,
)


# --- helpers shipped to workers (module-level so cloudpickle sends them
# by reference; the worker imports this module) -------------------------


def _rank_and_world():
    from ray_lightning_tpu.runtime import session

    return session.get_actor_rank(), session.get_world_size()


def _read_env(name):
    return os.environ.get(name)


def _enqueue_items():
    from ray_lightning_tpu.runtime import session

    session.put_queue({"metric": 0.5, "rank": session.get_actor_rank()})
    return "done"


def _boom():
    raise RuntimeError("kaboom from worker")


def _pid():
    return os.getpid()


def _spmd_global_sum(scale):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    local = np.ones((4,), np.float32) * (jax.process_index() + 1) * scale
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local
    )
    s = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    return (
        jax.process_index(),
        jax.device_count(),
        float(jax.device_get(s.addressable_shards[0].data)),
    )


# ---------------------------------------------------------------- tests


def test_group_run_and_session(tmp_path):
    with WorkerGroup(2, log_dir=str(tmp_path)) as g:
        results = g.run(_rank_and_world)
    assert results == [(0, 2), (1, 2)]


def test_env_injection_and_node_ip(tmp_path):
    # reference ray_ddp.py:27-35: set_env_vars + get_node_ip on the actor.
    with WorkerGroup(2, env={"RLT_TEST_A": "1"}, log_dir=str(tmp_path)) as g:
        assert g.run(_read_env, per_rank_args=[("RLT_TEST_A",)] * 2) == ["1", "1"]
        g.set_env_vars({"RLT_TEST_B": "2"})
        assert g.run(_read_env, per_rank_args=[("RLT_TEST_B",)] * 2) == ["2", "2"]
        assert all(isinstance(ex.get_node_ip(), str) for ex in g.executors)


def test_init_hook_runs_on_every_worker(tmp_path):
    # reference ray_ddp.py:66-67,118-119: per-worker init_hook before train.
    def hook():
        os.environ["RLT_HOOKED"] = "yes"

    with WorkerGroup(2, init_hook=hook, log_dir=str(tmp_path)) as g:
        assert g.run(_read_env, per_rank_args=[("RLT_HOOKED",)] * 2) == [
            "yes",
            "yes",
        ]


def test_queue_trampoline_executes_callables_driver_side(tmp_path):
    # reference util.py:88-93: callable queue items run in the driver.
    sentinel = []

    def _remote():
        from ray_lightning_tpu.runtime import session

        session.put_queue(lambda: sentinel.append("ran-in-driver"))
        return "ok"

    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        assert g.run(_remote) == ["ok"]
    # The lambda was created worker-side, shipped back, and executed here.
    # (Closure state can't flow back into OUR list via pickle — cloudpickle
    # captures `sentinel` by value. Use the non-callable path to assert
    # driver-side collection instead.)
    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        g.run(_enqueue_items)
        items = g.queue_items()
    assert items == [(0, {"metric": 0.5, "rank": 0})]


def test_worker_error_fails_fast(tmp_path):
    # reference §5.3 failure model: first worker exception propagates.
    with WorkerGroup(2, log_dir=str(tmp_path)) as g:
        with pytest.raises(WorkerError, match="kaboom"):
            g.run(_boom)


def test_shutdown_kills_processes(tmp_path):
    # reference tests/test_ddp.py:29-41: all actors DEAD after teardown.
    g = WorkerGroup(2, log_dir=str(tmp_path)).start()
    pids = g.run(_pid)
    procs = [ex.proc for ex in g.executors]
    g.shutdown()
    assert len(set(pids)) == 2
    assert all(p.poll() is not None for p in procs)


def test_remote_transport_two_hosts(tmp_path):
    """Cross-host placement through the remote-transport code path
    (reference ray_ddp.py:106-164: actor-per-node placement + env
    propagation + IP-based rank map). LoopbackTransport runs the FULL
    remote protocol — stdin bootstrap, scrubbed env (driver env does NOT
    leak), routable listener — with the ssh prefix removed."""
    transport = LoopbackTransport()
    os.environ["RLT_DRIVER_ONLY"] = "should-not-leak"
    try:
        group = WorkerGroup(
            hosts=["host-a", "host-b"],
            transport=transport,
            env={"RLT_EXPLICIT": "42", "JAX_PLATFORMS": "cpu"},
            log_dir=str(tmp_path),
        )
        with group as g:
            assert g.is_remote
            # per-host rank resolution from hellos, as on a real pod
            assert g.run(_rank_and_world) == [(0, 2), (1, 2)]
            assert [ex.host for ex in g.executors] == ["host-a", "host-b"]
            # env propagation is EXPLICIT (travels in the bootstrap), not
            # inherited — remote semantics on one machine
            assert g.run(_read_env, per_rank_args=[("RLT_EXPLICIT",)] * 2) \
                == ["42", "42"]
            assert g.run(
                _read_env, per_rank_args=[("RLT_DRIVER_ONLY",)] * 2
            ) == [None, None]
            # targeted single-rank execution (the MASTER_PORT-probe path)
            assert g.run_single(1, _rank_and_world) == (1, 2)
    finally:
        os.environ.pop("RLT_DRIVER_ONLY", None)
    assert transport.spawned == [("host-a", 0), ("host-b", 1)]


def test_per_host_env_overrides(tmp_path):
    """SSH-family transports apply host_env on top of the launch env —
    the multi-NIC escape hatch: RLT_NODE_IP pinned per host changes the
    address that host's worker advertises in its hello (which worker 0
    feeds to the jax coordinator resolution)."""
    transport = LoopbackTransport(host_env={
        "host-b": {"RLT_TEST_HOSTENV": "b-only",
                   "RLT_NODE_IP": "10.99.0.2"},
    })
    with WorkerGroup(
        hosts=["host-a", "host-b"],
        transport=transport,
        env={"RLT_TEST_HOSTENV": "default", "JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
    ) as g:
        assert g.run(_read_env, per_rank_args=[("RLT_TEST_HOSTENV",)] * 2) \
            == ["default", "b-only"]
        assert g.executors[1].get_node_ip() == "10.99.0.2"
        assert g.executors[0].get_node_ip() != "10.99.0.2"


def test_unmatched_host_env_key_warns(tmp_path, monkeypatch):
    """A typo'd host_env key must be surfaced — silently dropping an
    RLT_NODE_IP override reproduces the multi-NIC hang it exists to fix.
    (Asserted on the logger call: the package logger owns its handler
    and does not propagate to root, so caplog cannot see it.)"""
    from ray_lightning_tpu.runtime import group as group_mod

    warnings = []
    monkeypatch.setattr(
        group_mod.log, "warning",
        lambda msg, *args, **kw: warnings.append(msg % args if args else msg),
    )
    transport = LoopbackTransport(host_env={
        "user@host-b": {"RLT_NODE_IP": "10.99.0.2"},  # hosts= says "host-b"
    })
    with WorkerGroup(hosts=["host-a", "host-b"], transport=transport,
                     env={"JAX_PLATFORMS": "cpu"},
                     log_dir=str(tmp_path)) as g:
        g.run(_pid)
    assert any("user@host-b" in w for w in warnings)


def test_ssh_transport_command_and_bootstrap():
    """SSHTransport mechanics without an ssh binary: the argv it would
    exec, and the self-contained bootstrap program piped over stdin."""
    from ray_lightning_tpu.runtime import SSHTransport
    from ray_lightning_tpu.runtime.transport import _bootstrap_source

    t = SSHTransport(ssh=("ssh", "-p", "2222"), remote_python="python3.11",
                     pythonpath=("/opt/rlt",), env={"A": "1"})
    assert t._command("10.0.0.7") == [
        "ssh", "-p", "2222", "10.0.0.7", "--", "python3.11", "-u", "-",
    ]
    with pytest.raises(ValueError, match="host"):
        t._command(None)

    src = _bootstrap_source(("192.168.1.1", 5555, 3, 8),
                            {"A": "1", "B": "x y"}, "deadbeef",
                            ["/opt/rlt"])
    compile(src, "<bootstrap>", "exec")  # must be a valid program
    # env + authkey travel INSIDE the program (never on a command line)
    assert "'RLT_WORKER_AUTHKEY': 'deadbeef'" in src
    assert "'B': 'x y'" in src
    assert "'/opt/rlt'" in src
    # argv wiring for the embedded worker loop
    assert "'192.168.1.1', '5555', '3', '8'" in src
    # the worker source itself rides along, entrypoint guard included
    assert "def main(argv)" in src
    assert '__name__ == "__main__"' in src


def test_remote_transport_failure_propagates(tmp_path):
    with WorkerGroup(
        hosts=["host-a", "host-b"],
        transport=LoopbackTransport(),
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
    ) as g:
        with pytest.raises(WorkerError, match="kaboom"):
            g.run(_boom)


@pytest.mark.slow
def test_spmd_over_remote_transport(tmp_path):
    """The flagship protocol driven through the cross-host path: 2 'hosts'
    x 2 CPU devices joined into ONE global mesh, with the jax coordinator
    resolved on worker 0 (routable IP + remotely-probed port — the
    reference's MASTER_ADDR/PORT dance, ray_ddp.py:152-156)."""
    out = launch(
        _spmd_global_sum,
        2,
        args=(1,),
        platform="cpu",
        num_cpu_devices_per_process=2,
        hosts=["host-a", "host-b"],
        transport=LoopbackTransport(),
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
        timeout=240,
    )
    assert sorted(r for r, _, _ in out) == [0, 1]
    assert all(n == 4 for _, n, _ in out)
    assert all(s == 12.0 for _, _, s in out)


def _payload_len(payload):
    return len(payload)


def test_ship_once_serializes_job_once(tmp_path, monkeypatch):
    """The reference `ray.put(model)` analog (ray_ddp.py:168-171): the fat
    (fn, shared_args) blob is cloudpickled ONCE per run regardless of
    worker count — not once per rank — and workers cache it by digest, so
    a repeat run with the same payload ships no blob at all."""
    from ray_lightning_tpu.runtime import group as group_mod

    big_dumps = []
    real_dumps = group_mod.cloudpickle.dumps

    def counting_dumps(obj, *a, **kw):
        blob = real_dumps(obj, *a, **kw)
        if len(blob) > 100_000:
            big_dumps.append(len(blob))
        return blob

    monkeypatch.setattr(group_mod.cloudpickle, "dumps", counting_dumps)
    payload = b"\x7f" * 1_000_000  # the "model": fat, shared by all ranks
    with WorkerGroup(4, log_dir=str(tmp_path)) as g:
        assert g.run(_payload_len, shared_args=(payload,)) == [1_000_000] * 4
        # ONE fat serialization for 4 workers
        assert len(big_dumps) == 1
        # repeat run: serialized again (for the digest) but NOT resent —
        # every executor already holds the digest, and the workers answer
        # from their cache (an out-of-sync cache would raise)
        assert g.run(_payload_len, shared_args=(payload,)) == [1_000_000] * 4
        assert all(len(ex._sent_digests) == 1 for ex in g.executors)
    assert len(big_dumps) == 2


def test_ship_once_survives_worker_cache_eviction(tmp_path):
    """The worker's blob cache is a small FIFO; the driver mirrors its
    eviction, so re-running a payload evicted worker-side must RESEND
    the blob (not reply from a stale 'already sent' record and crash)."""
    from ray_lightning_tpu.runtime.worker import _BLOB_CACHE_CAP

    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        payloads = [bytes([i]) * 32 for i in range(_BLOB_CACHE_CAP + 1)]
        for p in payloads:  # fills the cache past its cap
            assert g.run(_payload_len, shared_args=(p,)) == [32]
        # payloads[0] was evicted on both sides; this must resend + rerun
        assert g.run(_payload_len, shared_args=(payloads[0],)) == [32]
        assert len(g.executors[0]._sent_digests) == _BLOB_CACHE_CAP


def test_ship_once_need_blob_self_heals(tmp_path):
    """A desynced digest mirror (driver believes the worker caches a blob
    it does not have) must self-heal through the need_blob resend path,
    not fail the task."""
    import hashlib

    from ray_lightning_tpu.runtime import group as group_mod

    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        payload = b"q" * 1000
        blob = group_mod.cloudpickle.dumps((_payload_len, (payload,), {}))
        digest = hashlib.sha256(blob).hexdigest()
        # poison the mirror: driver now thinks the worker has this blob
        assert g.executors[0]._note_digest(digest)
        assert g.run(_payload_len, shared_args=(payload,)) == [1000]


def test_dead_worker_fails_start_fast(tmp_path):
    """A worker that dies before its hello (bad host, bootstrap crash)
    must fail start() in seconds with its log tail — not burn the whole
    start_timeout (the fast-fail the threaded ssh stdin feed must not
    lose)."""
    import time as _time

    from ray_lightning_tpu.runtime.transport import LocalTransport

    class _CrashingTransport(LocalTransport):
        def spawn(self, *, host, connect, env, authkey_hex, log_path):
            import subprocess
            import sys

            with open(log_path, "w") as f:
                return subprocess.Popen(
                    [sys.executable, "-c",
                     "import sys; print('boom on purpose'); sys.exit(3)"],
                    stdout=f, stderr=subprocess.STDOUT,
                )

    g = WorkerGroup(1, transport=_CrashingTransport(),
                    log_dir=str(tmp_path), start_timeout=60.0)
    t0 = _time.monotonic()
    with pytest.raises(WorkerError, match="before connecting"):
        g.start()
    assert _time.monotonic() - t0 < 15  # seconds, not start_timeout


def test_worker_dead_after_auth_aborts_start(tmp_path):
    """A worker that completes the authkey handshake but dies before its
    hello must abort start() — and kill the OTHER spawned workers — not
    leak them while the driver raises (full-round review finding)."""
    import subprocess
    import sys
    import time as _time

    from ray_lightning_tpu.runtime.transport import LocalTransport

    class _DiesAfterAuth(LocalTransport):
        def __init__(self):
            self.procs = []

        def spawn(self, *, host, connect, env, authkey_hex, log_path):
            driver_host, port, rank, world = connect
            if rank == 0:
                proc = super().spawn(host=host, connect=connect, env=env,
                                     authkey_hex=authkey_hex,
                                     log_path=log_path)
            else:
                # rank 1: authenticate, send nothing, exit
                code = (
                    "import sys\n"
                    "from multiprocessing.connection import Client\n"
                    f"Client(({driver_host!r}, {port}), "
                    f"authkey=bytes.fromhex({authkey_hex!r}))\n"
                    "sys.exit(0)\n"
                )
                with open(log_path, "w") as f:
                    proc = subprocess.Popen([sys.executable, "-c", code],
                                            stdout=f,
                                            stderr=subprocess.STDOUT)
            self.procs.append(proc)
            return proc

    transport = _DiesAfterAuth()
    g = WorkerGroup(2, transport=transport, log_dir=str(tmp_path),
                    start_timeout=60.0)
    t0 = _time.monotonic()
    # match pins the authenticated-then-died EOF branch (the sibling
    # test covers the died-before-connecting branch)
    with pytest.raises(WorkerError, match="authenticating"):
        g.start()
    assert _time.monotonic() - t0 < 30  # aborted, not start_timeout'd
    # nothing leaked: the abort killed rank 0's healthy worker too
    deadline = _time.monotonic() + 10
    while (any(p.poll() is None for p in transport.procs)
           and _time.monotonic() < deadline):
        _time.sleep(0.1)
    assert all(p.poll() is not None for p in transport.procs)


def test_node_ip_env_override(monkeypatch):
    """RLT_NODE_IP pins the advertised interface on multi-homed hosts."""
    from ray_lightning_tpu.runtime.group import routable_ip

    monkeypatch.setenv("RLT_NODE_IP", "10.9.8.7")
    assert routable_ip() == "10.9.8.7"


def test_remote_without_routable_address_fails_fast(tmp_path, monkeypatch):
    """A remote transport on a box whose routable_ip() degrades to
    loopback must fail in seconds naming the fix (advertise_host /
    RLT_NODE_IP) — not tell remote workers to dial 127.0.0.1 and hang
    into start_timeout (VERDICT r3 weak #4)."""
    from ray_lightning_tpu.runtime import group as group_mod
    from ray_lightning_tpu.runtime.transport import Transport

    class _DeadRemote(Transport):
        is_remote = True

        def spawn(self, **kw):  # pragma: no cover — must never be reached
            raise AssertionError("spawn before address validation")

    monkeypatch.setattr(group_mod, "routable_ip", lambda: "127.0.0.1")
    g = WorkerGroup(2, hosts=["host-a", "host-b"], transport=_DeadRemote(),
                    log_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="advertise_host"):
        g.start()
    # and the named overrides unblock it (listener binds, spawn is reached)
    g2 = WorkerGroup(2, hosts=["host-a", "host-b"], transport=_DeadRemote(),
                     advertise_host="127.0.0.1", log_dir=str(tmp_path))
    with pytest.raises(AssertionError, match="spawn"):
        g2.start()


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("RLT_SSH_TEST") != "1",
                    reason="real-sshd integration (reference CLUSTER=1 "
                           "gate, tests/test_ddp_gpu.py:102-113); set "
                           "RLT_SSH_TEST=1 with a localhost sshd + keys")
def test_real_ssh_fit_distributed(tmp_path):
    """The actual ssh stdin-bootstrap path end-to-end: a 2-process SPMD
    fit over SSHTransport to localhost. Everything LoopbackTransport
    can't prove — the real ssh argv, BatchMode auth, remote login-shell
    env — runs here."""
    import sys

    from ray_lightning_tpu.runtime import SSHTransport, fit_distributed
    from tests.test_fit_distributed import (
        _make_data,
        _make_module,
        _make_trainer,
    )

    transport = SSHTransport(
        ssh=("ssh", "-o", "BatchMode=yes",
             "-o", "StrictHostKeyChecking=accept-new"),
        remote_python=sys.executable,
        pythonpath=(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),),
    )
    result = fit_distributed(
        _make_module,
        _make_trainer,
        _make_data,
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        hosts=["127.0.0.1", "127.0.0.1"],
        transport=transport,
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
        timeout=420,
    )
    assert result.metrics["ptl/val_accuracy"] > 0.9


@pytest.mark.slow
def test_multiprocess_spmd_gloo(tmp_path):
    """2 processes x 2 CPU devices = one 4-device global mesh; a sharded
    sum must see ALL shards (1+1+1+1 from rank0's scale + 2+2+2+2 ... no —
    each process contributes 4 local elements of value rank+1, so the
    global sum is 4*1 + 4*2 = 12)."""
    out = launch_cpu_spmd(
        _spmd_global_sum,
        num_processes=2,
        devices_per_process=2,
        args=(1,),
        log_dir=str(tmp_path),
        timeout=240,
    )
    ranks = sorted(r for r, _, _ in out)
    assert ranks == [0, 1]
    assert all(n == 4 for _, n, _ in out)
    assert all(s == 12.0 for _, _, s in out)


def test_invalid_rank_hello_aborts_start(tmp_path):
    """A hello carrying an out-of-range rank must abort start() with a
    WorkerError (killing every spawned worker), not KeyError into
    procs[rank] and leak the group; a duplicate rank must fail at the
    second hello, not burn the whole start_timeout (ADVICE r4)."""
    import subprocess
    import sys
    import time as _time

    from ray_lightning_tpu.runtime.transport import LocalTransport

    def _lying_transport(lie_rank):
        class _Lying(LocalTransport):
            def __init__(self):
                self.procs = []

            def spawn(self, *, host, connect, env, authkey_hex, log_path):
                driver_host, port, rank, world = connect
                if rank == 0:
                    proc = super().spawn(host=host, connect=connect,
                                         env=env, authkey_hex=authkey_hex,
                                         log_path=log_path)
                else:
                    # authenticate, claim a rank that isn't ours, park
                    code = (
                        "import time\n"
                        "from multiprocessing.connection import Client\n"
                        f"c = Client(({driver_host!r}, {port}), "
                        f"authkey=bytes.fromhex({authkey_hex!r}))\n"
                        f"c.send(('hello', {lie_rank}, {{}}))\n"
                        "time.sleep(60)\n"
                    )
                    with open(log_path, "w") as f:
                        proc = subprocess.Popen(
                            [sys.executable, "-c", code],
                            stdout=f, stderr=subprocess.STDOUT)
                self.procs.append(proc)
                return proc
        return _Lying()

    for lie_rank, pattern in ((99, "invalid rank"),
                              (0, "duplicate hello|invalid rank")):
        transport = _lying_transport(lie_rank)
        g = WorkerGroup(2, transport=transport, log_dir=str(tmp_path),
                        start_timeout=60.0)
        t0 = _time.monotonic()
        with pytest.raises(WorkerError, match=pattern):
            g.start()
        assert _time.monotonic() - t0 < 30
        deadline = _time.monotonic() + 10
        while (any(p.poll() is None for p in transport.procs)
               and _time.monotonic() < deadline):
            _time.sleep(0.1)
        assert all(p.poll() is not None for p in transport.procs)


def test_public_accept_fallback(tmp_path, monkeypatch):
    """When the stdlib internals the split accept/auth path needs are
    missing (a future CPython moving Listener._listener or the challenge
    pair), startup must degrade to the public blocking accept() and still
    bring up a working group — not break every driver start (VERDICT r4
    weak #4)."""
    from ray_lightning_tpu.runtime import group as group_mod

    monkeypatch.setattr(group_mod, "_split_accept_supported",
                        lambda listener: False)
    with WorkerGroup(2, log_dir=str(tmp_path)) as g:
        assert g.run(_rank_and_world) == [(0, 2), (1, 2)]


def test_hello_acceptor_post_close_enqueue_closes_conn():
    """A connection that authenticates concurrently with close() must be
    closed, not stranded on the queue (the worker would park in recv()
    forever) — the enqueue/close race is serialized by a lock
    (ADVICE r4)."""
    from multiprocessing.connection import Listener

    from ray_lightning_tpu.runtime.group import _HelloAcceptor

    listener = Listener(("127.0.0.1", 0), authkey=b"k")
    acceptor = _HelloAcceptor(listener, b"k")
    try:
        acceptor.close()

        closed = []

        class _Conn:
            def close(self):
                closed.append(True)

        acceptor._enqueue(_Conn())
        assert closed == [True]
        assert acceptor.get(0.0) is None
    finally:
        listener.close()
