"""Runtime substrate tests: worker launch, env injection, side channel,
failure propagation, and TRUE multi-process SPMD over gloo.

Reference test analog: tests/test_ddp.py:29-41 (actor lifecycle/teardown)
plus the process_results behavior implicit in every fit test. The
multi-process SPMD test is the rebuild's version of "real distributed
training on a laptop" (reference fixtures ray.init(num_cpus=2),
tests/test_ddp.py:16-21).
"""
import os

import pytest

from ray_lightning_tpu.runtime import (
    LoopbackTransport,
    WorkerError,
    WorkerGroup,
    launch,
    launch_cpu_spmd,
)


# --- helpers shipped to workers (module-level so cloudpickle sends them
# by reference; the worker imports this module) -------------------------


def _rank_and_world():
    from ray_lightning_tpu.runtime import session

    return session.get_actor_rank(), session.get_world_size()


def _read_env(name):
    return os.environ.get(name)


def _enqueue_items():
    from ray_lightning_tpu.runtime import session

    session.put_queue({"metric": 0.5, "rank": session.get_actor_rank()})
    return "done"


def _boom():
    raise RuntimeError("kaboom from worker")


def _pid():
    return os.getpid()


def _spmd_global_sum(scale):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    local = np.ones((4,), np.float32) * (jax.process_index() + 1) * scale
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local
    )
    s = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    return (
        jax.process_index(),
        jax.device_count(),
        float(jax.device_get(s.addressable_shards[0].data)),
    )


# ---------------------------------------------------------------- tests


def test_group_run_and_session(tmp_path):
    with WorkerGroup(2, log_dir=str(tmp_path)) as g:
        results = g.run(_rank_and_world)
    assert results == [(0, 2), (1, 2)]


def test_env_injection_and_node_ip(tmp_path):
    # reference ray_ddp.py:27-35: set_env_vars + get_node_ip on the actor.
    with WorkerGroup(2, env={"RLT_TEST_A": "1"}, log_dir=str(tmp_path)) as g:
        assert g.run(_read_env, per_rank_args=[("RLT_TEST_A",)] * 2) == ["1", "1"]
        g.set_env_vars({"RLT_TEST_B": "2"})
        assert g.run(_read_env, per_rank_args=[("RLT_TEST_B",)] * 2) == ["2", "2"]
        assert all(isinstance(ex.get_node_ip(), str) for ex in g.executors)


def test_init_hook_runs_on_every_worker(tmp_path):
    # reference ray_ddp.py:66-67,118-119: per-worker init_hook before train.
    def hook():
        os.environ["RLT_HOOKED"] = "yes"

    with WorkerGroup(2, init_hook=hook, log_dir=str(tmp_path)) as g:
        assert g.run(_read_env, per_rank_args=[("RLT_HOOKED",)] * 2) == [
            "yes",
            "yes",
        ]


def test_queue_trampoline_executes_callables_driver_side(tmp_path):
    # reference util.py:88-93: callable queue items run in the driver.
    sentinel = []

    def _remote():
        from ray_lightning_tpu.runtime import session

        session.put_queue(lambda: sentinel.append("ran-in-driver"))
        return "ok"

    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        assert g.run(_remote) == ["ok"]
    # The lambda was created worker-side, shipped back, and executed here.
    # (Closure state can't flow back into OUR list via pickle — cloudpickle
    # captures `sentinel` by value. Use the non-callable path to assert
    # driver-side collection instead.)
    with WorkerGroup(1, log_dir=str(tmp_path)) as g:
        g.run(_enqueue_items)
        items = g.queue_items()
    assert items == [(0, {"metric": 0.5, "rank": 0})]


def test_worker_error_fails_fast(tmp_path):
    # reference §5.3 failure model: first worker exception propagates.
    with WorkerGroup(2, log_dir=str(tmp_path)) as g:
        with pytest.raises(WorkerError, match="kaboom"):
            g.run(_boom)


def test_shutdown_kills_processes(tmp_path):
    # reference tests/test_ddp.py:29-41: all actors DEAD after teardown.
    g = WorkerGroup(2, log_dir=str(tmp_path)).start()
    pids = g.run(_pid)
    procs = [ex.proc for ex in g.executors]
    g.shutdown()
    assert len(set(pids)) == 2
    assert all(p.poll() is not None for p in procs)


def test_remote_transport_two_hosts(tmp_path):
    """Cross-host placement through the remote-transport code path
    (reference ray_ddp.py:106-164: actor-per-node placement + env
    propagation + IP-based rank map). LoopbackTransport runs the FULL
    remote protocol — stdin bootstrap, scrubbed env (driver env does NOT
    leak), routable listener — with the ssh prefix removed."""
    transport = LoopbackTransport()
    os.environ["RLT_DRIVER_ONLY"] = "should-not-leak"
    try:
        group = WorkerGroup(
            hosts=["host-a", "host-b"],
            transport=transport,
            env={"RLT_EXPLICIT": "42", "JAX_PLATFORMS": "cpu"},
            log_dir=str(tmp_path),
        )
        with group as g:
            assert g.is_remote
            # per-host rank resolution from hellos, as on a real pod
            assert g.run(_rank_and_world) == [(0, 2), (1, 2)]
            assert [ex.host for ex in g.executors] == ["host-a", "host-b"]
            # env propagation is EXPLICIT (travels in the bootstrap), not
            # inherited — remote semantics on one machine
            assert g.run(_read_env, per_rank_args=[("RLT_EXPLICIT",)] * 2) \
                == ["42", "42"]
            assert g.run(
                _read_env, per_rank_args=[("RLT_DRIVER_ONLY",)] * 2
            ) == [None, None]
            # targeted single-rank execution (the MASTER_PORT-probe path)
            assert g.run_single(1, _rank_and_world) == (1, 2)
    finally:
        os.environ.pop("RLT_DRIVER_ONLY", None)
    assert transport.spawned == [("host-a", 0), ("host-b", 1)]


def test_ssh_transport_command_and_bootstrap():
    """SSHTransport mechanics without an ssh binary: the argv it would
    exec, and the self-contained bootstrap program piped over stdin."""
    from ray_lightning_tpu.runtime import SSHTransport
    from ray_lightning_tpu.runtime.transport import _bootstrap_source

    t = SSHTransport(ssh=("ssh", "-p", "2222"), remote_python="python3.11",
                     pythonpath=("/opt/rlt",), env={"A": "1"})
    assert t._command("10.0.0.7") == [
        "ssh", "-p", "2222", "10.0.0.7", "--", "python3.11", "-u", "-",
    ]
    with pytest.raises(ValueError, match="host"):
        t._command(None)

    src = _bootstrap_source(("192.168.1.1", 5555, 3, 8),
                            {"A": "1", "B": "x y"}, "deadbeef",
                            ["/opt/rlt"])
    compile(src, "<bootstrap>", "exec")  # must be a valid program
    # env + authkey travel INSIDE the program (never on a command line)
    assert "'RLT_WORKER_AUTHKEY': 'deadbeef'" in src
    assert "'B': 'x y'" in src
    assert "'/opt/rlt'" in src
    # argv wiring for the embedded worker loop
    assert "'192.168.1.1', '5555', '3', '8'" in src
    # the worker source itself rides along, entrypoint guard included
    assert "def main(argv)" in src
    assert '__name__ == "__main__"' in src


def test_remote_transport_failure_propagates(tmp_path):
    with WorkerGroup(
        hosts=["host-a", "host-b"],
        transport=LoopbackTransport(),
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
    ) as g:
        with pytest.raises(WorkerError, match="kaboom"):
            g.run(_boom)


@pytest.mark.slow
def test_spmd_over_remote_transport(tmp_path):
    """The flagship protocol driven through the cross-host path: 2 'hosts'
    x 2 CPU devices joined into ONE global mesh, with the jax coordinator
    resolved on worker 0 (routable IP + remotely-probed port — the
    reference's MASTER_ADDR/PORT dance, ray_ddp.py:152-156)."""
    out = launch(
        _spmd_global_sum,
        2,
        args=(1,),
        platform="cpu",
        num_cpu_devices_per_process=2,
        hosts=["host-a", "host-b"],
        transport=LoopbackTransport(),
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path),
        timeout=240,
    )
    assert sorted(r for r, _, _ in out) == [0, 1]
    assert all(n == 4 for _, n, _ in out)
    assert all(s == 12.0 for _, _, s in out)


@pytest.mark.slow
def test_multiprocess_spmd_gloo(tmp_path):
    """2 processes x 2 CPU devices = one 4-device global mesh; a sharded
    sum must see ALL shards (1+1+1+1 from rank0's scale + 2+2+2+2 ... no —
    each process contributes 4 local elements of value rank+1, so the
    global sum is 4*1 + 4*2 = 12)."""
    out = launch_cpu_spmd(
        _spmd_global_sum,
        num_processes=2,
        devices_per_process=2,
        args=(1,),
        log_dir=str(tmp_path),
        timeout=240,
    )
    ranks = sorted(r for r, _, _ in out)
    assert ranks == [0, 1]
    assert all(n == 4 for _, n, _ in out)
    assert all(s == 12.0 for _, _, s in out)
