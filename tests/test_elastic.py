"""elastic/budget.py + supervisor elastic integration (ISSUE 9).

Fast legs: budget legality rides the plan checker's own divisibility
machinery (MeshSpec.resolve / dp_degree); the supervisor's decision
function shrinks exactly when the same-size relaunch is refused and
grows exactly when capacity returns; the goodput vocabulary knows the
`reshard` phase. The full kill -> shrink -> converge drill runs as a
slow 2-proc test (and as the format.sh `elastic --smoke` gate).
"""
import jax.numpy as jnp
import pytest

from ray_lightning_tpu.elastic import ElasticBudget
from ray_lightning_tpu.parallel.mesh import MeshSpec
from ray_lightning_tpu.resilience.supervisor import (
    ResilienceConfig,
    _elastic_target_world,
)


# ---- budget legality -------------------------------------------------------


def test_legal_worlds_default_template():
    b = ElasticBudget(min_world=1)
    assert b.legal_worlds(4) == [1, 2, 3, 4]
    assert b.largest_legal(3, 4) == 3
    assert b.largest_legal(0, 4) is None


def test_divisibility_via_mesh_template():
    # a fixed tensor=2 axis: only even worlds resolve — the SAME
    # refusal MeshSpec.resolve gives the pre-flight plan checker
    b = ElasticBudget(
        min_world=2,
        spec_for=lambda w: MeshSpec(data=-1, tensor=2))
    assert b.legal_worlds(8) == [2, 4, 6, 8]
    assert b.largest_legal(7, 8) == 6
    assert not b.legal(3, 8)


def test_divisible_by_and_bounds():
    b = ElasticBudget(min_world=4, max_world=12, divisible_by=4)
    assert b.legal_worlds(16) == [4, 8, 12]
    assert b.largest_legal(16, 16) == 12   # capped by max_world
    assert b.largest_legal(3, 16) is None  # below min_world


def test_global_batch_divisibility():
    # global batch 48 on an all-data mesh: dp degree == world
    b = ElasticBudget(min_world=1, global_batch=48)
    assert b.legal(6, 8) and b.legal(8, 8)
    assert not b.legal(5, 8)  # 48 % 5 != 0


def test_batch_plan_honesty():
    b = ElasticBudget(min_world=1, global_batch=64)
    plan = b.batch_plan(8, 4)
    assert plan["old_dp"] == 8 and plan["new_dp"] == 4
    assert plan["grad_accum_to_preserve"] == 2
    assert plan["global_batch_preserved"] is False
    assert plan["replanned_global_batch"] == 32
    # no whole factor: 8 -> 3
    plan = b.batch_plan(8, 3)
    assert "grad_accum_to_preserve" not in plan
    assert "re-planned" in plan["note"]
    # same dp: preserved
    assert b.batch_plan(4, 4)["global_batch_preserved"] is True


def test_capacity_oracle_fallback_and_failure():
    b = ElasticBudget(min_world=1)
    assert b.capacity(8) == 8  # no oracle: assumed restored at max
    b = ElasticBudget(min_world=1, capacity_fn=lambda: 5)
    assert b.capacity(8) == 5
    def boom():
        raise RuntimeError("oracle down")
    b = ElasticBudget(min_world=1, capacity_fn=boom)
    assert b.capacity(8) == 0  # broken oracle reads as nothing back


# ---- the supervisor's decision function ------------------------------------


def test_shrink_only_when_same_size_refused():
    b = ElasticBudget(min_world=1)
    # policy still allows a same-size relaunch and capacity is full:
    # no change
    assert _elastic_target_world(b, 2, 2, True, 0) is None
    # refused: shrink strictly below
    assert _elastic_target_world(b, 2, 2, False, 0) == 1
    # refused at min_world: nothing left
    assert _elastic_target_world(b, 1, 2, False, 0) is None


def test_grow_on_capacity_return():
    calls = {"cap": 1}
    b = ElasticBudget(min_world=1, capacity_fn=lambda: calls["cap"])
    # shrunk to 1 earlier; capacity still 1: no change
    assert _elastic_target_world(b, 1, 4, True, 1) is None
    # capacity returns: grow back toward it on the next relaunch
    calls["cap"] = 4
    assert _elastic_target_world(b, 1, 4, True, 1) == 4
    # capacity above launch world never exceeds the resolved max
    calls["cap"] = 16
    assert _elastic_target_world(b, 1, 4, True, 1) == 4


def test_reshard_budget_caps_changes():
    b = ElasticBudget(min_world=1, max_reshards=1)
    assert _elastic_target_world(b, 2, 2, False, 0) == 1
    assert _elastic_target_world(b, 2, 2, False, 1) is None  # spent


def test_capacity_loss_shrinks_even_when_allowed():
    # the oracle says only 2 of 4 hosts exist: move toward capacity on
    # an allowed relaunch instead of thrashing the full-size launch
    b = ElasticBudget(min_world=1, capacity_fn=lambda: 2)
    assert _elastic_target_world(b, 4, 4, True, 0) == 2


def test_no_budget_means_fixed_world():
    assert _elastic_target_world(None, 2, 2, False, 0) is None


# ---- reshard goodput vocabulary --------------------------------------------


def test_goodput_reshard_bucket():
    from ray_lightning_tpu.telemetry.goodput import (
        GOODPUT_BUCKETS,
        _PHASE_TO_BUCKET,
    )
    from ray_lightning_tpu.telemetry.spans import PH_RESHARD, PHASES

    assert "reshard_s" in GOODPUT_BUCKETS
    assert PH_RESHARD in PHASES
    assert _PHASE_TO_BUCKET[PH_RESHARD] == "reshard_s"


def test_worker_ledger_carries_reshard(tmp_path):
    from ray_lightning_tpu.telemetry.goodput import worker_ledger
    from ray_lightning_tpu.telemetry.spans import (
        PH_RESHARD,
        TelemetryRecorder,
    )

    import time

    rec = TelemetryRecorder(directory=str(tmp_path), rank=0)
    rec.record(PH_RESHARD, time.perf_counter(), 0.25, step=0)
    led = worker_ledger(rec, 10.0, rank=0, start_step=0, end_step=5)
    rec.close()
    assert led["buckets"]["reshard_s"] == pytest.approx(0.25)
    # buckets still sum to wall exactly (productive closes the books)
    assert sum(led["buckets"].values()) == pytest.approx(10.0)


# ---- supervisor config surface ---------------------------------------------


def test_resilience_config_carries_elastic(tmp_path):
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path),
                           elastic=ElasticBudget(min_world=1))
    assert cfg.elastic.min_world == 1


def test_begin_reshard_refuses_legacy_checkpoint(tmp_path):
    """An elastic resize whose resume source has no provenance must
    fail with the gap named, never silently move a legacy
    checkpoint."""
    from ray_lightning_tpu.checkpoint.io import (
        save_checkpoint,
        wait_for_checkpoints,
    )
    from ray_lightning_tpu.elastic.reshard import ReshardError
    from ray_lightning_tpu.resilience.supervisor import _begin_reshard

    path = str(tmp_path / "legacy")
    save_checkpoint(path, {"params": {"w": jnp.ones((4,))}},
                    {"global_step": 3})
    wait_for_checkpoints()
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path),
                           elastic=ElasticBudget(min_world=1))
    with pytest.raises(ReshardError, match="no sharding provenance"):
        _begin_reshard(cfg, 2, 1, path, 2, None)


def test_begin_reshard_records_ledger_entry(tmp_path):
    from ray_lightning_tpu.checkpoint.io import (
        save_checkpoint,
        sharding_provenance,
        wait_for_checkpoints,
    )
    from ray_lightning_tpu.parallel.strategy import DataParallel
    from ray_lightning_tpu.resilience.supervisor import _begin_reshard

    s = DataParallel(num_workers=2)
    s.setup()
    state = {"params": s.shard_params({"w": jnp.ones((8,))})}
    path = str(tmp_path / "ck")
    save_checkpoint(path, state,
                    {"global_step": 3,
                     **sharding_provenance(s.mesh, state)})
    wait_for_checkpoints()
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path),
                           elastic=ElasticBudget(min_world=1))
    entry = _begin_reshard(cfg, 2, 1, path, 2, None)
    assert entry["from_world"] == 2 and entry["to_world"] == 1
    assert entry["reason"] == "shrink"
    assert entry["from_mesh"] == {"data": 2}
    assert entry["batch_plan"]["new_dp"] == 1


# ---- the full drill (slow; also the format.sh elastic --smoke gate) --------


@pytest.mark.slow
def test_supervised_shrink_2proc_converges(tmp_path):
    """Kill one of two workers with the same-size relaunch refused
    (max_restarts=0): the supervisor must consult the budget, reshard
    onto the survivor (world 2 -> 1), resume, and converge — with the
    world change in the ledger and the reshard_s goodput bucket
    present."""
    from ray_lightning_tpu.elastic.cli import (
        _smoke_data,
        _smoke_module,
        _smoke_trainer,
    )
    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import fit_supervised

    cfg = ResilienceConfig(
        checkpoint_dir=str(tmp_path / "shrink"),
        policy=RetryPolicy(max_restarts=0, backoff_base_s=0.2,
                           jitter=0.0),
        save_every_n_steps=1,
        stall_timeout_s=0.0,
        heartbeat_interval_s=1.0,
        elastic=ElasticBudget(min_world=1, max_reshards=2),
        faults="kill:rank=1,step=3",
    )
    supervised = fit_supervised(
        _smoke_module, _smoke_trainer, _smoke_data, 2,
        resilience=cfg, platform="cpu", num_cpu_devices_per_process=1,
        return_weights=False, timeout=300.0)
    assert len(supervised.reshards) == 1
    assert supervised.reshards[0]["from_world"] == 2
    assert supervised.reshards[0]["to_world"] == 1
    assert supervised.final_world == 1
    acc = supervised.result.metrics.get("ptl/val_accuracy")
    assert acc is not None and float(acc) > 0.8
    buckets = (supervised.goodput or {}).get("buckets") or {}
    assert "reshard_s" in buckets


def test_begin_reshard_validates_against_real_template(tmp_path):
    # review regression: the driver validates the move against the
    # budget's REAL mesh template, not a fabricated all-data mesh
    from ray_lightning_tpu.checkpoint.io import (
        save_checkpoint,
        sharding_provenance,
        wait_for_checkpoints,
    )
    from ray_lightning_tpu.parallel.mesh import MeshSpec
    from ray_lightning_tpu.parallel.strategy import FSDP
    from ray_lightning_tpu.resilience.supervisor import _begin_reshard

    s = FSDP(num_workers=4, min_shard_size=8)
    s.setup()
    state = {"params": s.shard_params({"w": jnp.ones((8, 8))})}
    path = str(tmp_path / "ck")
    save_checkpoint(path, state,
                    {"global_step": 3,
                     **sharding_provenance(s.mesh, state)})
    wait_for_checkpoints()
    cfg = ResilienceConfig(
        checkpoint_dir=str(tmp_path),
        elastic=ElasticBudget(min_world=1,
                              spec_for=lambda w: MeshSpec(fsdp=w)))
    entry = _begin_reshard(cfg, 4, 2, path, 2, None)
    assert entry["from_mesh"] == {"fsdp": 4}
    assert entry["batch_plan"]["new_dp"] == 2
