"""Text packing utilities tests."""
from __future__ import annotations

import numpy as np
import pytest

from ray_lightning_tpu.core.text import chunk_tokens, pack_sequences


def test_chunk_tokens_layout():
    t = np.arange(33)
    out = chunk_tokens(t, seq_len=8)
    assert out["tokens"].shape == (4, 9)
    # next-token alignment: row i starts at i*8 (one-token overlap)
    np.testing.assert_array_equal(out["tokens"][0], np.arange(9))
    np.testing.assert_array_equal(out["tokens"][1], np.arange(8, 17))


def test_chunk_too_short_raises():
    with pytest.raises(ValueError, match="cannot fill"):
        chunk_tokens(np.arange(4), seq_len=8)


def test_pack_sequences_with_eos_and_mask():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11]]
    out = pack_sequences(docs, seq_len=6, eos_id=99, drop_last=False)
    toks, mask = out["tokens"], out["mask"]
    assert toks.shape[1] == 7 and mask.shape[1] == 6
    # stream: 1 2 3 99 4 5 99 6 7 8 9 10 11 99
    np.testing.assert_array_equal(toks[0], [1, 2, 3, 99, 4, 5, 99])
    assert mask[0].sum() == 6  # full row, everything contributes loss
    # tail row padded; padded targets masked out
    assert mask[-1].sum() < 6
    assert (toks[-1][int(mask[-1].sum()) + 1:] == 0).all()


def test_pack_feeds_llama(devices8):
    """Packed output trains the Llama family directly."""
    import jax.numpy as jnp

    from ray_lightning_tpu import DataLoader, SingleDevice, Trainer
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule

    cfg = LlamaConfig.tiny(use_flash=False)
    rng = np.random.default_rng(0)
    docs = [rng.integers(1, cfg.vocab_size, rng.integers(5, 40)).tolist()
            for _ in range(64)]
    data = pack_sequences(docs, seq_len=32, eos_id=0)
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=4)
    trainer = Trainer(strategy=SingleDevice(), max_epochs=1,
                      limit_train_batches=2, enable_checkpointing=False,
                      enable_progress_bar=False)
    trainer.fit(module, DataLoader(data, batch_size=8))
    assert np.isfinite(float(trainer.callback_metrics["loss"]))


def test_chunk_short_stream_keep_tail():
    out = chunk_tokens(np.arange(5), seq_len=8, drop_last=False)
    assert out["tokens"].shape == (1, 9)
    np.testing.assert_array_equal(out["tokens"][0][:5], np.arange(5))
    assert out["mask"][0].sum() == 4  # 4 real targets, rest padded
    with pytest.raises(ValueError):
        chunk_tokens(np.arange(4), seq_len=8)  # drop_last=True still raises


def test_empty_stream_raises_not_fabricates():
    with pytest.raises(ValueError, match="cannot fill"):
        pack_sequences([], seq_len=8, drop_last=False)
    with pytest.raises(ValueError, match="cannot fill"):
        chunk_tokens(np.zeros(0, np.int32), seq_len=8, drop_last=False)
