"""Multi-slice (DCN) cost-model + tracecheck tier itemization (ISSUE 9,
docs/ELASTIC.md "DCN cost model" / docs/STATIC_ANALYSIS.md).

The contract: `parse_topology("2xv5p-64")` is two slices over DCN;
crossing collectives are priced hierarchically (ICI intra stage + DCN
inter stage on the intra-reduced payload); the slice-major layout math
says which mesh axes cross; tracecheck itemizes dcn_bytes per event and
flags non-`data` crossing axes as RLT306 — the data-across-slices HSDP
placement audits clean.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ray_lightning_tpu.analysis.costmodel import (
    DCN_SPECS,
    Topology,
    collective_cost,
    parse_topology,
)
from ray_lightning_tpu.parallel.plan import (
    dcn_crossing_axes,
    group_dcn_span,
)


# ---- parse_topology --------------------------------------------------------


def test_parse_multislice_topology():
    t = parse_topology("2xv5p-64")
    assert t.n_slices == 2
    assert t.n_devices == 128          # two slices OF 64
    assert t.devices_per_slice == 64
    assert t.device_kind == "TPU v5p"
    assert t.dcn_gbps == DCN_SPECS["v5p"][0]
    assert "2 slices" in t.describe()


def test_parse_single_slice_unchanged():
    t = parse_topology("v5p-64")
    assert t.n_slices == 1 and t.n_devices == 64
    assert t.dcn_gbps is not None  # resolved, just unused


def test_parse_rejects_garbage():
    with pytest.raises(ValueError, match="cannot parse"):
        parse_topology("2x-64")
    with pytest.raises(ValueError, match="unknown topology family"):
        parse_topology("3xv9z-8")


def test_topology_rejects_uneven_slices():
    with pytest.raises(ValueError, match="equal slices"):
        Topology(name="bad", device_kind="TPU v5p", n_devices=10,
                 ici_gbps=600.0, ici_hop_latency_us=1.0,
                 hbm_bytes=1 << 30, n_slices=4)


# ---- slice-layout math (parallel/plan.py) ----------------------------------


def test_group_dcn_span_data_outermost():
    sizes = {"data": 2, "fsdp": 64}
    assert group_dcn_span(("data",), sizes, 2) == 2
    assert group_dcn_span(("fsdp",), sizes, 2) == 1
    assert group_dcn_span(("data", "fsdp"), sizes, 2) == 2
    assert group_dcn_span(("data",), sizes, 1) == 1  # single slice


def test_group_dcn_span_fsdp_across():
    # no data axis: fsdp IS the outermost non-trivial axis and spans
    # both slices
    assert group_dcn_span(("fsdp",), {"fsdp": 128}, 2) == 2
    # data=4 over 2 slices: 2 data-coords per slice — the data group
    # touches both slices, fsdp stays inside one
    sizes = {"data": 4, "fsdp": 8}
    assert group_dcn_span(("data",), sizes, 2) == 2
    assert group_dcn_span(("fsdp",), sizes, 2) == 1


def test_dcn_crossing_axes():
    assert dcn_crossing_axes({"data": 2, "fsdp": 64}, 2) == {"data": 2}
    assert dcn_crossing_axes({"fsdp": 128}, 2) == {"fsdp": 2}
    assert dcn_crossing_axes({"data": 2, "fsdp": 64}, 1) == {}
    # tensor inside a slice, data across
    out = dcn_crossing_axes({"data": 2, "tensor": 4}, 2)
    assert out == {"data": 2}


# ---- hierarchical collective_cost ------------------------------------------


def _topo(n, s):
    return Topology(name=f"{s}xcpu-{n // s}", device_kind="cpu",
                    n_devices=n, ici_gbps=100.0, ici_hop_latency_us=0.0,
                    hbm_bytes=1 << 30, n_slices=s, dcn_gbps=10.0,
                    dcn_hop_latency_us=0.0)


def test_psum_hierarchical_split():
    # group 8 over 2 slices: intra ring of 4 on ICI, inter ring of 2 on
    # the reduce-scattered payload (P/4) on DCN
    P = 1 << 20
    c = collective_cost("psum", P, {"data": 8}, _topo(8, 2), dcn_group=2)
    assert c.wire_bytes == int(2 * P * 3 / 4)
    assert c.dcn_bytes == int(2 * (P / 4) * 1 / 2)
    assert c.dcn_time_us > 0
    # single-slice call unchanged (back-compat)
    c1 = collective_cost("psum", P, {"data": 8}, _topo(8, 2))
    assert c1.dcn_bytes == 0
    assert c1.wire_bytes == int(2 * P * 7 / 8)


def test_pure_cross_slice_psum_all_dcn():
    P = 1 << 20
    c = collective_cost("psum", P, {"data": 2}, _topo(2, 2), dcn_group=2)
    assert c.wire_bytes == 0          # no intra stage (n_intra == 1)
    assert c.dcn_bytes == int(2 * P * 1 / 2)


def test_all_gather_and_ppermute_split():
    F = 1 << 20
    c = collective_cost("all_gather", F, {"data": 8}, _topo(8, 2),
                        dcn_group=2)
    assert c.wire_bytes == int(F * 3 / 4)
    assert c.dcn_bytes == int((F / 4) * 1 / 2)
    # a crossing ppermute rides DCN whole, one hop
    c = collective_cost("ppermute", F, {"data": 2}, _topo(8, 2),
                        dcn_group=2)
    assert c.wire_bytes == 0 and c.dcn_bytes == F


def test_all_to_all_no_intra_reduction():
    # all_to_all sends raw chunks: the remote (s-1)/s fraction crosses
    # DCN at FULL size — no /n_intra shrink (review finding: the
    # hierarchical shortcut would undercharge by n_intra)
    P = 1 << 20
    c = collective_cost("all_to_all", P, {"expert": 8}, _topo(8, 2),
                        dcn_group=2)
    assert c.dcn_bytes == int(P * 1 / 2)
    assert c.wire_bytes == int(P * 3 / 8)  # (n_intra-1)/n stays on ICI
    # single-slice unchanged
    c1 = collective_cost("all_to_all", P, {"expert": 8}, _topo(8, 2))
    assert c1.dcn_bytes == 0 and c1.wire_bytes == int(P * 7 / 8)


# ---- tracecheck itemization + RLT306 ---------------------------------------


def _audit(strategy, topo_name, batch_rows=16):
    from ray_lightning_tpu.analysis.tracecheck import audit_step
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return audit_step(
        MLPClassifier(features=(32,), num_classes=4), strategy,
        {"x": np.zeros((batch_rows, 8), np.float32),
         "y": np.zeros((batch_rows,), np.int32)},
        topology=topo_name)


def test_data_across_slices_audits_clean_with_dcn_bytes():
    from ray_lightning_tpu.parallel.strategy import DataParallel

    report = _audit(DataParallel(), "2xcpu-2")
    assert report.topology.n_slices == 2
    assert report.dcn_bytes_per_step > 0       # grad psum crosses DCN
    assert not any(f.rule == "RLT306" for f in report.findings)
    # the JSON carries the tier split per event and in total
    d = report.to_dict()
    assert d["dcn_bytes_per_step"] == report.dcn_bytes_per_step
    assert d["topology"]["n_slices"] == 2
    assert any(e["dcn_bytes"] > 0 for e in d["collectives"])
    assert "DCN total" in report.summary()


def test_fsdp_across_slices_flags_rlt306():
    from ray_lightning_tpu.parallel.strategy import FSDP

    report = _audit(FSDP(min_shard_size=8), "2xcpu-2")
    flagged = [f for f in report.findings if f.rule == "RLT306"]
    assert flagged, [f.rule for f in report.findings]
    assert "fsdp" in flagged[0].message
    assert "data" in flagged[0].message  # names the fix


def test_hsdp_placement_keeps_fsdp_on_ici():
    from ray_lightning_tpu.parallel.strategy import ShardedMesh

    report = _audit(ShardedMesh(data=2, fsdp=2, min_shard_size=8),
                    "2xcpu-2")
    assert not any(f.rule == "RLT306" for f in report.findings)
    # fsdp collectives (weight gathers) carry NO dcn bytes; data psums do
    for e in report.collectives:
        if e.axes == ("fsdp",):
            assert e.dcn_bytes == 0


def test_single_slice_reports_zero_dcn():
    from ray_lightning_tpu.parallel.strategy import DataParallel

    report = _audit(DataParallel(), "cpu-4")
    assert report.dcn_bytes_per_step == 0
    assert "DCN total" not in report.summary()


def test_trace_cli_multislice_json():
    # hermetic subprocess: the autouse fixture chdirs into a tmp dir,
    # so pin the repo root for the package import instead of relying on
    # the runner's cwd/PYTHONPATH
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "ray_lightning_tpu", "trace",
         "llama3-8b", "--topo", "2xcpu-4", "--json"],
        capture_output=True, text=True, timeout=300, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": repo + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    assert out.returncode == 0, out.stdout + out.stderr
    r = json.loads(out.stdout)
    assert r["topology"]["n_slices"] == 2
    assert r["mesh"] == {"data": 2, "fsdp": 4}  # HSDP builder placement
    assert r["dcn_bytes_per_step"] > 0
    assert not any(f["rule"] == "RLT306" for f in r["findings"])


def test_bench_multislice_summary_schema():
    import bench

    s = bench._multislice_summary()
    assert "multislice_error" not in s, s
    ms = s["multislice"]
    assert ms["topology"] == "2xv5p-64" and ms["n_slices"] == 2
    assert ms["mesh"] == {"data": 2, "fsdp": 64}
    assert s["dcn_bytes_per_step"] == ms["dcn_bytes_per_step"] > 0
    assert ms["ici_bytes_per_step"] > ms["dcn_bytes_per_step"]
    assert ms["dcn_crossing_flags"] == []


def test_bench_gate_dcn_ceiling(tmp_path):
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate", path)
    bench_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_gate)
    ceilings = {"dcn_bytes_per_step": (1000.0, "BENCH_r09.json")}
    # within the ceiling: pass
    fails = bench_gate.gate(
        {"metric": "m", "value": 1.0, "dcn_bytes_per_step": 1000},
        {}, 0.05, ceilings)
    assert not fails
    # grew past it: fail
    fails = bench_gate.gate(
        {"metric": "m", "value": 1.0, "dcn_bytes_per_step": 1100},
        {}, 0.05, ceilings)
    assert any("dcn_bytes_per_step" in f for f in fails)
    # dropped the field with no analysis error: fail
    fails = bench_gate.gate({"metric": "m", "value": 1.0}, {}, 0.05,
                            ceilings)
    assert any("dropped the field" in f for f in fails)
    # dropped WITH the analysis-error escape hatch: waived
    fails = bench_gate.gate(
        {"metric": "m", "value": 1.0, "multislice_error": "boom"},
        {}, 0.05, ceilings)
    assert not fails
    # reshard_restore_s bound: over the cap fails on a measured line
    fails = bench_gate.gate(
        {"metric": "m", "value": 1.0, "reshard_restore_s": 1e9},
        {}, 0.05, {})
    assert any("reshard_restore_s" in f for f in fails)


def test_sub_deployment_mesh_never_fabricates_dcn():
    # review regression: an n_devices override SMALLER than the
    # topology (a 4-device mesh on a 2x4 deployment) packs into the
    # fewest slices — no DCN bytes, no RLT306, even for an fsdp mesh
    from ray_lightning_tpu.analysis.tracecheck import audit_step
    from ray_lightning_tpu.models.mlp import MLPClassifier
    from ray_lightning_tpu.parallel.strategy import FSDP

    report = audit_step(
        MLPClassifier(features=(32,), num_classes=4),
        FSDP(min_shard_size=8),
        {"x": np.zeros((8, 8), np.float32),
         "y": np.zeros((8,), np.int32)},
        topology="2xcpu-4", n_devices=4)
    assert report.dcn_bytes_per_step == 0
    assert not any(f.rule == "RLT306" for f in report.findings)
