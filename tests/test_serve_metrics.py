"""Live serving metrics (telemetry/metrics.py + the serve/ wiring,
docs/OBSERVABILITY.md "serving metrics"): registry units (exact
histogram merge, ring bounds, flush cadence), scheduler/engine
instrumentation counts vs ground truth, the zero-overhead pin (metrics
off => byte-identical engine program, no jax values ever recorded),
flight-recorder persistence + driver finalization, preempted/in-flight
span accounting, the load-signal oracle, monitor/report CLI smoke, and
the bench schema + gate legs."""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, LlamaConfig
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.scheduler import Request, Scheduler
from ray_lightning_tpu.telemetry.metrics import (
    HIST_BUCKETS,
    HIST_GROWTH,
    HIST_LO,
    FlightRecorder,
    Histogram,
    MetricsRegistry,
    NullMetrics,
    finalize_flight,
    flight_path,
    merge_histograms,
    metrics_paths,
    read_flight,
    read_metrics,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    model = Llama(cfg)
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(40 + i), (1, 3 + (i % 5)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    params = jax.jit(model.init)(jax.random.key(3), prompts[0])["params"]
    return cfg, model, params, prompts


# ---------------------------------------------------------- histogram units


def test_histogram_records_and_quantiles():
    h = Histogram()
    for v in (0.001, 0.002, 0.004, 0.008, 0.016, 0.5, 2.0):
        h.observe(v)
    assert h.n == 7
    assert sum(h.counts.values()) == 7
    assert h.min == 0.001 and h.max == 2.0
    p50, p95, p99 = h.quantile(0.5), h.quantile(0.95), h.quantile(0.99)
    assert p50 <= p95 <= p99
    # bucket-upper quantiles are conservative but clamped to the true
    # max (which merges exactly), so a p99 never exceeds any sample
    assert p99 <= 2.0
    assert h.quantile(1.0) == 2.0
    # the sketch is the auditable tail: counts sum to n, ascending
    sketch = h.sketch()
    assert sum(c for _, c in sketch) == 7
    assert [le for le, _ in sketch] == sorted(le for le, _ in sketch)


def test_histogram_edge_buckets():
    h = Histogram()
    h.observe(0.0)            # underflow
    h.observe(HIST_LO / 2)    # underflow
    h.observe(1e12)           # overflow
    assert h.counts[0] == 2
    assert h.counts[h.n_buckets + 1] == 1
    assert h.quantile(0.5) == HIST_LO
    # overflow quantile reads the exact (merge-safe) max
    assert h.quantile(1.0) == 1e12


def test_histogram_merge_is_exact_and_order_independent():
    rng = np.random.default_rng(0)
    values = rng.lognormal(mean=-4, sigma=2, size=300)
    whole = Histogram()
    parts = [Histogram() for _ in range(3)]
    for i, v in enumerate(values):
        whole.observe(v)
        parts[i % 3].observe(v)
    fwd = merge_histograms(parts)
    rev = merge_histograms(list(reversed(parts)))
    # EXACT: merged counts equal the single-stream histogram's, bucket
    # for bucket — not approximately, integer-identical
    assert fwd.counts == whole.counts
    assert rev.counts == whole.counts
    assert fwd.n == whole.n == 300
    assert fwd.min == whole.min and fwd.max == whole.max
    for q in (0.5, 0.9, 0.95, 0.99):
        assert fwd.quantile(q) == rev.quantile(q) == whole.quantile(q)


def test_histogram_merge_rejects_layout_mismatch():
    a = Histogram()
    b = Histogram(lo=1e-3)
    with pytest.raises(ValueError, match="layout mismatch"):
        a.merge(b)


def test_histogram_dict_roundtrip():
    h = Histogram()
    for v in (0.01, 0.02, 3.0):
        h.observe(v)
    back = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert back.counts == h.counts
    assert back.n == h.n and back.max == h.max
    assert back.quantile(0.99) == h.quantile(0.99)


# ----------------------------------------------------------- registry units


def test_registry_ring_bounds_and_drop_accounting():
    reg = MetricsRegistry(ring_size=4)
    for i in range(10):
        reg.gauge("queue_depth", i)
        reg.tick_end()
    assert reg.ticks == 10
    ring = reg.ring()
    assert len(ring) == 4                  # bounded
    assert ring[-1]["g"]["queue_depth"] == 9.0
    assert reg.dropped == 6                # overwrites counted, not lost


def test_registry_flush_cadence_and_read(tmp_path):
    reg = MetricsRegistry(str(tmp_path), replica=3,
                          flush_every_n_ticks=4)
    path = reg._path
    # before the cadence fires, only the header line exists
    for i in range(3):
        reg.count("admissions")
        reg.observe("ttft_s", 0.01 * (i + 1))
        reg.tick_end()
    assert sum(1 for _ in open(path)) == 1
    reg.tick_end()  # 4th tick: the cadence flush
    parsed = read_metrics(path)
    assert len(parsed["ticks"]) == 4
    assert parsed["header"]["replica"] == 3
    assert parsed["header"]["hist"] == {
        "lo": HIST_LO, "growth": HIST_GROWTH, "n_buckets": HIST_BUCKETS}
    assert parsed["counters"]["admissions"] == 3
    assert parsed["hists"]["ttft_s"].n == 3
    # a second flush appends a NEWER cumulative snapshot; last wins
    reg.observe("ttft_s", 0.5)
    reg.close()
    parsed = read_metrics(path)
    assert parsed["hists"]["ttft_s"].n == 4
    assert metrics_paths(str(tmp_path)) == [path]


def test_read_metrics_survives_garbage_lines(tmp_path):
    reg = MetricsRegistry(str(tmp_path), replica=0,
                          flush_every_n_ticks=1)
    reg.gauge("queue_depth", 1)
    reg.tick_end()
    with open(reg._path, "a") as f:
        f.write("{torn line\n")
    parsed = read_metrics(reg._path)
    assert parsed["unparseable_lines"] == 1
    assert len(parsed["ticks"]) == 1


def test_null_metrics_is_inert():
    null = NullMetrics()
    null.count("x")
    null.gauge("y", 1.0)
    null.observe("z", 2.0)
    null.tick_end()
    assert null.counters() == {} and null.gauges() == {}
    assert null.histogram("z") is None and null.ring() == []
    assert null.flush() == 0 and not null.enabled


# ------------------------------------------- scheduler/engine ground truth


class _Recording(MetricsRegistry):
    """A registry that additionally asserts every recorded value is a
    plain host scalar — a jax.Array arriving here would mean the
    instrumentation touched device memory (a potential sync)."""

    def __init__(self):
        super().__init__()
        self.jax_values = []

    def _check(self, value):
        if isinstance(value, jax.Array):
            self.jax_values.append(value)

    def count(self, name, n=1):
        self._check(n)
        super().count(name, n)

    def gauge(self, name, value):
        self._check(value)
        super().gauge(name, value)

    def observe(self, name, value):
        self._check(value)
        super().observe(name, value)


def test_scheduler_engine_counts_vs_ground_truth(tiny):
    cfg, model, params, prompts = tiny
    reg = _Recording()
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, prefill_chunk=4),
        metrics=reg)
    eng.warmup()
    sched = Scheduler(eng, metrics=reg)
    reqs = [Request(rid=f"g{i}", prompt=prompts[i][0],
                    max_new_tokens=5, seed=i) for i in range(4)]
    for r in reqs:
        sched.submit(r)
    ticks = 0
    done = {}
    while sched.busy():
        for c in sched.tick():
            done[c.rid] = c
        ticks += 1
    c = reg.counters()
    assert c["admissions"] == 4
    assert c["completions"] == 4
    # ground truth: every emitted token was counted exactly once
    assert c["decode_tokens"] == sum(len(d.tokens) for d in done.values())
    # every prefill tick advanced one chunk of width 4 (single-slot lane)
    assert c["prefill_tokens"] % 4 == 0 and c["prefill_tokens"] > 0
    # one ring sample per scheduler tick (warmup ticks the ENGINE, not
    # the scheduler, so it contributes no sample)
    assert reg.ticks == ticks
    assert reg.gauges()["compile_count"] == 1
    assert reg.gauges()["queue_depth"] == 0  # drained
    for name in ("queue_wait_s", "ttft_s", "tpot_s", "decode_s"):
        assert reg.histogram(name).n == 4, name
    # the no-new-host-syncs pin: nothing recorded was a jax array
    assert reg.jax_values == []


def test_scheduler_counts_preemptions_and_growth_stalls(tiny):
    cfg, model, params, prompts = tiny
    reg = MetricsRegistry()
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, n_blocks=9,
        prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng, reserve="on_demand", metrics=reg)
    reqs = [Request(rid=f"p{i}", prompt=prompts[4][0],
                    max_new_tokens=20, seed=50 + i) for i in range(2)]
    for r in reqs:
        sched.submit(r)
    done = {}
    details = []
    while sched.busy():
        for c in sched.tick():
            done[c.rid] = c
        details.extend(sched.last_preemption_details)
    preempts = sum(c.preempted for c in done.values())
    assert preempts >= 1
    c = reg.counters()
    assert c["preemptions"] == preempts
    assert c["growth_stalls"] >= c["preemptions"]
    # the preemption details the driver turns into replayed-tagged
    # spans: one per preemption event, with partial timings
    assert len(details) == preempts
    for d in details:
        assert d["rid"] in done
        assert d["prefill_s"] >= 0 and d["decode_s"] >= 0


def test_inflight_snapshot_mid_run(tiny):
    cfg, model, params, prompts = tiny
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng)
    for i in range(4):
        sched.submit(Request(rid=f"f{i}", prompt=prompts[i][0],
                             max_new_tokens=8, seed=i))
    for _ in range(3):
        sched.tick()
    snap = {s["rid"]: s for s in sched.inflight_snapshot()}
    assert len(snap) == 4  # 2 slotted + 2 queued, nothing lost
    states = {s["state"] for s in snap.values()}
    assert "queued" in states
    assert states & {"prefilling", "decoding"}
    queued = [s for s in snap.values() if s["state"] == "queued"]
    assert all(s["queue_wait_s"] > 0 for s in queued)


# ------------------------------------------------------- zero-overhead pin


def test_metrics_off_is_byte_identical_program(tiny):
    """The compile-count + program pin: metrics on vs off lowers a
    byte-identical step program (instrumentation lives entirely on the
    host side of the tick), and churn with metrics armed still
    compiles exactly once."""
    cfg, model, params, prompts = tiny
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)

    def lowered_text(engine):
        C = ecfg.capacity
        spec = ecfg.pool_spec
        from ray_lightning_tpu.serve.engine import idle_prefill

        pslot, ptoks, ppos, plast = idle_prefill(ecfg)
        return engine._step.lower(
            engine.params, engine.pool_k, engine.pool_v,
            engine.last_logits,
            jnp.asarray(np.zeros((C, spec.blocks_per_slot), np.int32)),
            jnp.asarray(np.zeros(C, np.int32)),
            jnp.asarray(np.zeros(C, bool)),
            jnp.asarray(np.zeros(C, np.float32)),
            jnp.asarray(np.zeros(C, np.int32)),
            jnp.asarray(np.zeros((C, 2), np.uint32)),
            jnp.asarray(pslot), jnp.asarray(ptoks), jnp.asarray(ppos),
            jnp.asarray(plast)).as_text()

    eng_off = DecodeEngine(model, params, ecfg)
    eng_on = DecodeEngine(model, params, ecfg,
                          metrics=MetricsRegistry())
    assert lowered_text(eng_off) == lowered_text(eng_on)
    # churn through the instrumented engine: compile count stays 1
    sched = Scheduler(eng_on, metrics=eng_on.metrics)
    for i in range(4):
        sched.submit(Request(rid=f"z{i}", prompt=prompts[i][0],
                             max_new_tokens=4, seed=i))
    while sched.busy():
        sched.tick()
    assert eng_on.compile_count in (1, -1)


def test_metrics_off_streams_identical(tiny):
    cfg, model, params, prompts = tiny
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)

    def run(metrics):
        eng = DecodeEngine(model, params, ecfg, metrics=metrics)
        eng.warmup()
        sched = Scheduler(eng, metrics=metrics or None)
        for i in range(4):
            sched.submit(Request(rid=f"s{i}", prompt=prompts[i][0],
                                 max_new_tokens=6,
                                 temperature=0.7 if i % 2 else 0.0,
                                 top_k=3 if i % 2 else None,
                                 seed=20 + i))
        out = {}
        while sched.busy():
            for c in sched.tick():
                out[c.rid] = c.tokens
        return out

    assert run(None) == run(MetricsRegistry())


# ----------------------------------------------------------- flight recorder


def test_flight_recorder_persists_bounded_ring(tmp_path):
    path = str(tmp_path / "replica0.flight.json")
    fr = FlightRecorder(path, replica=0, maxlen=8, persist_every=4)
    for i in range(20):
        fr.record("tick", tick=i, queue_depth=i % 3)
    doc = read_flight(path)
    assert doc is not None
    assert len(doc["events"]) <= 8            # bounded ring
    fr.close()
    doc = read_flight(path)
    assert doc["events"][-1]["tick"] == 19    # close() persists the tail
    assert doc["replica"] == 0


def test_finalize_flight_stamps_death_and_appends(tmp_path):
    tdir = str(tmp_path)
    fr = FlightRecorder(flight_path(tdir, 1), replica=1,
                        persist_every=1)
    fr.record("tick", tick=1)
    fr.record("preempt", rid="r0")
    out = str(tmp_path / "flight.json")
    death = {"kind": "retryable", "cause": "worker-signal:SIGKILL",
             "detail": "rc=-9", "restartable": True}
    dump = finalize_flight(tdir, 1, death, out)
    assert dump["death"]["kind"] == "retryable"
    assert [e["kind"] for e in dump["events"]] == ["tick", "preempt"]
    # a second death APPENDS — postmortems never truncate each other
    finalize_flight(tdir, 1, dict(death, kind="fatal"), out)
    with open(out) as f:
        doc = json.load(f)
    assert len(doc["dumps"]) == 2
    assert doc["dumps"][1]["death"]["kind"] == "fatal"
    # a replica that never persisted still gets a named gap, not a crash
    dump = finalize_flight(tdir, 7, death, out)
    assert dump["events"] == [] and "note" in dump


# ------------------------------------------- driver wiring + load signal


@pytest.fixture(scope="module")
def inline_run(tiny, tmp_path_factory):
    """One instrumented 2-replica inline serve, shared by the driver /
    report / monitor / load-signal tests."""
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )

    cfg, model, params, prompts = tiny
    run_dir = str(tmp_path_factory.mktemp("serve_metrics_run"))
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=2, backend="inline", reserve="on_demand",
        engine=EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                            prefill_chunk=4),
        run_dir=run_dir, metrics_flush_every_n_ticks=2,
        flight_persist_every=2))
    reqs = [Request(rid=f"m{i}", prompt=prompts[i][0],
                    max_new_tokens=6, seed=i) for i in range(6)]
    res = drv.run(reqs)
    return run_dir, res


def test_driver_emits_per_replica_metrics_jsonl(inline_run):
    run_dir, res = inline_run
    tdir = os.path.join(run_dir, "telemetry")
    paths = metrics_paths(tdir)
    assert len(paths) == 2
    total = 0
    for p in paths:
        parsed = read_metrics(p)
        assert parsed["header"]["version"] == "rlt-metrics-v1"
        assert len(parsed["ticks"]) >= 1
        h = parsed["hists"]["ttft_s"]
        assert h.n == parsed["counters"]["completions"]
        total += h.n
    assert total == len(res.meta) == 6
    # the driver's run-level rollup landed in serving.json
    with open(os.path.join(run_dir, "serving.json")) as f:
        doc = json.load(f)
    assert doc["metrics"]["counters"]["completions"] == 6
    lat = doc["metrics"]["latency"]["ttft_s"]
    assert lat["n"] == 6 and lat["p99"] is not None
    assert sum(c for _, c in lat["sketch"]) == 6
    assert doc["load"]["available"] is True


def test_load_signal_oracle(inline_run, tmp_path):
    from ray_lightning_tpu.serve.driver import load_signal

    run_dir, _ = inline_run
    sig = load_signal(run_dir)
    assert sig["available"] is True
    assert sig["replicas_reporting"] == 2
    assert sig["total_slots"] == 4.0
    assert sig["pressure"] is not None
    assert 0.0 <= sig["occupancy"] <= 1.0
    assert sig["queue_depth_max"] >= sig["queue_depth_p50"] >= 0
    # no metrics => explicitly unavailable, never silently zero load
    empty = load_signal(str(tmp_path))
    assert empty["available"] is False and "reason" in empty


def test_preempted_requests_get_replayed_tagged_spans(tiny, tmp_path):
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )
    from ray_lightning_tpu.telemetry.report import build_serving_section
    from ray_lightning_tpu.telemetry.spans import PH_QUEUE_WAIT, read_spans

    cfg, model, params, prompts = tiny
    run_dir = str(tmp_path / "run")
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", reserve="on_demand",
        engine=EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                            n_blocks=9, prefill_chunk=4),
        run_dir=run_dir))
    reqs = [Request(rid=f"p{i}", prompt=prompts[4][0],
                    max_new_tokens=20, seed=50 + i) for i in range(2)]
    res = drv.run(reqs)
    preempts = sum(m["preempted"] for m in res.meta.values())
    assert preempts >= 1
    import glob

    spans = [s for f in glob.glob(os.path.join(
        run_dir, "telemetry", "rank*.spans.jsonl"))
        for s in read_spans(f)["spans"]]
    replayed = [s for s in spans
                if (s.get("meta") or {}).get("replayed")]
    # the discarded prefix is accounted: >= one queue_wait span per
    # preemption, tagged so nothing double-counts it
    assert len([s for s in replayed
                if s["phase"] == PH_QUEUE_WAIT]) == preempts
    assert all("ttft_s" not in (s.get("meta") or {}) for s in replayed)
    # and the report counts each request ONCE despite the extra spans
    section = build_serving_section(run_dir)
    assert section["requests"] == 2
    assert section["counters"]["preemptions"] == preempts


def test_drain_records_inflight_spans(tiny, tmp_path):
    """A serve loop that stops with work in flight leaves
    inflight-tagged spans for the unfinished requests."""
    from ray_lightning_tpu.serve.driver import _record_drain
    from ray_lightning_tpu.telemetry.spans import (
        PH_QUEUE_WAIT, TelemetryRecorder, read_spans,
    )

    cfg, model, params, prompts = tiny
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng)
    for i in range(3):
        sched.submit(Request(rid=f"d{i}", prompt=prompts[i][0],
                             max_new_tokens=8, seed=i))
    for _ in range(4):
        sched.tick()
    assert sched.busy()
    rec = TelemetryRecorder(str(tmp_path), rank=0)
    _record_drain(rec, sched, replica=0)
    rec.close()
    spans = read_spans(rec._path)["spans"]
    inflight = [s for s in spans
                if (s.get("meta") or {}).get("inflight")]
    rids = {(s.get("meta") or {}).get("rid") for s in inflight}
    assert rids == {"d0", "d1", "d2"}
    assert all(s["phase"] == PH_QUEUE_WAIT or s["dur"] >= 0
               for s in inflight)


# ----------------------------------------------------- monitor/report CLI


def test_report_serving_section_has_p99_and_sketch(inline_run):
    from ray_lightning_tpu.telemetry.report import build_report

    run_dir, _ = inline_run
    out = build_report(run_dir)
    sv = out["serving"]
    for key in ("ttft_p99_s", "tpot_p99_s", "queue_wait_p99_s",
                "ttft_sketch", "counters", "timeline", "load_signal"):
        assert key in sv, key
    assert sv["ttft_p50_s"] <= sv["ttft_p95_s"] <= sv["ttft_p99_s"]
    assert sv["timeline"]["0"]["restart_markers"] == 0
    assert sv["load_signal"]["available"] is True


def test_monitor_serve_view(inline_run, capsys):
    from ray_lightning_tpu.telemetry.report import (
        _monitor_serve_once, run_monitor,
    )

    run_dir, _ = inline_run
    view = _monitor_serve_once(run_dir)
    assert set(view["replicas"]) == {"0", "1"}
    for rep in view["replicas"].values():
        assert rep["tick"] >= 1
        assert rep["queue_depth"] is not None
        assert rep["compile_count"] == 1
    assert view["load_signal"]["available"] is True

    rd = run_dir

    class Args:
        smoke = False
        run_dir = rd
        follow = False
        serve = True
        interval = 5.0
        as_json = True

    assert run_monitor(Args()) == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(line["replicas"]) == {"0", "1"}


# ------------------------------------------------------ bench schema + gate


def test_bench_serving_leg_schema():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    row = bench._measure_serving(tiny=True, autoscale=False)
    assert row["ttft_p99_s"] is not None
    sm = row["serve_metrics"]
    for key in ("queue_depth_p50", "queue_depth_max", "preemptions",
                "growth_stalls", "ttft_p99_s", "ticks"):
        assert key in sm, key
    assert sm["completions"] == sm["admissions"] > 0
    assert sm["ticks"] > 0
    assert row["serving_compile_count"] in (1, -1)


def _load_bench_gate():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_gate_bounds_ttft_p99():
    bg = _load_bench_gate()
    base = {"metric": "m", "value": 100.0}
    # over the bound on a measured line: fails, naming the SLO
    msgs = bg.gate({**base, "ttft_p99_s": 99.0}, {}, 0.05)
    assert any("ttft_p99_s" in m and "SLO" in m for m in msgs)
    # within the bound: passes
    assert bg.gate({**base, "ttft_p99_s": 0.5}, {}, 0.05) == []
    # null waives (probe failed), absent waives (historic line)
    assert bg.gate({**base, "ttft_p99_s": None}, {}, 0.05) == []
    assert bg.gate(dict(base), {}, 0.05) == []
    # an environmental skip line waives the bound entirely
    skip = {"metric": "m", "value": 0.0, "skipped": "backend down",
            "ttft_p99_s": 99.0}
    assert bg.gate(skip, {}, 0.05) == []
