"""Test harness: a virtual 8-device CPU mesh, no TPU required.

This is the analog of the reference's throwaway local Ray clusters
(`ray.init(num_cpus=2)` fixtures, reference tests/test_ddp.py:16-21):
`--xla_force_host_platform_device_count=8` gives true multi-device SPMD
semantics (real shardings, real collectives compiled by XLA's CPU backend)
on any box.
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compilation cache: repeat runs (and the many subprocess
# workers, which inherit this env) skip recompiles of identical programs —
# the dominant cost of the suite. Keyed per jax version automatically.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "rlt_jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_seed_env(monkeypatch, tmp_path):
    monkeypatch.delenv("RLT_GLOBAL_SEED", raising=False)
    monkeypatch.chdir(tmp_path)
    yield


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
