"""Test harness: a virtual 8-device CPU mesh, no TPU required.

This is the analog of the reference's throwaway local Ray clusters
(`ray.init(num_cpus=2)` fixtures, reference tests/test_ddp.py:16-21):
`--xla_force_host_platform_device_count=8` gives true multi-device SPMD
semantics (real shardings, real collectives compiled by XLA's CPU backend)
on any box.
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compilation cache: repeat runs (and the many subprocess
# workers, which inherit this env) skip recompiles of identical programs —
# the dominant cost of the suite. Keyed per jax version automatically.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "rlt_jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
# Arm the lock-order sanitizer (analysis/lockwatch.py) for the whole
# suite: every san_lock the package creates becomes order-watched, so
# tier-1 doubles as a concurrency drill. Must be set BEFORE any package
# module is imported — san_lock decides armed-ness at creation time and
# module-level locks are created at import. Subprocess workers inherit
# it and sanitize themselves too.
os.environ.setdefault("RLT_LOCKWATCH", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_seed_env(monkeypatch, tmp_path):
    monkeypatch.delenv("RLT_GLOBAL_SEED", raising=False)
    monkeypatch.chdir(tmp_path)
    yield


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def tiny_llama_f32():
    """The suite's canonical tiny-Llama build — `LlamaConfig.tiny(
    use_flash=False, dtype=float32)`, init key 1 — compiled and
    initialized ONCE per session. Several module fixtures used to
    re-derive this identical build (generate, serve, serve_driver);
    the jitted `model.init` is one of the suite's compile-heaviest
    shared steps, and init params depend only on the RNG key and the
    param shapes (not the example batch), so one build serves them
    all. Treat the params as read-only."""
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    model = Llama(cfg)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (2, 8), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(model.init)(jax.random.key(1), tokens)["params"]
    return cfg, model, params, tokens


def pytest_sessionfinish(session, exitstatus):
    """The lockwatch verdict for the whole run: any lock-order cycle the
    suite's real execution exercised fails the session (held-too-long is
    report-only — wall-clock on shared CI is not a correctness signal)."""
    from ray_lightning_tpu.analysis.lockwatch import (
        lockwatch_armed, lockwatch_cycles, lockwatch_findings,
    )

    if not lockwatch_armed():
        return
    cycles = lockwatch_cycles()
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.write_line(
            f"lockwatch: {len(cycles)} lock-order cycle(s) observed "
            f"across the suite", bold=bool(cycles))
    if cycles:
        for f in lockwatch_findings():
            if f.rule == "RLT702" and tr is not None:
                tr.write_line(f.format())
        session.exitstatus = 1
