"""AOT warm start + persistent compilation cache
(pipeline/compile_cache.py): compile-time metrics, cache hits across
trainers, shape-drift fallback, and plan cache-key stability."""
import os

import jax
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, SingleDevice, Trainer
from ray_lightning_tpu.pipeline.compile_cache import (
    WarmStep,
    plan_cache_dir,
    plan_cache_key,
)

from tests.utils import BoringModel, random_dataset


def _cache_files(d):
    return sorted(f for f in os.listdir(d) if f.endswith("-cache"))


def _fit(tmp_path, cache_dir, *, warm_start=True, data=None, seed=3):
    data = data if data is not None else random_dataset(n=128)
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=1,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        enable_progress_bar=False, seed=seed, warm_start=warm_start,
        compile_cache_dir=str(cache_dir) if cache_dir else None,
    )
    module = BoringModel()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))
    return trainer, module


class TestWarmStep:
    def test_aot_path_used_and_stats_recorded(self, tmp_path):
        trainer, _ = _fit(tmp_path, None)
        assert isinstance(trainer._train_step, WarmStep)
        assert trainer._train_step.aot_active
        assert trainer.callback_metrics["compile_time_s"] > 0
        # eval step auto-warms on its first batch
        assert trainer.callback_metrics["val_compile_time_s"] > 0

    def test_bitwise_parity_warm_vs_lazy(self, tmp_path):
        data = random_dataset(n=128)
        _, m_warm = _fit(tmp_path / "a", None, warm_start=True, data=data)
        _, m_lazy = _fit(tmp_path / "b", None, warm_start=False, data=data)
        for a, b in zip(jax.tree.leaves(jax.device_get(m_warm.params)),
                        jax.tree.leaves(jax.device_get(m_lazy.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_warm_start_off_is_plain_jit(self, tmp_path):
        trainer, _ = _fit(tmp_path, None, warm_start=False)
        assert not trainer._train_step.aot_active
        assert "compile_time_s" not in trainer.callback_metrics

    def test_shape_drift_falls_back_to_jit(self, tmp_path):
        """A loader yielding ragged batches must get classic jit
        semantics (retrace per shape), never an AOT shape error."""
        rng = np.random.default_rng(0)

        def batches():
            for bs in (32, 32, 16, 32):  # drift at batch 3
                yield {"x": rng.standard_normal((bs, 32),
                                                dtype=np.float32),
                       "y": rng.integers(0, 2, bs).astype(np.int32)}

        trainer = Trainer(
            strategy=SingleDevice(), max_epochs=1,
            default_root_dir=str(tmp_path), enable_checkpointing=False,
            enable_progress_bar=False, warm_start=True,
        )
        trainer.fit(BoringModel(), batches())
        assert trainer.global_step == 4
        assert not trainer._train_step.aot_active  # drift disabled AOT

    def test_second_trainer_hits_persistent_cache(self, tmp_path):
        """Two trainers compiling the identical program against one
        persistent cache dir: the second must ADD no cache entries (its
        lowered program hashes to the first's key — a disk hit, which is
        what makes supervisor restart N recompile nothing)."""
        cache = tmp_path / "cache"
        data = random_dataset(n=128)
        t1, _ = _fit(tmp_path / "a", cache, data=data)
        files_after_first = _cache_files(cache)
        assert files_after_first, "no persistent cache entries written"
        t2, _ = _fit(tmp_path / "b", cache, data=data)
        assert _cache_files(cache) == files_after_first
        # both report the metric; the second's XLA share is a disk hit
        assert t1.callback_metrics["compile_time_s"] > 0
        assert t2.callback_metrics["compile_time_s"] > 0


class TestPlanCacheKey:
    def test_stable_and_distinct(self):
        assert plan_cache_key("a", 1) == plan_cache_key("a", 1)
        assert plan_cache_key("a", 1) != plan_cache_key("a", 2)
        d = plan_cache_dir("/tmp/base", "a", 1)
        assert d.startswith(os.path.abspath("/tmp/base") + os.sep)

    def test_strategy_compile_cache_key(self):
        from ray_lightning_tpu.parallel.strategy import DataParallel

        s1 = DataParallel(num_workers=4)
        s1.setup()
        key = s1.compile_cache_key()
        s2 = DataParallel(num_workers=4)
        s2.setup()
        assert s2.compile_cache_key() == key
        s3 = DataParallel(num_workers=2)
        s3.setup()
        assert s3.compile_cache_key() != key


class TestWarmStepUnit:
    def test_warm_failure_degrades_to_jit(self):
        """warm() on something that cannot lower must not break calls."""
        step = WarmStep(jax.jit(lambda x: x + 1), label="t")
        step.warm(object())  # not abstractable -> logged fallback
        assert not step.aot_active
        assert int(step(jax.numpy.ones(()))) == 2

    def test_matching_shapes_dispatch_compiled(self):
        calls = {"n": 0}
        jitted = jax.jit(lambda x: x * 2)
        step = WarmStep(jitted, label="t")
        x = jax.numpy.arange(8, dtype=jax.numpy.float32)
        step.warm(x)
        assert step.aot_active
        assert np.array_equal(np.asarray(step(x)), np.asarray(x) * 2)
        # drifted shape: falls back, stays functional
        y = jax.numpy.arange(4, dtype=jax.numpy.float32)
        assert np.array_equal(np.asarray(step(y)), np.asarray(y) * 2)
        assert not step.aot_active
        del calls


@pytest.mark.slow  # spawns a subprocess to prove the cross-process hit
def test_cross_process_cache_reuse(tmp_path):
    """The supervisor's restart story: a FRESH process pointed at the
    same per-plan cache dir must not add entries either."""
    import subprocess
    import sys

    cache = tmp_path / "cache"
    script = f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from tests.utils import BoringModel, random_dataset
from ray_lightning_tpu import DataLoader, SingleDevice, Trainer
data = random_dataset(n=128)
t = Trainer(strategy=SingleDevice(), max_epochs=1,
            default_root_dir={str(tmp_path / "run")!r},
            enable_checkpointing=False, enable_progress_bar=False,
            seed=3, compile_cache_dir={str(cache)!r})
t.fit(BoringModel(), DataLoader(data, batch_size=32))
print("COMPILE_S", t.callback_metrics["compile_time_s"])
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out1 = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert out1.returncode == 0, out1.stderr[-2000:]
    files_first = _cache_files(cache)
    assert files_first
    out2 = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert out2.returncode == 0, out2.stderr[-2000:]
    assert _cache_files(cache) == files_first
