"""Prefix-sharing copy-on-write block tables (docs/SERVING.md "prefix
sharing").

Three layers, cheapest first:

- `BlockAllocator` refcount semantics: sharing maps one physical block
  into many tables, so the free/decref bookkeeping must refuse the bugs
  that silently corrupt a *different* request's cache.
- `PrefixCache` unit behavior: digest chains, LRU match/register,
  refcount-1-only eviction.
- Scheduler integration on the real engine: an 8-stream fleet sharing a
  system prompt stays BITWISE equal to per-stream `generate()` while
  prefilling the shared prefix exactly once (strictly fewer prefill
  tokens than the unshared run), the slide-back fork path, and
  preemption decref-not-free with bitwise replay off the still-cached
  chain.
"""
import jax
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import generate
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.kv_cache import (BlockAllocator, PagedPoolSpec,
                                              PrefixCache,
                                              prefix_block_hashes)
from ray_lightning_tpu.serve.scheduler import Request, Scheduler


def _drain(sched, reqs):
    out = {}
    for r in reqs:
        sched.submit(r)
    while sched.busy():
        for comp in sched.tick():
            out[comp.rid] = comp
    return out


def _shared_prompts(cfg, n=8, prefix_len=9):
    """n prompts sharing a ``prefix_len``-token system prompt with
    ragged per-stream tails."""
    sys_prompt = np.asarray(
        jax.random.randint(jax.random.key(5), (prefix_len,), 0,
                           cfg.vocab_size), np.int32)
    prompts = []
    for i in range(n):
        tail = np.asarray(
            jax.random.randint(jax.random.key(50 + i), (2 + (i % 3),), 0,
                               cfg.vocab_size), np.int32)
        prompts.append(np.concatenate([sys_prompt, tail]))
    return prompts


# ---------------------------------------------------------------------------
# BlockAllocator refcounts
# ---------------------------------------------------------------------------


def test_allocator_refcount_lifecycle():
    spec = PagedPoolSpec(n_blocks=6, block_size=4, blocks_per_slot=4)
    alloc = BlockAllocator(spec)
    ids = alloc.alloc(2)
    assert ids is not None and len(ids) == 2
    assert all(alloc.refcount(b) == 1 for b in ids)
    alloc.incref(ids)
    assert all(alloc.refcount(b) == 2 for b in ids)
    # first decref drops a sharer, frees nothing
    assert alloc.decref(ids) == []
    assert alloc.free_blocks == 3
    # last reference dies -> both blocks return to the free list
    assert sorted(alloc.decref(ids)) == sorted(ids)
    assert alloc.free_blocks == 5
    assert all(alloc.refcount(b) == 0 for b in ids)


def test_allocator_double_decref_refused():
    spec = PagedPoolSpec(n_blocks=4, block_size=4, blocks_per_slot=2)
    alloc = BlockAllocator(spec)
    (b,) = alloc.alloc(1)
    alloc.decref([b])
    with pytest.raises(ValueError, match="double free"):
        alloc.decref([b])
    # `free` is a decref alias — the refusal covers historical sites
    with pytest.raises(ValueError, match="double free"):
        alloc.free([b])


def test_allocator_incref_of_unallocated_refused():
    spec = PagedPoolSpec(n_blocks=4, block_size=4, blocks_per_slot=2)
    alloc = BlockAllocator(spec)
    with pytest.raises(ValueError, match="incref of unallocated"):
        alloc.incref([2])
    with pytest.raises(ValueError, match="invalid block"):
        alloc.decref([0])  # scratch is never allocatable


# ---------------------------------------------------------------------------
# Digest chains + PrefixCache
# ---------------------------------------------------------------------------


def test_prefix_block_hashes_are_cumulative():
    a = np.arange(12, dtype=np.int32)
    b = np.arange(12, dtype=np.int32)
    b[0] = 99  # differ in the FIRST token only
    ha, hb = prefix_block_hashes(a, 4), prefix_block_hashes(b, 4)
    assert len(ha) == 3  # full blocks only
    # equal prefixes -> equal digests; an early divergence poisons
    # EVERY later digest (a block is only shareable with its prefix)
    assert all(x != y for x, y in zip(ha, hb))
    assert prefix_block_hashes(a[:11], 4) == ha[:2]
    assert prefix_block_hashes(a, 4) == ha  # deterministic


def test_prefix_cache_match_register_and_refs():
    spec = PagedPoolSpec(n_blocks=8, block_size=4, blocks_per_slot=4)
    alloc = BlockAllocator(spec)
    cache = PrefixCache(alloc)
    toks = np.arange(12, dtype=np.int32)
    hashes = prefix_block_hashes(toks, 4)
    blocks = alloc.alloc(3)
    cache.register(hashes, blocks)
    # the cache holds exactly ONE reference per block on top of the
    # slot's own
    assert all(alloc.refcount(b) == 2 for b in blocks)
    assert cache.match(hashes) == blocks
    assert cache.match(hashes, max_blocks=2) == blocks[:2]
    # diverging second block truncates the match at the chain break
    other = toks.copy()
    other[5] = 77
    assert cache.match(prefix_block_hashes(other, 4)) == blocks[:1]
    # re-registering under a different block keeps the first publication
    dup = alloc.alloc(3)
    cache.register(hashes, dup)
    assert cache.match(hashes) == blocks
    assert all(alloc.refcount(b) == 1 for b in dup)


def test_prefix_cache_evicts_only_sole_holder_lru():
    spec = PagedPoolSpec(n_blocks=8, block_size=4, blocks_per_slot=4)
    alloc = BlockAllocator(spec)
    cache = PrefixCache(alloc)
    h_a = prefix_block_hashes(np.arange(8, dtype=np.int32), 4)
    h_b = prefix_block_hashes(np.arange(100, 108, dtype=np.int32), 4)
    blocks_a, blocks_b = alloc.alloc(2), alloc.alloc(2)
    cache.register(h_a, blocks_a)
    cache.register(h_b, blocks_b)
    # release the slots' own refs: the cache is now the sole holder
    alloc.decref(blocks_a)
    alloc.decref(blocks_b)
    # ...except a live slot re-attaches to chain A
    alloc.incref(blocks_a)
    assert cache.evict(4) == 2  # only chain B (refcount 1) is evictable
    assert cache.match(h_b) == []
    assert cache.match(h_a) == blocks_a  # shared chain survived
    assert alloc.free_blocks == 3 + 2


def test_scheduler_evicts_prefix_cache_when_pool_dry(tiny_llama_f32):
    # pool sized so the second DISTINCT prompt cannot be admitted
    # without reclaiming the first prompt's cached (idle) chain
    cfg, model, params, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=1, block_size=4, blocks_per_slot=3,
                        prefill_chunk=4)
    eng = DecodeEngine(model, params, ecfg)
    eng.warmup()
    sched = Scheduler(eng, prefix_cache=True)
    p1 = np.asarray(jax.random.randint(jax.random.key(8), (8,), 0,
                                       cfg.vocab_size), np.int32)
    p2 = np.asarray(jax.random.randint(jax.random.key(9), (8,), 0,
                                       cfg.vocab_size), np.int32)
    out = _drain(sched, [Request(rid="a", prompt=p1, max_new_tokens=2,
                                 seed=1)])
    assert len(sched.prefix) > 0 and sched.alloc.free_blocks < 2
    out.update(_drain(sched, [Request(rid="b", prompt=p2,
                                      max_new_tokens=2, seed=2)]))
    for rid, prompt, seed in (("a", p1, 1), ("b", p2, 2)):
        ref = np.asarray(generate(model, params, prompt[None], 2,
                                  temperature=0.0, seed=seed))[0]
        assert np.array_equal(ref, np.array(out[rid].tokens)), rid


# ---------------------------------------------------------------------------
# Scheduler integration on the real engine
# ---------------------------------------------------------------------------


def test_shared_prefix_fleet_bitwise_and_prefills_once(tiny_llama_f32):
    cfg, model, params, _ = tiny_llama_f32
    prompts = _shared_prompts(cfg)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)

    def fleet(prefix_cache):
        eng = DecodeEngine(model, params, ecfg)
        eng.warmup()
        sched = Scheduler(eng, prefix_cache=prefix_cache)
        reqs = [Request(rid=f"r{i}", prompt=p, max_new_tokens=6,
                        seed=3 + i) for i, p in enumerate(prompts)]
        out = _drain(sched, reqs)
        return eng, sched, reqs, out

    eng, sched, reqs, out = fleet(prefix_cache=True)
    for i, r in enumerate(reqs):
        ref = np.asarray(generate(model, params, prompts[i][None],
                                  r.max_new_tokens, temperature=0.0,
                                  seed=r.seed))[0]
        assert np.array_equal(ref, np.array(out[r.rid].tokens,
                                            np.int32)), i
    assert eng.compile_count == 1  # sharing never re-traces the step
    assert sched.shared_block_fraction > 0.0

    _, unshared, _, _ = fleet(prefix_cache=False)
    assert unshared.shared_block_fraction == 0.0
    # the prefill-once pin: the common prefix is computed for ONE
    # stream only, so issued prefill tokens drop strictly
    assert sched.prefill_tokens_issued < unshared.prefill_tokens_issued


def test_fork_on_slide_back_window_stays_bitwise(tiny_llama_f32):
    # blocks_per_slot=4 -> max_slot_len=16, chunk=8, P=4: a 15-token
    # prompt matches 3 shared blocks (12 tokens) but the final chunk's
    # slide-back window [8, 16) overlaps shared block 2 -> the slot
    # must FORK that block before the in-place rewrite
    cfg, model, params, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=8)
    eng = DecodeEngine(model, params, ecfg)
    eng.warmup()
    sched = Scheduler(eng, prefix_cache=True)
    prompt = np.asarray(jax.random.randint(jax.random.key(7), (15,), 0,
                                           cfg.vocab_size), np.int32)
    reqs = [Request(rid=f"f{i}", prompt=prompt, max_new_tokens=1,
                    seed=11 + i) for i in range(3)]
    out = _drain(sched, reqs)
    for r in reqs:
        ref = np.asarray(generate(model, params, prompt[None], 1,
                                  temperature=0.0, seed=r.seed))[0]
        assert np.array_equal(ref, np.array(out[r.rid].tokens)), r.rid
    assert sched.shared_block_fraction > 0.0
    assert eng.compile_count == 1


def test_preempted_shared_blocks_decref_then_replay_reattaches(
        tiny_llama_f32):
    cfg, model, params, _ = tiny_llama_f32
    prompts = _shared_prompts(cfg, n=2)
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    eng = DecodeEngine(model, params, ecfg)
    eng.warmup()
    sched = Scheduler(eng, prefix_cache=True)
    out = _drain(sched, [Request(rid="seed", prompt=prompts[0],
                                 max_new_tokens=4, seed=3)])
    cached = len(sched.prefix)
    assert cached > 0
    chain_hashes = prefix_block_hashes(prompts[0], ecfg.block_size)
    chain = sched.prefix.match(chain_hashes, max_blocks=cached)
    free_before = sched.alloc.free_blocks
    # admit a sharer of the cached chain, then yank it mid-flight: the
    # eviction must DECREF its shared blocks (the cache's reference
    # keeps the chain alive), never free them
    sched.submit(Request(rid="v", prompt=prompts[1], max_new_tokens=4,
                         seed=4))
    sched.tick()
    evicted = sched.evict_slotted()
    assert [r.rid for r, _ in evicted] == ["v"]
    # the seed chain survived the preemption, same physical blocks;
    # the sharer's own full tail block may have been newly registered
    # during its prefill tick (the cache retains those too)
    assert sched.prefix.match(chain_hashes, max_blocks=cached) == chain
    assert all(sched.alloc.refcount(b) == 1 for b in chain)
    newly_cached = len(sched.prefix) - cached
    assert sched.alloc.free_blocks == free_before - newly_cached
    # bitwise replay re-attaches to the still-cached chain
    for req, preempts in evicted:
        sched.enqueue(req, preempts)
    while sched.busy():
        for comp in sched.tick():
            out[comp.rid] = comp
    ref = np.asarray(generate(model, params, prompts[1][None], 4,
                              temperature=0.0, seed=4))[0]
    assert np.array_equal(ref, np.array(out["v"].tokens))
    assert out["v"].preempted == 1
    assert sched.shared_block_fraction > 0.0


def test_prefix_cache_requires_single_prefill_lane(tiny_llama_f32):
    cfg, model, params, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4, prefill_batch=2)
    eng = DecodeEngine(model, params, ecfg)
    with pytest.raises(ValueError, match="prefill_batch"):
        Scheduler(eng, prefix_cache=True)


# ---------------------------------------------------------------------------
# Audit pricing
# ---------------------------------------------------------------------------


def test_shared_prefix_plan_prices_saved_pool_bytes(tiny_llama_f32):
    from ray_lightning_tpu.serve.audit import shared_prefix_plan

    cfg, _, _, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    plan = shared_prefix_plan(cfg, ecfg, n_streams=8, prefix_tokens=16)
    assert plan["shared_full_blocks"] == 16 // 4
    # n-1 streams skip the prefix: bytes and prefill tokens both scale
    assert plan["shared_pool_bytes_saved"] == (
        7 * plan["shared_full_blocks"] * plan["block_bytes"])
    assert plan["prefill_tokens_saved"] == 7 * 16
    assert (plan["pool_bytes_without_sharing"]
            - plan["pool_bytes_with_sharing"]
            == plan["shared_pool_bytes_saved"])
    with pytest.raises(ValueError, match="n_streams"):
        shared_prefix_plan(cfg, ecfg, n_streams=0)
