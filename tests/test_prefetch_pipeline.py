"""Device prefetch pipeline (pipeline/prefetch.py + the trainer's hot
loop): ordering, backpressure, shutdown, error propagation, mid-epoch
resume interaction, and bitwise parity against the synchronous path."""
import threading
import time

import jax
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ModelCheckpoint, SingleDevice, Trainer
from ray_lightning_tpu.core.data import ThrottledLoader
from ray_lightning_tpu.pipeline.prefetch import (
    DevicePrefetcher,
    prefetch_to_device,
)

from tests.utils import BoringModel, random_dataset


class TestDevicePrefetcher:
    def test_preserves_order(self):
        pf = DevicePrefetcher(range(50), lambda x: x * 10, depth=3)
        assert list(pf) == [x * 10 for x in range(50)]
        assert pf.stats.batches == 50

    def test_backpressure_bounds_lookahead(self):
        produced = []

        def place(x):
            produced.append(x)
            return x

        pf = DevicePrefetcher(range(100), place, depth=2)
        try:
            # give the producer time to run ahead as far as it can
            deadline = time.time() + 2.0
            while len(produced) < 3 and time.time() < deadline:
                time.sleep(0.01)
            # nothing consumed yet: at most depth buffered + 1 in hand
            assert len(produced) <= 3, produced
            assert next(pf) == 0
            time.sleep(0.1)
            assert len(produced) <= 4, produced
        finally:
            pf.close()

    def test_shutdown_mid_stream_joins_producer(self):
        pf = DevicePrefetcher(range(1000), lambda x: x, depth=2)
        assert next(pf) == 0
        pf.close()
        assert not pf._thread.is_alive()
        with pytest.raises(StopIteration):
            next(pf)
        pf.close()  # idempotent

    def test_exhaustion_joins_producer(self):
        pf = DevicePrefetcher(range(3), lambda x: x, depth=2)
        assert list(pf) == [0, 1, 2]
        pf._thread.join(timeout=5.0)
        assert not pf._thread.is_alive()

    def test_producer_error_reraised_at_consumer(self):
        def place(x):
            if x == 3:
                raise ValueError("bad batch 3")
            return x

        pf = DevicePrefetcher(range(10), place, depth=2)
        got = []
        with pytest.raises(ValueError, match="bad batch 3"):
            for item in pf:
                got.append(item)
        assert got == [0, 1, 2]
        assert not pf._thread.is_alive()

    def test_occupancy_with_slow_consumer(self):
        pf = DevicePrefetcher(range(20), lambda x: x, depth=2)
        out = []
        for item in pf:
            out.append(item)
            time.sleep(0.005)  # consumer slower than producer
        assert out == list(range(20))
        assert pf.stats.occupancy > 0.5

    def test_depth_zero_is_synchronous(self):
        calls = []
        gen = prefetch_to_device(range(5), lambda x: calls.append(x) or x,
                                 depth=0)
        assert not isinstance(gen, DevicePrefetcher)
        assert calls == []  # lazy: nothing placed until consumed
        assert next(iter(gen)) == 0
        assert calls == [0]

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            DevicePrefetcher(range(5), lambda x: x, depth=0)


def _fit_boring(tmp_path, *, prefetch, seed=11, max_epochs=2,
                callbacks=None, ckpt_path=None, max_steps=-1,
                warm_start=True, data=None):
    data = data if data is not None else random_dataset(n=192)
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=max_epochs, max_steps=max_steps,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        enable_progress_bar=False, seed=seed, callbacks=callbacks,
        prefetch_to_device=prefetch, warm_start=warm_start,
    )
    module = BoringModel()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32), ckpt_path=ckpt_path)
    return trainer, module


class TestTrainerIntegration:
    def test_bitwise_parity_prefetch_vs_sync(self, tmp_path):
        """The prefetcher reorders WORK, never data: training with the
        pipeline on must produce bit-identical parameters."""
        _, m_pre = _fit_boring(tmp_path / "a", prefetch=3)
        _, m_sync = _fit_boring(tmp_path / "b", prefetch=0,
                                warm_start=False)
        for a, b in zip(jax.tree.leaves(jax.device_get(m_pre.params)),
                        jax.tree.leaves(jax.device_get(m_sync.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_occupancy_metric_with_slow_consumer(self, tmp_path):
        """When the consumer side is the bottleneck (per-step host work
        dwarfing the loader delay), the pipeline must demonstrably run
        ahead: occupancy > 0 and the metrics land in callback_metrics.
        (A loader slower than the step correctly reads occupancy ~0 —
        no pipeline can conjure batches faster than the source.)"""
        from ray_lightning_tpu.core.callbacks import Callback

        class _SlowConsumer(Callback):
            def on_train_batch_end(self, trainer, module, metrics,
                                   batch_idx):
                time.sleep(0.01)

        data = random_dataset(n=192)
        loader = ThrottledLoader(DataLoader(data, batch_size=32), 0.002)
        trainer = Trainer(
            strategy=SingleDevice(), max_epochs=2,
            default_root_dir=str(tmp_path), enable_checkpointing=False,
            enable_progress_bar=False, log_every_n_steps=1,
            prefetch_to_device=2, callbacks=[_SlowConsumer()],
        )
        trainer.fit(BoringModel(), loader)
        assert trainer.callback_metrics["prefetch_occupancy"] > 0.0
        assert trainer.callback_metrics["prefetch_batches"] > 0

    def test_mid_epoch_resume_with_prefetch(self, tmp_path):
        """A mid-epoch checkpoint + resume with the prefetcher on must
        replay the rest of the epoch exactly (no batch twice, none
        skipped — the prefetcher's read-ahead must not disturb the
        resume offset): final params bitwise-equal to an uninterrupted
        run."""
        data = random_dataset(n=192)  # 6 batches/epoch at bs=32
        _, m_full = _fit_boring(tmp_path / "full", prefetch=2, data=data)

        cb = ModelCheckpoint(dirpath=str(tmp_path / "ck"),
                             every_n_train_steps=4, save_top_k=-1)
        _fit_boring(tmp_path / "head", prefetch=2, callbacks=[cb],
                    max_steps=4, max_epochs=2, data=data)
        assert cb.best_model_path

        _, m_resumed = _fit_boring(tmp_path / "tail", prefetch=2,
                                   ckpt_path=cb.best_model_path, data=data)
        for a, b in zip(jax.tree.leaves(jax.device_get(m_full.params)),
                        jax.tree.leaves(jax.device_get(m_resumed.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_early_stop_leaves_no_threads(self, tmp_path):
        """max_steps inside an epoch exits through the prefetcher's
        finally-close; no producer thread may outlive the fit."""
        before = {t.ident for t in threading.enumerate()}
        _fit_boring(tmp_path, prefetch=2, max_steps=2, max_epochs=5)
        time.sleep(0.1)
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before and "rlt-prefetch" in t.name
                  and t.is_alive()]
        assert not leaked, leaked
