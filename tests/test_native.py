"""Native C++ batcher tests: build, exact parity with the numpy path,
prefetch-through-DataLoader training."""
from __future__ import annotations

import numpy as np
import pytest

from ray_lightning_tpu import DataLoader
from ray_lightning_tpu.native import NativeBatcher, available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def _data(n=100, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.standard_normal((n, dim)).astype(np.float32),
        "y": rng.integers(0, 5, n).astype(np.int32),
    }


def test_native_matches_numpy_batches():
    data = _data()
    order = np.random.default_rng(1).permutation(100)
    b = NativeBatcher(data, batch_size=16)
    b.set_epoch(order)
    native = list(b)
    assert len(native) == 100 // 16
    for i, batch in enumerate(native):
        take = order[i * 16:(i + 1) * 16]
        np.testing.assert_array_equal(batch["x"], data["x"][take])
        np.testing.assert_array_equal(batch["y"], data["y"][take])
    b.close()


def test_native_partial_tail_and_epochs():
    data = _data(n=20)
    b = NativeBatcher(data, batch_size=8, drop_last=False)
    for _ in range(3):  # multiple epochs through the same batcher
        b.set_epoch(np.arange(20))
        batches = list(b)
        assert [len(x["y"]) for x in batches] == [8, 8, 4]
        np.testing.assert_array_equal(batches[2]["y"], data["y"][16:])
    b.close()


def test_native_zero_copy_mode():
    data = _data(n=32)
    b = NativeBatcher(data, batch_size=8, zero_copy=True)
    b.set_epoch(np.arange(32))
    seen = []
    for batch in b:
        seen.append(batch["y"].copy())  # views die on the next pull
    np.testing.assert_array_equal(np.concatenate(seen), data["y"])
    b.close()


def test_dataloader_prefetch_parity():
    """DataLoader(prefetch=True) yields exactly the numpy path's batches
    (same shuffle order, same shards)."""
    data = _data(n=64)
    plain = DataLoader(data, batch_size=16, shuffle=True, seed=3)
    fast = DataLoader(data, batch_size=16, shuffle=True, seed=3,
                      prefetch=True)
    for epoch in range(2):
        plain.set_epoch(epoch)
        fast.set_epoch(epoch)
        for a, b in zip(plain, fast):
            np.testing.assert_array_equal(a["x"], b["x"])
            np.testing.assert_array_equal(a["y"], b["y"])


def test_trainer_with_prefetch(devices8, tmp_path):
    from ray_lightning_tpu import DataParallel, Trainer

    from tests.utils import BoringModel, random_dataset

    data = random_dataset(n=128)
    module = BoringModel()
    trainer = Trainer(
        strategy=DataParallel(num_workers=8, devices=devices8),
        max_epochs=2, default_root_dir=str(tmp_path),
        enable_checkpointing=False, enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=32, shuffle=True,
                                   prefetch=True))
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
