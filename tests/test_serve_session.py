"""Dynamic-session tests for the driver→worker request channel
(serve/channel.py, docs/SERVING.md "the request channel"): host-only
command-log semantics fast (seqs, epochs, torn tails, the deferred-send
epoch guard), then — slow, real processes — a 2-process TP=2 replica
streaming bitwise against single-process `generate()` and the
mid-stream SIGKILL drill respawning the WHOLE replica group with
bitwise replay."""
from __future__ import annotations

import time

import jax
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import generate
from ray_lightning_tpu.serve.channel import (
    ChannelReader,
    ChannelWriter,
    channel_dir,
    epoch_path,
    request_from_wire,
    request_to_wire,
)
from ray_lightning_tpu.serve.driver import (
    ReplicaGroupConfig,
    ServeDriver,
    save_params_npz,
)
from ray_lightning_tpu.serve.engine import EngineConfig
from ray_lightning_tpu.serve.scheduler import Request

# ---- host-only channel semantics ------------------------------------------


def test_channel_seqs_monotonic_and_acked_batchwise(tmp_path):
    w = ChannelWriter(tmp_path, 0)
    r = ChannelReader(tmp_path, 0, 0)
    assert r.poll() == []          # racing the first send: empty, not err
    s1 = w.send("submit", req={"rid": "a"})
    s2 = w.send("drain")
    assert (s1, s2) == (1, 2) == (s1, w.last_seq)
    cmds = r.poll()
    assert [c["op"] for c in cmds] == ["submit", "drain"]
    assert r.last_seq == 2         # ONE highest-seq ack per poll batch
    assert r.poll() == []


def test_channel_torn_tail_reads_as_nothing_new(tmp_path):
    w = ChannelWriter(tmp_path, 0)
    w.send("submit", req={"rid": "a"})
    path = epoch_path(tmp_path, 0, 0)
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"seq": 2, "op": "dr')   # a torn mid-write line
    r = ChannelReader(tmp_path, 0, 0)
    assert [c["seq"] for c in r.poll()] == [1]
    with open(path, "a", encoding="utf-8") as f:
        f.write('ain"}\n')                # the write completes
    assert [c["op"] for c in r.poll()] == ["drain"]


def test_channel_replay_safety_across_respawn(tmp_path):
    """The respawn seam: begin_epoch seals the log and pre-populates the
    next one with the unfinished assignment — a fresh reader at the new
    epoch sees exactly the replay, seqs keep counting (never reused),
    and the dead epoch's file is left intact for postmortems."""
    w = ChannelWriter(tmp_path, 3)
    for rid in ("a", "b", "c"):
        w.send("submit", req={"rid": rid})
    ChannelReader(tmp_path, 3, 0).poll()    # the doomed worker read these
    epoch = w.begin_epoch([{"op": "submit", "req": {"rid": "b"}},
                           {"op": "submit", "req": {"rid": "c"}},
                           {"op": "drain"}])
    assert epoch == w.epoch == 1
    fresh = ChannelReader(tmp_path, 3, 1)
    cmds = fresh.poll()
    assert [c["op"] for c in cmds] == ["submit", "submit", "drain"]
    assert [c["seq"] for c in cmds] == [4, 5, 6]
    # post-respawn commands keep flowing on the same log
    w.send("stop", mode="finish")
    assert [c["seq"] for c in fresh.poll()] == [7]
    assert epoch_path(tmp_path, 3, 0).exists()
    assert sorted(p.name for p in channel_dir(tmp_path, 3).iterdir()) \
        == ["epoch0.jsonl", "epoch1.jsonl"]


def test_channel_send_at_drops_on_epoch_roll(tmp_path):
    """The deferred-send guard: a send decided against an epoch that
    rolled underneath (replica respawned between the driver's locked
    decision and the append) is dropped — the new epoch's replay
    already carries it, appending again would duplicate the stream."""
    w = ChannelWriter(tmp_path, 0)
    assert w.send_at(0, "submit", req={"rid": "a"}) == 1
    w.begin_epoch([{"op": "submit", "req": {"rid": "a"}}])
    assert w.send_at(0, "submit", req={"rid": "a"}) is None   # stale
    assert w.send_at(1, "drain") == 3                         # current
    cmds = ChannelReader(tmp_path, 0, 1).poll()
    assert [(c["seq"], c["op"]) for c in cmds] \
        == [(2, "submit"), (3, "drain")]


def test_channel_follower_take_upto_buffers_newer(tmp_path):
    """A follower consumes exactly the leader's journaled prefix,
    buffering newer commands for the next lockstep iteration."""
    w = ChannelWriter(tmp_path, 0)
    for rid in ("a", "b", "c"):
        w.send("submit", req={"rid": rid})
    r = ChannelReader(tmp_path, 0, 0)
    assert [c["seq"] for c in r.take_upto(2)] == [1, 2]
    assert r.last_seq == 2
    assert [c["seq"] for c in r.take_upto(3)] == [3]
    assert r.take_upto(3) == []


def test_request_wire_roundtrip():
    req = Request(rid="r7", prompt=np.arange(5, dtype=np.int32),
                  max_new_tokens=6, temperature=0.6, top_k=3, seed=12,
                  eos_id=2, arrival=1.25)
    back = request_from_wire(request_to_wire(req))
    assert back.rid == req.rid and back.seed == req.seed
    assert back.temperature == req.temperature
    assert back.top_k == req.top_k and back.eos_id == req.eos_id
    np.testing.assert_array_equal(np.asarray(back.prompt),
                                  np.asarray(req.prompt))


# ---- real-process sessions (slow) -----------------------------------------

ECFG = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                    prefill_chunk=4)


@pytest.fixture(scope="module")
def setup(tiny_llama_f32):
    cfg, model, params, _ = tiny_llama_f32
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(80 + i), (1, 3 + (i % 4)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(6)
    ]
    return cfg, model, params, prompts


def _requests(prompts, max_new=8):
    return [Request(rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
                    temperature=0.6 if i % 2 else 0.0,
                    top_k=3 if i % 2 else None, seed=9 + i)
            for i, p in enumerate(prompts)]


def _refs(model, params, prompts, reqs):
    return {r.rid: np.asarray(generate(
        model, params, prompts[i], r.max_new_tokens,
        temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)}


def _session_cfg(tmp_path, **over):
    kw = dict(n_replicas=1, backend="process", engine=ECFG,
              run_dir=str(tmp_path / "run"),
              compile_cache_dir=str(tmp_path / "cc"),
              platform="cpu", cpu_devices_per_rank=1,
              env={"JAX_PLATFORMS": "cpu"}, max_restarts=2,
              metrics_flush_every_n_ticks=2)
    kw.update(over)
    return ReplicaGroupConfig(**kw)


def _drive(drv, reqs):
    for req in reqs:
        drv.submit(req)
    while drv.busy():
        drv.tick()
        time.sleep(0.01)
    return drv.stop()


@pytest.mark.slow
def test_session_tp2_streams_bitwise_and_compiles_once(setup, tmp_path):
    """A 2-process TP=2 replica (one WorkerGroup over its own tensor
    mesh, scheduler in lockstep off the request channel) streams every
    request token-for-token bitwise against single-process `generate()`
    — and the whole churn compiles the SPMD step exactly once."""
    cfg, model, params, prompts = setup
    reqs = _requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    pp = str(tmp_path / "params.npz")
    save_params_npz(params, pp)
    drv = ServeDriver(cfg, pp, _session_cfg(tmp_path, tp=2))
    drv.start()
    res = _drive(drv, reqs)
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(res.outputs[rid]), ref,
                                      err_msg=rid)
    assert res.stats["compile_count"] == 1
    assert res.restarts == {0: 0}
    assert len(res.meta) == len(reqs)


@pytest.mark.slow
def test_session_kill_respawns_whole_group_and_replays(setup, tmp_path):
    """Mid-stream leader SIGKILL on a TP=2 replica: the death classifies
    retryable (resilience.policy), the WHOLE worker group respawns on a
    fresh channel epoch, the epoch replay re-serves the unfinished
    assignment, and every stream still matches `generate()` bitwise."""
    cfg, model, params, prompts = setup
    reqs = _requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    pp = str(tmp_path / "params.npz")
    save_params_npz(params, pp)
    drv = ServeDriver(cfg, pp, _session_cfg(tmp_path, tp=2))
    drv.start(fault={"replica": 0, "kill_after_tokens": 10})
    res = _drive(drv, reqs)
    assert res.restarts[0] >= 1, "kill did not trigger a respawn"
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(res.outputs[rid]), ref,
                                      err_msg=rid)
    # the respawn rolled the command log to a fresh epoch
    session_dir = str(tmp_path / "run")
    epochs = sorted(p.name for p in channel_dir(session_dir, 0).iterdir())
    assert "epoch1.jsonl" in epochs
