"""KV-cache generation tests: the cached decode path must reproduce
full-forward greedy decoding token for token."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
    generate,
    init_cache,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    model = Llama(cfg)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (2, 8), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(model.init)(jax.random.key(1), tokens)["params"]
    return cfg, model, params, tokens


def _greedy_nocache(model, params, prompt, n):
    """Reference: full forward over the growing sequence each step."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, toks)
        nxt = np.asarray(logits[:, -1, :].argmax(-1), dtype=np.int32)
        out.append(nxt)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_cached_decode_matches_full_forward(tiny):
    cfg, model, params, prompt = tiny
    ref = _greedy_nocache(model, params, prompt, 6)
    out = np.asarray(generate(model, params, prompt, 6, temperature=0.0))
    np.testing.assert_array_equal(out, ref)


def test_prefill_logits_match_plain_forward(tiny):
    cfg, model, params, prompt = tiny
    plain = model.apply({"params": params}, prompt)
    cache = init_cache(cfg, prompt.shape[0], 16)
    cached, new_cache = model.apply({"params": params}, prompt,
                                    cache=cache, pos=0)
    np.testing.assert_allclose(np.asarray(cached), np.asarray(plain),
                               atol=1e-4, rtol=1e-4)
    # the cache really holds S0 entries per layer
    assert new_cache[0].shape == (cfg.n_layers, 2, 16, cfg.n_kv_heads,
                                  cfg.head_dim)
    assert not np.allclose(np.asarray(new_cache[0][:, :, :8]), 0.0)
    assert np.allclose(np.asarray(new_cache[0][:, :, 8:]), 0.0)


def test_sampling_modes_and_bounds(tiny):
    cfg, model, params, prompt = tiny
    out = np.asarray(generate(model, params, prompt, 4, temperature=0.8,
                              top_k=8, seed=3))
    assert out.shape == (2, 4)
    assert out.dtype == np.int32
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, cfg.max_seq_len)


def test_module_level_generate(tiny):
    cfg, model, params, prompt = tiny
    module = LlamaModule(cfg)
    module.setup()
    module.params = params
    out = module.generate(prompt, 3)
    assert np.asarray(out).shape == (2, 3)


@pytest.mark.slow  # second full decode compile; scan-variant stays non-slow
def test_generate_nonscan_layers():
    """The per-layer (non-scan) code path decodes identically too."""
    import dataclasses

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (1, 6), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(Llama(cfg).init)(jax.random.key(1), tokens)["params"]
    # same weights restacked for the unscanned module layout
    ns_cfg = dataclasses.replace(cfg, scan_layers=False)
    ns_params = dict(params)
    stacked = ns_params.pop("layers")
    for i in range(cfg.n_layers):
        ns_params[f"layer_{i}"] = jax.tree.map(lambda x, i=i: x[i], stacked)
    ref = _greedy_nocache(Llama(ns_cfg), ns_params, tokens, 4)
    out = np.asarray(generate(Llama(ns_cfg), ns_params, tokens, 4))
    np.testing.assert_array_equal(out, ref)
