"""KV-cache generation tests: the cached decode path must reproduce
full-forward greedy decoding token for token."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
    generate,
    init_cache,
)


@pytest.fixture
def tiny(tiny_llama_f32):
    # the session-scope canonical build (tests/conftest.py) — same cfg,
    # same keys this fixture used to construct per-module
    return tiny_llama_f32


def _greedy_nocache(model, params, prompt, n):
    """Reference: full forward over the growing sequence each step."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, toks)
        nxt = np.asarray(logits[:, -1, :].argmax(-1), dtype=np.int32)
        out.append(nxt)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_cached_decode_matches_full_forward(tiny):
    cfg, model, params, prompt = tiny
    ref = _greedy_nocache(model, params, prompt, 6)
    out = np.asarray(generate(model, params, prompt, 6, temperature=0.0))
    np.testing.assert_array_equal(out, ref)


def test_prefill_logits_match_plain_forward(tiny):
    cfg, model, params, prompt = tiny
    plain = model.apply({"params": params}, prompt)
    cache = init_cache(cfg, prompt.shape[0], 16)
    cached, new_cache = model.apply({"params": params}, prompt,
                                    cache=cache, pos=0)
    np.testing.assert_allclose(np.asarray(cached), np.asarray(plain),
                               atol=1e-4, rtol=1e-4)
    # the cache really holds S0 entries per layer
    assert new_cache[0].shape == (cfg.n_layers, 2, 16, cfg.n_kv_heads,
                                  cfg.head_dim)
    assert not np.allclose(np.asarray(new_cache[0][:, :, :8]), 0.0)
    assert np.allclose(np.asarray(new_cache[0][:, :, 8:]), 0.0)


def test_sampling_modes_and_bounds(tiny):
    cfg, model, params, prompt = tiny
    out = np.asarray(generate(model, params, prompt, 4, temperature=0.8,
                              top_k=8, seed=3))
    assert out.shape == (2, 4)
    assert out.dtype == np.int32
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, cfg.max_seq_len)


def test_module_level_generate(tiny):
    cfg, model, params, prompt = tiny
    module = LlamaModule(cfg)
    module.setup()
    module.params = params
    out = module.generate(prompt, 3)
    assert np.asarray(out).shape == (2, 3)


# ---- sampling path (ISSUE 8 satellite): the serving engine's
# ---- single-stream reference behaviors --------------------------------


def test_sampling_same_key_same_tokens(tiny):
    """Seeded sampling is reproducible: same key => same tokens; a
    different key (very probably) differs."""
    cfg, model, params, prompt = tiny
    a = np.asarray(generate(model, params, prompt, 8, temperature=0.9,
                            top_k=8, seed=5))
    b = np.asarray(generate(model, params, prompt, 8, temperature=0.9,
                            top_k=8, seed=5))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(generate(model, params, prompt, 8, temperature=0.9,
                            top_k=8, seed=6))
    assert not np.array_equal(a, c)


def test_top_k_one_is_greedy(tiny):
    """top_k=1 collapses sampling to argmax regardless of temperature
    or seed — pins the threshold-filter semantics."""
    cfg, model, params, prompt = tiny
    greedy = np.asarray(generate(model, params, prompt, 6))
    for seed in (0, 9):
        sampled = np.asarray(generate(model, params, prompt, 6,
                                      temperature=1.3, top_k=1,
                                      seed=seed))
        np.testing.assert_array_equal(sampled, greedy)


def test_donated_cache_and_cache_len(tiny):
    """The cache is donated through the decode program and its length
    is an explicit knob: any cache_len >= prompt + max_new decodes
    identically (the tail is masked context no query ever sees)."""
    cfg, model, params, prompt = tiny
    base = np.asarray(generate(model, params, prompt, 6))
    padded = np.asarray(generate(model, params, prompt, 6,
                                 cache_len=prompt.shape[1] + 6 + 9))
    np.testing.assert_array_equal(padded, base)
    with pytest.raises(ValueError, match="cache_len"):
        generate(model, params, prompt, 6, cache_len=7)


# ---- ragged left-padded prefill (ISSUE 8 satellite) -------------------


def test_left_padded_ragged_batch_matches_unpadded(tiny):
    """A left-padded ragged batch decodes row-for-row exactly like each
    unpadded prompt on its own — the batched-prefill reference the
    serving engine is validated against."""
    cfg, model, params, _ = tiny
    lens = [3, 8, 5]
    prompts = [np.asarray(jax.random.randint(
        jax.random.key(70 + i), (l,), 0, cfg.vocab_size), dtype=np.int32)
        for i, l in enumerate(lens)]
    s0 = max(lens)
    padded = np.zeros((3, s0), np.int32)
    for i, p in enumerate(prompts):
        padded[i, s0 - len(p):] = p
    out = np.asarray(generate(model, params, jnp.asarray(padded), 5,
                              prompt_lengths=lens))
    for i, p in enumerate(prompts):
        ref = np.asarray(generate(model, params, p[None], 5))[0]
        np.testing.assert_array_equal(out[i], ref, err_msg=f"row {i}")


def test_left_padded_full_length_row_matches_plain(tiny):
    """A row with zero padding through the padded program equals the
    plain unpadded program — the pad machinery is inert at pad=0."""
    cfg, model, params, prompt = tiny
    out = np.asarray(generate(model, params, prompt, 5,
                              prompt_lengths=[prompt.shape[1]] * 2))
    ref = np.asarray(generate(model, params, prompt, 5))
    np.testing.assert_array_equal(out, ref)


def test_prompt_lengths_shape_validated(tiny):
    cfg, model, params, prompt = tiny
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(model, params, prompt, 4, prompt_lengths=[3])
    # out-of-range lengths would silently shift RoPE positions
    with pytest.raises(ValueError, match="within"):
        generate(model, params, prompt, 4,
                 prompt_lengths=[prompt.shape[1] + 1, 2])
    with pytest.raises(ValueError, match="within"):
        generate(model, params, prompt, 4, prompt_lengths=[0, 2])


@pytest.mark.slow  # second full decode compile; scan-variant stays non-slow
def test_generate_nonscan_layers():
    """The per-layer (non-scan) code path decodes identically too."""
    import dataclasses

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (1, 6), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(Llama(cfg).init)(jax.random.key(1), tokens)["params"]
    # same weights restacked for the unscanned module layout
    ns_cfg = dataclasses.replace(cfg, scan_layers=False)
    ns_params = dict(params)
    stacked = ns_params.pop("layers")
    for i in range(cfg.n_layers):
        ns_params[f"layer_{i}"] = jax.tree.map(lambda x, i=i: x[i], stacked)
    ref = _greedy_nocache(Llama(ns_cfg), ns_params, tokens, 4)
    out = np.asarray(generate(Llama(ns_cfg), ns_params, tokens, 4))
    np.testing.assert_array_equal(out, ref)
