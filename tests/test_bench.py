"""bench.py contract tests: structured failure JSON and backend retry.

Round-4 postmortem (VERDICT r4 weak #1): the TPU backend was unavailable
when the driver ran the bench, ``jax.devices()`` raised a raw
``JaxRuntimeError: UNAVAILABLE`` traceback, and the round shipped zero
perf evidence. The contract under test: EVERY failure mode — hang
(watchdog), backend-init exception, mid-run OOM — surfaces as ONE
parseable JSON line with an "error" field (exit 3), never a bare
traceback.
"""
import json

import pytest

import bench


def test_backend_retry_recovers_from_transient_failure(monkeypatch):
    """Transient backend-init failures (flaky tunnel) are retried with
    backoff; the device comes back on a later attempt."""
    import jax

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: tunnel mid-wedge")
        return ["fake-device"]

    monkeypatch.setattr(jax, "devices", flaky)
    dev = bench._backend_with_retry(tries=4, base_backoff=0.01)
    assert dev == "fake-device"
    assert calls["n"] == 3


def test_backend_retry_env_knobs(monkeypatch):
    """RLT_BENCH_INIT_RETRIES/BACKOFF_S size the retry loop (the driver
    box needs long patience; tests need short); malformed values fall
    back to defaults rather than crashing the error path itself."""
    import jax

    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise RuntimeError("UNAVAILABLE")

    monkeypatch.setattr(jax, "devices", dead)
    monkeypatch.setenv("RLT_BENCH_INIT_RETRIES", "2")
    monkeypatch.setenv("RLT_BENCH_INIT_BACKOFF_S", "0.01")
    with pytest.raises(RuntimeError, match="after 2 attempts"):
        bench._backend_with_retry()
    assert calls["n"] == 2
    assert bench._env_float("RLT_BENCH_INIT_BACKOFF_S", 9.0) == 0.01
    monkeypatch.setenv("RLT_BENCH_INIT_BACKOFF_S", "junk")
    assert bench._env_float("RLT_BENCH_INIT_BACKOFF_S", 9.0) == 9.0


def test_backend_init_failure_emits_structured_error(monkeypatch, capsys):
    """main() on an unavailable backend: exit 3 and ONE JSON line with
    an 'error' naming the exception — the watchdog guards hangs, this
    guards exceptions (the round-4 failure mode)."""

    def unavailable():
        raise RuntimeError("UNAVAILABLE: device tunnel down")

    monkeypatch.setattr(bench, "_backend_with_retry", unavailable)
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")  # isolate this path
    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 3
    line = capsys.readouterr().out.strip().splitlines()[-1]
    obj = json.loads(line)
    assert obj["value"] == 0.0
    assert "UNAVAILABLE" in obj["error"]
    assert obj["metric"] == "llama_0.5b_train_tokens_per_sec_per_chip"


def test_mid_run_exception_emits_structured_error(monkeypatch, capsys):
    """An exception AFTER backend init (compile failure, OOM) takes the
    same structured path — not only init errors."""
    monkeypatch.setattr(bench, "_backend_with_retry",
                        lambda: type("D", (), {"device_kind": "fake"})())
    monkeypatch.setattr(bench, "_probe_matmul_tflops",
                        lambda: (_ for _ in ()).throw(
                            MemoryError("RESOURCE_EXHAUSTED: hbm")))
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")
    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 3
    obj = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "RESOURCE_EXHAUSTED" in obj["error"]


def test_verify_kernels_passes_on_cpu():
    """The on-chip kernel-parity gate also holds in CPU interpret mode
    (the same kernel code); errors are reported per check."""
    out = bench._verify_kernels()
    assert out["kernels_verified"] is True, out
    assert set(out["kernel_errors"]) == {
        "flash_fwd", "flash_bwd", "fused_ce_loss", "fused_ce_grad",
        "inline_ce_loss", "inline_ce_grad"}
