"""bench.py contract tests: structured failure JSON and backend retry.

Round-4 postmortem (VERDICT r4 weak #1): the TPU backend was unavailable
when the driver ran the bench, ``jax.devices()`` raised a raw
``JaxRuntimeError: UNAVAILABLE`` traceback, and the round shipped zero
perf evidence. The contract under test: EVERY failure mode — hang
(watchdog), backend-init exception, mid-run OOM — surfaces as ONE
parseable JSON line with an "error" field (exit 3), never a bare
traceback.
"""
import json

import pytest

import bench


def test_backend_retry_recovers_from_transient_failure(monkeypatch):
    """Transient backend-init failures (flaky tunnel) are retried with
    backoff; the device comes back on a later attempt."""
    import jax

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: tunnel mid-wedge")
        return ["fake-device"]

    monkeypatch.setattr(jax, "devices", flaky)
    dev = bench._backend_with_retry(tries=4, base_backoff=0.01)
    assert dev == "fake-device"
    assert calls["n"] == 3


def test_backend_retry_env_knobs(monkeypatch):
    """RLT_BENCH_INIT_RETRIES/BACKOFF_S size the retry loop (the driver
    box needs long patience; tests need short); malformed values fall
    back to defaults rather than crashing the error path itself."""
    import jax

    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise RuntimeError("UNAVAILABLE")

    monkeypatch.setattr(jax, "devices", dead)
    monkeypatch.setenv("RLT_BENCH_INIT_RETRIES", "2")
    monkeypatch.setenv("RLT_BENCH_INIT_BACKOFF_S", "0.01")
    with pytest.raises(RuntimeError, match="after 2 attempts"):
        bench._backend_with_retry()
    assert calls["n"] == 2
    assert bench._env_float("RLT_BENCH_INIT_BACKOFF_S", 9.0) == 0.01
    monkeypatch.setenv("RLT_BENCH_INIT_BACKOFF_S", "junk")
    assert bench._env_float("RLT_BENCH_INIT_BACKOFF_S", 9.0) == 9.0


def test_backend_retry_wall_clock_cap(monkeypatch):
    """RLT_BENCH_MAX_WAIT caps the retry loop's TOTAL wall-clock: the
    exponential ladder alone (20+40+...+320s) outlived the harness
    timeout in round 5 (BENCH_r05 rc=124 — no JSON at all). With the cap
    the loop gives up early with a BackendUnavailable instead of
    sleeping past the budget."""
    import time as _time

    import jax

    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise RuntimeError("UNAVAILABLE")

    monkeypatch.setattr(jax, "devices", dead)
    t0 = _time.monotonic()
    with pytest.raises(bench.BackendUnavailable, match="RLT_BENCH_MAX_WAIT"):
        bench._backend_with_retry(tries=50, base_backoff=0.2,
                                  max_wait_s=0.3)
    assert _time.monotonic() - t0 < 5.0
    assert calls["n"] < 50  # the cap cut the ladder short

    # env knob spells the same cap
    monkeypatch.setenv("RLT_BENCH_MAX_WAIT", "0.3")
    monkeypatch.setenv("RLT_BENCH_INIT_RETRIES", "50")
    monkeypatch.setenv("RLT_BENCH_INIT_BACKOFF_S", "0.2")
    with pytest.raises(bench.BackendUnavailable, match="exhausted"):
        bench._backend_with_retry()


def test_backend_unavailable_emits_skipped_json(monkeypatch, capsys):
    """The ISSUE-1 contract: a backend that never comes up yields ONE
    parseable JSON line carrying {"skipped": "backend unavailable"} (so
    the recorder can tell an environmental skip from a failure on
    merit), exit 3, never a hang or a bare traceback."""

    def unavailable():
        raise bench.BackendUnavailable(
            "jax backend unavailable after 6 attempts: UNAVAILABLE")

    monkeypatch.setattr(bench, "_backend_with_retry", unavailable)
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")
    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 3
    obj = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert obj["skipped"] == "backend unavailable"
    assert obj["value"] == 0.0
    assert "UNAVAILABLE" in obj["error"]
    assert obj["metric"] == "llama_0.5b_train_tokens_per_sec_per_chip"


def test_backend_init_failure_emits_structured_error(monkeypatch, capsys):
    """main() on an unavailable backend: exit 3 and ONE JSON line with
    an 'error' naming the exception — the watchdog guards hangs, this
    guards exceptions (the round-4 failure mode)."""

    def unavailable():
        raise RuntimeError("UNAVAILABLE: device tunnel down")

    monkeypatch.setattr(bench, "_backend_with_retry", unavailable)
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")  # isolate this path
    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 3
    line = capsys.readouterr().out.strip().splitlines()[-1]
    obj = json.loads(line)
    assert obj["value"] == 0.0
    assert "UNAVAILABLE" in obj["error"]
    assert obj["metric"] == "llama_0.5b_train_tokens_per_sec_per_chip"


def test_mid_run_exception_emits_structured_error(monkeypatch, capsys):
    """An exception AFTER backend init (compile failure, OOM) takes the
    same structured path — not only init errors."""
    monkeypatch.setattr(bench, "_backend_with_retry",
                        lambda: type("D", (), {"device_kind": "fake"})())
    monkeypatch.setattr(bench, "_probe_matmul_tflops",
                        lambda: (_ for _ in ()).throw(
                            MemoryError("RESOURCE_EXHAUSTED: hbm")))
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")
    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 3
    obj = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "RESOURCE_EXHAUSTED" in obj["error"]


def test_verify_kernels_passes_on_cpu():
    """The on-chip kernel-parity gate also holds in CPU interpret mode
    (the same kernel code); errors are reported per check."""
    out = bench._verify_kernels()
    assert out["kernels_verified"] is True, out
    assert set(out["kernel_errors"]) == {
        "flash_fwd", "flash_bwd", "fused_ce_loss", "fused_ce_grad",
        "inline_ce_loss", "inline_ce_grad"}


def test_secondary_leg_failure_degrades_not_fatal(monkeypatch):
    """One OOMing secondary leg must cost only its own fields
    (<leg>_error), never the headline or the other legs — the round-4
    lesson applied at leg granularity."""

    def fake_measure(use_flash, fused_ce, batch, seq, vocab=32768,
                     remat=True, scan=True, remat_policy="nothing",
                     ce_chunk_tokens=2048, ce_inline=False,
                     timing=None):
        if vocab == 128256 and not remat:
            raise MemoryError("RESOURCE_EXHAUSTED: hbm")  # the v128k leg
        cfg = bench._bench_cfg(use_flash, fused_ce, seq, vocab, remat,
                               scan, remat_policy, ce_chunk_tokens,
                               ce_inline)
        if timing is not None:
            timing.update({"wall_s": 1.2, "productive_s": 1.0,
                           "step_dt_s": 0.01})
        return 1000.0, cfg

    monkeypatch.setattr(bench, "_measure", fake_measure)
    monkeypatch.setattr(bench, "_verify_kernels",
                        lambda: {"kernels_verified": True,
                                 "kernel_errors": {}})
    monkeypatch.setattr(bench, "_probe_matmul_tflops", lambda: 1e6)
    monkeypatch.setattr(
        bench, "_backend_with_retry",
        lambda: type("D", (), {"device_kind": "fake"})())
    out = bench._run()
    assert out["value"] > 0  # headline intact
    assert "RESOURCE_EXHAUSTED" in out["v128k_error"]
    assert "v128k_mfu" not in out
    assert out["vs_baseline"] == 1.0  # baseline leg intact
    assert "flagship_mfu" in out and "flagship_rematce_mfu" in out
    assert out["probe_consistent"] is True


def test_kernel_verify_crash_degrades_not_fatal(monkeypatch):
    """A CRASHING kernel gate (raises, not just wrong numbers) reports
    kernels_verified=False + kernel_verify_error; throughput legs that
    don't use the kernel still land in the artifact."""

    def fake_measure(*a, **k):
        return 1000.0, bench._bench_cfg(True, False, 2048)

    monkeypatch.setattr(bench, "_measure", fake_measure)
    monkeypatch.setattr(
        bench, "_verify_kernels",
        lambda: (_ for _ in ()).throw(RuntimeError("pallas crashed")))
    monkeypatch.setattr(bench, "_probe_matmul_tflops", lambda: 1e6)
    monkeypatch.setattr(
        bench, "_backend_with_retry",
        lambda: type("D", (), {"device_kind": "fake"})())
    out = bench._run()
    assert out["value"] > 0
    assert out["kernels_verified"] is False
    assert "pallas crashed" in out["kernel_verify_error"]


@pytest.mark.slow  # sleeps by design: must outwait the watchdog window
def test_watchdog_fires_on_hang():
    """A hang anywhere in the run (wedged device tunnel: every op blocks
    forever) must yield the structured error JSON and exit 3 within the
    watchdog window — the documented contract for the hang mode."""
    import os
    import subprocess
    import sys

    code = (
        "import bench, time\n"
        "bench._backend_with_retry = lambda **k: time.sleep(60)\n"
        "bench.main()\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=30,
        env={**os.environ, "RLT_BENCH_WATCHDOG_S": "3"},
        cwd=repo_root,
    )
    assert p.returncode == 3, (p.returncode, p.stderr[-500:])
    obj = json.loads(p.stdout.strip().splitlines()[-1])
    assert "did not complete" in obj["error"]
    assert obj["value"] == 0.0


def test_flagship_leg_inline_fallback_reuses_rematce():
    """The flagship leg's documented degradation ladder: inline compile
    rejected -> reuse the rematce measurement (same config, no second
    compile) with the failure cause preserved; nothing to reuse ->
    re-raise so the row degrades with the REAL error."""
    class Cfg:  # _flops_per_token stand-in not needed: mfu_of is injected
        pass

    calls = []

    def ok_measure(ce_inline):
        calls.append(ce_inline)
        return 1000.0, Cfg()

    row, m = bench._flagship_leg(ok_measure, {"rematce": (900.0, 0.4)},
                                 lambda t, c: 0.5, "B=8 test-shape")
    assert row["flagship_tokens_per_sec"] == 1000.0
    assert m == 0.5
    assert "B=8 test-shape" in row["flagship_config"]
    assert "inline" in row["flagship_config"]
    assert "flagship_inline_error" not in row
    assert calls == [True]  # the rematce measurement was NOT re-run

    def failing_measure(ce_inline):
        raise RuntimeError("remote_compile HTTP 500")

    row, m = bench._flagship_leg(failing_measure, {"rematce": (900.0, 0.4)},
                                 lambda t, c: 0.5, "B=8 test-shape")
    assert row["flagship_tokens_per_sec"] == 900.0
    assert m == 0.4
    assert "fallback" in row["flagship_config"]
    assert "HTTP 500" in row["flagship_inline_error"]

    with pytest.raises(RuntimeError, match="HTTP 500"):
        bench._flagship_leg(failing_measure, {}, lambda t, c: 0.5,
                            "B=8 test-shape")


def test_trace_summary_is_parseable():
    """The tracecheck summary is computed WITHOUT any backend touch and
    carries ICI bytes + an HBM estimate against an assumed chip."""
    s = bench._trace_summary()
    assert "tracecheck" in s, s.get("tracecheck_error")
    t = s["tracecheck"]
    assert t["ici_bytes_per_step"] == 0  # one chip: nothing on the wire
    assert t["est_peak_hbm_bytes"] > 0
    assert t["hbm_budget_bytes"] > 0
    assert t["assumed_device_kind"] == "TPU v5e"
    json.dumps(s)  # must embed into the JSON line as-is


def test_kill_line_schema(monkeypatch):
    """The line a driver kill flushes: same schema as the skip lines —
    metric/value/vs_baseline present, a 'skipped' field naming the
    signal, and the tracecheck summary riding along."""
    monkeypatch.setitem(bench._ANALYSIS, "tracecheck", {"findings": 0})
    obj = json.loads(bench._kill_line("SIGTERM"))
    assert obj["metric"] == "llama_0.5b_train_tokens_per_sec_per_chip"
    assert obj["value"] == 0.0 and obj["vs_baseline"] == 0.0
    assert obj["skipped"] == "killed: SIGTERM"
    assert "SIGTERM" in obj["error"]
    assert obj["tracecheck"] == {"findings": 0}


def test_sigterm_flushes_structured_json():
    """End-to-end BENCH_r05 regression: a driver SIGTERM mid-run
    produces ONE parseable JSON line (exit 3), never `parsed: null`."""
    import os
    import signal
    import subprocess
    import sys
    import time

    code = (
        "import bench, time, sys\n"
        "bench._install_kill_handlers()\n"
        "print('READY', flush=True)\n"
        "time.sleep(60)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True, env=env,
                            cwd=repo)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 3
    obj = json.loads(out.strip().splitlines()[-1])
    assert obj["skipped"] == "killed: SIGTERM"
    assert obj["value"] == 0.0


def test_attnout_leg_fallback_and_double_failure_chaining():
    """ADVICE r5: the attn_out leg falls back to the non-inline config
    with the inline cause preserved; when the fallback ALSO fails, both
    causes must survive — folded into the raised message, inline chained
    as __cause__ — instead of the inline root cause being discarded."""
    class Cfg:
        pass

    def inline_only_fails(ce_inline):
        if ce_inline:
            raise RuntimeError("inline compile rejected")
        return 800.0, Cfg()

    row, m = bench._attnout_leg(inline_only_fails, lambda t, c: 0.3)
    assert row["flagship_attnout_tokens_per_sec"] == 800.0
    assert m == 0.3
    assert "inline compile rejected" in row["flagship_attnout_inline_error"]

    def both_fail(ce_inline):
        if ce_inline:
            raise RuntimeError("inline compile rejected")
        raise MemoryError("fallback OOM")

    with pytest.raises(RuntimeError) as ei:
        bench._attnout_leg(both_fail, lambda t, c: 0.3)
    msg = str(ei.value)
    assert "inline compile rejected" in msg  # first cause kept
    assert "fallback OOM" in msg             # second cause kept
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert "inline compile rejected" in str(ei.value.__cause__)

    def ok(ce_inline):
        return 1200.0, Cfg()

    row, m = bench._attnout_leg(ok, lambda t, c: 0.6)
    assert row["flagship_attnout_tokens_per_sec"] == 1200.0
    assert "flagship_attnout_inline_error" not in row


def test_skip_line_carries_serving_schema(monkeypatch, capsys):
    """ISSUE 8: every bench JSON line — including the backend-down skip
    — carries the serving section (schema + the flagship serve plan),
    so a round with no chip still documents what the serving leg will
    measure when one returns."""

    def unavailable():
        raise bench.BackendUnavailable("jax backend unavailable")

    monkeypatch.setattr(bench, "_backend_with_retry", unavailable)
    monkeypatch.setenv("RLT_BENCH_WATCHDOG_S", "0")
    with pytest.raises(SystemExit):
        bench.main()
    obj = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert obj["skipped"] == "backend unavailable"
    serving = obj.get("serving")
    assert serving is not None, obj.get("serving_error")
    assert set(serving["schema"]) == {
        "decode_tokens_per_s", "prefill_tokens_per_s",
        "ttft_cold_s", "ttft_warm_s", "ttft_p99_s", "slot_occupancy",
        "serving_attention_path", "serving_prefill_path",
        "serve_metrics", "scale_up_s", "autoscale",
        "shared_block_fraction", "accepted_tokens_per_step",
        "slo_attainment", "slo_attainment_latency_critical",
        "shed_fraction"}
    assert "scale_up_s" in serving["autoscale_schema"]  # ISSUE 13
    assert serving["flagship_plan"]["pool_bytes"] > 0
    # measured serving values belong to success lines only
    assert "decode_tokens_per_s" not in obj
