"""Driver-gate tests: the hooks in __graft_entry__.py must work exactly as
the external driver invokes them (fresh process, no test-harness env).

These guard the two externally-checked signals — the single-chip compile
check and the multi-chip dryrun (reference capability: multi-worker
correctness, reference ray_lightning/ray_ddp.py:257-264).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    # The driver runs the hooks without our conftest's virtual-device flags;
    # dryrun_multichip must self-provision. Strip anything the harness set.
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    # Keep it CPU in CI regardless of what hardware the box exposes.
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_dryrun_multichip_self_provisions():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK')"],
        cwd=REPO, env=_clean_env(), capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


@pytest.mark.slow
def test_entry_compiles():
    # The config re-assert mirrors the framework's platform contract
    # (tuner._trial_main / launch._spmd_main): site hooks that register
    # an accelerator backend may config.update jax_platforms at
    # interpreter start, overriding the env — this test must compile on
    # CPU, not on whatever device the box tunnels to.
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax\n"
         "try:\n"
         "    jax.config.update('jax_platforms', 'cpu')\n"
         "except Exception:\n"
         "    pass  # initialized backends win (cf. tuner._trial_main)\n"
         "import __graft_entry__ as g;"
         "fn, args = g.entry();"
         "out = jax.jit(fn)(*args);"
         "jax.block_until_ready(out); print('OK', out.shape)"],
        cwd=REPO, env=_clean_env(), capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
