"""End-to-end distributed fit over the runtime substrate: the rebuild of
the reference's main path (SURVEY §3.1) — driver ships the job, H
processes jointly train one SPMD program, rank 0's metrics/weights come
back, and the driver's module holds trained weights (reference
ray_ddp.py:178-193)."""
from functools import partial

import numpy as np
import pytest

from ray_lightning_tpu.runtime import (
    FitResult,
    fit_distributed,
    predict_distributed,
    validate_distributed,
)


def _make_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=4, lr=5e-2)


def _make_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,
        seed=0,
    )


def _make_data():
    import jax

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=256)
    x = (centers[y] + rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    train = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        shuffle=True,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    val = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    return train, val


@pytest.mark.slow
def test_fit_distributed_round_trip(tmp_path):
    module = _make_module()
    assert module.params is None
    result = fit_distributed(
        _make_module,
        _make_trainer,
        _make_data,
        num_processes=2,
        module=module,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path),
        timeout=420,
    )
    assert isinstance(result, FitResult)
    # Trained to (near-)perfect separability.
    assert result.metrics["ptl/val_accuracy"] > 0.9
    # C5: the DRIVER's module object now holds the trained weights.
    assert module.params is not None
    leaves = [np.asarray(l) for l in _tree_leaves(module.params)]
    assert all(np.isfinite(l).all() for l in leaves)
    # And they are usable for local inference.
    import jax

    jax.config.update("jax_platforms", "cpu")
    logits = module.apply(module.params, np.zeros((2, 8), np.float32))
    assert logits.shape == (2, 4)


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def _raw_data():
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=256)
    x = (centers[y] + rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    return x, y


def _make_ckpt_trainer(ckpt_dir):
    from ray_lightning_tpu import DataParallel, Trainer
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        callbacks=[ModelCheckpoint(dirpath=ckpt_dir,
                                   monitor="ptl/val_accuracy", mode="max")],
        seed=0,
    )


def _make_eval_data():
    import jax

    from ray_lightning_tpu import DataLoader

    x, y = _raw_data()
    return DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )


@pytest.mark.slow
def test_distributed_train_load_predict_matrix(tmp_path):
    """The reference's canonical matrix — train, load the checkpoint,
    predict — run through the distributed round-trip protocol over a
    2-process SPMD group (reference tests/test_ddp.py:79-113 +
    tests/utils.py:172-208 predicates)."""
    ckpt_dir = str(tmp_path / "ckpts")
    spmd = dict(
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path / "logs"),
        timeout=420,
    )
    # --- train leg: fit writes a monitored best checkpoint
    result = fit_distributed(
        _make_module, partial(_make_ckpt_trainer, ckpt_dir), _make_data,
        return_weights=False, **spmd,
    )
    assert result.best_model_path, "fit must register a best checkpoint"

    # --- load leg: a FRESH distributed job restores the checkpoint and
    # validates it (load_test predicate: the checkpoint is loadable and
    # reproduces trained quality)
    val = validate_distributed(
        _make_module, _make_trainer, _make_eval_data,
        ckpt_path=result.best_model_path, **spmd,
    )
    assert val.metrics["ptl/val_accuracy"] > 0.9

    # --- predict leg: distributed predict returns the globally-gathered
    # predictions from rank 0; accuracy >= 0.5 (predict_test predicate,
    # reference tests/utils.py:192-208)
    pred = predict_distributed(
        _make_module, _make_trainer, _make_eval_data,
        ckpt_path=result.best_model_path, **spmd,
    )
    assert pred.predictions is not None
    x, y = _raw_data()
    # unshuffled contiguous shards: batch b gathers rank0's rows
    # [16b:16b+16) and rank1's [128+16b : 128+16b+16)
    correct = total = 0
    for b, p in enumerate(pred.predictions):
        labels = np.concatenate(
            [y[16 * b: 16 * b + 16], y[128 + 16 * b: 128 + 16 * b + 16]]
        )
        assert p.shape == labels.shape
        correct += int((np.asarray(p) == labels).sum())
        total += labels.size
    assert total == 256
    assert correct / total > 0.9


# --- mid-epoch failure + resume at process scale (SURVEY §5.3's
# fail-fast + checkpoint-resume story, proven where it exists for) -------


def _make_idsum_module():
    from tests.utils import IdSumModel

    return IdSumModel(lr=1e-2)


def _idsum_rows():
    rng = np.random.default_rng(0)
    x = np.zeros((64, 8), np.float32)
    x[:, 0] = np.arange(64)  # row id in column 0
    y = rng.integers(0, 2, 64).astype(np.int32)
    return x, y


def _make_idsum_data():
    import jax

    from ray_lightning_tpu import DataLoader

    x, y = _idsum_rows()
    # unshuffled contiguous shards: 32 rows/process, local batch 8 ->
    # 4 global steps/epoch; global batch b carries ids
    # [8b..8b+8) U [32+8b..32+8b+8)
    return DataLoader(
        {"x": x, "y": y},
        batch_size=8,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )


from ray_lightning_tpu import Callback  # noqa: E402 — test-local helpers


class _StepCounter(Callback):
    """Counts batches trained in THIS run and publishes the count as a
    metric, so the driver can assert how much of the interrupted epoch
    the resumed run replayed."""

    def __init__(self):
        self.n = 0

    def on_train_batch_end(self, trainer, module, metrics, batch_idx):
        self.n += 1
        trainer.callback_metrics["steps_this_run"] = float(self.n)


class _DieAtStep(Callback):
    """Deterministic mid-epoch 'kill': raises in every worker once the
    jitted step count reaches `at` — after ModelCheckpoint's batch-end
    hook has durably written that step's checkpoint."""

    def __init__(self, at: int):
        self.at = at

    def on_train_batch_end(self, trainer, module, metrics, batch_idx):
        if trainer.global_step >= self.at:
            raise RuntimeError(f"injected mid-epoch failure at step {self.at}")


def _make_failing_trainer(ckpt_dir):
    from ray_lightning_tpu import DataParallel, ModelCheckpoint, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=1,
        enable_progress_bar=False,
        # order matters: ModelCheckpoint's batch-end hook runs (and
        # blocks on the write) BEFORE the failure fires
        callbacks=[ModelCheckpoint(dirpath=ckpt_dir, monitor=None,
                                   every_n_train_steps=1, save_top_k=-1),
                   _DieAtStep(2)],
        seed=0,
    )


def _make_resume_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=1,
        enable_progress_bar=False,
        enable_checkpointing=False,
        callbacks=[_StepCounter()],
        seed=0,
    )


@pytest.mark.slow
def test_distributed_mid_epoch_failure_then_resume(tmp_path):
    """The fail-fast + resume story at process scale (VERDICT r4 next
    #5): a 2-process SPMD fit checkpointing every step dies mid-epoch at
    step 2 of 4 — the driver gets the fail-fast WorkerError with the
    injected traceback — then a FRESH 2-process group resumes from the
    step-2 checkpoint and replays exactly the remaining 2 batches of the
    interrupted epoch (reference discipline: stateful resume,
    tests/test_ddp.py:116-132)."""
    from ray_lightning_tpu.runtime import WorkerError

    ckpt_dir = str(tmp_path / "ck")
    spmd = dict(
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        timeout=420,
    )
    with pytest.raises(WorkerError, match="injected mid-epoch failure"):
        fit_distributed(
            _make_idsum_module, partial(_make_failing_trainer, ckpt_dir),
            _make_idsum_data, log_dir=str(tmp_path / "logs_a"), **spmd,
        )
    import os

    assert sorted(os.listdir(ckpt_dir)) == ["step=1", "step=2"]

    result = fit_distributed(
        _make_idsum_module, _make_resume_trainer, _make_idsum_data,
        ckpt_path=os.path.join(ckpt_dir, "step=2"),
        log_dir=str(tmp_path / "logs_b"), return_weights=False, **spmd,
    )
    # exactly the REST of the interrupted epoch: batches 2 and 3, not a
    # restart from batch 0 and not a skip to epoch end
    assert result.metrics["steps_this_run"] == 2.0
    # the final trained batch was the epoch's LAST global batch — ids
    # 24..31 from rank 0's shard + 56..63 from rank 1's, each row once
    assert result.metrics["id_sum"] == float(
        sum(range(24, 32)) + sum(range(56, 64)))
    assert result.metrics["dup_rows"] == 0.0
