"""End-to-end distributed fit over the runtime substrate: the rebuild of
the reference's main path (SURVEY §3.1) — driver ships the job, H
processes jointly train one SPMD program, rank 0's metrics/weights come
back, and the driver's module holds trained weights (reference
ray_ddp.py:178-193)."""
import numpy as np
import pytest

from ray_lightning_tpu.runtime import FitResult, fit_distributed


def _make_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=4, lr=5e-2)


def _make_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,
        seed=0,
    )


def _make_data():
    import jax

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=256)
    x = (centers[y] + rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    train = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        shuffle=True,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    val = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    return train, val


@pytest.mark.slow
def test_fit_distributed_round_trip(tmp_path):
    module = _make_module()
    assert module.params is None
    result = fit_distributed(
        _make_module,
        _make_trainer,
        _make_data,
        num_processes=2,
        module=module,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path),
        timeout=420,
    )
    assert isinstance(result, FitResult)
    # Trained to (near-)perfect separability.
    assert result.metrics["ptl/val_accuracy"] > 0.9
    # C5: the DRIVER's module object now holds the trained weights.
    assert module.params is not None
    leaves = [np.asarray(l) for l in _tree_leaves(module.params)]
    assert all(np.isfinite(l).all() for l in leaves)
    # And they are usable for local inference.
    import jax

    jax.config.update("jax_platforms", "cpu")
    logits = module.apply(module.params, np.zeros((2, 8), np.float32))
    assert logits.shape == (2, 4)


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)
