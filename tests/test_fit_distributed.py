"""End-to-end distributed fit over the runtime substrate: the rebuild of
the reference's main path (SURVEY §3.1) — driver ships the job, H
processes jointly train one SPMD program, rank 0's metrics/weights come
back, and the driver's module holds trained weights (reference
ray_ddp.py:178-193)."""
from functools import partial

import numpy as np
import pytest

from ray_lightning_tpu.runtime import (
    FitResult,
    fit_distributed,
    predict_distributed,
    validate_distributed,
)


def _make_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=4, lr=5e-2)


def _make_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,
        seed=0,
    )


def _make_data():
    import jax

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=256)
    x = (centers[y] + rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    train = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        shuffle=True,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    val = DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    return train, val


@pytest.mark.slow
def test_fit_distributed_round_trip(tmp_path):
    module = _make_module()
    assert module.params is None
    result = fit_distributed(
        _make_module,
        _make_trainer,
        _make_data,
        num_processes=2,
        module=module,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path),
        timeout=420,
    )
    assert isinstance(result, FitResult)
    # Trained to (near-)perfect separability.
    assert result.metrics["ptl/val_accuracy"] > 0.9
    # C5: the DRIVER's module object now holds the trained weights.
    assert module.params is not None
    leaves = [np.asarray(l) for l in _tree_leaves(module.params)]
    assert all(np.isfinite(l).all() for l in leaves)
    # And they are usable for local inference.
    import jax

    jax.config.update("jax_platforms", "cpu")
    logits = module.apply(module.params, np.zeros((2, 8), np.float32))
    assert logits.shape == (2, 4)


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def _raw_data():
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=256)
    x = (centers[y] + rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    return x, y


def _make_ckpt_trainer(ckpt_dir):
    from ray_lightning_tpu import DataParallel, Trainer
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        callbacks=[ModelCheckpoint(dirpath=ckpt_dir,
                                   monitor="ptl/val_accuracy", mode="max")],
        seed=0,
    )


def _make_eval_data():
    import jax

    from ray_lightning_tpu import DataLoader

    x, y = _raw_data()
    return DataLoader(
        {"x": x, "y": y},
        batch_size=16,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )


@pytest.mark.slow
def test_distributed_train_load_predict_matrix(tmp_path):
    """The reference's canonical matrix — train, load the checkpoint,
    predict — run through the distributed round-trip protocol over a
    2-process SPMD group (reference tests/test_ddp.py:79-113 +
    tests/utils.py:172-208 predicates)."""
    ckpt_dir = str(tmp_path / "ckpts")
    spmd = dict(
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path / "logs"),
        timeout=420,
    )
    # --- train leg: fit writes a monitored best checkpoint
    result = fit_distributed(
        _make_module, partial(_make_ckpt_trainer, ckpt_dir), _make_data,
        return_weights=False, **spmd,
    )
    assert result.best_model_path, "fit must register a best checkpoint"

    # --- load leg: a FRESH distributed job restores the checkpoint and
    # validates it (load_test predicate: the checkpoint is loadable and
    # reproduces trained quality)
    val = validate_distributed(
        _make_module, _make_trainer, _make_eval_data,
        ckpt_path=result.best_model_path, **spmd,
    )
    assert val.metrics["ptl/val_accuracy"] > 0.9

    # --- predict leg: distributed predict returns the globally-gathered
    # predictions from rank 0; accuracy >= 0.5 (predict_test predicate,
    # reference tests/utils.py:192-208)
    pred = predict_distributed(
        _make_module, _make_trainer, _make_eval_data,
        ckpt_path=result.best_model_path, **spmd,
    )
    assert pred.predictions is not None
    x, y = _raw_data()
    # unshuffled contiguous shards: batch b gathers rank0's rows
    # [16b:16b+16) and rank1's [128+16b : 128+16b+16)
    correct = total = 0
    for b, p in enumerate(pred.predictions):
        labels = np.concatenate(
            [y[16 * b: 16 * b + 16], y[128 + 16 * b: 128 + 16 * b + 16]]
        )
        assert p.shape == labels.shape
        correct += int((np.asarray(p) == labels).sum())
        total += labels.size
    assert total == 256
    assert correct / total > 0.9
