"""ResNet + BERT family tests: the BASELINE.json configs 2 and 3 shapes,
trained end-to-end on the virtual mesh (reference test pattern:
train/predict predicates over strategies, reference tests/test_ddp.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, DataParallel, FSDP, Trainer
from ray_lightning_tpu.models import (
    BertClassifierModule,
    BertConfig,
    ResNetModule,
)


def synthetic_cifar(n=64, num_classes=4, seed=0):
    """Separable synthetic images: class-dependent channel means."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    base = rng.standard_normal((num_classes, 1, 1, 3)).astype(np.float32) * 3
    x = base[y] + 0.3 * rng.standard_normal((n, 16, 16, 3)).astype(np.float32)
    return {"x": x, "y": y}


def synthetic_text(n=64, num_classes=2, seq=16, vocab=256, seed=0):
    """Label determined by leading-token range — linearly separable."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    ids = rng.integers(4, vocab, (n, seq)).astype(np.int32)
    ids[:, 0] = np.where(y == 0, 1, 2)  # class token
    mask = np.ones((n, seq), np.int32)
    return {"input_ids": ids, "attention_mask": mask, "labels": y}


@pytest.mark.slow  # ResNet fwd+bwd compile dominates (~3 min on 1 core)
def test_resnet18_trains_dp(devices8, tmp_path):
    data = synthetic_cifar()
    module = ResNetModule(variant="resnet18", num_classes=4, lr=0.05,
                          total_steps=20)
    trainer = Trainer(
        strategy=DataParallel(num_workers=8, devices=devices8),
        max_epochs=5, default_root_dir=str(tmp_path),
        enable_checkpointing=False, enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=16, shuffle=True),
                DataLoader(data, batch_size=16))
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
    # separable classes: accuracy should clear the reference's 0.5 floor
    assert float(trainer.callback_metrics["val_acc"]) >= 0.5


@pytest.mark.slow  # ~100s: the deepest compile in the suite (50 conv layers)
def test_resnet50_builds_and_steps(devices8, tmp_path):
    data = synthetic_cifar(n=16)
    module = ResNetModule(variant="resnet50", num_classes=4, lr=0.01,
                          total_steps=2)
    trainer = Trainer(
        strategy=FSDP(devices=devices8, min_shard_size=1),
        max_epochs=1, limit_train_batches=1,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False, enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=8))
    assert trainer.global_step == 1
    assert module.num_params() > 2e7  # it really is a ResNet-50


@pytest.mark.slow  # ~30s compile; bert coverage stays via padding-mask test
def test_bert_finetune_dp(devices8, tmp_path):
    data = synthetic_text()
    cfg = BertConfig.tiny(use_flash=False, dropout=0.0)
    module = BertClassifierModule(cfg, num_classes=2, lr=5e-4,
                                  warmup_steps=1, total_steps=16)
    trainer = Trainer(
        strategy=DataParallel(num_workers=8, devices=devices8),
        max_epochs=4, default_root_dir=str(tmp_path),
        enable_checkpointing=False, enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=32, shuffle=True),
                DataLoader(data, batch_size=32))
    assert float(trainer.callback_metrics["val_acc"]) >= 0.5


def test_bert_padding_mask_matters(devices8):
    """Masked positions must not influence the logits."""
    cfg = BertConfig.tiny(use_flash=False, dropout=0.0)
    module = BertClassifierModule(cfg)
    module.setup()
    batch = synthetic_text(n=4, seq=8)
    params = module.init_params(jax.random.key(0), batch)

    base = np.asarray(module._forward(params, batch, deterministic=True))
    # scramble the tail AND mask it out — logits must be unchanged
    batch2 = dict(batch)
    ids = batch["input_ids"].copy()
    ids[:, 4:] = 7
    mask = batch["attention_mask"].copy()
    mask[:, 4:] = 0
    batch["input_ids"], batch["attention_mask"] = ids, mask
    masked1 = np.asarray(module._forward(params, batch, deterministic=True))
    ids2 = ids.copy()
    ids2[:, 4:] = 99
    batch2 = {"input_ids": ids2, "attention_mask": mask}
    masked2 = np.asarray(module._forward(params, batch2, deterministic=True))
    np.testing.assert_allclose(masked1, masked2, atol=1e-5)
    assert not np.allclose(base, masked1)  # masking did change vs full
