"""Shared test fixtures: canonical micro-models + assertion helpers.

Mirrors the reference's test toolkit (reference tests/utils.py):
RandomDataset (:14-23), BoringModel (:26-93), LightningMNISTClassifier
(:96-145), get_trainer (:148-169), and the train/load/predict predicates
(:172-208) — rebuilt for the functional TpuModule API.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu import (
    DataLoader,
    EarlyStopping,
    ModelCheckpoint,
    TpuModule,
    Trainer,
)


def random_dataset(n: int = 256, dim: int = 32, seed: int = 0):
    """Reference RandomDataset analog: gaussian features, 2-class labels."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, dim), dtype=np.float32)
    w = rng.standard_normal((dim, 2)).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int32)
    return {"x": x, "y": y}


class _Boring(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(2)(x)


class BoringModel(TpuModule):
    """Tiny Linear(32,2) module exercising the full hook surface
    (reference tests/utils.py:26-93)."""

    def __init__(self, lr: float = 1e-2):
        super().__init__()
        self.save_hyperparameters(lr=lr)
        self.lr = lr
        self.hook_calls: list[str] = []
        self.saved_extra = None

    def configure_model(self):
        return _Boring()

    def configure_optimizers(self):
        return optax.sgd(self.lr)

    def _loss(self, params, batch):
        logits = self.apply(params, batch["x"])
        labels = jax.nn.one_hot(batch["y"], 2)
        return optax.softmax_cross_entropy(logits, labels).mean(), logits

    def training_step(self, params, batch, rng):
        loss, _ = self._loss(params, batch)
        self.log("train_loss", loss)
        return loss

    def validation_step(self, params, batch):
        loss, logits = self._loss(params, batch)
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return {"val_loss": loss, "val_acc": acc}

    def predict_step(self, params, batch):
        return self.apply(params, batch["x"]).argmax(-1)

    # hook coverage (reference BoringModel asserts these fire)
    def on_fit_start(self, trainer):
        self.hook_calls.append("on_fit_start")

    def on_fit_end(self, trainer):
        self.hook_calls.append("on_fit_end")

    def on_train_epoch_start(self, trainer):
        self.hook_calls.append("on_train_epoch_start")

    def on_train_epoch_end(self, trainer):
        self.hook_calls.append("on_train_epoch_end")

    def on_validation_epoch_end(self, trainer, metrics):
        self.hook_calls.append("on_validation_epoch_end")

    def on_save_checkpoint(self, checkpoint):
        self.hook_calls.append("on_save_checkpoint")

    def on_load_checkpoint(self, checkpoint):
        self.hook_calls.append("on_load_checkpoint")


class IdSumModel(TpuModule):
    """Duplicated-rows detector for the forced-sharding tests: x[:, 0]
    carries the row id, and every step logs (a) `dup_rows` — the number
    of equal adjacent ids after sorting the GLOBAL batch's ids (0 iff
    every host contributed distinct rows), and (b) `id_sum` — the global
    batch's id total. The analog of the reference's worker-side
    DistributedSampler assertions (reference tests/test_ddp.py:44-76)."""

    def __init__(self, lr: float = 1e-2):
        super().__init__()
        self.save_hyperparameters(lr=lr)
        self.lr = lr

    def configure_model(self):
        return _Boring()

    def configure_optimizers(self):
        return optax.sgd(self.lr)

    def _id_metrics(self, batch):
        ids = jnp.sort(batch["x"][:, 0])
        dups = (ids[1:] == ids[:-1]).sum().astype(jnp.float32)
        return dups, ids.sum()

    def training_step(self, params, batch, rng):
        logits = self.apply(params, batch["x"])
        labels = jax.nn.one_hot(batch["y"], 2)
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        dups, id_sum = self._id_metrics(batch)
        self.log("dup_rows", dups)
        self.log("id_sum", id_sum)
        self.log("train_loss", loss)
        return loss

    def validation_step(self, params, batch):
        dups, id_sum = self._id_metrics(batch)
        return {"val_dup_rows": dups, "val_id_sum": id_sum}


class _MLP(nn.Module):
    """3-layer MLP, the reference's LightningMNISTClassifier shape
    (tests/utils.py:96-120): 128 → 256 → num_classes."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(self.num_classes)(x)


class MNISTClassifier(TpuModule):
    def __init__(self, lr: float = 1e-3, num_classes: int = 10):
        super().__init__()
        self.save_hyperparameters(lr=lr, num_classes=num_classes)
        self.lr = lr
        self.num_classes = num_classes

    def configure_model(self):
        return _MLP(self.num_classes)

    def configure_optimizers(self):
        return optax.adam(self.lr)

    def training_step(self, params, batch, rng):
        logits = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()
        self.log("ptl/train_loss", loss)
        acc = (logits.argmax(-1) == batch["y"]).mean()
        self.log("ptl/train_accuracy", acc)
        return loss

    def validation_step(self, params, batch):
        logits = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return {"ptl/val_loss": loss, "ptl/val_accuracy": acc}

    def predict_step(self, params, batch):
        return self.apply(params, batch["x"]).argmax(-1)


def synthetic_mnist(n: int = 512, seed: int = 0, num_classes: int = 10):
    """Separable synthetic stand-in for MNIST (no downloads in the sandbox):
    class-dependent means make ≥0.5 accuracy reachable in one epoch."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    centers = rng.standard_normal((num_classes, 64)).astype(np.float32) * 3.0
    x = centers[y] + rng.standard_normal((n, 64)).astype(np.float32)
    return {"x": x, "y": y}


def get_trainer(
    root_dir,
    strategy,
    max_epochs: int = 1,
    limit_train_batches: int = 10,
    limit_val_batches: int = 10,
    callbacks=None,
    checkpoint_callback: bool = True,
    **kwargs,
):
    """Reference get_trainer analog (tests/utils.py:148-169)."""
    return Trainer(
        strategy=strategy,
        max_epochs=max_epochs,
        limit_train_batches=limit_train_batches,
        limit_val_batches=limit_val_batches,
        default_root_dir=str(root_dir),
        enable_checkpointing=checkpoint_callback,
        enable_progress_bar=False,
        callbacks=callbacks,
        **kwargs,
    )


# ---- assertion predicates (reference tests/utils.py:172-208) -------------


def train_test(trainer: Trainer, module: TpuModule, data=None):
    """Train and assert parameters changed from their true initial values.

    The module is warm-started with known params (the Trainer then uses
    exactly those, not a fresh draw), so the before/after comparison is
    against the real starting point — a zero-update fit fails this assert.
    """
    data = data or random_dataset()
    train = DataLoader(data, batch_size=32, shuffle=True)
    val = DataLoader(data, batch_size=32)
    module.setup()
    module.params = module.init_params(jax.random.key(0), next(iter(train)))
    before = jax.device_get(module.params)
    trainer.fit(module, train, val)
    assert module.params is not None
    changed = jax.tree.map(
        lambda a, b: not np.allclose(np.asarray(a), np.asarray(b)),
        jax.device_get(module.params),
        before,
    )
    assert any(jax.tree.leaves(changed)), "params did not change during fit"
    return trainer


def load_test(trainer: Trainer, module_cls):
    """Assert the best checkpoint is loadable (reference :184-189)."""
    path = trainer.checkpoint_callback.best_model_path
    assert path, "no checkpoint was written"
    loaded = module_cls.load_from_checkpoint(path)
    assert loaded.params is not None
    return loaded


def predict_test(trainer: Trainer, module: TpuModule, data=None):
    """Assert accuracy ≥ 0.5 (reference :192-208)."""
    data = data or synthetic_mnist()
    loader = DataLoader(data, batch_size=32)
    preds = trainer.predict(module, loader)
    y_all = np.concatenate([np.asarray(p) for p in preds])
    n = len(y_all)
    acc = float((y_all == data["y"][:n]).mean())
    assert acc >= 0.5, f"accuracy {acc} < 0.5"
    return acc
