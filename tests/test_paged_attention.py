"""Fused paged-attention decode kernel (ISSUE 11): op-level parity
matrix (pallas interpret mode vs the gathering XLA reference), dispatch
predicate honesty, the engine's fused lane (streams vs the reference
lane, churn compile pin), the batched left-padded prefill lane (bitwise
vs generate), the decode-step audit on BOTH paths incl. RLT307, and the
fused-aware serve plan / bench / bench_gate legs."""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, LlamaConfig, generate
from ray_lightning_tpu.ops import dispatch
from ray_lightning_tpu.ops.attention import (
    paged_attention,
    paged_attention_reference,
    paged_attention_uses_pallas,
)
from ray_lightning_tpu.ops.pallas.paged_attention import (
    paged_attention_pallas,
    paged_shapes_supported,
)
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.scheduler import Request, Scheduler


# ---- op-level parity matrix ------------------------------------------------


def _rand_case(rng, C, H, hd, Hkv, P, M, N, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((C, H, hd)), dtype)
    pk = jnp.asarray(rng.standard_normal((N, P, Hkv, hd)), dtype)
    pv = jnp.asarray(rng.standard_normal((N, P, Hkv, hd)), dtype)
    tables = jnp.asarray(rng.integers(0, N, (C, M)), jnp.int32)
    lengths = jnp.asarray(rng.integers(1, M * P + 1, (C,)), jnp.int32)
    return q, pk, pv, tables, lengths


@pytest.mark.parametrize("C,H,hd,Hkv,P,M,N", [
    (4, 4, 64, 2, 8, 3, 10),     # GQA 2:1
    (3, 8, 64, 8, 16, 2, 7),     # MHA, 16-token blocks
    (2, 4, 128, 1, 8, 4, 6),     # MQA, lane-wide head dim
    (5, 6, 64, 2, 8, 1, 4),      # single-block table
])
def test_kernel_matches_reference_matrix(C, H, hd, Hkv, P, M, N):
    """The parity matrix: block_size x gathered_len x GQA ratio x
    ragged per-slot lengths, interpret mode on CPU."""
    rng = np.random.default_rng(C * 100 + P)
    q, pk, pv, tables, lengths = _rand_case(rng, C, H, hd, Hkv, P, M, N)
    ref = paged_attention_reference(q, pk, pv, tables, lengths)
    got = paged_attention_pallas(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_pad_masking_matches_reference():
    """Left-pad masking (the batched-prefill contract): positions
    < pad[c] are invisible on both paths."""
    rng = np.random.default_rng(7)
    q, pk, pv, tables, lengths = _rand_case(rng, 4, 4, 64, 2, 8, 3, 9)
    pad = jnp.asarray([0, 3, 5, 1], jnp.int32)
    ref = paged_attention_reference(q, pk, pv, tables, lengths, pad)
    got = paged_attention_pallas(q, pk, pv, tables, lengths, pad)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # and the pad actually matters: an unpadded run differs
    unpadded = paged_attention_reference(q, pk, pv, tables, lengths)
    assert not np.allclose(np.asarray(unpadded), np.asarray(ref))


def test_kernel_scratch_block_zero_masked():
    """The scratch-block-0 edge: table tails past a slot's length point
    at block 0 (reserved scratch, garbage by contract). Poisoning
    scratch with huge values must not perturb any visible output —
    masked positions contribute exactly zero through the softmax."""
    rng = np.random.default_rng(11)
    q, pk, pv, tables, lengths = _rand_case(rng, 3, 4, 64, 2, 8, 4, 8)
    # slot 0: short length, tail table entries -> scratch block 0
    tables = tables.at[0, 2:].set(0)
    lengths = lengths.at[0].set(12)  # only blocks 0-1 visible
    poisoned_k = pk.at[0].set(1e9)
    poisoned_v = pv.at[0].set(1e9)
    base = paged_attention_pallas(q, pk.at[0].set(0.0),
                                  pv.at[0].set(0.0), tables, lengths)
    hot = paged_attention_pallas(q, poisoned_k, poisoned_v, tables,
                                 lengths)
    np.testing.assert_array_equal(np.asarray(base[0]),
                                  np.asarray(hot[0]))


def test_kernel_fully_masked_slot_emits_zeros():
    """A slot whose pad swallows its whole length (an idle slot's stale
    pad) must emit zeros, not NaN (the safe-l discipline)."""
    rng = np.random.default_rng(13)
    q, pk, pv, tables, lengths = _rand_case(rng, 2, 4, 64, 2, 8, 2, 5)
    lengths = lengths.at[0].set(1)
    pad = jnp.asarray([5, 0], jnp.int32)  # pad > length on slot 0
    out = paged_attention_pallas(q, pk, pv, tables, lengths, pad)
    assert np.all(np.asarray(out[0]) == 0.0)
    assert np.all(np.isfinite(np.asarray(out)))


def test_bf16_parity_tolerance():
    rng = np.random.default_rng(17)
    q, pk, pv, tables, lengths = _rand_case(rng, 4, 4, 64, 2, 8, 3, 9,
                                            dtype=jnp.bfloat16)
    ref = paged_attention_reference(q, pk, pv, tables, lengths)
    got = paged_attention_pallas(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


# ---- dispatch predicate ----------------------------------------------------


def test_shapes_supported_contract():
    ok = ((4, 8, 64), (16, 8, 2, 64))
    assert paged_shapes_supported(*ok)
    assert paged_shapes_supported((4, 8, 128), (16, 8, 2, 128))
    # lane-misaligned head dim (the main tiny config's hd=16)
    assert not paged_shapes_supported((4, 4, 16), (16, 8, 2, 16))
    # sublane-misaligned block size
    assert not paged_shapes_supported((4, 8, 64), (16, 4, 2, 64))
    # ragged GQA ratio
    assert not paged_shapes_supported((4, 3, 64), (16, 8, 2, 64))
    # head-dim mismatch between q and pool
    assert not paged_shapes_supported((4, 8, 64), (16, 8, 2, 128))


def test_uses_pallas_respects_dispatch_context():
    q_shape, pool_shape = (4, 8, 64), (16, 8, 2, 64)
    with dispatch.force_pallas():
        assert paged_attention_uses_pallas(q_shape, pool_shape)
        # shape gate still wins under force
        assert not paged_attention_uses_pallas((4, 4, 16),
                                               (16, 8, 2, 16))
    with dispatch.force_xla():
        assert not paged_attention_uses_pallas(q_shape, pool_shape)
    # explicit override beats the context
    with dispatch.force_xla():
        assert paged_attention_uses_pallas(q_shape, pool_shape,
                                           use_pallas=True)


def test_paged_attention_dispatches_both_paths():
    rng = np.random.default_rng(23)
    q, pk, pv, tables, lengths = _rand_case(rng, 4, 4, 64, 2, 8, 3, 9)
    ref = paged_attention(q, pk, pv, tables, lengths, use_pallas=False)
    with dispatch.force_pallas():
        got = paged_attention(q, pk, pv, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- engine: fused lane ----------------------------------------------------


@pytest.fixture(scope="module")
def kernel_tiny():
    """A kernel-TILING tiny model (head_dim 64, GQA 2:1) — the main
    serve suite's tiny config has head_dim 16, which the kernel
    correctly refuses."""
    cfg = LlamaConfig(vocab_size=256, dim=128, n_layers=2, n_heads=2,
                      n_kv_heads=1, hidden_dim=256, max_seq_len=128,
                      remat=False, dtype=jnp.float32)
    model = Llama(cfg)
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(100 + i), (1, 3 + (i % 5)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    params = jax.jit(model.init)(jax.random.key(1),
                                 prompts[0])["params"]
    return cfg, model, params, prompts


def _mixed_requests(prompts, max_new=6):
    return [Request(rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
                    temperature=0.7 if i % 2 else 0.0,
                    top_k=5 if i % 2 else None, seed=21 + i)
            for i, p in enumerate(prompts)]


def _drain(sched, submit):
    pending = list(submit)
    out = {}
    while sched.busy() or pending:
        if pending:
            sched.submit(pending.pop(0))
        for comp in sched.tick():
            out[comp.rid] = comp
    return out


def _refs(model, params, prompts, reqs):
    return {
        r.rid: np.asarray(generate(
            model, params, prompts[i], r.max_new_tokens,
            temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)
    }


def test_fused_engine_selected_and_streams_match(kernel_tiny):
    """The fused lane serves the full mixed-sampling workload with
    token streams equal to the reference lane's (which is itself
    bitwise vs generate) — the kernel-path parity pin at the stream
    level, same tolerance discipline as flash (token-level equality at
    these scales; op-level parity is the allclose matrix above)."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    reqs = _mixed_requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    ref_engine = DecodeEngine(model, params, ecfg, use_pallas=False)
    assert not ref_engine.fused
    assert ref_engine.attention_path == "reference-gather"
    out_ref = _drain(Scheduler(ref_engine), _mixed_requests(prompts))
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out_ref[rid].tokens),
                                      ref, err_msg=rid)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        assert eng.fused
        assert eng.attention_path == "paged-pallas"
        out_fused = _drain(Scheduler(eng), _mixed_requests(prompts))
    for rid in refs:
        assert out_fused[rid].tokens == out_ref[rid].tokens, rid


def test_fused_engine_churn_compile_count_pinned(kernel_tiny):
    """Request churn through the FUSED step stays one compiled program
    — the dispatch decision is build-time static."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        assert eng.fused
        sched = Scheduler(eng)
        for wave in range(3):
            _drain(sched, _mixed_requests(prompts[wave * 2:
                                                  wave * 2 + 2],
                                          max_new=4))
    assert eng.compile_count in (1, -1)


def test_fused_program_pins_kernel_against_ambient_dispatch(kernel_tiny):
    """The build-time decision is baked as STATIC aux
    (PagedDecodeView.use_pallas): a fused step traced under force_xla
    — the worst-case ambient context a late jit trace could see —
    still lowers the paged-attention kernel, so
    `DecodeEngine.attention_path` can never describe a program that
    compiled the gathering reference op instead (review finding,
    regression-pinned)."""
    from ray_lightning_tpu.serve.audit import trace_decode_step

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    with dispatch.force_xla():
        _, meta = trace_decode_step(cfg, ecfg, fused=True)
    assert any("paged_attention" in k for k in meta["pallas_kernels"])
    assert not meta["dense_paged_gathers"]


def test_fused_respects_use_flash_false(kernel_tiny):
    """A reference-forced model config (use_flash=False) must never
    take the kernel, even under force_pallas — the flash discipline."""
    cfg, model, params, prompts = kernel_tiny
    import dataclasses

    rcfg = dataclasses.replace(cfg, use_flash=False)
    rmodel = Llama(rcfg)
    with dispatch.force_pallas():
        eng = DecodeEngine(rmodel, params, EngineConfig(
            capacity=2, block_size=8, blocks_per_slot=4,
            prefill_chunk=4))
    assert not eng.fused


# ---- batched left-padded prefill lane --------------------------------------


@pytest.mark.parametrize("prefill_batch", [2, 4])
def test_batched_prefill_bitwise_vs_generate(kernel_tiny,
                                             prefill_batch):
    """ROADMAP 1d: up to prefill_batch queued prompts advance together
    per tick through the model's left-pad cache path; streams stay
    BITWISE vs single-stream generate() on the reference path, under
    both staggered and burst arrivals."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4, prefill_batch=prefill_batch)
    eng = DecodeEngine(model, params, ecfg, use_pallas=False)
    reqs = _mixed_requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    out = _drain(Scheduler(eng), _mixed_requests(prompts))
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out[rid].tokens), ref,
                                      err_msg=rid)
    # burst arrival: all 8 submitted before the first tick
    sched = Scheduler(eng)
    for r in _mixed_requests(prompts):
        sched.submit(r)
    out2 = _drain(sched, ())
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out2[rid].tokens), ref,
                                      err_msg=f"burst {rid}")
    assert eng.compile_count in (1, -1)


def test_batched_prefill_fused_combination(kernel_tiny):
    """fused x batched: the padded decode lane's kernel-side pad mask
    agrees with the reference lane's."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4, prefill_batch=3)
    ref_eng = DecodeEngine(model, params, ecfg, use_pallas=False)
    out_ref = _drain(Scheduler(ref_eng), _mixed_requests(prompts))
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        assert eng.fused
        out = _drain(Scheduler(eng), _mixed_requests(prompts))
    for rid in out_ref:
        assert out[rid].tokens == out_ref[rid].tokens, rid


def test_batched_prefill_on_demand_preemption(kernel_tiny):
    """Oversubscribed pool + batched prefill: growth, preemption and
    bitwise replay still compose."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        n_blocks=9, prefill_chunk=4, prefill_batch=2)
    eng = DecodeEngine(model, params, ecfg, use_pallas=False)
    reqs = _mixed_requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    out = _drain(Scheduler(eng, reserve="on_demand"),
                 _mixed_requests(prompts))
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out[rid].tokens), ref,
                                      err_msg=rid)


def test_batched_prefill_submit_accounts_padding(kernel_tiny):
    """submit() on a batched engine rejects by the CHUNK-PADDED span:
    right-alignment makes the padded width the real reservation."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=2,
                        prefill_chunk=8, prefill_batch=2)
    eng = DecodeEngine(model, params, ecfg, use_pallas=False)
    sched = Scheduler(eng)
    # prompt 9 pads to 16; 16 + 1 > max_slot_len 16 -> must reject
    with pytest.raises(ValueError, match="chunk-padded"):
        sched.submit(Request(rid="big", prompt=np.arange(9, dtype=np.int32),
                             max_new_tokens=1))
    # the same request fits an unbatched engine (9 + 1 <= 16)
    eng1 = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=8, blocks_per_slot=2, prefill_chunk=8),
        use_pallas=False)
    Scheduler(eng1).submit(Request(
        rid="big", prompt=np.arange(9, dtype=np.int32),
        max_new_tokens=1))


def test_prefill_batch_config_validation():
    with pytest.raises(ValueError, match="prefill_batch"):
        EngineConfig(capacity=2, prefill_batch=3)
    with pytest.raises(ValueError, match="prefill_batch"):
        EngineConfig(capacity=2, prefill_batch=0)


# ---- audit: both paths, RLT307, fused plan ---------------------------------


def _flagship():
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, serve_memory_summary, trace_decode_step,
    )

    cfg = LlamaConfig.llama3_8b(max_seq_len=4096, dtype=jnp.bfloat16)
    ecfg = EngineConfig(capacity=8, block_size=16, blocks_per_slot=256,
                        prefill_chunk=256)
    return cfg, ecfg, audit_decode_step, serve_memory_summary, \
        trace_decode_step


@pytest.mark.slow
def test_flagship_audit_reference_flags_rlt307():
    """The acceptance pin: the reference-path flagship trace
    materializes the dense slot-gathered view on a kernel-tiling shape
    -> RLT307 fires; the fused trace has no view -> absent, audit
    clean, and the kernel is present in the trace."""
    cfg, ecfg, audit, _, trace = _flagship()
    rep = audit(cfg, ecfg, topology="v5p-8", fused=False)
    rules = sorted({f.rule for f in rep.findings})
    assert "RLT307" in rules
    assert "RLT301" not in rules and "RLT303" not in rules
    rep_f = audit(cfg, ecfg, topology="v5p-8", fused=True)
    rules_f = sorted({f.rule for f in rep_f.findings})
    assert "RLT307" not in rules_f
    assert "RLT301" not in rules_f and "RLT303" not in rules_f
    closed, meta = trace(cfg, ecfg, fused=True)
    assert any("paged_attention" in k for k in meta["pallas_kernels"])
    assert not meta["dense_paged_gathers"]


def test_small_shape_audit_both_paths_clean(kernel_tiny):
    """Kernel-tiling tiny shape: reference trace HAS the dense gather
    (RLT307 evidence) and flags; fused trace audits clean with the
    kernel present."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    closed, meta = trace_decode_step(cfg, ecfg, fused=False)
    assert meta["dense_paged_gathers"], "reference trace lost its view?"
    rep = audit_decode_step(cfg, ecfg, fused=False)
    assert "RLT307" in {f.rule for f in rep.findings}
    rep_f = audit_decode_step(cfg, ecfg, fused=True)
    assert not {f.rule for f in rep_f.findings} & {
        "RLT301", "RLT303", "RLT307"}
    _, meta_f = trace_decode_step(cfg, ecfg, fused=True)
    assert any("paged_attention" in k for k in meta_f["pallas_kernels"])
    assert not meta_f["dense_paged_gathers"]


def test_rlt307_sanctioned_on_unsupported_shape():
    """The main tiny config (head_dim 16) cannot take the kernel: its
    reference trace keeps the dense view WITHOUT an RLT307 — the rule
    fires only where the fused kernel is actually available."""
    from ray_lightning_tpu.serve.audit import audit_decode_step

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    rep = audit_decode_step(cfg, ecfg, fused=False)
    assert "RLT307" not in {f.rule for f in rep.findings}


def test_serve_memory_summary_fused_retires_view():
    """plan --serve acceptance: the fused path's per-replica HBM is
    STRICTLY below the reference path's, with the retired term
    itemized and the traffic model reflecting the dropped copy."""
    cfg, ecfg, _, summary, _ = _flagship()
    s_auto = summary(cfg, ecfg)
    s_ref = summary(cfg, ecfg, fused=False)
    assert s_auto["attention_path"] == "paged-pallas"
    assert s_ref["attention_path"] == "reference-gather"
    assert s_auto["per_device_bytes"] < s_ref["per_device_bytes"]
    assert s_auto["gathered_view_retired_bytes"] > 0
    assert s_ref["gathered_view_retired_bytes"] == 0
    assert (s_auto["decode_kv_traffic_bytes_per_tick"]
            < s_ref["decode_kv_traffic_bytes_per_tick"])
    # the retired term is reporting, not a resident buffer
    resident = (s_auto["params_bytes"] + s_auto["pool_bytes"]
                + s_auto["gathered_view_bytes"]
                + s_auto["last_logits_bytes"])
    assert s_auto["per_device_bytes"] == resident


def test_plan_serve_cli_reports_fused(capsys):
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "llama3-8b", "--serve", "--seq",
               "4096", "--json", "--no-trace"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["serve"]["attention_path"] == "paged-pallas"
    assert out["serve"]["gathered_view_retired_bytes"] > 0


# ---- bench + bench_gate ----------------------------------------------------


def test_bench_serve_summary_carries_hbm_metric():
    import bench

    s = bench._serve_summary()
    assert "serving_error" not in s, s
    assert s["serve_hbm_bytes_per_replica"] > 0
    sv = s["serving"]
    assert sv["attention_path"] == "paged-pallas"
    assert sv["gathered_view_retired_bytes"] > 0
    # the fused replica must sit strictly below the reference story
    assert (s["serve_hbm_bytes_per_replica"]
            < sv["reference_hbm_bytes_per_replica"])
    assert "serving_attention_path" in sv["schema"]


def test_measured_serving_records_attention_path():
    import bench

    got = bench._measure_serving(tiny=True, autoscale=False)
    assert got["serving_attention_path"] in ("paged-pallas",
                                             "reference-gather")
    assert got["decode_tokens_per_s"] > 0


def _gate(fresh, priors, tmp_path):
    import importlib
    import os
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_gate = importlib.import_module("bench_gate")
    for i, p in enumerate(priors):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": p}))
    best = bench_gate.best_prior("BENCH_r*.json", str(tmp_path))
    ceilings = bench_gate.ceiling_prior("BENCH_r*.json", str(tmp_path))
    return bench_gate.gate(fresh, best, 0.05, ceilings)


def test_bench_gate_serve_hbm_ceiling(tmp_path):
    base = {"metric": "m", "value": 1.0,
            "serve_hbm_bytes_per_replica": 40 * 2**30}
    # shrinking passes (the ratchet's whole point)
    ok = _gate({"metric": "m", "value": 1.0,
                "serve_hbm_bytes_per_replica": 35 * 2**30},
               [base], tmp_path)
    assert not ok
    # growth past tolerance fails
    bad = _gate({"metric": "m", "value": 1.0,
                 "serve_hbm_bytes_per_replica": 60 * 2**30},
                [base], tmp_path)
    assert any("serve_hbm_bytes_per_replica" in f for f in bad)
    # static class: ratchets on skip lines too
    bad_skip = _gate({"metric": "m", "skipped": "backend unavailable",
                      "serve_hbm_bytes_per_replica": 60 * 2**30},
                     [base], tmp_path)
    assert any("serve_hbm_bytes_per_replica" in f for f in bad_skip)
    # serving_error waives an ABSENT value...
    waived = _gate({"metric": "m", "value": 1.0,
                    "serving_error": "TypeError: boom"},
                   [base], tmp_path)
    assert not any("serve_hbm" in f for f in waived)
    # ...but a silently dropped field fails
    dropped = _gate({"metric": "m", "value": 1.0}, [base], tmp_path)
    assert any("dropped the field" in f for f in dropped)
