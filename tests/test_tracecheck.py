"""tracecheck unit tests: the cost model, the ppermute schedule checks,
and the jaxpr auditor's three finding classes (RLT301/302/303) on small
synthetic modules — all CPU-only, no devices beyond the trace."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.analysis.costmodel import (
    collective_cost, parse_topology, topology_for_kind,
)
from ray_lightning_tpu.analysis.tracecheck import (
    audit_step, check_permutation,
)
from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.models.mlp import MLPClassifier
from ray_lightning_tpu.ops.dispatch import shard_map
from ray_lightning_tpu.ops.pipeline import pipeline_perm
from ray_lightning_tpu.ops.ring_attention import ring_perm
from ray_lightning_tpu.parallel.strategy import DataParallel, ShardedMesh


# ---- cost model ----------------------------------------------------------


def test_parse_topology():
    t = parse_topology("v5p-64")
    assert t.n_devices == 64
    assert t.device_kind == "TPU v5p"
    assert t.hbm_bytes == 95 * 1024**3
    assert t.ici_gbps > 0


def test_parse_topology_rejects_unknown_family():
    with pytest.raises(ValueError, match="v5p"):
        parse_topology("v99-8")
    with pytest.raises(ValueError, match="expected"):
        parse_topology("not a topology")


def test_topology_for_kind_unknown_falls_back():
    t = topology_for_kind("FPGA mystery", 4, hbm_bytes=2 * 1024**3)
    assert t.n_devices == 4
    assert t.hbm_bytes == 2 * 1024**3  # override honored


def test_collective_cost_ring_algebra():
    topo = parse_topology("v5e-8")
    n, payload = 8, 1024**2
    psum = collective_cost("psum", payload, {"data": n}, topo)
    ag = collective_cost("all_gather", payload, {"data": n}, topo)
    rs = collective_cost("reduce_scatter", payload, {"data": n}, topo)
    pp = collective_cost("ppermute", payload, {"data": n}, topo)
    assert psum.wire_bytes == int(2 * payload * (n - 1) / n)
    assert ag.wire_bytes == rs.wire_bytes == int(payload * (n - 1) / n)
    assert pp.wire_bytes == payload
    # a single-member group moves nothing
    assert collective_cost("psum", payload, {"data": 1}, topo).wire_bytes == 0


# ---- ppermute schedule checks (RLT303) -----------------------------------


def test_canonical_schedules_are_clean():
    assert check_permutation(ring_perm(8), 8) == []
    assert check_permutation(pipeline_perm(4), 4) == []
    assert check_permutation([], 4) == []


def test_two_disjoint_cycles_flagged():
    f = check_permutation([(0, 1), (1, 0), (2, 3), (3, 2)], 4)
    assert [x.rule for x in f] == ["RLT303"]
    assert "2 disjoint cycles" in f[0].message


def test_duplicate_and_out_of_range_flagged():
    assert any("duplicate destination" in x.message
               for x in check_permutation([(0, 1), (2, 1)], 4))
    assert any("duplicate source" in x.message
               for x in check_permutation([(0, 1), (0, 2)], 4))
    assert any("outside the axis" in x.message
               for x in check_permutation([(0, 9)], 4))


# ---- auditor: collective schedule ---------------------------------------


def _mlp_batch(b=32):
    return {"x": np.zeros((b, 784), np.float32),
            "y": np.zeros((b,), np.int32)}


def test_dp_gradient_psums_detected():
    rep = audit_step(MLPClassifier(features=(128,), num_classes=10),
                     DataParallel(), _mlp_batch(),
                     topology="v5e-8", label="mlp")
    assert rep.findings == []
    psums = [e for e in rep.collectives if e.kind == "psum"]
    assert psums, "data-parallel gradient all-reduce not detected"
    assert all(e.axes == ("data",) for e in psums)
    # the [784, 128] f32 kernel grad is the dominant payload
    assert max(e.payload_bytes for e in psums) == 784 * 128 * 4
    assert rep.ici_bytes_per_step > 0
    assert rep.fits


def test_report_to_dict_roundtrips_json():
    import json

    rep = audit_step(MLPClassifier(features=(16,), num_classes=4),
                     DataParallel(), _mlp_batch(16),
                     topology="v5e-4", label="mlp")
    d = json.loads(json.dumps(rep.to_dict()))
    assert d["topology"]["name"] == "v5e-4"
    assert d["fits"] is True
    assert isinstance(d["collectives"], list)
    assert d["ici_bytes_per_step"] == rep.ici_bytes_per_step


def test_fsdp_weight_gathers_and_grad_reduce_scatters():
    rep = audit_step(MLPClassifier(features=(512,), num_classes=16),
                     ShardedMesh(fsdp=4), _mlp_batch(16),
                     topology="v5e-4", label="mlp-fsdp")
    assert not [f for f in rep.findings if f.rule == "RLT301"]
    kinds = {e.kind for e in rep.collectives}
    assert "all_gather" in kinds, "ZeRO weight gather not scheduled"


# ---- auditor: RESHARD-IMPLICIT (RLT301) ----------------------------------


class _TPModule(TpuModule):
    """Two-matmul Megatron-style module. ``drop_spec`` drops w2's
    tensor spec — the fsdp auto-placement then collides with the
    tensor-sharded activation: the ISSUE's mis-sharded variant."""

    def __init__(self, drop_spec=False):
        super().__init__()
        self.drop_spec = drop_spec

    def init_params(self, rng, batch):
        return {"w1": jnp.zeros((256, 512), jnp.float32),
                "w2": jnp.zeros((512, 256), jnp.float32)}

    def configure_model(self):
        return None

    def configure_optimizers(self):
        return optax.sgd(1e-2)

    def param_specs(self, params):
        specs = {"w1": P(None, "tensor")}
        if not self.drop_spec:
            specs["w2"] = P("tensor", None)
        return specs

    def training_step(self, params, batch, rng):
        h = jax.nn.relu(batch["x"] @ params["w1"])
        return ((h @ params["w2"]) ** 2).mean()


def _tp_batch():
    return {"x": np.zeros((32, 256), np.float32)}


def test_correct_tensor_plan_is_clean():
    rep = audit_step(_TPModule(False), ShardedMesh(fsdp=2, tensor=2),
                     _tp_batch(), topology="v5e-4", label="tp-ok")
    assert rep.findings == []
    # row-parallel second matmul: psum over tensor is the SCHEDULE,
    # not a finding
    assert any(e.kind == "psum" and "tensor" in e.axes
               for e in rep.collectives)


def test_dropped_output_spec_flags_reshard_implicit():
    rep = audit_step(_TPModule(True), ShardedMesh(fsdp=2, tensor=2),
                     _tp_batch(), topology="v5e-4", label="tp-bad")
    assert any(f.rule == "RLT301" for f in rep.findings), \
        "mis-sharded matmul not flagged RESHARD-IMPLICIT"


# ---- auditor: HBM-OVERCOMMIT (RLT302) ------------------------------------


def test_hbm_overcommit_flagged_on_tiny_budget():
    from ray_lightning_tpu.analysis.costmodel import parse_topology

    topo = parse_topology("v5e-4", hbm_bytes=1024**2)  # 1 MiB chips
    rep = audit_step(MLPClassifier(features=(512, 512), num_classes=10),
                     DataParallel(), _mlp_batch(),
                     topology=topo, label="mlp-tiny-hbm")
    assert any(f.rule == "RLT302" for f in rep.findings)
    assert not rep.fits


# ---- auditor: RING-DEADLOCK (RLT303) in a traced step --------------------


class _RingModule(TpuModule):
    def __init__(self, perm_kind="ring"):
        super().__init__()
        self.perm_kind = perm_kind

    def init_params(self, rng, batch):
        return {"w": jnp.zeros((64, 64), jnp.float32)}

    def configure_model(self):
        return None

    def configure_optimizers(self):
        return optax.sgd(1e-2)

    def training_step(self, params, batch, rng):
        x = batch["x"] @ params["w"]
        n = self.mesh.shape["seq"]
        perm = {"ring": ring_perm(n),
                "two_cycles": [(0, 1), (1, 0), (2, 3), (3, 2)]}[
                    self.perm_kind]

        def local(x):
            y = jax.lax.ppermute(x, "seq", perm)
            return jax.lax.psum(x * y, "seq")

        f = shard_map(local, mesh=self.mesh, in_specs=P(None, "seq"),
                      out_specs=P(None, "seq"), check_replication=False)
        return (f(x) ** 2).mean()


def _ring_batch():
    return {"x": np.zeros((8, 64), np.float32)}


def test_explicit_shard_map_collectives_scheduled():
    rep = audit_step(_RingModule("ring"), ShardedMesh(seq=4),
                     _ring_batch(), topology="v5e-4", label="ring")
    assert not [f for f in rep.findings if f.rule == "RLT303"]
    explicit = [e for e in rep.collectives if not e.implicit]
    assert {"ppermute", "psum"} <= {e.kind for e in explicit}


def test_broken_ring_flags_deadlock():
    rep = audit_step(_RingModule("two_cycles"), ShardedMesh(seq=4),
                     _ring_batch(), topology="v5e-4", label="ring-bad")
    assert any(f.rule == "RLT303" for f in rep.findings)


# ---- API wrappers --------------------------------------------------------


def test_strategy_and_module_audit_step_wrappers():
    rep = ShardedMesh(fsdp=2).audit_step(
        MLPClassifier(features=(64,), num_classes=4), _mlp_batch(16),
        topology="v5e-2")
    assert rep.label  # auto-label from types
    rep2 = MLPClassifier(features=(64,), num_classes=4).audit_step(
        DataParallel(), _mlp_batch(16), topology="v5e-2")
    assert rep2.mesh_axes == {"data": 2}
    assert "tracecheck" in rep2.summary()
