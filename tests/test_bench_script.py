"""bench.py structural pins: leg ordering and the static analysis block.

``bench._run`` executes measurement legs in ``LEG_ORDER`` — flagship
legs first so a watchdog kill mid-run still flushes driver-verified
flagship numbers (the legs a partial sink MUST contain). These tests
pin that order and the one data dependency inside it, plus the
``concurrency`` summary block every bench JSON line now carries.
"""
import ast
import os

import bench

_BENCH_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


def test_leg_order_is_flagship_first():
    """A watchdog timeout or driver kill flushes the partial sink; the
    flagship numbers must already be in it."""
    order = list(bench.LEG_ORDER)
    flagship_legs = [n for n in order if n.startswith("flagship")]
    assert order[:len(flagship_legs)] == flagship_legs, order
    # comparison/secondary legs all come after
    assert order.index("vs_baseline") > order.index("flagship")


def test_rematce_immediately_precedes_flagship():
    """`_flagship_remat_ce` publishes shared["rematce"], the flagship
    leg's compile-rejection fallback — the dependency that makes the
    order a contract rather than a preference."""
    order = list(bench.LEG_ORDER)
    i = order.index("flagship_rematce")
    assert order[i + 1] == "flagship", order


def test_run_iterates_exactly_leg_order():
    """_run's dispatch table and LEG_ORDER name the same set, and the
    loop walks LEG_ORDER — verified in the AST so a hand-reordered
    `leg(...)` call sequence cannot silently diverge from the pin."""
    with open(_BENCH_PY, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    run = next(n for n in tree.body
               if isinstance(n, ast.FunctionDef) and n.name == "_run")
    loops = [n for n in ast.walk(run)
             if isinstance(n, ast.For)
             and isinstance(n.iter, ast.Name)
             and n.iter.id == "LEG_ORDER"]
    assert loops, "_run no longer iterates LEG_ORDER"
    # no stray direct leg("name", ...) calls outside the LEG_ORDER loop
    direct = [n for n in ast.walk(run)
              if isinstance(n, ast.Call)
              and isinstance(n.func, ast.Name) and n.func.id == "leg"
              and n.args and isinstance(n.args[0], ast.Constant)]
    assert direct == [], [ast.dump(d) for d in direct]


def test_concurrency_summary_block():
    """Every bench JSON line carries the threadcheck audit — and on this
    repo it reports zero findings (the self-lint pin, from the bench
    side). Pure host-side AST work: must succeed with no backend."""
    block = bench._concurrency_summary()
    assert set(block) == {"concurrency"}
    assert block["concurrency"] == {"total": 0, "by_rule": {}}
