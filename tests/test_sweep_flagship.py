"""Harness tests for scripts/sweep_flagship.py — the on-chip tuning
sweep's record/carry logic, smoke-run on the CPU backend with a tiny
shape (RLT_SWEEP_RESULTS redirects the record so the real chip JSONL is
never polluted; the reference's analog is examples-as-smoke-tests,
reference .github/workflows/test.yaml:70-77)."""
import json

import pytest

from scripts.sweep_flagship import best_so_far, run_one


@pytest.fixture
def results_path(tmp_path, monkeypatch):
    p = tmp_path / "sweep.jsonl"
    monkeypatch.setenv("RLT_SWEEP_RESULTS", str(p))
    # the module captured RESULTS at import — repoint it for the test
    import scripts.sweep_flagship as sf

    monkeypatch.setattr(sf, "RESULTS", str(p))
    return p


@pytest.mark.slow  # real (tiny) compile + timed steps; tooling, not library
def test_run_one_records_success_and_flags(results_path, monkeypatch):
    # shrink the model (the real _bench_cfg hardcodes the 0.5B bench
    # dims — minutes of CPU compile); run_one's own measurement path,
    # flags included, still runs end-to-end
    import bench
    from ray_lightning_tpu.models.llama import LlamaConfig

    def tiny_cfg(use_flash, fused_ce, seq, vocab=64, remat=True,
                 scan=True, remat_policy="nothing", ce_chunk_tokens=16,
                 ce_inline=False):
        return LlamaConfig(
            vocab_size=vocab, dim=32, n_layers=2, n_heads=2, n_kv_heads=1,
            hidden_dim=64, max_seq_len=seq, use_flash=False,
            fused_ce=fused_ce, ce_chunk_tokens=ce_chunk_tokens,
            ce_inline_bwd=ce_inline, remat=remat,
            remat_policy=remat_policy, scan_layers=scan)

    monkeypatch.setattr(bench, "_bench_cfg", tiny_cfg)
    rec = run_one("smoke-tiny", batch=2, policy="attn_out", chunk=16,
                  vocab=64, seq=32, inline=True, mu_bf16=True)
    assert rec["tokens_per_sec"] > 0
    assert rec["mu_bf16"] is True and rec["inline"] is True
    on_disk = [json.loads(x) for x in results_path.read_text().splitlines()]
    assert on_disk[-1]["tag"] == "smoke-tiny"
    assert on_disk[-1]["tokens_per_sec"] == rec["tokens_per_sec"]


def test_run_one_records_failure_as_data(results_path, monkeypatch):
    import bench

    def boom(**kw):
        raise RuntimeError("remote_compile HTTP 500")

    monkeypatch.setattr(bench, "_make_step", boom)
    rec = run_one("smoke-fail", batch=2, policy="nothing", chunk=16,
                  vocab=64, seq=32)
    assert "HTTP 500" in rec["error"]
    assert "tokens_per_sec" not in rec
    # a failed point must not become the incumbent
    assert best_so_far() is None


def test_best_so_far_keeps_full_config(results_path):
    for tag, tps, extra in (
            ("a", 100.0, {"inline": False, "mu_bf16": False}),
            ("b", 200.0, {"inline": True, "mu_bf16": True}),
            ("c", 150.0, {"inline": False, "mu_bf16": False})):
        with open(results_path, "a") as f:
            f.write(json.dumps({"tag": tag, "batch": 4, "policy": "nothing",
                                "chunk": 16, "tokens_per_sec": tps,
                                **extra}) + "\n")
    best = best_so_far()
    # the incumbent's fit-critical flags survive for later phases'
    # _carry (a best that only fits with bf16 mu must not be re-run
    # without it)
    assert best["tag"] == "b"
    assert best["inline"] is True and best["mu_bf16"] is True


def test_phase4_carries_incumbent_mu_bf16(results_path, monkeypatch):
    """ADVICE r5: a standalone phase-4 re-run after phase 6/7 records
    exist must carry the incumbent's mu_bf16 (minus the forced
    inline=True) — a batch that only fits with a bf16 mu must not be
    silently re-run without it and recorded as a spurious OOM."""
    import sys

    import scripts.sweep_flagship as sf

    # seed the record with a phase-6-style incumbent: bf16 mu, inline
    with open(results_path, "a") as f:
        f.write(json.dumps({
            "tag": "p6-mubf16-b12-inline", "batch": 12,
            "policy": "nothing", "chunk": 4096,
            "inline": True, "mu_bf16": True,
            "tokens_per_sec": 999.0}) + "\n")

    calls = []

    def record_run_one(tag, **kw):
        calls.append({"tag": tag, **kw})
        return {"tag": tag, **kw}  # no tokens_per_sec: chunk sweep skipped

    monkeypatch.setattr(sf, "run_one", record_run_one)
    monkeypatch.setattr(sys, "argv", ["sweep_flagship.py", "4"])
    sf.main()
    p4 = [c for c in calls if c["tag"].startswith("p4-")]
    assert p4, calls
    assert all(c["inline"] is True for c in p4)
    assert all(c["mu_bf16"] is True for c in p4)
