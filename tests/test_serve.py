"""Serving engine tests (serve/, docs/SERVING.md): the paged KV cache,
the continuous-batching step's bitwise parity with single-stream
`generate`, the no-recompile-under-churn pin, scheduler lifecycle
(admission, growth, preemption), the decode-step audit, and the serve
plan leg."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, LlamaConfig, generate
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.kv_cache import (
    BlockAllocator,
    PagedPoolSpec,
    pool_bytes,
    serve_kv_plan_bytes,
)
from ray_lightning_tpu.serve.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def tiny(tiny_llama_f32):
    # params from the session-scope canonical build (tests/conftest.py):
    # same cfg, same init key 1 — init params depend only on key and
    # param shapes, so the shared build is bitwise what this fixture
    # used to construct per-module
    cfg, model, params, _ = tiny_llama_f32
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(10 + i), (1, 3 + (i % 5)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    return cfg, model, params, prompts


@pytest.fixture(scope="module")
def engine(tiny):
    cfg, model, params, _ = tiny
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=4, block_size=4, blocks_per_slot=8, prefill_chunk=4))
    eng.warmup()
    return eng


def _mixed_requests(prompts, max_new=6):
    reqs = []
    for i, p in enumerate(prompts):
        sampled = i % 2 == 1
        reqs.append(Request(
            rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
            temperature=0.7 if sampled else 0.0,
            top_k=5 if sampled else None, seed=21 + i))
    return reqs


def _drain(sched, submit=(), stagger=True):
    """Run to empty, submitting one pending request per tick (the
    staggered-arrival shape of real traffic)."""
    pending = list(submit)
    out = {}
    while sched.busy() or pending:
        if pending:
            sched.submit(pending.pop(0))
            if not stagger:
                continue
        for comp in sched.tick():
            out[comp.rid] = comp
    return out


def _refs(model, params, prompts, reqs):
    return {
        r.rid: np.asarray(generate(
            model, params, prompts[i], r.max_new_tokens,
            temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)
    }


# ---- kv_cache --------------------------------------------------------------


def test_pool_spec_shapes_and_bytes():
    spec = PagedPoolSpec(n_blocks=9, block_size=4, blocks_per_slot=2)
    assert spec.gathered_len == 8
    cfg = LlamaConfig.tiny()
    kv = serve_kv_plan_bytes(cfg, spec, capacity=3)
    assert kv["pool_bytes"] == pool_bytes(cfg, spec)
    assert kv["gathered_view_bytes"] > 0
    assert kv["last_logits_bytes"] == 3 * cfg.vocab_size * 4
    with pytest.raises(ValueError, match="scratch"):
        PagedPoolSpec(n_blocks=1, block_size=4, blocks_per_slot=1)


def test_allocator_scratch_reserved_and_double_free():
    alloc = BlockAllocator(PagedPoolSpec(
        n_blocks=5, block_size=4, blocks_per_slot=2))
    got = alloc.alloc(4)
    assert sorted(got) == [1, 2, 3, 4]  # block 0 never handed out
    assert alloc.alloc(1) is None       # pool dry -> None, not partial
    alloc.free(got[:2])
    assert alloc.free_blocks == 2
    with pytest.raises(ValueError, match="double free"):
        alloc.free([got[0], got[0]])
    with pytest.raises(ValueError, match="invalid block"):
        alloc.free([0])


def test_for_capacity_oversubscribe():
    spec = PagedPoolSpec.for_capacity(4, max_len=32, block_size=8,
                                      oversubscribe=0.5)
    full = PagedPoolSpec.for_capacity(4, max_len=32, block_size=8)
    assert spec.blocks_per_slot == full.blocks_per_slot == 4
    assert spec.n_blocks < full.n_blocks


# ---- engine parity ---------------------------------------------------------


def test_staggered_streams_bitwise_match_generate(tiny, engine):
    """The acceptance pin: 8 concurrent staggered streams (ragged
    prompts, mixed greedy/temperature/top-k, per-request seeds) through
    4 slots decode bitwise-identical to 8 independent single-stream
    generate() runs."""
    cfg, model, params, prompts = tiny
    reqs = _mixed_requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    sched = Scheduler(engine)
    out = _drain(sched, submit=reqs)
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out[rid].tokens), ref,
                                      err_msg=rid)


def test_churn_never_recompiles(tiny, engine):
    """Admission/retirement across waves of requests is pure runtime
    data: the step stays ONE compiled program."""
    cfg, model, params, prompts = tiny
    before = engine.compile_count
    sched = Scheduler(engine)
    for wave in range(3):
        reqs = [Request(rid=f"w{wave}-{i}", prompt=prompts[i][0],
                        max_new_tokens=2 + wave, seed=wave * 10 + i)
                for i in range(4)]
        _drain(sched, submit=reqs)
    assert engine.compile_count == before == 1


def test_trainer_committed_params_compile_once(tiny):
    """Trainer-produced params arrive COMMITTED (NamedSharding over the
    training mesh). The engine canonicalizes weight placement and
    commits its own buffers, so the donated signature never flips after
    the first tick — without that, the fine-tune -> serve flow compiled
    a phantom second executable (caught by the install-drive, pinned
    here)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    cfg, model, params, prompts = tiny
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    committed = jax.device_put(
        params, NamedSharding(mesh, PartitionSpec()))
    eng = DecodeEngine(model, committed, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng)
    out = _drain(sched, submit=[Request(
        rid="c", prompt=prompts[0][0], max_new_tokens=5)])
    ref = np.asarray(generate(model, params, prompts[0], 5))[0]
    np.testing.assert_array_equal(np.array(out["c"].tokens), ref)
    assert eng.compile_count == 1


def test_batch_order_invariance(tiny, engine):
    """Per-slot RNG: the same request produces the same tokens no
    matter which slot it lands in or who its neighbors are."""
    cfg, model, params, prompts = tiny
    req = dict(prompt=prompts[3][0], max_new_tokens=5, temperature=0.9,
               top_k=4, seed=77)
    runs = []
    for order in ((0, 1, 2), (2, 1, 0)):
        sched = Scheduler(engine)
        reqs = [Request(rid=f"n{j}", prompt=prompts[j][0],
                        max_new_tokens=5, seed=j) for j in order]
        reqs.insert(1, Request(rid="probe", **req))
        out = _drain(sched, submit=reqs, stagger=False)
        runs.append(out["probe"].tokens)
    assert runs[0] == runs[1]


def test_eos_retires_slot(tiny, engine):
    """EOS mid-stream retires the slot; tokens up to and including EOS
    are kept and match the generate() prefix."""
    cfg, model, params, prompts = tiny
    ref = np.asarray(generate(model, params, prompts[0], 8))[0]
    eos = int(ref[2])  # force an early stop at the 3rd token
    sched = Scheduler(engine)
    out = _drain(sched, submit=[Request(
        rid="e", prompt=prompts[0][0], max_new_tokens=8, eos_id=eos)])
    comp = out["e"]
    assert comp.finish_reason == "eos"
    assert comp.tokens == list(ref[:3])


def test_completion_latency_fields(tiny, engine):
    cfg, model, params, prompts = tiny
    sched = Scheduler(engine)
    out = _drain(sched, submit=[Request(
        rid="m", prompt=prompts[0][0], max_new_tokens=4)])
    comp = out["m"]
    assert comp.ttft_s > 0 and comp.decode_s >= 0
    assert comp.tpot_s >= 0 and comp.queue_wait_s >= 0
    assert 0 < sched.slot_occupancy <= 1


# ---- scheduler lifecycle ---------------------------------------------------


def test_admission_defers_when_pool_short(tiny):
    """Worst-case reservation: requests queue (FIFO preserved) until
    blocks free up; everything still completes correctly."""
    cfg, model, params, prompts = tiny
    # pool of 9 usable blocks: one 24-token worst case = 6 blocks, so
    # only one request fits at a time
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=4, block_size=4, blocks_per_slot=6, n_blocks=10,
        prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng)
    reqs = [Request(rid=f"q{i}", prompt=prompts[i][0],
                    max_new_tokens=18, seed=i) for i in range(3)]
    out = _drain(sched, submit=reqs, stagger=False)
    assert set(out) == {"q0", "q1", "q2"}
    assert all(len(c.tokens) == 18 for c in out.values())


def test_on_demand_growth_and_preemption(tiny):
    """on_demand mode allocates per block boundary; when the pool runs
    dry mid-decode the youngest slot is preempted and REPLAYED — same
    seed, same tokens, just later."""
    cfg, model, params, prompts = tiny
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=2, block_size=4, blocks_per_slot=8, n_blocks=9,
        prefill_chunk=4))
    eng.warmup()
    sched = Scheduler(eng, reserve="on_demand")
    reqs = [Request(rid=f"p{i}", prompt=prompts[4][0],
                    max_new_tokens=20, seed=50 + i) for i in range(2)]
    out = _drain(sched, submit=reqs, stagger=False)
    refs = {f"p{i}": np.asarray(generate(
        model, params, prompts[4], 20, seed=50 + i))[0]
        for i in range(2)}
    preempts = sum(c.preempted for c in out.values())
    assert preempts >= 1, "the dry pool never forced a preemption"
    # the documented invariant: the OLDEST request is never evicted
    assert out["p0"].preempted == 0, \
        "the oldest request was preempted — the drain guarantee broke"
    for rid, c in out.items():
        np.testing.assert_array_equal(np.array(c.tokens), refs[rid],
                                      err_msg=f"{rid} corrupted by "
                                      "preemption")


def test_prefill_chunk_not_dividing_slot_len(tiny):
    """Review regression: a prefill chunk that does not divide
    max_slot_len used to slide past the slot end on the tail chunk —
    the clamped cache update and pool scatter scribbled REAL prompt
    entries and decode silently diverged from generate(). The window
    now slides back instead (re-sent rows recompute identical K/V)."""
    cfg, model, params, _ = tiny
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=1, block_size=4, blocks_per_slot=8, prefill_chunk=5))
    eng.warmup()
    prompt = np.array(jax.random.randint(
        jax.random.key(123), (1, 31), 0, cfg.vocab_size), dtype=np.int32)
    sched = Scheduler(eng)
    out = _drain(sched, submit=[Request(rid="t", prompt=prompt[0],
                                        max_new_tokens=1)])
    ref = np.asarray(generate(model, params, prompt, 1))[0]
    np.testing.assert_array_equal(np.array(out["t"].tokens), ref)
    with pytest.raises(ValueError, match="prefill_chunk"):
        EngineConfig(capacity=1, block_size=4, blocks_per_slot=2,
                     prefill_chunk=16)


def test_driver_outputs_exact_after_preemption(tiny):
    """Review regression: a scheduler-level preemption replays the
    stream from scratch, and the DRIVER's token stream must drop the
    pre-preemption prefix — outputs used to hold prefix + full replay."""
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )

    cfg, model, params, prompts = tiny
    reqs = [Request(rid=f"d{i}", prompt=prompts[4][0],
                    max_new_tokens=20, seed=70 + i) for i in range(2)]
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", reserve="on_demand",
        engine=EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                            n_blocks=9, prefill_chunk=4)))
    res = drv.run(reqs)
    assert any(m["preempted"] for m in res.meta.values()), \
        "the dry pool never preempted — the regression is untested"
    for i, r in enumerate(reqs):
        ref = np.asarray(generate(model, params, prompts[4], 20,
                                  seed=r.seed))[0]
        np.testing.assert_array_equal(np.array(res.outputs[r.rid]), ref,
                                      err_msg=r.rid)


def test_submit_rejects_oversized_request(tiny, engine):
    cfg, model, params, prompts = tiny
    sched = Scheduler(engine)
    with pytest.raises(ValueError, match="max_slot_len"):
        sched.submit(Request(rid="big", prompt=np.zeros(20, np.int32),
                             max_new_tokens=1000))


def test_engine_rejects_cache_beyond_rope(tiny):
    cfg, model, params, _ = tiny
    with pytest.raises(ValueError, match="max_seq_len"):
        DecodeEngine(model, params, EngineConfig(
            capacity=1, block_size=64,
            blocks_per_slot=cfg.max_seq_len // 64 + 1))


# ---- audit + plan ----------------------------------------------------------


def test_decode_step_audits_clean(tiny):
    """The acceptance pin: no RLT301 (the paged gather is explicit and
    masked, not an implicit reshard) and no RLT303 on the decode step."""
    from ray_lightning_tpu.serve.audit import audit_decode_step

    cfg, _, _, _ = tiny
    report = audit_decode_step(cfg, EngineConfig(
        capacity=4, block_size=4, blocks_per_slot=8, prefill_chunk=4),
        topology="v5p-8")
    rules = {f.rule for f in report.findings}
    assert "RLT301" not in rules and "RLT303" not in rules
    assert report.peak_hbm_bytes > 0


def test_serve_memory_summary_prices_pool(tiny):
    from ray_lightning_tpu.serve.audit import serve_memory_summary

    cfg, _, _, _ = tiny
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8)
    s = serve_memory_summary(cfg, ecfg, device_kind="TPU v5p")
    assert s["pool_bytes"] == pool_bytes(cfg, ecfg.pool_spec)
    assert s["per_device_bytes"] >= (s["params_bytes"] + s["pool_bytes"]
                                     + s["gathered_view_bytes"])
    assert s["fits"] is True


def test_plan_serve_cli(capsys):
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "tiny", "--serve", "--seq", "64",
               "--serve-slots", "2", "--no-trace", "--json"])
    import json

    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["fits"] is True
    assert out["serve"]["pool_bytes"] > 0


def test_plan_serve_does_not_fit_exit_1(capsys):
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "llama3-8b", "--serve", "--seq",
               "8192", "--serve-slots", "64", "--no-trace",
               "--hbm-bytes", str(2 * 1024**3), "--json"])
    assert rc == 1


# ---- bench serving leg -----------------------------------------------------


def test_bench_serving_leg_schema():
    import bench

    r = bench._measure_serving(tiny=True, autoscale=False)
    for key in ("decode_tokens_per_s", "ttft_cold_s", "ttft_warm_s",
                "slot_occupancy"):
        assert key in r, key
    assert r["decode_tokens_per_s"] > 0
    assert r["ttft_warm_s"] < r["ttft_cold_s"]  # compile paid once
    assert 0 < r["slot_occupancy"] <= 1
    assert r["serving_compile_count"] in (1, -1)


def test_bench_serve_summary_static():
    import bench

    s = bench._serve_summary()
    assert "serving" in s, s.get("serving_error")
    assert s["serving"]["flagship_plan"]["pool_bytes"] > 0
    assert set(s["serving"]["schema"]) == {
        "decode_tokens_per_s", "prefill_tokens_per_s",
        "ttft_cold_s", "ttft_warm_s", "ttft_p99_s", "slot_occupancy",
        "shared_block_fraction", "accepted_tokens_per_step",
        "serving_attention_path", "serving_prefill_path",
        "serve_metrics", "scale_up_s", "autoscale",
        "slo_attainment", "slo_attainment_latency_critical",
        "shed_fraction"}
    # the ISSUE 19 static pricing blocks ride every line
    assert s["serving"]["prefix_plan"]["shared_pool_bytes_saved"] > 0
    assert s["serving"]["prefix_plan"]["prefill_tokens_saved"] > 0
    sp = s["serving"]["speculative_plan"]
    assert sp["verify_step_flops"] == \
        sp["k"] * sp["base_decode_flops_per_token"]
    assert sp["expected_tokens_per_tick"] > 1.0
    # the TP=2 sharded-replica section (ISSUE 18): per-shard HBM halves
    # the replicated plan's params, and the decode collective schedule
    # carries the gate-ratcheted per-tick wire total
    tp = s["serve_tp"]
    assert tp["tp"] == 2
    # per-shard params: the sharded leaves halve, the (tiny) replicated
    # norm scales don't — so just over full/2, never more than 51%
    full = s["serving"]["flagship_plan"]["params_bytes"]
    assert full / 2 <= tp["params_bytes_per_shard"] < full * 0.51
    assert tp["hbm_bytes_per_shard"] < s["serve_hbm_bytes_per_replica"]
    assert tp["decode_ici_bytes_per_tick"] == \
        s["serve_decode_ici_bytes_per_tick"] == \
        sum(c["wire_bytes"] for c in tp["collectives"]) > 0
    kinds = {c["kind"] for c in tp["collectives"]}
    assert "psum" in kinds and "all_gather" in kinds


def test_bench_gate_ratchets_serving(tmp_path):
    """decode_tokens_per_s ratchets (measured: waived on skip lines);
    ttft_warm_s is upper-bounded on measured lines."""
    import importlib
    import os
    import sys

    scripts = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_gate = importlib.import_module("bench_gate")
    best = {"decode_tokens_per_s": (100.0, "BENCH_r09.json")}
    ok = {"metric": "m", "value": 1.0, "decode_tokens_per_s": 99.0,
          "ttft_warm_s": 0.5}
    assert bench_gate.gate(ok, best, tolerance=0.05) == []
    slow = {"metric": "m", "value": 1.0, "decode_tokens_per_s": 50.0}
    assert any("decode_tokens_per_s" in f
               for f in bench_gate.gate(slow, best, tolerance=0.05))
    laggy = {"metric": "m", "value": 1.0, "decode_tokens_per_s": 100.0,
             "ttft_warm_s": 99.0}
    assert any("ttft_warm_s" in f
               for f in bench_gate.gate(laggy, best, tolerance=0.05))
    skip = {"metric": "m", "value": 0.0, "skipped": "backend unavailable"}
    assert bench_gate.gate(skip, best, tolerance=0.05) == []
    # serve_decode_ici_bytes_per_tick CEILING-ratchets (static: holds on
    # skip lines too); growth fails, a serving_error line waives absence
    ceil = {"serve_decode_ici_bytes_per_tick": (1000.0, "BENCH_r09.json")}
    flat = dict(skip, serve_decode_ici_bytes_per_tick=1000.0)
    assert bench_gate.gate(flat, {}, tolerance=0.05, ceilings=ceil) == []
    grew = dict(skip, serve_decode_ici_bytes_per_tick=2000.0)
    assert any("serve_decode_ici_bytes_per_tick" in f
               for f in bench_gate.gate(grew, {}, tolerance=0.05,
                                        ceilings=ceil))
    dropped = dict(skip)
    assert any("dropped the field" in f
               for f in bench_gate.gate(dropped, {}, tolerance=0.05,
                                        ceilings=ceil))
    waived = dict(skip, serving_error="IndexError: boom")
    assert bench_gate.gate(waived, {}, tolerance=0.05,
                           ceilings=ceil) == []
