"""Unified run timeline (telemetry/timeline.py, docs/OBSERVABILITY.md
"unified timeline"): every evidence-ledger fixture round-trips into
Events with source/rank/ordering asserted, garbage lines are skipped
with a count, legacy headerless ledgers ingest with the unaligned tag
(never a crash), the Chrome-trace export validates against the
trace-event schema, and `report`/`monitor` degrade to a structured
partial report on a partial or empty run dir."""
from __future__ import annotations

import json
import os
import time

from ray_lightning_tpu.telemetry.incidents import append_incident
from ray_lightning_tpu.telemetry.metrics import (
    FlightRecorder,
    MetricsRegistry,
    finalize_flight,
)
from ray_lightning_tpu.telemetry.spans import (
    TelemetryRecorder,
    ledger_tail_lines,
)
from ray_lightning_tpu.telemetry.timeline import (
    load_timeline_events,
    render_text,
    timeline_excerpt,
    to_chrome_trace,
    validate_chrome_trace,
)


def _tdir(run_dir: str) -> str:
    return os.path.join(run_dir, "telemetry")


# -------------------------------------------------- per-ledger round-trips


def test_spans_roundtrip(tmp_path):
    run = str(tmp_path)
    rec = TelemetryRecorder(_tdir(run), rank=0)
    with rec.span("compile", step=0):
        time.sleep(0.002)
    with rec.span("step", step=1):
        time.sleep(0.001)
    rec.close()
    tl = load_timeline_events(run)
    spans = [e for e in tl["events"] if e.source == "spans"]
    assert [e.kind for e in spans] == ["compile", "step"]
    assert all(e.rank == 0 and e.aligned for e in spans)
    assert all(e.dur_s > 0 for e in spans)
    # wall reconstruction: header t0_wall + offset lands near now
    assert abs(spans[0].wall - time.time()) < 60
    assert spans[0].wall <= spans[1].wall


def test_metrics_roundtrip(tmp_path):
    run = str(tmp_path)
    reg = MetricsRegistry(_tdir(run), replica=1, flush_every_n_ticks=1)
    reg.gauge("queue_depth", 3.0)
    reg.tick_end()
    reg.gauge("queue_depth", 1.0)
    reg.tick_end()
    reg.close()
    tl = load_timeline_events(run)
    ticks = [e for e in tl["events"] if e.source == "metrics"]
    assert len(ticks) == 2
    assert all(e.replica == 1 and e.kind == "tick" and e.aligned
               for e in ticks)
    assert ticks[0].payload["queue_depth"] == 3.0


def test_flight_roundtrip(tmp_path):
    run = str(tmp_path)
    fpath = os.path.join(_tdir(run), "replica0.flight.json")
    fl = FlightRecorder(fpath, replica=0, persist_every=1)
    fl.record("admit", rid="r0")
    fl.record("retire", rid="r0")
    fl.close()
    finalize_flight(_tdir(run), 0,
                    {"kind": "retryable", "cause": "worker-signal"},
                    os.path.join(run, "flight.json"))
    tl = load_timeline_events(run)
    flight = [e for e in tl["events"] if e.source == "flight"]
    kinds = [e.kind for e in flight]
    # the live ring AND the finalized dump's copy both ingest, plus
    # the classified death stamp
    assert "admit" in kinds and "retire" in kinds and "death" in kinds
    death = next(e for e in flight if e.kind == "death")
    assert death.payload["kind"] == "retryable"
    assert all(e.aligned for e in flight)


def test_autoscale_roundtrip_aligned(tmp_path):
    from ray_lightning_tpu.autoscale.controller import (
        AutoscaleController,
        ControllerConfig,
        read_ledger,
    )

    run = str(tmp_path)

    class _Drv:
        n_live = 1
        driver_metrics = None
        driver_flight = None

    ctl = AutoscaleController(
        _Drv(), ControllerConfig(), run_dir=run,
        signal_fn=lambda: {"available": False, "reason": "test"})
    ctl.step(now=0.0)
    ctl.step(now=1.0)
    # the ledger opens with the clock-alignment header; read_ledger
    # skips it, the raw file carries it
    first, _body = ledger_tail_lines(os.path.join(run,
                                                  "autoscale.jsonl"))
    header = json.loads(first)
    assert header["version"] == "rlt-autoscale-v1"
    assert header["t0_wall"] > 0 and "t0_perf" in header
    entries = read_ledger(run)
    assert len(entries) == 2 and all("t" in e for e in entries)
    tl = load_timeline_events(run)
    asc = [e for e in tl["events"] if e.source == "autoscale"]
    assert len(asc) == 2
    assert all(e.aligned and e.kind == "hold" for e in asc)
    assert abs(asc[0].wall - header["t0_wall"]) < 60


def test_autoscale_legacy_headerless_unaligned(tmp_path):
    """A pre-PR-14 ledger (no header, no per-entry "t") must ingest
    with the unaligned tag on its policy-clock offsets — present and
    ordered among its peers, never a crash, never a guessed epoch."""
    run = str(tmp_path)
    with open(os.path.join(run, "autoscale.jsonl"), "w") as f:
        for now in (4.0, 6.0):
            f.write(json.dumps({
                "decision_index": 0, "now": now,
                "decision": {"action": "scale_up", "target": 2,
                             "delta": 1, "reason": "legacy"},
                "outcome": {"ok": True}, "replicas": 2,
            }) + "\n")
    tl = load_timeline_events(run)
    asc = [e for e in tl["events"] if e.source == "autoscale"]
    assert len(asc) == 2
    assert all(not e.aligned for e in asc)
    assert [e.wall for e in asc] == [4.0, 6.0]
    assert tl["unaligned"] == 2
    # unaligned events sort AFTER the aligned stream
    assert tl["events"][-2:] == asc


def test_reshard_ledger_roundtrip(tmp_path):
    from ray_lightning_tpu.resilience.supervisor import (
        _append_reshard_ledger,
    )

    run = str(tmp_path)
    _append_reshard_ledger(run, {
        "from_world": 2, "to_world": 1, "reason": "shrink",
        "attempt": 2, "at": time.time(),
        "batch_plan": {"note": "re-planned"}})
    _append_reshard_ledger(run, {
        "reason": "grow_refused", "from_world": 1, "resolved_max": 2,
        "capacity": 1, "capacity_source": "file",
        "attempt": 3, "at": time.time()})
    first, body = ledger_tail_lines(os.path.join(run, "reshards.jsonl"))
    assert json.loads(first)["version"] == "rlt-reshards-v1"
    assert len(body) == 2
    tl = load_timeline_events(run)
    rs = [e for e in tl["events"] if e.source == "reshard"]
    assert [e.kind for e in rs] == ["shrink", "grow_refused"]
    assert all(e.aligned for e in rs)
    assert rs[0].payload["from_world"] == 2
    assert rs[1].payload["capacity_source"] == "file"


def test_goodput_ledger_roundtrip(tmp_path):
    from ray_lightning_tpu.telemetry.goodput import (
        worker_ledger,
        write_ledger,
    )
    from ray_lightning_tpu.telemetry.spans import NULL_RECORDER

    run = str(tmp_path)
    led = worker_ledger(NULL_RECORDER, 2.0, rank=0, start_step=0,
                        end_step=10)
    write_ledger(_tdir(run), led, uid="1-0")
    tl = load_timeline_events(run)
    attempts = [e for e in tl["events"] if e.source == "goodput"]
    assert len(attempts) == 1
    assert attempts[0].kind == "attempt" and attempts[0].rank == 0
    assert attempts[0].dur_s == 2.0
    assert attempts[0].payload["end_step"] == 10


def test_incidents_roundtrip(tmp_path):
    run = str(tmp_path)
    append_incident(run, {"rule": "ttft_p99", "severity": "page",
                          "wall": time.time(),
                          "evidence": {"value": 3.0, "threshold": 2.0}})
    tl = load_timeline_events(run)
    inc = [e for e in tl["events"] if e.source == "incident"]
    assert len(inc) == 1 and inc[0].kind == "ttft_p99"
    assert inc[0].aligned and inc[0].payload["value"] == 3.0


# ------------------------------------------------- merge-level properties


def _multi_source_dir(tmp_path) -> str:
    run = str(tmp_path)
    rec = TelemetryRecorder(_tdir(run), rank=0)
    with rec.span("step", step=1):
        time.sleep(0.001)
    rec.close()
    reg = MetricsRegistry(_tdir(run), replica=0, flush_every_n_ticks=1)
    reg.gauge("queue_depth", 1.0)
    reg.tick_end()
    reg.close()
    fl = FlightRecorder(os.path.join(_tdir(run),
                                     "replica0.flight.json"),
                        replica=0, persist_every=1)
    fl.record("tick", n=1)
    fl.close()
    append_incident(run, {"rule": "r", "severity": "warn",
                          "wall": time.time(), "evidence": {}})
    return run


def test_merged_ordering_and_counts(tmp_path):
    run = _multi_source_dir(tmp_path)
    tl = load_timeline_events(run)
    assert set(tl["sources"]) >= {"spans", "metrics", "flight",
                                  "incident"}
    walls = [e.wall for e in tl["events"] if e.aligned]
    assert walls == sorted(walls)
    assert tl["garbage_lines"] == 0


def test_garbage_lines_skipped_with_count(tmp_path):
    run = _multi_source_dir(tmp_path)
    # tear two ledgers mid-line (the kill-mid-append shape)
    span_file = next(
        os.path.join(_tdir(run), f)
        for f in os.listdir(_tdir(run)) if f.endswith(".spans.jsonl"))
    with open(span_file, "a") as f:
        f.write('{"phase": "step", "t": 0.5, "du')
    with open(os.path.join(run, "autoscale.jsonl"), "w") as f:
        f.write("not json at all\n")
    tl = load_timeline_events(run)
    assert tl["garbage_lines"] == 2
    assert tl["sources"]["spans"] == 1  # the good span still ingests


def test_chrome_trace_schema(tmp_path):
    run = _multi_source_dir(tmp_path)
    tl = load_timeline_events(run)
    doc = to_chrome_trace(tl["events"])
    assert validate_chrome_trace(doc) == []
    non_meta = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    assert len({e["cat"] for e in non_meta}) >= 4
    ts = [e["ts"] for e in non_meta]
    assert ts == sorted(ts)
    # span entries are duration slices; instants carry a scope
    span_evs = [e for e in non_meta if e["cat"] == "spans"]
    assert span_evs and all(e["ph"] == "X" and e["dur"] > 0
                            for e in span_evs)
    assert json.loads(json.dumps(doc))  # JSON-serializable end to end


def test_chrome_trace_validator_rejects_garbage():
    assert validate_chrome_trace({"nope": 1})
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "x", "ph": "i"}]})  # no pid/tid/ts
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "x", "ph": "X", "pid": 0, "tid": 0,
                          "ts": 1.0}]})  # duration without dur


def test_timeline_excerpt_window(tmp_path):
    run = _multi_source_dir(tmp_path)
    tl = load_timeline_events(run)
    mid = tl["events"][len(tl["events"]) // 2]
    ex = timeline_excerpt(tl["events"], mid.wall, n=2)
    assert 1 <= len(ex) <= 5
    assert all("source" in d and "wall" in d for d in ex)


def test_empty_dir_is_partial_not_fatal(tmp_path):
    tl = load_timeline_events(str(tmp_path))
    assert tl["events"] == [] and tl["sources"] == {}
    assert render_text(tl).startswith("timeline:")


# ----------------------------------------------------------- CLI surface


def test_timeline_cli(tmp_path, capsys):
    from ray_lightning_tpu.__main__ import main

    run = _multi_source_dir(tmp_path / "run")
    out = str(tmp_path / "trace.json")
    assert main(["timeline", run, "--chrome", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert validate_chrome_trace(doc) == []
    capsys.readouterr()
    assert main(["timeline", run, "--limit", "5"]) == 0
    text = capsys.readouterr().out
    assert "timeline:" in text and "spans" in text
    assert main(["timeline", str(tmp_path / "nope")]) == 2


def test_timeline_cli_json_and_source_filter(tmp_path, capsys):
    from ray_lightning_tpu.__main__ import main

    run = _multi_source_dir(tmp_path / "run")
    assert main(["timeline", run, "--json", "--source", "spans"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["events"]
    assert all(e["source"] == "spans" for e in doc["events"])


# --------------------------------------- tail-bounded reads (RLT503 seam)


def test_ledger_tail_lines_keeps_header(tmp_path):
    path = str(tmp_path / "x.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"version": "v", "t0_wall": 1.0}) + "\n")
        for i in range(1000):
            f.write(json.dumps({"i": i}) + "\n")
    first, body = ledger_tail_lines(path, tail_bytes=256)
    assert json.loads(first)["version"] == "v"
    rows = [json.loads(ln) for ln in body]
    assert rows and rows[-1]["i"] == 999
    assert len(rows) < 1000  # actually bounded
    # partial line at the cut edge is dropped, not mangled
    assert all("i" in r for r in rows)
    # unbounded read returns everything
    _first, full = ledger_tail_lines(path)
    assert len(full) == 1000


def test_read_spans_tail_bounded(tmp_path):
    run = str(tmp_path)
    rec = TelemetryRecorder(_tdir(run), rank=0, ring_size=4096)
    for i in range(500):
        rec.record("step", 0.0, 0.001, step=i)
    rec.close()
    path = rec._path
    from ray_lightning_tpu.telemetry.spans import read_spans

    full = read_spans(path)
    assert len(full["spans"]) == 500
    tail = read_spans(path, tail_bytes=2048)
    assert tail["header"] == full["header"]  # header survives the cut
    assert 0 < len(tail["spans"]) < 500
    assert tail["spans"][-1] == full["spans"][-1]


def test_read_metrics_tail_keeps_hists(tmp_path):
    run = str(tmp_path)
    reg = MetricsRegistry(_tdir(run), replica=0, flush_every_n_ticks=8)
    for i in range(200):
        reg.gauge("queue_depth", float(i))
        reg.observe("ttft_s", 0.01)
        reg.tick_end()
    reg.close()
    from ray_lightning_tpu.telemetry.metrics import (
        metrics_paths,
        read_metrics,
    )

    path = metrics_paths(_tdir(run))[0]
    tail = read_metrics(path, tail_bytes=4096)
    assert tail["header"]["replica"] == 0
    # the cumulative hists snapshot lives at the end: the tail read
    # still sees the FULL histogram
    assert tail["hists"]["ttft_s"].n == 200
    assert 0 < len(tail["ticks"]) < 200
    assert tail["gauges"]["queue_depth"] == 199.0


# --------------------------- partial run dirs: report/monitor degradation


def test_report_empty_dir_degrades_structured(tmp_path, capsys):
    from ray_lightning_tpu.__main__ import main
    from ray_lightning_tpu.telemetry.report import build_report

    out = build_report(str(tmp_path))
    assert out["goodput"] is None and out["step_stats"] is None
    streams = out["streams"]
    assert streams["present"] == []
    assert set(streams["missing"]) >= {"spans", "goodput", "metrics",
                                       "autoscale", "incidents"}
    # the CLI renders it without raising and NAMES the missing streams
    assert main(["report", str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "missing" in text and "spans" in text


def test_report_ledger_subset_degrades(tmp_path, capsys):
    """A run dir holding ONLY an autoscale ledger (a run killed before
    the first span flush) must produce a partial report naming what is
    missing, with the autoscale section intact."""
    from ray_lightning_tpu.__main__ import main
    from ray_lightning_tpu.telemetry.report import build_report

    run = str(tmp_path)

    class _Drv:
        n_live = 2
        driver_metrics = None
        driver_flight = None

    from ray_lightning_tpu.autoscale.controller import (
        AutoscaleController,
        ControllerConfig,
    )

    ctl = AutoscaleController(
        _Drv(), ControllerConfig(), run_dir=run,
        signal_fn=lambda: {"available": False, "reason": "subset"})
    ctl.step(now=0.0)
    out = build_report(run)
    assert out["streams"]["present"] == ["autoscale"]
    assert "spans" in out["streams"]["missing"]
    assert main(["report", run]) == 0
    assert "autoscale" in capsys.readouterr().out


def test_monitor_partial_dirs_do_not_raise(tmp_path):
    from ray_lightning_tpu.telemetry.report import (
        _monitor_once,
        _monitor_serve_once,
    )

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    view = _monitor_once(empty)
    assert view["ranks"] == {} and view["goodput"] is None
    sview = _monitor_serve_once(empty, tail_bytes=4096)
    assert sview["replicas"] == {}
    assert sview["load_signal"]["available"] is False


def test_report_incidents_section(tmp_path, capsys):
    from ray_lightning_tpu.__main__ import main
    from ray_lightning_tpu.telemetry.report import build_report

    run = str(tmp_path)
    append_incident(run, {
        "rule": "ttft_p99", "severity": "page", "wall": time.time(),
        "evidence": {"metric": "serving.ttft_p99_s", "value": 3.0,
                     "op": ">", "threshold": 2.0},
        "actions": {"profiler_marker": "m"},
        "timeline_excerpt": [{"source": "spans"}]})
    out = build_report(run)
    inc = out["incidents"]
    assert inc["count"] == 1
    assert inc["by_rule"] == {"ttft_p99": 1}
    assert inc["last"]["evidence"]["value"] == 3.0
    assert inc["last"]["excerpt_events"] == 1
    assert "incidents" in out["streams"]["present"]
    assert main(["report", run]) == 0
    text = capsys.readouterr().out
    assert "incidents: 1" in text
