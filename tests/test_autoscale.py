"""autoscale/: the closed-loop serving autoscaler (ISSUE 13).

Fast legs: the policy core's decision-table matrix (pure — no I/O, no
clock), the capacity oracle's source chain, the ServeDriver dynamic
session seams (graceful-drain bitwise parity, forced-eviction replay,
the all-draining submit deferral), the controller's classified
spawn-retry drill, the ledger schema, the elastic grow-back wiring
(refused grows carry the oracle's answer), and the bench/report
surfaces. The full scripted ramp 1 -> 2 -> 1 runs here AND as the
format.sh ``autoscale --smoke`` gate.
"""
import json
import os

import numpy as np
import pytest

from ray_lightning_tpu.autoscale.capacity import (
    CapacityAnswer,
    CapacityOracle,
)
from ray_lightning_tpu.autoscale.controller import (
    AutoscaleController,
    ControllerConfig,
    read_ledger,
)
from ray_lightning_tpu.autoscale.policy import (
    HOLD,
    SCALE_DOWN,
    SCALE_UP,
    PolicyConfig,
    PolicyState,
    decide,
)

# ---- the policy decision table ---------------------------------------------


def _sig(pressure=0.0, queue=0.0, occ=0.0, available=True, slots=4.0):
    return {"available": available, "pressure": pressure,
            "queue_depth_now": queue, "occupancy": occ,
            "total_slots": slots}


CFG = PolicyConfig(min_replicas=1, max_replicas=4, high_pressure=0.5,
                   low_pressure=0.05, idle_occupancy=0.5,
                   sustain_polls=2, up_cooldown_s=10.0,
                   down_cooldown_s=20.0)


def test_no_signal_holds_and_resets_streaks():
    st = PolicyState(replicas=1, high_streak=5)
    d = decide(CFG, st, {"available": False}, now=0.0)
    assert d.action == HOLD and "no_signal" in d.clamps
    assert st.high_streak == 0
    d = decide(CFG, st, None, now=1.0)
    assert d.action == HOLD


def test_scale_up_needs_sustained_pressure():
    st = PolicyState(replicas=1)
    d1 = decide(CFG, st, _sig(pressure=1.0), now=0.0)
    assert d1.action == HOLD and "hysteresis" in d1.clamps
    d2 = decide(CFG, st, _sig(pressure=1.0), now=1.0)
    assert d2.action == SCALE_UP and d2.target == 2 and d2.delta == 1


def test_scale_down_needs_idle_queue_and_occupancy():
    st = PolicyState(replicas=2)
    # drained queue but busy slots: NOT idle — reclaiming would requeue
    d = decide(CFG, st, _sig(pressure=0.0, occ=0.9), now=0.0)
    assert d.action == HOLD and st.low_streak == 0
    # queue still nonzero: not idle either
    d = decide(CFG, st, _sig(pressure=0.0, queue=1.0), now=1.0)
    assert d.action == HOLD and st.low_streak == 0
    decide(CFG, st, _sig(), now=2.0)
    d = decide(CFG, st, _sig(), now=3.0)
    assert d.action == SCALE_DOWN and d.target == 1


def test_in_band_resets_both_streaks():
    st = PolicyState(replicas=1)
    decide(CFG, st, _sig(pressure=1.0), now=0.0)
    assert st.high_streak == 1
    d = decide(CFG, st, _sig(pressure=0.2), now=1.0)  # within band
    assert d.action == HOLD and st.high_streak == 0 and st.low_streak == 0


def test_flapping_load_does_not_flap_replicas():
    st = PolicyState(replicas=2)
    # alternate high / in-band for many polls: streak never sustains
    for i in range(20):
        sig = _sig(pressure=1.0 if i % 2 == 0 else 0.2)
        d = decide(CFG, st, sig, now=float(i))
        assert d.action == HOLD
    assert st.replicas == 2


def test_up_cooldown_suppresses_but_streak_survives():
    st = PolicyState(replicas=1)
    decide(CFG, st, _sig(pressure=1.0), now=0.0)
    d = decide(CFG, st, _sig(pressure=1.0), now=1.0)
    assert d.action == SCALE_UP
    st.applied(d, now=1.0)
    assert st.replicas == 2 and st.last_scale_up_t == 1.0
    # pressure persists (signal lag): cooldown holds, streak builds
    decide(CFG, st, _sig(pressure=1.0), now=2.0)
    d = decide(CFG, st, _sig(pressure=1.0), now=3.0)
    assert d.action == HOLD and "up_cooldown" in d.clamps
    # cooldown expires: the sustained streak acts immediately
    d = decide(CFG, st, _sig(pressure=1.0), now=12.0)
    assert d.action == SCALE_UP and d.target == 3


def test_down_cooldown_counts_any_scale_event():
    st = PolicyState(replicas=3, last_scale_up_t=100.0)
    decide(CFG, st, _sig(), now=105.0)
    d = decide(CFG, st, _sig(), now=106.0)
    # scale-UP at t=100 suppresses a scale-DOWN until 120
    assert d.action == HOLD and "down_cooldown" in d.clamps
    d = decide(CFG, st, _sig(), now=121.0)
    assert d.action == SCALE_DOWN and d.target == 2


def test_max_and_min_clamps():
    st = PolicyState(replicas=4, high_streak=1)
    d = decide(CFG, st, _sig(pressure=2.0), now=0.0)
    assert d.action == HOLD and "max_replicas" in d.clamps
    st = PolicyState(replicas=1, low_streak=1)
    d = decide(CFG, st, _sig(), now=0.0)
    assert d.action == HOLD and "min_replicas" in d.clamps


def test_capacity_clamp():
    st = PolicyState(replicas=2, high_streak=1)
    d = decide(CFG, st, _sig(pressure=2.0), now=0.0, capacity=2)
    assert d.action == HOLD and "capacity" in d.clamps
    st = PolicyState(replicas=2, high_streak=1)
    d = decide(CFG, st, _sig(pressure=2.0), now=0.0, capacity=3)
    assert d.action == SCALE_UP and d.target == 3


def test_none_pressure_means_unknown_slots():
    # pressure None + queued demand = infinite pressure; None + empty
    # queue = zero. Never a crash, never a scale on ignorance alone.
    st = PolicyState(replicas=1, high_streak=1)
    sig = {"available": True, "pressure": None, "queue_depth_now": 3.0,
           "occupancy": 0.0}
    d = decide(CFG, st, sig, now=0.0)
    assert d.action == SCALE_UP
    st = PolicyState(replicas=2, low_streak=1)
    sig = {"available": True, "pressure": None, "queue_depth_now": 0.0,
           "occupancy": 0.0}
    assert decide(CFG, st, sig, now=0.0).action == SCALE_DOWN


def test_below_min_floor_restores_regardless_of_signal():
    # review finding: with 0 live replicas every metrics stream is
    # retired and the signal reads unavailable — the floor must be
    # restored anyway (and without waiting out a cooldown)
    st = PolicyState(replicas=0, last_scale_up_t=0.0)
    d = decide(CFG, st, {"available": False}, now=1.0)
    assert d.action == SCALE_UP and d.target == CFG.min_replicas
    assert "min_replicas" in d.clamps
    # idle signal below the floor restores too
    st = PolicyState(replicas=0)
    d = decide(CFG, st, _sig(), now=0.0)
    assert d.action == SCALE_UP and d.target == CFG.min_replicas
    # the capacity clamp still applies
    st = PolicyState(replicas=0)
    d = decide(CFG, st, _sig(), now=0.0, capacity=0)
    assert d.action == HOLD and "capacity" in d.clamps


def test_final_world_skips_grow_refused_entries():
    # review finding: a grow_refused ledger entry carries no to_world;
    # final_world must report the last ACTUAL change, not crash
    from ray_lightning_tpu.resilience.supervisor import SupervisedResult

    r = SupervisedResult(
        result=None, restarts=1, preemptions=0, failures=[],
        reshards=[
            {"reason": "shrink", "from_world": 4, "to_world": 1},
            {"reason": "grow_refused", "from_world": 1,
             "resolved_max": 4, "capacity": 1,
             "capacity_source": "env"},
        ])
    assert r.final_world == 1
    r = SupervisedResult(result=None, restarts=0, preemptions=0,
                         failures=[], reshards=[])
    assert r.final_world is None


def test_policy_config_validation():
    with pytest.raises(ValueError):
        PolicyConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        PolicyConfig(low_pressure=0.9, high_pressure=0.5)
    with pytest.raises(ValueError):
        PolicyConfig(sustain_polls=0)


# ---- the capacity oracle ---------------------------------------------------


def test_oracle_env_override(monkeypatch):
    monkeypatch.setenv("RLT_CAPACITY", "3")
    ans = CapacityOracle().query(assume=8)
    assert ans.worlds == 3 and ans.source == "env"


def test_oracle_probe_file(tmp_path, monkeypatch):
    monkeypatch.delenv("RLT_CAPACITY", raising=False)
    p = tmp_path / "cap"
    p.write_text("2")
    ans = CapacityOracle(probe_file=str(p)).query(assume=8)
    assert ans.worlds == 2 and ans.source == "file"
    p.write_text(json.dumps({"capacity": 5}))
    assert CapacityOracle(probe_file=str(p)).query().worlds == 5
    p.write_text("not a number at all {")
    ans = CapacityOracle(probe_file=str(p)).query(assume=8)
    assert ans.source == "assumed" and ans.worlds == 8


def test_oracle_assumed_fallback_is_labeled(monkeypatch):
    monkeypatch.delenv("RLT_CAPACITY", raising=False)
    monkeypatch.delenv("RLT_CAPACITY_FILE", raising=False)
    ans = CapacityOracle().query(assume=4)
    assert ans.worlds == 4 and ans.source == "assumed"
    ans = CapacityOracle().query()
    assert ans.worlds is None and ans.source == "none"


def test_oracle_capacity_fn_adapter(monkeypatch):
    monkeypatch.delenv("RLT_CAPACITY", raising=False)
    monkeypatch.delenv("RLT_CAPACITY_FILE", raising=False)
    fn = CapacityOracle().capacity_fn(assume=6)
    assert fn() == 6
    assert CapacityOracle().capacity_fn()() == 0  # None -> 0 for ladders


@pytest.mark.slow
def test_oracle_spawn_probe(monkeypatch, tmp_path):
    monkeypatch.delenv("RLT_CAPACITY", raising=False)
    monkeypatch.delenv("RLT_CAPACITY_FILE", raising=False)
    oracle = CapacityOracle(spawn_probe_world=1, spawn_timeout_s=120.0,
                            spawn_env={"JAX_PLATFORMS": "cpu"})
    ans = oracle.query(assume=8)
    assert ans.source == "spawn_probe" and ans.worlds == 1
    # TTL cache: the second query answers without respawning
    assert oracle.query().worlds == 1


# ---- elastic grow-back wiring ----------------------------------------------


def test_budget_capacity_answer_sources(tmp_path, monkeypatch):
    from ray_lightning_tpu.elastic import ElasticBudget

    monkeypatch.delenv("RLT_CAPACITY", raising=False)
    monkeypatch.delenv("RLT_CAPACITY_FILE", raising=False)
    b = ElasticBudget(min_world=1)
    ans = b.capacity_answer(8)
    assert ans.worlds == 8 and ans.source == "assumed"
    monkeypatch.setenv("RLT_CAPACITY", "5")
    ans = b.capacity_answer(8)
    assert ans.worlds == 5 and ans.source == "env"
    assert b.capacity(8) == 5
    monkeypatch.delenv("RLT_CAPACITY")
    p = tmp_path / "cap"
    p.write_text("2")
    b = ElasticBudget(min_world=1,
                      oracle=CapacityOracle(probe_file=str(p)))
    assert b.capacity_answer(8).source == "file"
    assert b.capacity(8) == 2
    # the legacy hook still wins when set
    b = ElasticBudget(min_world=1, capacity_fn=lambda: 3)
    ans = b.capacity_answer(8)
    assert ans.worlds == 3 and ans.source == "capacity_fn"


def test_refused_grow_carries_oracle_answer(monkeypatch):
    from ray_lightning_tpu.elastic import ElasticBudget
    from ray_lightning_tpu.resilience.supervisor import (
        _elastic_decision,
        _elastic_target_world,
    )

    monkeypatch.setenv("RLT_CAPACITY", "1")
    b = ElasticBudget(min_world=1)
    # shrunk to 1 of 4 earlier; oracle says capacity has not returned:
    # no change, and the refusal names the oracle's answer + source
    target, refusal = _elastic_decision(b, 1, 4, True, 1)
    assert target is None
    assert refusal is not None
    assert refusal["reason"] == "grow_refused"
    assert refusal["capacity"] == 1
    assert refusal["capacity_source"] == "env"
    assert refusal["resolved_max"] == 4
    # back-compat wrapper unchanged
    assert _elastic_target_world(b, 1, 4, True, 1) is None
    # capacity returns: grow proposed, no refusal
    monkeypatch.setenv("RLT_CAPACITY", "4")
    target, refusal = _elastic_decision(b, 1, 4, True, 1)
    assert target == 4 and refusal is None
    # at the resolved max there is nothing to refuse
    target, refusal = _elastic_decision(b, 4, 4, True, 1)
    assert target is None and refusal is None


# ---- the dynamic session + controller (tiny real engines) ------------------


def _session_setup(n_requests=8, max_new=8):
    from ray_lightning_tpu.serve.cli import _references, _tiny_setup
    from ray_lightning_tpu.serve.engine import EngineConfig

    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    cfg, model, params, prompts, reqs = _tiny_setup(n_requests, max_new)
    refs = _references(model, params, prompts, reqs)
    return cfg, params, ecfg, reqs, refs


def _driver(cfg, params, ecfg, run_dir=None, n_replicas=1):
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig,
        ServeDriver,
    )

    return ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=n_replicas, backend="inline", engine=ecfg,
        run_dir=run_dir, metrics_flush_every_n_ticks=2))


def _mismatches(outputs, refs):
    return [rid for rid, ref in refs.items()
            if not np.array_equal(np.asarray(outputs.get(rid, [])),
                                  ref)]


def test_graceful_drain_bitwise_parity(tmp_path):
    # 2 replicas, scale down mid-stream: every completed stream must
    # match single-replica generate() bit for bit, nothing dropped
    cfg, params, ecfg, reqs, refs = _session_setup(8, 8)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"),
                  n_replicas=2)
    drv.start()
    for r in reqs:
        drv.submit(r)
    for _ in range(3):
        drv.tick()
    victim = drv.remove_replica(graceful=True)
    assert drv.replicas[victim].state in ("draining", "stopped")
    result = drv.stop()
    assert _mismatches(result.outputs, refs) == []
    assert len(result.meta) == len(reqs)
    assert result.stats["final_replicas"] == 1


def test_forced_eviction_replays_bitwise(tmp_path):
    cfg, params, ecfg, reqs, refs = _session_setup(6, 8)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"),
                  n_replicas=2)
    drv.start()
    for r in reqs:
        drv.submit(r)
    for _ in range(6):
        drv.tick()   # some streams are mid-decode now
    drv.remove_replica(graceful=False)   # partial streams dropped
    result = drv.stop()
    assert _mismatches(result.outputs, refs) == []
    assert len(result.meta) == len(reqs)


def test_submit_defers_when_all_draining(tmp_path):
    cfg, params, ecfg, reqs, refs = _session_setup(2, 6)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    drv.remove_replica(graceful=True)
    target = drv.submit(reqs[0])
    assert target is None
    assert drv.last_deferral["rid"] == reqs[0].rid
    assert "draining or dead" in drv.last_deferral["reason"]
    assert drv.driver_metrics.counters()["submit_deferrals"] == 1
    # a replica returns: the deferred stream routes and decodes bitwise
    drv.add_replica()
    result = drv.stop()
    assert _mismatches(result.outputs,
                       {reqs[0].rid: refs[reqs[0].rid]}) == []


def test_session_submit_validates_span(tmp_path):
    # review finding: the session path must refuse an unsatisfiable
    # request like Scheduler.submit does — enqueued raw it could never
    # admit and would head-of-line-block its replica forever
    import dataclasses

    cfg, params, ecfg, reqs, _ = _session_setup(1, 4)
    drv = _driver(cfg, params, ecfg)
    drv.start()
    oversized = dataclasses.replace(reqs[0], rid="huge",
                                    max_new_tokens=10_000)
    with pytest.raises(ValueError, match="max_slot_len"):
        drv.submit(oversized)
    assert not drv.busy()   # nothing was enqueued or deferred
    drv.stop()


def test_stop_drains_slots_then_refuses_stranded_pending(tmp_path):
    # review finding: pending can grow AFTER stop()'s drain begins
    # (here: deferred while the last replica drains) — the loop must
    # finish the drainable work, then refuse loudly instead of
    # ticking forever
    cfg, params, ecfg, reqs, refs = _session_setup(2, 6)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    drv.submit(reqs[0])
    for _ in range(3):
        drv.tick()          # reqs[0] is admitted / decoding
    drv.remove_replica(graceful=True)   # last live replica drains
    assert drv.submit(reqs[1]) is None  # deferred: no live replica
    with pytest.raises(RuntimeError, match="deferred"):
        drv.stop()
    # the drainable stream completed bitwise before the refusal
    assert _mismatches(drv.outputs,
                       {reqs[0].rid: refs[reqs[0].rid]}) == []
    drv.stop(drain=False)


def test_stop_refuses_to_strand_deferred_work(tmp_path):
    cfg, params, ecfg, reqs, _ = _session_setup(1, 4)
    drv = _driver(cfg, params, ecfg)
    drv.start()
    drv.remove_replica(graceful=True)
    drv.submit(reqs[0])
    with pytest.raises(RuntimeError, match="deferred"):
        drv.stop()
    drv.stop(drain=False)


def test_add_replica_is_respawn_path_with_npz(tmp_path):
    # params served from an .npz: every add_replica reloads from the
    # file — exactly the respawn path process replicas use
    from ray_lightning_tpu.serve.driver import save_params_npz

    cfg, params, ecfg, reqs, refs = _session_setup(4, 6)
    pp = str(tmp_path / "params.npz")
    save_params_npz(params, pp)
    drv = _driver(cfg, pp, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    drv.add_replica()
    assert drv.n_live == 2
    for r in reqs:
        drv.submit(r)
    result = drv.stop()
    assert _mismatches(result.outputs, refs) == []
    assert result.stats["compile_count"] == 1


def test_sigkill_during_scale_up_retried_within_budget(tmp_path):
    cfg, params, ecfg, _, _ = _session_setup(2, 4)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    high = {"available": True, "pressure": 2.0, "queue_depth_now": 8.0,
            "occupancy": 1.0, "total_slots": 4.0}
    ctl = AutoscaleController(
        drv,
        ControllerConfig(policy=PolicyConfig(
            min_replicas=1, max_replicas=2, sustain_polls=1),
            max_spawn_retries=2),
        run_dir=str(tmp_path / "run"), signal_fn=lambda: dict(high))
    drv.inject_spawn_faults(1, signal_name="SIGKILL")
    entry = ctl.step(now=0.0)
    out = entry["outcome"]
    assert out["ok"] and out["retries"] == 1
    assert out["failures"][0]["kind"] == "retryable"
    assert out["failures"][0]["cause"] == "worker-signal:SIGKILL"
    assert drv.n_live == 2   # the target was never dropped
    drv.stop()


def test_spawn_budget_exhaustion_reproposes_next_poll(tmp_path):
    cfg, params, ecfg, _, _ = _session_setup(2, 4)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    high = {"available": True, "pressure": 2.0, "queue_depth_now": 8.0,
            "occupancy": 1.0, "total_slots": 4.0}
    ctl = AutoscaleController(
        drv,
        ControllerConfig(policy=PolicyConfig(
            min_replicas=1, max_replicas=2, sustain_polls=1,
            up_cooldown_s=0.0), max_spawn_retries=0),
        run_dir=str(tmp_path / "run"), signal_fn=lambda: dict(high))
    drv.inject_spawn_faults(1, signal_name="SIGKILL")
    entry = ctl.step(now=0.0)
    assert not entry["outcome"]["ok"]
    assert drv.n_live == 1
    # the streak survived the failure: the NEXT poll re-proposes and
    # (faults exhausted) lands the target
    entry = ctl.step(now=1.0)
    assert entry["outcome"]["ok"] and drv.n_live == 2
    drv.stop()


def test_scale_up_aborts_whole_delta_on_exhausted_budget(tmp_path):
    # review finding: a dead spawn path must end the WHOLE scale-up —
    # the remaining delta would walk the same broken path
    cfg, params, ecfg, _, _ = _session_setup(2, 4)
    drv = _driver(cfg, params, ecfg, run_dir=str(tmp_path / "run"))
    drv.start()
    high = {"available": True, "pressure": 2.0, "queue_depth_now": 8.0,
            "occupancy": 1.0, "total_slots": 4.0}
    ctl = AutoscaleController(
        drv,
        ControllerConfig(policy=PolicyConfig(
            min_replicas=1, max_replicas=3, sustain_polls=1,
            max_step=2), max_spawn_retries=0),
        run_dir=str(tmp_path / "run"), signal_fn=lambda: dict(high))
    drv.inject_spawn_faults(1, signal_name="SIGKILL")
    entry = ctl.step(now=0.0)
    out = entry["outcome"]
    # budget exhausted on replica 1 of 2: replica 2 is NOT attempted
    # (it would have succeeded — the fault list is spent — so a
    # nonempty `added` here would prove the abort didn't happen)
    assert not out["ok"] and out["added"] == []
    assert len(out["failures"]) == 1
    assert drv.n_live == 1
    drv.stop()


def test_report_counts_partial_scale_events(tmp_path):
    # review finding: a partial scale-up (ok False, replicas added)
    # must still appear in the report's event timeline
    from ray_lightning_tpu.telemetry.report import (
        build_autoscale_section,
    )

    entry = {"decision_index": 0, "now": 0.0, "signal": {},
             "decision": {"action": "scale_up", "target": 3,
                          "delta": 2, "reason": "x", "clamps": []},
             "outcome": {"ok": False, "action": "scale_up",
                         "added": [1], "retries": 1},
             "replicas": 2, "duration_s": 0.1}
    (tmp_path / "autoscale.jsonl").write_text(json.dumps(entry) + "\n")
    sec = build_autoscale_section(str(tmp_path),
                                  str(tmp_path / "telemetry"))
    assert sec["scale_ups"] == 1
    assert sec["events"][0]["partial"] is True
    assert sec["spawn_retries"] == 1


def test_ledger_schema_and_counts(tmp_path):
    cfg, params, ecfg, _, _ = _session_setup(2, 4)
    run_dir = str(tmp_path / "run")
    drv = _driver(cfg, params, ecfg, run_dir=run_dir)
    drv.start()
    sigs = iter([
        {"available": False},
        {"available": True, "pressure": 2.0, "queue_depth_now": 8.0,
         "occupancy": 1.0, "total_slots": 4.0},
        {"available": True, "pressure": 0.0, "queue_depth_now": 0.0,
         "occupancy": 0.0, "total_slots": 8.0},
    ])
    ctl = AutoscaleController(
        drv,
        ControllerConfig(policy=PolicyConfig(
            min_replicas=1, max_replicas=2, sustain_polls=1,
            up_cooldown_s=0.0, down_cooldown_s=0.0)),
        run_dir=run_dir, signal_fn=lambda: next(sigs))
    ctl.step(now=0.0)    # no signal -> hold
    ctl.step(now=5.0)    # scale up
    ctl.step(now=50.0)   # scale down
    entries = read_ledger(run_dir)
    assert len(entries) == 3 == ctl.decisions
    for i, e in enumerate(entries):
        assert e["decision_index"] == i
        for key in ("now", "signal", "decision", "outcome",
                    "duration_s", "replicas"):
            assert key in e, f"ledger entry {i} missing {key}"
    assert entries[0]["decision"]["action"] == "hold"
    assert entries[1]["decision"]["action"] == "scale_up"
    assert entries[1]["signal"]["pressure"] == 2.0
    assert entries[2]["decision"]["action"] == "scale_down"
    counters = drv.driver_metrics.counters()
    assert counters["autoscale_decisions"] == 3
    assert counters["autoscale_scale_ups"] == 1
    assert counters["autoscale_scale_downs"] == 1
    drv.stop()


def test_scripted_ramp_scales_up_and_down_bitwise(tmp_path):
    # the full closed loop on REAL signal plumbing (flushed metrics ->
    # load_signal -> policy -> seams): 1 -> 2 on sustained pressure,
    # 2 -> 1 on idle, streams bitwise — the smoke's ramp leg as a
    # test. ONE ramp run also feeds the report-section assertions
    # below (a second full ramp would double the suite cost for no
    # extra coverage).
    from ray_lightning_tpu.autoscale.cli import _ramp_setup, _run_ramp
    from ray_lightning_tpu.telemetry.report import build_serving_section

    run_dir = str(tmp_path / "run")
    cfg, params, ecfg, reqs, refs = _ramp_setup(12, 8)
    drv, ctl, sim, result = _run_ramp(cfg, params, ecfg, reqs, run_dir)
    assert ctl.scale_ups == 1 and ctl.scale_downs == 1
    assert result.stats["final_replicas"] == 1
    assert _mismatches(result.outputs, refs) == []
    assert len(result.meta) == len(reqs)
    assert result.stats["compile_count"] == 1
    entries = read_ledger(run_dir)
    assert len(entries) == ctl.decisions >= 10
    events = [e for e in entries
              if e["decision"]["action"] != "hold"
              and e["outcome"]["ok"]]
    assert [e["decision"]["action"] for e in events] == \
        ["scale_up", "scale_down"]
    assert events[1]["now"] - events[0]["now"] >= 8.0  # down-cooldown
    # report surface: the serving section grows the autoscale block
    section = build_serving_section(run_dir)
    asc = section["autoscale"]
    assert asc["scale_ups"] == 1 and asc["scale_downs"] == 1
    assert asc["final_replicas"] == 1
    assert asc["decisions"] == ctl.decisions
    assert asc["last_decision"]["action"]
    assert [e["action"] for e in asc["events"]] == \
        ["scale_up", "scale_down"]


def test_retired_replica_excluded_from_load_signal(tmp_path):
    from ray_lightning_tpu.serve.driver import load_signal

    cfg, params, ecfg, reqs, _ = _session_setup(4, 6)
    run_dir = str(tmp_path / "run")
    drv = _driver(cfg, params, ecfg, run_dir=run_dir, n_replicas=2)
    drv.start()
    for _ in range(4):
        drv.tick()
    drv.remove_replica(graceful=True)
    drv.tick()   # drain completes -> retired stamp flushed
    sig = load_signal(run_dir, window=8)
    assert sig["available"]
    assert sig["replicas_reporting"] == 1
    assert sig.get("replicas_retired") == 1
    # only the live replica's slots count toward pressure's denominator
    assert sig["total_slots"] == ecfg.capacity
    drv.stop()


def test_run_batch_mode_untouched_by_session_state():
    # the historical fixed-batch run() still works on a driver that
    # never started a session (no seams consulted)
    cfg, params, ecfg, reqs, refs = _session_setup(4, 6)
    drv = _driver(cfg, params, ecfg)
    res = drv.run(list(reqs))
    assert _mismatches(res.outputs, refs) == []
    with pytest.raises(RuntimeError, match="start"):
        drv.tick()


# ---- surfaces: report + bench + gate ---------------------------------------


def test_bench_autoscale_drill():
    import jax
    import jax.numpy as jnp

    import bench
    from ray_lightning_tpu.models.llama import Llama, LlamaConfig
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    model = Llama(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0),
        np.zeros((1, 4), np.int32))["params"]
    r = bench._measure_autoscale(cfg, ecfg, params)
    assert "autoscale_error" not in r, r
    assert r["scale_up_s"] is not None and r["scale_up_s"] > 0
    asc = r["autoscale"]
    assert asc["scale_ups"] == 1 and asc["scale_downs"] == 1
    assert asc["final_replicas"] == 1
    assert asc["decisions"] == 2


def test_bench_serving_leg_threads_autoscale_fields(monkeypatch):
    # the serving leg merges the drill's fields into its row (and the
    # drill runs by default on real bench lines); the drill's own
    # mechanics are covered above without paying the full leg twice
    import bench

    stub = {"scale_up_s": 1.23,
            "autoscale": {"scale_up_s": 1.23, "decisions": 2,
                          "scale_ups": 1, "scale_downs": 1,
                          "final_replicas": 1}}
    monkeypatch.setattr(bench, "_measure_autoscale",
                        lambda *a, **k: dict(stub))
    r = bench._measure_serving(tiny=True)
    assert r["scale_up_s"] == 1.23
    assert r["autoscale"]["final_replicas"] == 1
    r = bench._measure_serving(tiny=True, autoscale=False)
    assert "scale_up_s" not in r and "autoscale" not in r


def test_bench_static_schema_names_autoscale():
    import bench

    s = bench._serve_summary()
    assert "serving" in s, s.get("serving_error")
    assert "scale_up_s" in s["serving"]["schema"]
    assert "autoscale" in s["serving"]["schema"]
    assert "scale_up_s" in s["serving"]["autoscale_schema"]


def test_bench_gate_bounds_scale_up_s():
    import importlib
    import sys

    scripts = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_gate = importlib.import_module("bench_gate")
    line = {"metric": "m", "value": 1.0, "scale_up_s": 1e9}
    failures = bench_gate.gate(line, {}, 0.05)
    assert any("scale_up_s" in f for f in failures)
    line["scale_up_s"] = 0.5
    assert not bench_gate.gate(line, {}, 0.05)
    # null / absent / skip waived
    line["scale_up_s"] = None
    assert not bench_gate.gate(line, {}, 0.05)
    skip = {"metric": "m", "skipped": "backend", "scale_up_s": 1e9}
    assert not bench_gate.gate(skip, {}, 0.05)


# ---- answer serialization --------------------------------------------------


def test_capacity_answer_to_dict():
    d = CapacityAnswer(3, "env", "RLT_CAPACITY=3").to_dict()
    assert d == {"worlds": 3, "source": "env",
                 "detail": "RLT_CAPACITY=3"}
    assert CapacityAnswer(None, "none").to_dict() == \
        {"worlds": None, "source": "none"}
