"""Fused paged-prefill kernel (ISSUE 15): op-level parity matrix
(pallas interpret mode vs the gathering XLA reference — block sizes,
GQA ratios, ragged left pads, chunk widths that do not divide the slot
length), scratch-block-0 poisoning, fully-masked-tile zeros, dispatch
predicate honesty, the engine's fused prefill lane (streams vs the
reference lane, churn compile pin, baked static dispatch), the RLT308
fire/sanction matrix, the fused-prefill serve plan (gather retired,
HBM strictly below the fused-decode-only figure), the block-size
autotune sweep + artifact round-trip, and the bench / bench_gate
legs."""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, LlamaConfig, generate
from ray_lightning_tpu.ops import dispatch
from ray_lightning_tpu.ops.attention import (
    PagedPrefillView,
    paged_prefill,
    paged_prefill_reference,
    paged_prefill_uses_pallas,
)
from ray_lightning_tpu.ops.pallas.paged_prefill import (
    _fit_q_block,
    paged_prefill_pallas,
    paged_prefill_shapes_supported,
)
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.scheduler import Request, Scheduler


# ---- op-level parity matrix ------------------------------------------------


def _rand_case(rng, B, CH, H, hd, Hkv, P, M, N, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((B, CH, H, hd)), dtype)
    pk = jnp.asarray(rng.standard_normal((N, P, Hkv, hd)), dtype)
    pv = jnp.asarray(rng.standard_normal((N, P, Hkv, hd)), dtype)
    tables = jnp.asarray(rng.integers(1, N, (B, M)), jnp.int32)
    return q, pk, pv, tables


@pytest.mark.parametrize("B,CH,H,hd,Hkv,P,M,N,pos", [
    (2, 16, 4, 64, 2, 8, 4, 10, 8),    # GQA 2:1, mid-prompt chunk
    (1, 8, 8, 64, 8, 16, 2, 7, 0),     # MHA, 16-token blocks, chunk 0
    (3, 32, 4, 128, 1, 8, 5, 9, 4),    # MQA, lane-wide head dim
    (2, 12, 4, 64, 2, 8, 4, 9, 16),    # chunk 12: not a power of two
])
def test_kernel_matches_reference_matrix(B, CH, H, hd, Hkv, P, M, N,
                                         pos):
    """The parity matrix: block_size x chunk width x GQA ratio, with
    causal in-chunk masking, interpret mode on CPU."""
    rng = np.random.default_rng(B * 100 + CH)
    q, pk, pv, tables = _rand_case(rng, B, CH, H, hd, Hkv, P, M, N)
    ref = paged_prefill_reference(q, pk, pv, tables, pos)
    got = paged_prefill_pallas(q, pk, pv, tables, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_ragged_pad_masking_matches_reference():
    """Ragged left pads (the batched right-aligned group): positions
    < pad[b] are invisible on both paths, and the pad matters."""
    rng = np.random.default_rng(7)
    q, pk, pv, tables = _rand_case(rng, 3, 16, 4, 64, 2, 8, 4, 9)
    pad = jnp.asarray([0, 5, 11], jnp.int32)
    pos = 16
    ref = paged_prefill_reference(q, pk, pv, tables, pos, pad=pad)
    got = paged_prefill_pallas(q, pk, pv, tables, pos, pad=pad)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    unpadded = paged_prefill_reference(q, pk, pv, tables, pos)
    assert not np.allclose(np.asarray(unpadded), np.asarray(ref))


def test_kernel_scratch_block_zero_masked():
    """Table tails past the chunk's causal horizon point at scratch
    block 0 (garbage by contract). Poisoning scratch with huge values
    must not perturb any visible output."""
    rng = np.random.default_rng(11)
    B, CH, pos = 2, 8, 8
    q, pk, pv, tables = _rand_case(rng, B, CH, 4, 64, 2, 8, 4, 8)
    # positions visible end at pos + CH - 1 = 15 -> blocks 2..3 of the
    # table are never visible; point them at scratch
    tables = tables.at[:, 2:].set(0)
    base = paged_prefill_pallas(q, pk.at[0].set(0.0),
                                pv.at[0].set(0.0), tables, pos)
    hot = paged_prefill_pallas(q, pk.at[0].set(1e9),
                               pv.at[0].set(1e9), tables, pos)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(hot))


def test_kernel_fully_masked_rows_emit_zeros():
    """A row whose pad swallows the whole causal window (a vacant
    group row riding the all-scratch table) must emit zeros, not NaN —
    the exp(-1e30 - (-1e30)) sentinel trap, prefill edition. Pad-column
    QUERIES (q_pos < pad) also see nothing and emit zeros."""
    rng = np.random.default_rng(13)
    q, pk, pv, tables = _rand_case(rng, 2, 8, 4, 64, 2, 8, 2, 5)
    pos = 4
    pad = jnp.asarray([pos + 8, 6], jnp.int32)  # row 0: pad > window
    out = paged_prefill_pallas(q, pk, pv, tables, pos, pad=pad)
    assert np.all(np.asarray(out[0]) == 0.0)
    # row 1: queries at positions 4..5 sit under pad=6 -> zeros; later
    # queries see something
    assert np.all(np.asarray(out[1, :2]) == 0.0)
    assert np.any(np.asarray(out[1, 2:]) != 0.0)
    assert np.all(np.isfinite(np.asarray(out)))


def test_bf16_parity_tolerance():
    rng = np.random.default_rng(17)
    q, pk, pv, tables = _rand_case(rng, 2, 16, 4, 64, 2, 8, 3, 9,
                                   dtype=jnp.bfloat16)
    ref = paged_prefill_reference(q, pk, pv, tables, 8)
    got = paged_prefill_pallas(q, pk, pv, tables, 8)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


# ---- dispatch predicate ----------------------------------------------------


def test_shapes_supported_contract():
    assert paged_prefill_shapes_supported((2, 16, 8, 64),
                                          (16, 8, 2, 64))
    assert paged_prefill_shapes_supported((2, 16, 8, 128),
                                          (16, 8, 2, 128))
    # lane-misaligned head dim (the main tiny config's hd=16)
    assert not paged_prefill_shapes_supported((2, 16, 4, 16),
                                              (16, 8, 2, 16))
    # sublane-misaligned block size
    assert not paged_prefill_shapes_supported((2, 16, 8, 64),
                                              (16, 4, 2, 64))
    # ragged GQA ratio
    assert not paged_prefill_shapes_supported((2, 16, 3, 64),
                                              (16, 8, 2, 64))
    # head-dim mismatch between q and pool
    assert not paged_prefill_shapes_supported((2, 16, 8, 64),
                                              (16, 8, 2, 128))
    # chunk x heads panel not sublane-aligned: CH=6, H=2 -> q tile 6,
    # 12 rows (the smoke leg's chunk-6 refusal)
    assert not paged_prefill_shapes_supported((2, 6, 2, 64),
                                              (16, 8, 1, 64))
    # but CH=12, H=2 -> 24 rows, aligned
    assert paged_prefill_shapes_supported((2, 12, 2, 64),
                                          (16, 8, 1, 64))


def test_fit_q_block_halving():
    assert _fit_q_block(256) == 128
    assert _fit_q_block(12) == 12
    assert _fit_q_block(6) == 6
    assert _fit_q_block(192) == 64  # 128 does not divide -> halve


def test_uses_pallas_respects_dispatch_context():
    q_shape, pool_shape = (2, 16, 8, 64), (16, 8, 2, 64)
    with dispatch.force_pallas():
        assert paged_prefill_uses_pallas(q_shape, pool_shape)
        # shape gate still wins under force
        assert not paged_prefill_uses_pallas((2, 16, 4, 16),
                                             (16, 8, 2, 16))
    with dispatch.force_xla():
        assert not paged_prefill_uses_pallas(q_shape, pool_shape)
    # explicit override beats the context
    with dispatch.force_xla():
        assert paged_prefill_uses_pallas(q_shape, pool_shape,
                                         use_pallas=True)


def test_paged_prefill_dispatches_both_paths():
    rng = np.random.default_rng(23)
    q, pk, pv, tables = _rand_case(rng, 2, 16, 4, 64, 2, 8, 3, 9)
    ref = paged_prefill(q, pk, pv, tables, 8, use_pallas=False)
    with dispatch.force_pallas():
        got = paged_prefill(q, pk, pv, tables, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- engine: fused prefill lane --------------------------------------------


@pytest.fixture(scope="module")
def kernel_tiny():
    """A kernel-TILING tiny model (head_dim 64, GQA 2:1) — the main
    serve suite's tiny config has head_dim 16, which both kernels
    correctly refuse."""
    cfg = LlamaConfig(vocab_size=256, dim=128, n_layers=2, n_heads=2,
                      n_kv_heads=1, hidden_dim=256, max_seq_len=128,
                      remat=False, dtype=jnp.float32)
    model = Llama(cfg)
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(700 + i), (1, 2 + (i % 7)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    params = jax.jit(model.init)(jax.random.key(3),
                                 prompts[0])["params"]
    return cfg, model, params, prompts


def _mixed_requests(prompts, max_new=6):
    return [Request(rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
                    temperature=0.7 if i % 2 else 0.0,
                    top_k=5 if i % 2 else None, seed=31 + i)
            for i, p in enumerate(prompts)]


def _drain(sched, submit):
    pending = list(submit)
    out = {}
    while sched.busy() or pending:
        if pending:
            sched.submit(pending.pop(0))
        for comp in sched.tick():
            out[comp.rid] = comp
    return out


@pytest.mark.parametrize("prefill_chunk,prefill_batch", [
    (4, 1),    # chunk divides the 32-token slot, single-slot lane
    (12, 2),   # chunk does NOT divide the slot (the PR 8 tail-window
               # class) on the ragged left-padded batched lane
])
def test_fused_prefill_streams_match_reference(kernel_tiny,
                                               prefill_chunk,
                                               prefill_batch):
    """The stream-level parity pin: the fused-prefill engine serves the
    mixed-sampling ragged workload token-for-token equal to the
    reference-lane engine (itself bitwise vs generate — re-proven
    here), across a chunk width that does not divide the slot
    length."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=prefill_chunk,
                        prefill_batch=prefill_batch)
    reqs = _mixed_requests(prompts)
    refs = {
        r.rid: np.asarray(generate(
            model, params, prompts[i], r.max_new_tokens,
            temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)
    }
    ref_engine = DecodeEngine(model, params, ecfg, use_pallas=False)
    assert ref_engine.prefill_path == "reference-gather"
    out_ref = _drain(Scheduler(ref_engine), _mixed_requests(prompts))
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(out_ref[rid].tokens),
                                      ref, err_msg=rid)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        assert eng.fused_prefill
        assert eng.prefill_path == "paged-pallas"
        out_fused = _drain(Scheduler(eng), _mixed_requests(prompts))
    for rid in refs:
        assert out_fused[rid].tokens == out_ref[rid].tokens, rid


def test_fused_prefill_churn_compile_count_pinned(kernel_tiny):
    """Request churn through the fused-prefill step stays one compiled
    program — the prefill dispatch decision is build-time static."""
    cfg, model, params, prompts = kernel_tiny
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
        assert eng.fused_prefill
        sched = Scheduler(eng)
        for wave in range(3):
            _drain(sched, _mixed_requests(prompts[wave * 2:
                                                  wave * 2 + 2],
                                          max_new=4))
    assert eng.compile_count in (1, -1)


def test_prefill_view_bakes_static_dispatch(kernel_tiny):
    """The PR 11 force-context lesson, prefill edition: the build-time
    decision rides `PagedPrefillView.use_pallas` as STATIC pytree aux,
    so a fused-prefill step traced under force_xla (the worst ambient
    context a late jit trace could see) still lowers the prefill
    kernel."""
    from ray_lightning_tpu.serve.audit import trace_decode_step

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    with dispatch.force_xla():
        _, meta = trace_decode_step(cfg, ecfg, fused=True)
    assert any("paged_prefill" in k for k in meta["pallas_kernels"])
    assert not meta["prefill_paged_gathers"]
    # aux round-trips through tree flatten/unflatten
    view = PagedPrefillView(jnp.zeros((1, 2), jnp.int32),
                            jnp.zeros((1, 4), jnp.int32),
                            jnp.zeros((1, 4), jnp.int32),
                            use_pallas=True)
    leaves, treedef = jax.tree_util.tree_flatten(view)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.use_pallas is True


def test_fused_prefill_respects_use_flash_false(kernel_tiny):
    """A use_flash=False model must keep the gathering reference
    prefill even under force_pallas — the flash discipline."""
    cfg, _, params, prompts = kernel_tiny
    rcfg = LlamaConfig(**{**cfg.__dict__, "use_flash": False})
    rmodel = Llama(rcfg)
    with dispatch.force_pallas():
        eng = DecodeEngine(rmodel, params, EngineConfig(
            capacity=2, block_size=8, blocks_per_slot=4,
            prefill_chunk=4))
    assert not eng.fused_prefill
    assert eng.prefill_path == "reference-gather"


# ---- audit: RLT308 fire/sanction -------------------------------------------


def test_rlt308_fires_on_reference_prefill_gather(kernel_tiny):
    """Kernel-tiling shape: the reference trace's cond-nested prefill
    gather is RLT308 evidence and flags; the fused trace has neither
    gather at any nesting level and audits clean with both kernels in
    the trace."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    _, meta = trace_decode_step(cfg, ecfg, fused=False)
    assert meta["prefill_paged_gathers"], \
        "reference trace lost its cond-nested prefill gather?"
    rep = audit_decode_step(cfg, ecfg, fused=False)
    rules = {f.rule for f in rep.findings}
    assert "RLT308" in rules
    rep_f = audit_decode_step(cfg, ecfg, fused=True)
    assert not {f.rule for f in rep_f.findings} & {
        "RLT301", "RLT303", "RLT307", "RLT308"}
    _, meta_f = trace_decode_step(cfg, ecfg, fused=True)
    assert not meta_f["dense_paged_gathers"]
    assert not meta_f["prefill_paged_gathers"]
    assert any("paged_prefill" in k for k in meta_f["pallas_kernels"])


def test_rlt308_fires_on_batched_group_gather(kernel_tiny):
    """The batched lane's [L, B, M, P, Hkv, hd] group view is RLT308
    evidence too (B < capacity — a shape RLT307's top-level
    capacity-wide matcher would never see)."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4, prefill_batch=2)
    _, meta = trace_decode_step(cfg, ecfg, fused=False)
    assert any(len(s) == 6 for s in meta["prefill_paged_gathers"])
    rep = audit_decode_step(cfg, ecfg, fused=False)
    assert "RLT308" in {f.rule for f in rep.findings}
    rep_f = audit_decode_step(cfg, ecfg, fused=True)
    assert "RLT308" not in {f.rule for f in rep_f.findings}


def test_rlt308_sanctioned_on_unsupported_shape():
    """The main tiny config (head_dim 16) cannot take the prefill
    kernel: its reference trace keeps the group gather WITHOUT an
    RLT308 — the historical sanction survives where the kernel cannot
    tile."""
    from ray_lightning_tpu.serve.audit import audit_decode_step

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    rep = audit_decode_step(cfg, ecfg, fused=False)
    assert "RLT308" not in {f.rule for f in rep.findings}


def test_audit_default_mirrors_engine_on_asymmetric_shape(kernel_tiny):
    """The lanes gate shapes INDEPENDENTLY: chunk 6 with 2 heads tiles
    the decode kernel but the prefill kernel refuses it (the 12-row
    score panel misses the sublane floor), so DecodeEngine compiles
    the MIXED program — and `trace_decode_step(fused=True)`'s
    fused_prefill=None default must trace that same mix (decode kernel
    present, prefill gather present-but-sanctioned), not a
    fused-prefill program the replica never runs."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )

    cfg, model, params, _ = kernel_tiny
    ecfg = EngineConfig(capacity=4, block_size=8, blocks_per_slot=4,
                        prefill_chunk=6)
    with dispatch.force_pallas():
        eng = DecodeEngine(model, params, ecfg)
    assert eng.fused and not eng.fused_prefill
    _, meta = trace_decode_step(cfg, ecfg, fused=True)
    assert meta["fused_prefill"] is False
    assert any("paged_attention" in k for k in meta["pallas_kernels"])
    assert not any("paged_prefill" in k
                   for k in meta["pallas_kernels"])
    assert meta["prefill_paged_gathers"], \
        "the mixed program's prefill gather went missing"
    rep = audit_decode_step(cfg, ecfg, fused=True)
    rules = {f.rule for f in rep.findings}
    assert "RLT307" not in rules       # decode view retired
    assert "RLT308" not in rules       # gather present but sanctioned


# ---- flagship plan ----------------------------------------------------------


def _flagship():
    from ray_lightning_tpu.serve.audit import serve_memory_summary

    cfg = LlamaConfig.llama3_8b(max_seq_len=4096, dtype=jnp.bfloat16)
    ecfg = EngineConfig(capacity=8, block_size=16, blocks_per_slot=256,
                        prefill_chunk=256)
    return cfg, ecfg, serve_memory_summary


def test_flagship_fused_prefill_plan_below_pr11():
    """The acceptance pin: the fused-both flagship plan itemizes the
    prefill gather at 0 and sits STRICTLY below the PR-11 figure
    (fused decode, reference prefill), which itself sits strictly
    below the all-reference plan."""
    cfg, ecfg, summary = _flagship()
    s_auto = summary(cfg, ecfg)
    s_pr11 = summary(cfg, ecfg, fused=True, fused_prefill=False)
    s_ref = summary(cfg, ecfg, fused=False, fused_prefill=False)
    assert s_auto["attention_path"] == "paged-pallas"
    assert s_auto["prefill_attention_path"] == "paged-pallas"
    assert s_auto["prefill_gather_bytes"] == 0
    assert s_auto["gathered_view_bytes"] == 0
    assert s_pr11["prefill_gather_bytes"] > 0
    assert (s_auto["per_device_bytes"] < s_pr11["per_device_bytes"]
            < s_ref["per_device_bytes"])
    # what the prefill kernel bought back is exactly the group view
    assert (s_pr11["per_device_bytes"] - s_auto["per_device_bytes"]
            == s_pr11["prefill_gather_bytes"])
    # traffic model: fused prefill drops the view write+read
    assert (s_auto["prefill_kv_traffic_bytes_per_chunk"]
            < s_pr11["prefill_kv_traffic_bytes_per_chunk"])
    # the itemization terms are reporting, never resident buffers
    resident = (s_auto["params_bytes"] + s_auto["pool_bytes"]
                + s_auto["gathered_view_bytes"]
                + s_auto["last_logits_bytes"])
    assert s_auto["per_device_bytes"] == resident


def test_plan_serve_cli_reports_fused_prefill(capsys):
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "llama3-8b", "--serve", "--seq",
               "4096", "--json", "--no-trace"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["serve"]["prefill_attention_path"] == "paged-pallas"
    assert out["serve"]["prefill_gather_bytes"] == 0


@pytest.mark.slow
def test_flagship_audit_reference_flags_rlt308():
    """The reference-path flagship trace still gathers the per-group
    prefill view on a shape the prefill kernel tiles -> RLT308 fires;
    the fused flagship trace has no gather at any nesting level."""
    from ray_lightning_tpu.serve.audit import (
        audit_decode_step, trace_decode_step,
    )

    cfg, ecfg, _ = _flagship()
    rep = audit_decode_step(cfg, ecfg, topology="v5p-8", fused=False)
    assert "RLT308" in {f.rule for f in rep.findings}
    rep_f = audit_decode_step(cfg, ecfg, topology="v5p-8", fused=True)
    assert not {f.rule for f in rep_f.findings} & {
        "RLT301", "RLT303", "RLT307", "RLT308"}
    _, meta = trace_decode_step(cfg, ecfg, fused=True)
    assert any("paged_prefill" in k for k in meta["pallas_kernels"])
    assert not meta["prefill_paged_gathers"]


# ---- block-size autotune ----------------------------------------------------


def test_candidate_grid_preserves_span():
    from ray_lightning_tpu.serve.sweep import candidate_grid

    ecfg = EngineConfig(capacity=4, block_size=16, blocks_per_slot=4,
                        prefill_chunk=8)
    grid = candidate_grid(ecfg)
    assert grid, "no candidates for a 64-token span?"
    assert all(c.span == 64 for c in grid)
    assert all(c.block_size % 8 == 0 for c in grid)
    # the incumbent geometry is always in the grid
    assert any(c.block_size == 16 and c.blocks_per_slot == 4
               for c in grid)


def test_autotune_sweep_smoke_and_artifact_roundtrip(kernel_tiny,
                                                     tmp_path):
    """The sweep smoke (interpret mode on CPU): every candidate runs
    BOTH kernels' correctness, timing degrades to the structured skip,
    the winner falls back to the incumbent labeled default-untimed,
    and the artifact round-trips through save/load/apply."""
    from ray_lightning_tpu.serve.sweep import (
        apply_autotune, load_artifact, model_fingerprint,
        save_artifact, sweep_paged_kernels,
    )

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    art = sweep_paged_kernels(cfg, ecfg, block_sizes=(8, 16),
                              topology="v5p-8")
    assert art["kind"] == "rlt-paged-kernel-autotune"
    assert art["model"] == model_fingerprint(cfg)
    assert len(art["results"]) == 2
    for r in art["results"]:
        assert r["decode"]["ok"], r
        assert r["prefill"]["ok"], r
        assert "skipped" in r["timing"]  # CPU: structured skip
    assert art["winner"] == {"block_size": 8, "blocks_per_slot": 4}
    assert art["winner_source"] == "default-untimed"
    path = str(tmp_path / "autotune.json")
    save_artifact(art, path)
    art2 = load_artifact(path)
    assert art2 == json.loads(json.dumps(art))
    tuned = apply_autotune(ecfg, art2, model_cfg=cfg)
    assert (tuned.block_size, tuned.blocks_per_slot) == (8, 4)
    assert tuned.block_size * tuned.blocks_per_slot == \
        ecfg.block_size * ecfg.blocks_per_slot


def test_autotune_apply_refusals(kernel_tiny, tmp_path):
    """apply_autotune refuses: no winner, span mismatch, model
    fingerprint mismatch; load_artifact refuses foreign JSON."""
    from ray_lightning_tpu.serve.sweep import (
        apply_autotune, load_artifact,
    )

    cfg, _, _, _ = kernel_tiny
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    art = {"kind": "rlt-paged-kernel-autotune", "model": "L2-X",
           "span": 32, "winner": {"block_size": 16,
                                  "blocks_per_slot": 2}}
    with pytest.raises(ValueError, match="no winner"):
        apply_autotune(ecfg, {**art, "winner": None})
    with pytest.raises(ValueError, match="span"):
        apply_autotune(ecfg, {**art, "span": 64})
    with pytest.raises(ValueError, match="swept for model"):
        apply_autotune(ecfg, art, model_cfg=cfg)
    tuned = apply_autotune(ecfg, art)  # no model check requested
    assert tuned.block_size == 16
    p = tmp_path / "foreign.json"
    p.write_text(json.dumps({"kind": "something-else"}))
    with pytest.raises(ValueError, match="not a paged-kernel"):
        load_artifact(str(p))


def test_autotune_unsupported_model_has_no_winner():
    """The main tiny config (head_dim 16): both kernels refuse every
    candidate, so the artifact is honest — no winner, correctness
    entries carry the refusal."""
    from ray_lightning_tpu.serve.sweep import sweep_paged_kernels

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    ecfg = EngineConfig(capacity=2, block_size=8, blocks_per_slot=4,
                        prefill_chunk=4)
    art = sweep_paged_kernels(cfg, ecfg, block_sizes=(8,))
    assert art["winner"] is None
    assert art["winner_source"] is None
    assert all(not r["decode"]["ok"] and not r["prefill"]["ok"]
               for r in art["results"])


# ---- bench + bench_gate ----------------------------------------------------


def test_bench_serve_summary_carries_prefill_metrics():
    import bench

    s = bench._serve_summary()
    assert "serving_error" not in s, s
    # the flagship prefill gather is retired: itemized at 0, on every
    # line (this is the static value bench_gate ceiling-ratchets)
    assert s["serve_prefill_gather_bytes"] == 0
    sv = s["serving"]
    assert sv["prefill_attention_path"] == "paged-pallas"
    assert "prefill_tokens_per_s" in sv["schema"]
    assert "serving_prefill_path" in sv["schema"]
    # the fused-both replica sits strictly below the all-reference
    # story (the serve_hbm ceiling re-anchors to this lower figure)
    assert (s["serve_hbm_bytes_per_replica"]
            < sv["reference_hbm_bytes_per_replica"])
    plan = sv["flagship_plan"]
    assert (s["serve_hbm_bytes_per_replica"]
            == plan["per_device_bytes"])
    assert plan["prefill_gather_bytes"] == 0


def test_measured_serving_records_prefill_throughput():
    import bench

    got = bench._measure_serving(tiny=True, autoscale=False)
    assert got["prefill_tokens_per_s"] > 0
    assert got["serving_prefill_path"] in ("paged-pallas",
                                           "reference-gather")
    assert got["serving_compile_count"] in (1, -1)


def _gate(fresh, priors, tmp_path):
    import importlib
    import os
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_gate = importlib.import_module("bench_gate")
    for i, p in enumerate(priors):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": p}))
    best = bench_gate.best_prior("BENCH_r*.json", str(tmp_path))
    ceilings = bench_gate.ceiling_prior("BENCH_r*.json", str(tmp_path))
    return bench_gate.gate(fresh, best, 0.05, ceilings)


def test_bench_gate_prefill_gather_ceiling(tmp_path):
    base = {"metric": "m", "value": 1.0,
            "serve_prefill_gather_bytes": 0}
    # holding at zero passes
    ok = _gate({"metric": "m", "value": 1.0,
                "serve_prefill_gather_bytes": 0}, [base], tmp_path)
    assert not ok
    # re-materializing the gather fails (anchored at 0, any growth
    # breaks the ceiling)
    bad = _gate({"metric": "m", "value": 1.0,
                 "serve_prefill_gather_bytes": 3 * 2**30},
                [base], tmp_path)
    assert any("serve_prefill_gather_bytes" in f for f in bad)
    # static class: ratchets on skip lines too
    bad_skip = _gate({"metric": "m", "skipped": "backend unavailable",
                      "serve_prefill_gather_bytes": 3 * 2**30},
                     [base], tmp_path)
    assert any("serve_prefill_gather_bytes" in f for f in bad_skip)
    # serving_error waives an ABSENT value...
    waived = _gate({"metric": "m", "value": 1.0,
                    "serving_error": "TypeError: boom"},
                   [base], tmp_path)
    assert not any("serve_prefill_gather_bytes" in f for f in waived)
    # ...but a silently dropped field fails
    dropped = _gate({"metric": "m", "value": 1.0}, [base], tmp_path)
    assert any("dropped the field" in f for f in dropped)


def test_bench_gate_serve_hbm_reanchors_to_fused_prefill(tmp_path):
    """The ISSUE 15 re-anchor: a fresh fused-prefill line BELOW the
    PR-11 prior passes and becomes the next anchor; a later line
    regressing past tolerance (back to the all-reference figure) then
    fails against the LOWER anchor. (The 0.5 GiB prefill-gather delta
    alone sits inside the gate's 5% tolerance on a 34 GiB total —
    which is exactly why `serve_prefill_gather_bytes` gets its OWN
    zero-anchored ceiling above: the params-dominated aggregate can
    never watch the gather precisely.)"""
    pr11 = {"metric": "m", "value": 1.0,
            "serve_hbm_bytes_per_replica": 36958375936}  # 34.42 GiB
    fused_pf = {"metric": "m", "value": 1.0,
                "serve_hbm_bytes_per_replica": 36421636096}  # 33.92
    assert not _gate(fused_pf, [pr11], tmp_path)
    regress = {"metric": "m", "value": 1.0,
               "serve_hbm_bytes_per_replica": 40718958592}  # 37.92
    bad = _gate(regress, [pr11, fused_pf], tmp_path)
    assert any("serve_hbm_bytes_per_replica" in f for f in bad)
