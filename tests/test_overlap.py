"""Collective overlap (ISSUE 6): the double-buffered weight-gather
prefetch schedule in the scanned Llama stack + tracecheck's
hidden-vs-exposed classification.

The guarantees pinned here:
  * overlap="on" and overlap="serial" (the same explicit gather schedule
    minus the prefetch) train BITWISE-identically — the only delta
    between the two programs is where the gather latency sits;
  * overlap="off" compiles the exact pre-knob program (no prefetch
    fingerprint, `_loss` takes the historical path);
  * tracecheck classifies the overlapped schedule's collectives against
    the compute-window roofline (fully hidden / partially exposed /
    zero-compute), flags the un-overlapped scan with RLT305, and the
    flagship 8B/v5p-64 trace hides >= 70% of prefetchable ICI time;
  * the plan CLI charges the double-buffer HBM;
  * scripts/bench_gate.py ratchets bench metrics and passes structured
    skips.
"""
import importlib.util
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer
from ray_lightning_tpu.analysis.costmodel import (
    Topology, compute_time_us, parse_topology, topology_for_kind,
)
from ray_lightning_tpu.analysis.tracecheck import (
    CollectiveEvent, audit_step, classify_overlap,
)
from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
from ray_lightning_tpu.ops.dispatch import OVERLAP_PREFETCH_NAME

jnp = jax.numpy


def _tiny_cfg(**kw):
    return LlamaConfig.tiny(use_flash=False, **kw)


def _data(cfg, n=64, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"tokens": rng.integers(
        0, cfg.vocab_size, (n, seq + 1)).astype(np.int32)}


def _fit(overlap, cfg=None, seed=0, **mesh_kw):
    cfg = cfg or _tiny_cfg()
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=50)
    data = _data(cfg)
    trainer = Trainer(
        strategy=ShardedMesh(overlap=overlap,
                             **(mesh_kw or {"fsdp": 4, "data": 2})),
        max_epochs=1, enable_progress_bar=False,
        enable_checkpointing=False, seed=seed)
    trainer.fit(module, DataLoader(data, batch_size=16, shuffle=True))
    return jax.device_get(module.params)


def _assert_tree_bitwise(a, b, what):
    for (pa, la), (pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        assert pa == pb
        la, lb = np.asarray(la), np.asarray(lb)
        assert la.tobytes() == lb.tobytes(), (
            f"{what}: {jax.tree_util.keystr(pa)} differs "
            f"(max abs diff {np.abs(la - lb).max()})")


# --------------------------------------------------------------------------
# bitwise equivalence of the schedules
# --------------------------------------------------------------------------


class TestScheduleEquivalence:
    def test_on_matches_serial_bitwise(self):
        """The prefetched and serial gather schedules are the same math
        in a different order on the wire — final params bitwise equal
        (full Trainer fit: donated state, optimizer, per-step RNG)."""
        on = _fit("on")
        serial = _fit("serial")
        _assert_tree_bitwise(on, serial, "overlap=on vs overlap=serial")

    def test_on_matches_single_device_ground_truth(self):
        """The overlapped hidden path computes exactly what the model
        computes with no sharding at all: forward on the fsdp x data
        mesh vs a single CPU device, bitwise."""
        cfg = _tiny_cfg(n_layers=4, dtype=jnp.float32)
        batch = _data(cfg, n=8)
        module = LlamaModule(cfg)
        strat = ShardedMesh(fsdp=4, data=2, overlap="on")
        strat.setup(module)
        module.setup()
        params = module.init_params(jax.random.PRNGKey(0), batch)
        host_params = jax.device_get(params)
        params = strat.shard_params(params)
        tokens = strat.shard_batch(batch)["tokens"][:, :-1]
        h_overlap = np.asarray(
            jax.jit(module._overlapped_hidden)(params, tokens))

        ref = LlamaModule(cfg)
        ref.mesh = None
        ref.setup()
        dev0 = jax.devices()[0]
        h_ref = np.asarray(jax.jit(
            lambda p, t: ref.apply(p, t, return_hidden=True),
            device=dev0)(jax.device_put(host_params, dev0),
                         jax.device_put(
                             np.asarray(batch["tokens"][:, :-1]), dev0)))
        assert h_overlap.tobytes() == h_ref.tobytes(), (
            f"max abs diff {np.abs(h_overlap - h_ref).max()}")

    def test_on_close_to_off(self):
        """Same math as the historical path up to XLA fusion
        reassociation (the schedules compile different programs, so
        bitwise equality is NOT expected — the serial ablation is the
        bitwise pin)."""
        cfg = _tiny_cfg(dtype=jnp.float32)
        on = _fit("on", cfg=cfg, fsdp=8)
        off = _fit("off", cfg=cfg, fsdp=8)
        for la, lb in zip(jax.tree.leaves(on), jax.tree.leaves(off)):
            np.testing.assert_allclose(
                np.asarray(la), np.asarray(lb), rtol=2e-4, atol=2e-4)

    def test_composes_with_trainguard_and_donation(self):
        """The guarded, donated train step compiles and trains with the
        overlap schedule on — the resilience paths see the same
        TrainState contract."""
        cfg = _tiny_cfg()
        module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=50)
        trainer = Trainer(
            strategy=ShardedMesh(fsdp=4, data=2, overlap="on"),
            max_epochs=1, enable_progress_bar=False,
            enable_checkpointing=False, seed=0, guard=True)
        trainer.fit(module, DataLoader(_data(cfg), batch_size=16))
        loss = float(trainer.callback_metrics["train_loss"])
        assert np.isfinite(loss)
        assert int(trainer.callback_metrics.get("guard_anomaly", 0)) == 0


def _spmd_overlap_fit(overlap):
    """Worker body for the 2-proc bitwise pin: fit the tiny Llama on a
    REAL multi-process fsdp=4 mesh (2 procs x 2 CPU devices, gloo
    collectives) and return every param leaf's LOCAL shard bytes in
    shard-index order — cross-process arrays are not fetchable whole, so
    each rank pins its own slice of the final state."""
    import jax
    import numpy as np

    from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule

    cfg = LlamaConfig.tiny(use_flash=False)
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=50)
    rng = np.random.default_rng(0)
    data = {"tokens": rng.integers(
        0, cfg.vocab_size, (64, 33)).astype(np.int32)}
    trainer = Trainer(
        strategy=ShardedMesh(fsdp=4, overlap=overlap),
        max_epochs=1, enable_progress_bar=False,
        enable_checkpointing=False, seed=0)
    trainer.fit(module, DataLoader(
        data, batch_size=16, num_shards=jax.process_count(),
        shard_index=jax.process_index()))
    out = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(module.params):
        shards = sorted(leaf.addressable_shards, key=lambda s: s.index)
        out[jax.tree_util.keystr(path)] = b"".join(
            np.asarray(s.data).tobytes() for s in shards)
    return out


@pytest.mark.slow
def test_two_process_fsdp_bitwise():
    """The satellite's 2-proc leg: overlap='on' vs the serial ablation on
    a real 2-process CPU-SPMD fsdp mesh — the prefetched gathers ride
    gloo across process boundaries and the final params must still match
    bit for bit on every rank's local shards."""
    from ray_lightning_tpu.runtime.launch import launch_cpu_spmd

    on = launch_cpu_spmd(_spmd_overlap_fit, num_processes=2,
                         devices_per_process=2, args=("on",), timeout=420)
    serial = launch_cpu_spmd(
        _spmd_overlap_fit, num_processes=2, devices_per_process=2,
        args=("serial",), timeout=420)
    for rank, (a, b) in enumerate(zip(on, serial)):
        assert a.keys() == b.keys()
        for k in a:
            assert a[k] == b[k], (
                f"rank {rank}: {k} differs between overlap=on and serial")


# --------------------------------------------------------------------------
# overlap=off pins the pre-PR program
# --------------------------------------------------------------------------


class TestOffPin:
    def _loss_jaxpr(self, overlap):
        cfg = _tiny_cfg()
        module = LlamaModule(cfg)
        strat = ShardedMesh(fsdp=4, data=2, overlap=overlap)
        strat.setup(module)
        module.setup()
        batch = _data(cfg, n=8)
        params = module.init_params(jax.random.PRNGKey(0), batch)
        tokens = jnp.asarray(batch["tokens"][:, :-1])
        targets = jnp.asarray(batch["tokens"][:, 1:])
        return jax.make_jaxpr(
            lambda p, i, t: module._loss(p, i, t, None))(
                params, tokens, targets)

    def test_off_is_byte_identical_to_unbound_module(self):
        """overlap='off' must trace the EXACT program a module that
        never saw the knob traces (the pre-PR schedule)."""
        off = str(self._loss_jaxpr("off"))

        cfg = _tiny_cfg()
        module = LlamaModule(cfg)  # never bound to a strategy knob
        strat = ShardedMesh(fsdp=4, data=2)
        strat.setup(module)
        module.setup()
        batch = _data(cfg, n=8)
        params = module.init_params(jax.random.PRNGKey(0), batch)
        vanilla = str(jax.make_jaxpr(
            lambda p, i, t: module._loss(p, i, t, None))(
                params, jnp.asarray(batch["tokens"][:, :-1]),
                jnp.asarray(batch["tokens"][:, 1:])))
        assert off == vanilla

    def test_fingerprint_present_iff_scheduled(self):
        off = str(self._loss_jaxpr("off"))
        on = str(self._loss_jaxpr("on"))
        serial = str(self._loss_jaxpr("serial"))
        assert OVERLAP_PREFETCH_NAME not in off
        assert OVERLAP_PREFETCH_NAME in on
        # the serial ablation runs the explicit gather schedule with the
        # prefetch REMOVED — no fingerprint, tracecheck reads it as
        # unscheduled
        assert OVERLAP_PREFETCH_NAME not in serial

    def test_use_overlap_gates(self):
        cfg = _tiny_cfg()
        module = LlamaModule(cfg)
        strat = ShardedMesh(fsdp=4, data=2, overlap="on")
        strat.setup(module)
        assert module._use_overlap()
        # no fsdp latency to hide -> the knob is inert
        module2 = LlamaModule(cfg)
        strat2 = ShardedMesh(data=8, overlap="on")
        strat2.setup(module2)
        assert not module2._use_overlap()
        # unscanned stacks cannot pipeline
        module3 = LlamaModule(_tiny_cfg(scan_layers=False))
        strat3 = ShardedMesh(fsdp=4, data=2, overlap="on")
        strat3.setup(module3)
        assert not module3._use_overlap()


# --------------------------------------------------------------------------
# classify_overlap unit tests (hand-built schedules)
# --------------------------------------------------------------------------


def _topo(gbps=600.0, peak_tflops=459.0) -> Topology:
    return Topology(name="test", device_kind="TPU v5p", n_devices=8,
                    ici_gbps=gbps, ici_hop_latency_us=1.0,
                    hbm_bytes=95 * 1024**3, peak_tflops=peak_tflops)


def _ev(time_us, *, prefetchable=True, scope=0, kind="all_gather"):
    return CollectiveEvent(
        kind=kind, axes=("fsdp",), payload_bytes=1 << 20, count=8,
        wire_bytes=8 << 20, time_us=time_us, implicit=False,
        source="test", prefetchable=prefetchable, scope=scope)


def _flops_for_window(topo, window_us):
    # invert compute_time_us: flops whose roofline time is window_us
    from ray_lightning_tpu.analysis.costmodel import MXU_EFFICIENCY

    return window_us / 1e6 * topo.peak_tflops * 1e12 * MXU_EFFICIENCY


class TestClassifyOverlap:
    def test_fully_hidden(self):
        """Compute window >= per-trip comm: the whole gather hides."""
        topo = _topo()
        ev = _ev(800.0)
        scopes = {0: {"trips": 8, "marker": True,
                      "flops": _flops_for_window(topo, 200.0),
                      "source": "scan"}}
        out = classify_overlap([ev], scopes, topo)
        assert out["scheduled"] is True
        assert out["overlap_hidden_fraction"] == pytest.approx(1.0)
        assert ev.hidden_us == pytest.approx(ev.time_us)
        assert ev.exposed_us == pytest.approx(0.0)

    def test_partially_exposed(self):
        """Window covers half the per-trip comm: half the time hides,
        the remainder is exposed — max(0, t_comm - t_compute)."""
        topo = _topo()
        ev = _ev(800.0)  # 100 us/trip over 8 trips
        scopes = {0: {"trips": 8, "marker": True,
                      "flops": _flops_for_window(topo, 50.0),
                      "source": "scan"}}
        out = classify_overlap([ev], scopes, topo)
        assert out["overlap_hidden_fraction"] == pytest.approx(0.5)
        assert ev.hidden_us == pytest.approx(400.0)
        assert ev.exposed_us == pytest.approx(400.0)
        sc = out["per_scope"][0]
        assert sc["hidden_fraction"] == pytest.approx(0.5)
        assert sc["compute_us_per_trip"] == pytest.approx(50.0)
        assert sc["prefetch_comm_us_per_trip"] == pytest.approx(100.0)

    def test_zero_compute_pathological(self):
        """A scope with nothing to hide behind hides nothing, even with
        the schedule live."""
        topo = _topo()
        ev = _ev(800.0)
        scopes = {0: {"trips": 8, "marker": True, "flops": 0.0,
                      "source": "scan"}}
        out = classify_overlap([ev], scopes, topo)
        assert out["overlap_hidden_fraction"] == 0.0
        assert ev.hidden_us == 0.0
        assert ev.exposed_us == pytest.approx(800.0)

    def test_unscheduled_trace_hides_nothing(self):
        """No prefetch fingerprint anywhere -> scheduled False -> the
        whole prefetchable time is exposed regardless of the window."""
        topo = _topo()
        ev = _ev(800.0)
        scopes = {0: {"trips": 8, "marker": False,
                      "flops": _flops_for_window(topo, 1e6),
                      "source": "scan"}}
        out = classify_overlap([ev], scopes, topo)
        assert out["scheduled"] is False
        assert out["overlap_hidden_fraction"] == 0.0
        assert ev.hidden_us == 0.0

    def test_unmarked_scope_earns_no_credit(self):
        """Hidden credit is per scope: the backward scan (marker-free
        transpose of the marked forward, SAME source) is credited, an
        unrelated scan (the fused-CE chunk loop) with a huge window is
        not — program-wide credit would pad the gated fraction with
        time the knob never earned."""
        topo = _topo()
        fwd = _ev(800.0, scope=0)
        bwd = _ev(800.0, scope=1)
        other = _ev(800.0, scope=2)
        scopes = {
            0: {"trips": 8, "marker": True,
                "source": "scan @ llama.py:1",
                "flops": _flops_for_window(topo, 200.0)},
            1: {"trips": 8, "marker": False,
                "source": "scan @ llama.py:1",
                "flops": _flops_for_window(topo, 200.0)},
            2: {"trips": 8, "marker": False, "source": "scan @ ce.py:2",
                "flops": _flops_for_window(topo, 1e6)},
        }
        out = classify_overlap([fwd, bwd, other], scopes, topo)
        assert fwd.hidden_us == pytest.approx(800.0)
        assert bwd.hidden_us == pytest.approx(800.0)
        assert other.hidden_us == 0.0
        by_src = {(s["source"], s["scheduled"])
                  for s in out["per_scope"]}
        assert ("scan @ ce.py:2", False) in by_src
        assert ("scan @ llama.py:1", True) in by_src

    def test_non_prefetchable_never_hidden(self):
        """Activation reshards etc. are not part of the prefetch
        schedule — they stay exposed and out of the fraction."""
        topo = _topo()
        pref = _ev(100.0)
        act = _ev(900.0, prefetchable=False)
        scopes = {0: {"trips": 8, "marker": True,
                      "flops": _flops_for_window(topo, 1e6),
                      "source": "scan"}}
        out = classify_overlap([pref, act], scopes, topo)
        assert act.hidden_us == 0.0
        assert out["overlap_hidden_fraction"] == pytest.approx(1.0)
        assert out["ici_exposed_us"] == pytest.approx(900.0)

    def test_compute_time_us_roofline(self):
        topo = _topo(peak_tflops=100.0)
        # 100 TFLOP/s * 0.6 efficiency = 60e12 flops/s
        assert compute_time_us(60e12, topo) == pytest.approx(1e6)
        assert compute_time_us(0.0, topo) == 0.0


# --------------------------------------------------------------------------
# end-to-end classification on real traces
# --------------------------------------------------------------------------


def _audit_tiny(overlap, n=8):
    cfg = LlamaConfig.tiny(dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
                           hidden_dim=1024, max_seq_len=512,
                           use_flash=False)
    return audit_step(
        LlamaModule(cfg), ShardedMesh(fsdp=n, overlap=overlap),
        {"tokens": np.zeros((n, 513), np.int32)},
        topology=topology_for_kind("TPU v5e", n),
        label=f"tiny overlap={overlap}")


class TestTraceClassification:
    def test_on_is_scheduled_and_hides(self):
        report = _audit_tiny("on")
        assert report.overlap["scheduled"] is True
        assert report.overlap_hidden_fraction > 0.0
        assert report.ici_hidden_us > 0.0
        assert not [f for f in report.findings if f.rule == "RLT305"]
        # per-scope breakdown names the scanned stack
        assert any(sc["trips"] == 4 or sc["trips"] >= 1
                   for sc in report.overlap["per_scope"])

    def test_off_flags_rlt305(self):
        report = _audit_tiny("off")
        assert report.overlap["scheduled"] is False
        assert report.overlap_hidden_fraction == 0.0
        flagged = [f for f in report.findings if f.rule == "RLT305"]
        assert flagged, "exposed per-trip weight gathers must be flagged"
        assert any("overlap" in f.message for f in flagged)
        # the layer-stack kernels are the flagged symbols
        symbols = {f.symbol for f in flagged}
        assert any("layers/" in (s or "") for s in symbols)

    def test_serial_is_unscheduled(self):
        """The ablation control traces as exposed — any measured delta
        between on and serial is therefore pure latency hiding."""
        report = _audit_tiny("serial")
        assert report.overlap["scheduled"] is False
        assert report.overlap_hidden_fraction == 0.0

    def test_off_schedule_signature_unchanged(self):
        """The off-trace's collective schedule must not see ANY of the
        overlap machinery (no explicit gathers from the constraint, no
        marker): the exact pre-PR implicit-ZeRO schedule."""
        report = _audit_tiny("off")
        assert all(e.implicit for e in report.collectives
                   if e.kind == "all_gather")

    def test_report_json_carries_overlap_fields(self):
        d = _audit_tiny("on").to_dict()
        assert "overlap_hidden_fraction" in d
        assert "ici_hidden_us" in d and "ici_exposed_us" in d
        assert d["overlap"]["scheduled"] is True
        assert all("hidden_us" in e for e in d["collectives"])


def test_nested_scan_marker_stays_on_inner_scope():
    """A marked scan nested inside an outer scan must stamp the prefetch
    marker on ITSELF only: the outer scan's own collectives are not part
    of the double-buffer schedule and must not earn hidden-credit.
    (Regression: the scan fixpoint pass runs before the inner scope is
    pushed, so an ungated marker handler stamped the ENCLOSING scope.)"""
    import optax
    from jax.sharding import PartitionSpec as P

    from ray_lightning_tpu.core.module import TpuModule
    from ray_lightning_tpu.ops.dispatch import prefetch_named

    class _Nested(TpuModule):
        def init_params(self, rng, batch):
            return {"w_stack": jnp.zeros((2, 64, 64), jnp.float32),
                    "w_out": jnp.zeros((64, 64), jnp.float32)}

        def configure_model(self):
            return None

        def configure_optimizers(self):
            return optax.sgd(1e-2)

        def param_specs(self, params):
            return {"w_stack": P(None, "fsdp", None),
                    "w_out": P("fsdp", None)}

        def training_step(self, params, batch, rng):
            def inner(c, w):
                # marker rides the per-trip slice (like the real
                # schedule's gathered layer) so it stays IN the body
                w = prefetch_named(w)
                return jnp.tanh(c @ w), None

            def outer(c, x):
                c2, _ = jax.lax.scan(inner, c + x.sum(),
                                     params["w_stack"])
                # outer-scope prefetchable gather, NOT in the schedule
                return jnp.tanh(c2 @ params["w_out"]), None

            out, _ = jax.lax.scan(outer, jnp.zeros((64, 64)), batch["x"])
            return (out ** 2).mean()

    rep = audit_step(_Nested(), ShardedMesh(fsdp=4),
                     {"x": np.zeros((3, 1), np.float32)},
                     topology="v5e-4", label="nested-scan")
    assert rep.overlap["scheduled"] is True
    scopes = rep.overlap["per_scope"]
    inner_scopes = [s for s in scopes if s["trips"] == 2]
    outer_scopes = [s for s in scopes if s["trips"] == 3]
    assert inner_scopes and any(s["scheduled"] for s in inner_scopes)
    assert outer_scopes
    assert not any(s["scheduled"] for s in outer_scopes), scopes


def test_llama8b_v5p64_overlap_acceptance():
    """ISSUE 6 acceptance: the flagship trace hides >= 70% of ZeRO
    prefetchable ICI time with overlap=on, and fits HBM with the
    double buffer live."""
    from ray_lightning_tpu.analysis.cli import resolve_trace_target

    topo = parse_topology("v5p-64")
    module, strategy, batch, label = resolve_trace_target(
        "llama3-8b", topo, overlap="on")
    report = audit_step(module, strategy, batch, topology=topo,
                        label=label)
    assert report.overlap["scheduled"] is True
    assert report.overlap_hidden_fraction >= 0.7, report.summary()
    assert report.fits, report.summary()
    assert not [f for f in report.findings
                if f.severity == "error"], report.summary()
    # the weight gathers hide behind the layer compute window
    gathers = [e for e in report.collectives
               if e.kind == "all_gather" and e.prefetchable
               and e.scope is not None]
    assert gathers
    assert sum(e.hidden_us for e in gathers) > 0


# --------------------------------------------------------------------------
# plan: double-buffer HBM accounting
# --------------------------------------------------------------------------


class TestPlanAccounting:
    def test_buffer_bytes_scale(self):
        from ray_lightning_tpu.parallel.plan import (
            llama_overlap_buffer_bytes,
        )

        cfg = LlamaConfig.llama3_8b()
        b64 = llama_overlap_buffer_bytes(cfg, fsdp=64)
        b8 = llama_overlap_buffer_bytes(cfg, fsdp=8)
        assert b64 > 0
        # the gathered-layer term is fsdp-independent; the shard terms
        # shrink with fsdp — so more shards = smaller charge
        assert b8 > b64
        # tensor parallelism splits the gathered buffer too
        assert llama_overlap_buffer_bytes(cfg, fsdp=64, tensor=4) < b64
        # one 8B layer gathered is ~0.8 GiB f32; the charge must be at
        # least that and far less than the whole stack
        layer = 4 * (4096 * (32 + 16) * 128 + 32 * 128 * 4096
                     + 4096 * 2 * 14336 + 14336 * 4096 + 2 * 4096)
        assert b64 >= layer // 1
        assert b64 < 32 * layer

    def test_inert_config_charges_zero(self):
        """Configs where the schedule never goes live (models/llama.py
        _use_overlap: fsdp > 1, scanned, >= 2 layers) compile the naive
        program — charging phantom double-buffer bytes there would flip
        a fitting job to DOES-NOT-FIT."""
        import dataclasses

        from ray_lightning_tpu.parallel.plan import (
            llama_overlap_buffer_bytes,
        )

        cfg = LlamaConfig.llama3_8b()
        assert llama_overlap_buffer_bytes(cfg, fsdp=1) == 0
        assert llama_overlap_buffer_bytes(cfg, fsdp=1, mode="serial") == 0
        assert llama_overlap_buffer_bytes(
            dataclasses.replace(cfg, scan_layers=False), fsdp=64) == 0
        assert llama_overlap_buffer_bytes(
            dataclasses.replace(cfg, n_layers=1), fsdp=64) == 0

    def test_plan_cli_charges_overlap(self):
        from ray_lightning_tpu.__main__ import main

        def run(*extra):
            import io
            from contextlib import redirect_stdout

            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = main(["plan", "--preset", "llama3-8b", "--fsdp",
                           "64", "--batch", "64", "--seq", "8192",
                           "--no-trace", "--json", *extra])
            return rc, json.loads(buf.getvalue())

        rc_off, off = run()
        rc_on, on = run("--overlap", "on")
        assert rc_off == 0 and rc_on == 0
        assert off["overlap_buffer_bytes"] == 0
        assert on["overlap_buffer_bytes"] > 0
        assert on["overlap"] == "on"
        assert on["per_device_bytes"] == pytest.approx(
            off["per_device_bytes"] + on["overlap_buffer_bytes"])
        # the serial ablation holds no double buffer and no rolled xs
        # copy — only the in-flight grad shard is charged
        rc_serial, serial = run("--overlap", "serial")
        assert rc_serial == 0
        assert 0 < serial["overlap_buffer_bytes"] \
            < on["overlap_buffer_bytes"]


# --------------------------------------------------------------------------
# bench gate
# --------------------------------------------------------------------------


def _bench_gate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchGate:
    def _priors(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "parsed": {"metric": "m", "value": 100.0, "mfu": 0.5,
                       "overlap_hidden_fraction": 0.8}}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "parsed": {"metric": "m", "value": 90.0, "mfu": 0.6}}))
        # a skipped round must not set the measured-metric bar ...
        (tmp_path / "BENCH_r03.json").write_text(json.dumps({
            "parsed": {"metric": "m", "value": 0.0,
                       "skipped": "backend unavailable",
                       "overlap_hidden_fraction": 0.9}}))
        return tmp_path

    def test_pass_and_regress(self, tmp_path):
        bg = _bench_gate()
        self._priors(tmp_path)
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        # per-metric max across rounds; the static overlap fraction
        # ratchets even from the skip round
        assert best["tokens_per_sec_per_chip"][0] == 100.0
        assert best["mfu"][0] == 0.6
        assert best["overlap_hidden_fraction"][0] == 0.9

        ok = {"metric": "m", "value": 99.0, "mfu": 0.59,
              "overlap_hidden_fraction": 0.88}
        assert bg.gate(ok, best, 0.05) == []
        bad = {"metric": "m", "value": 50.0, "mfu": 0.59,
               "overlap_hidden_fraction": 0.88}
        msgs = bg.gate(bad, best, 0.05)
        assert len(msgs) == 1 and "tokens_per_sec_per_chip" in msgs[0]

    def test_dropped_field_fails(self, tmp_path):
        bg = _bench_gate()
        self._priors(tmp_path)
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        naked = {"metric": "m", "value": 200.0, "mfu": 0.7}
        msgs = bg.gate(naked, best, 0.05)
        assert any("overlap_hidden_fraction" in m and "dropped" in m
                   for m in msgs)

    def test_analysis_error_waives_static_metric(self, tmp_path):
        """A success line whose static analysis DIED (overlap_error, or
        tracecheck_error when the whole trace failed) is an analysis
        bug, not a deleted field — it must not cost the measured run
        its perf evidence."""
        bg = _bench_gate()
        self._priors(tmp_path)
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        for err_key in ("overlap_error", "tracecheck_error"):
            line = {"metric": "m", "value": 200.0, "mfu": 0.7,
                    err_key: "boom"}
            assert bg.gate(line, best, 0.05) == [], err_key

    def test_null_value_prior_tolerated(self, tmp_path):
        """A prior round whose line carries "value": null (a partial
        result) must be skipped, not crash best_prior with a
        TypeError."""
        bg = _bench_gate()
        self._priors(tmp_path)
        (tmp_path / "BENCH_r04.json").write_text(json.dumps({
            "parsed": {"metric": "m", "value": None, "mfu": 0.99}}))
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        # the null round is unmeasured: its mfu must not set the bar
        assert best["mfu"][0] == 0.6

    def test_skip_passes_structured_only(self, tmp_path):
        bg = _bench_gate()
        self._priors(tmp_path)
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        assert bg.gate({"metric": "m", "value": 0.0,
                        "skipped": "backend unavailable"}, best, 0.05) == []
        assert bg.gate({"skipped": "backend unavailable"}, best, 0.05)

    def test_skip_still_ratchets_static_metric(self, tmp_path):
        """overlap_hidden_fraction is static analysis — carried on a
        backend-down skip line and ratcheted there too (on the TPU-less
        boxes format.sh targets it is the ONLY checkable metric)."""
        bg = _bench_gate()
        self._priors(tmp_path)  # best prior fraction: 0.9 (r03, a skip)
        best = bg.best_prior("BENCH_r0*.json", str(tmp_path))
        fails = bg.gate({"metric": "m", "value": 0.0,
                         "skipped": "backend unavailable",
                         "overlap_hidden_fraction": 0.2}, best, 0.05)
        assert fails and "overlap_hidden_fraction" in fails[0]
        assert bg.gate({"metric": "m", "value": 0.0,
                        "skipped": "backend unavailable",
                        "overlap_hidden_fraction": 0.9},
                       best, 0.05) == []

    def test_cli_against_repo_history(self):
        """The gate must accept the repo's own best round (no
        self-regression) and reject a gutted line."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(root, "scripts", "bench_gate.py")
        r = subprocess.run(
            [sys.executable, script, os.path.join(root, "BENCH_r03.json")],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        r = subprocess.run(
            [sys.executable, script, "-"],
            input=json.dumps({"metric": "m", "value": 1.0, "mfu": 0.01}),
            capture_output=True, text=True)
        assert r.returncode == 1
        assert "REGRESSION" in r.stderr

    def test_unparseable_fails(self, tmp_path):
        bg = _bench_gate()
        assert bg._last_json_line("rc=124 no json at all") is None
        f = tmp_path / "garbage.json"
        f.write_text("not json\n")
        assert bg.main([str(f)]) == 2


# --------------------------------------------------------------------------
# perf overlap leg
# --------------------------------------------------------------------------


def test_simulated_interleave_beats_serial():
    from ray_lightning_tpu.pipeline.collective_overlap import (
        simulate_overlap_schedule,
    )

    # wall-clock measurement: a loaded CI box can squeeze the thread
    # scheduling, so take the best of a few attempts before judging —
    # the schedule either interleaves (~1.8x ideal here) or it doesn't
    # (observed 1.142 vs the 1.15 floor on a box running two suites:
    # five attempts, not three, before calling it a regression)
    best = {"overlap_speedup": 0.0}
    for _ in range(5):
        out = simulate_overlap_schedule(n_layers=6, t_comm_s=0.03,
                                        compute_ms_target=30.0)
        if out["overlap_speedup"] > best["overlap_speedup"]:
            best = out
        if best["overlap_speedup"] > 1.15:
            break
    assert best["overlap_speedup"] > 1.15, best
    assert best["serial_s"] > best["overlapped_s"]


def test_bench_overlap_summary_fields():
    """Every bench JSON line carries the overlap evidence (success or
    backend-down: _ANALYSIS is computed before any backend touch)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    cfg = bench._bench_cfg(use_flash=True, fused_ce=True, seq=512,
                           vocab=4096)
    out = bench._overlap_summary(cfg, topology_for_kind)
    assert "overlap_hidden_fraction" in out, out
    assert out["overlap"]["scheduled"] is True
    assert out["overlap_hidden_fraction"] > 0.0
