"""Resilience subsystem: failure taxonomy, retry policy, fault injection,
preemption drain, health monitoring, checkpoint validity, and the
supervised restart loop (docs/RESILIENCE.md; ISSUE 3).

Fast tests run in-process (the taxonomy, the injector, the guard, the
monitor, checkpoint verification, and a full deterministic
kill-at-step-J + resume at Trainer level). The @slow tests drive REAL
2-process SPMD groups through supervise() — worker kill, coordinator
drop, corrupt-latest-checkpoint, SIGTERM preemption, and FATAL
fail-fast — the acceptance matrix of the issue.
"""
import json
import os
import signal as _signal
import time

import numpy as np
import pytest

from ray_lightning_tpu.resilience.policy import (
    FailureKind,
    RetryPolicy,
    StallError,
    classify_failure,
)
from ray_lightning_tpu.runtime.group import WorkerError

# ---------------------------------------------------------------- policy


def test_classify_sigterm_as_preemption_sigkill_as_retryable():
    term = WorkerError.from_death(2, -15, "tail", "(EOF on channel)")
    assert term.cause == "signal" and term.signal_name == "SIGTERM"
    fc = classify_failure(term)
    assert fc.kind == FailureKind.PREEMPTION and fc.rank == 2
    assert "SIGTERM" in fc.cause
    # SIGKILL announces no grace window: OOM killer / hard host failure —
    # restartable, but from the BOUNDED budget, never the preemption one
    kill = WorkerError.from_death(2, -9, "tail", "(EOF on channel)")
    fc = classify_failure(kill)
    assert fc.kind == FailureKind.RETRYABLE
    assert "SIGKILL" in fc.cause


def test_classify_plain_exit_as_retryable():
    err = WorkerError.from_death(1, 7, "", "without returning a result")
    assert err.cause == "exit" and err.exit_code == 7
    fc = classify_failure(err)
    assert fc.kind == FailureKind.RETRYABLE
    assert fc.restartable


def test_classify_user_traceback_as_fatal():
    err = WorkerError(0, "Traceback (most recent call last):\n"
                         "  ...\nValueError: shapes do not match")
    fc = classify_failure(err)
    assert fc.kind == FailureKind.FATAL
    assert not fc.restartable
    assert "ValueError" in fc.detail  # the last traceback line, not the
    #                                   "worker rank 0 failed" boilerplate


def test_classify_backend_loss_in_worker_as_retryable():
    err = WorkerError(3, "Traceback ...\njaxlib.xla_extension."
                         "XlaRuntimeError: UNAVAILABLE: socket closed")
    assert classify_failure(err).kind == FailureKind.RETRYABLE


def test_classify_collective_peer_loss_as_retryable():
    """A surviving rank whose collective dies because its PEER was
    killed must classify like the peer's death itself — which rank's
    failure reaches the driver first is a race (observed: the kill
    drill flaking FATAL when the survivor's gloo error won)."""
    err = WorkerError(0, "Traceback ...\njaxlib.xla_extension."
                         "XlaRuntimeError: FAILED_PRECONDITION: Buffer "
                         "Definition Event: Gloo all-reduce failed: "
                         "[gloo/transport/tcp/pair.cc:534] Connection "
                         "closed by peer [127.0.0.1]:14000")
    fc = classify_failure(err)
    assert fc.kind == FailureKind.RETRYABLE
    assert fc.restartable
    # lowercase transport-path variants too
    low = WorkerError(0, "Traceback ...\ngloo::IoException: "
                         "[gloo/transport/tcp/pair.cc:598] Timed out "
                         "waiting for clients")
    assert classify_failure(low).kind == FailureKind.RETRYABLE
    # but a deterministic bug RAISING THROUGH a collective is still
    # fatal — the marker is the transport path, not the word "gloo"
    bug = WorkerError(0, "Traceback ...\ngloo::EnforceNotMet: "
                         "invalid tensor size mismatch")
    assert classify_failure(bug).kind == FailureKind.FATAL


def test_classify_preempted_drain_as_preemption():
    err = WorkerError(1, "Traceback ...\nray_lightning_tpu.resilience."
                         "preempt.PreemptedError: training drained after "
                         "preemption notice (SIGTERM)")
    assert classify_failure(err).kind == FailureKind.PREEMPTION


def test_classify_driver_side_exceptions():
    assert classify_failure(TimeoutError("pending")).kind == \
        FailureKind.RETRYABLE
    assert classify_failure(StallError(1, 200.0)).kind == \
        FailureKind.RETRYABLE
    assert classify_failure(ValueError("bad config")).kind == \
        FailureKind.FATAL


def test_retry_policy_backoff_caps_and_budget():
    p = RetryPolicy(max_restarts=2, backoff_base_s=1.0, backoff_factor=4.0,
                    backoff_max_s=5.0, jitter=0.0)
    assert p.next_delay(1) == 1.0
    assert p.next_delay(2) == 4.0
    assert p.next_delay(3) == 5.0  # capped
    retry = classify_failure(TimeoutError("x"))
    preempt = classify_failure(WorkerError.from_death(0, -15, "", "ctx"))
    fatal = classify_failure(ValueError("x"))
    assert p.allows(0, 0, retry) and p.allows(1, 0, retry)
    assert not p.allows(2, 0, retry)          # budget spent
    assert not p.allows(0, 0, fatal)          # never
    # preemptions have their own (large) budget by default
    assert p.allows(2, 0, preempt)
    strict = RetryPolicy(max_restarts=1, preemptions_count=True)
    assert not strict.allows(1, 0, preempt)


# ---------------------------------------------------------------- faults


def test_parse_faults_roundtrip_and_errors():
    from ray_lightning_tpu.resilience.faults import parse_faults

    faults = parse_faults("kill:rank=1,step=3; preempt:rank=*,step=2;"
                          "corrupt_latest:rank=0,step=4,dir=/tmp/ck")
    assert [f.kind for f in faults] == ["kill", "preempt", "corrupt_latest"]
    assert faults[0].rank == 1 and faults[0].step == 3
    assert faults[1].rank is None  # "*"
    assert faults[2].args["dir"] == "/tmp/ck"
    assert faults[0].matches(1, 3) and not faults[0].matches(0, 3)
    assert not faults[0].matches(1, 2)
    assert parse_faults(None) == [] and parse_faults("") == []
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_faults("explode:rank=0,step=1")
    with pytest.raises(ValueError, match="malformed fault arg"):
        parse_faults("kill:rank")


def test_fault_injector_fires_once_across_restarts(tmp_path):
    """The marker is written BEFORE the fault fires, so a restarted run
    (same state dir) sails past the step that killed its predecessor."""
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector

    state = str(tmp_path / "fault_state")

    class _T:
        global_step = 3

    inj = FaultInjector([Fault("raise", None, 3, {}, index=0)], state)
    with pytest.raises(RuntimeError, match="injected fatal failure"):
        inj.on_train_batch_end(_T(), None, {}, 0)
    # a FRESH injector (new process after restart) sees the marker
    inj2 = FaultInjector([Fault("raise", None, 3, {}, index=0)], state)
    inj2.on_train_batch_end(_T(), None, {}, 0)  # no raise


def test_corrupt_checkpoint_flips_state_not_meta(tmp_path):
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import (
        save_checkpoint,
        verify_checkpoint,
    )
    from ray_lightning_tpu.resilience.faults import corrupt_checkpoint

    path = str(tmp_path / "ck")
    save_checkpoint(path, {"w": jnp.arange(1024, dtype=jnp.float32)},
                    {"global_step": 5})
    ok, _ = verify_checkpoint(path)
    assert ok
    assert corrupt_checkpoint(path)
    ok, reason = verify_checkpoint(path)
    assert not ok and "digest mismatch" in reason
    # meta.json survived: the checkpoint looks FINISHED but damaged —
    # exactly the case the digest exists to catch
    assert os.path.exists(os.path.join(path, "meta.json"))


# ----------------------------------------------------------- checkpoints


def test_latest_checkpoint_skips_torn_and_corrupt(tmp_path):
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint,
    )
    from ray_lightning_tpu.resilience.faults import corrupt_checkpoint

    root = tmp_path / "ckpts"
    for step in (1, 2, 3):
        save_checkpoint(str(root / f"step={step}"),
                        {"w": jnp.full((16,), float(step))},
                        {"global_step": step})
    # newest (step=3) corrupted, step=2 torn (meta never finalized)
    corrupt_checkpoint(str(root / "step=3"))
    os.remove(root / "step=2" / "meta.json")
    assert latest_checkpoint(str(root)) == str(root / "step=1")
    # all invalid -> None (resume from scratch, not from garbage)
    corrupt_checkpoint(str(root / "step=1"))
    assert latest_checkpoint(str(root)) is None
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_latest_checkpoint_orders_by_step_not_name(tmp_path):
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint,
    )

    root = tmp_path / "ckpts"
    for step in (9, 10):  # lexicographic would pick "step=9"
        save_checkpoint(str(root / f"step={step}"),
                        {"w": jnp.zeros((4,))}, {"global_step": step})
    assert latest_checkpoint(str(root)) == str(root / "step=10")


def test_meta_json_written_atomically(tmp_path):
    """No .tmp residue and a parseable meta with digest fields."""
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import save_checkpoint

    path = str(tmp_path / "ck")
    save_checkpoint(path, {"w": jnp.ones((8,))}, {"global_step": 1})
    assert not os.path.exists(os.path.join(path, "meta.json.tmp"))
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["ckpt_digest_mode"] == "full"
    assert len(meta["ckpt_digest"]) == 64 and meta["ckpt_files"] >= 1


# -------------------------------------------------------------- preempt


def test_preemption_flag_and_guard_drain(tmp_path):
    """SIGTERM -> flag only (async-signal-safe); the guard drains at the
    next batch boundary: emergency checkpoint (valid!) then
    PreemptedError."""
    from ray_lightning_tpu import DataLoader, SingleDevice, Trainer
    from ray_lightning_tpu.checkpoint import (
        latest_checkpoint,
        verify_checkpoint,
    )
    from ray_lightning_tpu.resilience.preempt import (
        PreemptedError,
        PreemptionGuard,
        install_preemption_handlers,
        preemption_requested,
        reset_preemption,
    )
    from tests.utils import BoringModel, random_dataset

    old = _signal.getsignal(_signal.SIGTERM)
    try:
        install_preemption_handlers()
        assert preemption_requested() is None
        os.kill(os.getpid(), _signal.SIGTERM)
        assert preemption_requested() == "SIGTERM"

        ck = str(tmp_path / "ck")
        trainer = Trainer(strategy=SingleDevice(), max_epochs=1,
                          enable_checkpointing=False,
                          enable_progress_bar=False,
                          callbacks=[PreemptionGuard(ck, install=False)],
                          default_root_dir=str(tmp_path), seed=0)
        with pytest.raises(PreemptedError) as exc_info:
            trainer.fit(BoringModel(),
                        DataLoader(random_dataset(), batch_size=32))
        assert exc_info.value.checkpoint_path is not None
        ok, reason = verify_checkpoint(exc_info.value.checkpoint_path)
        assert ok, reason
        assert latest_checkpoint(ck) == exc_info.value.checkpoint_path
    finally:
        reset_preemption()
        _signal.signal(_signal.SIGTERM, old)


# --------------------------------------------------------------- health


def test_health_monitor_distinguishes_compiling_from_hung():
    from ray_lightning_tpu.resilience.health import (
        HealthMonitor,
        make_heartbeat,
    )

    mon = HealthMonitor(num_workers=2, stall_timeout_s=10.0,
                        startup_grace_s=30.0, step_stall_note_s=5.0)
    now = time.monotonic()
    assert mon.consume(0, make_heartbeat(0, step=1))
    assert mon.consume(1, make_heartbeat(1, step=1))
    assert not mon.consume(0, {"some": "other item"})
    mon.check(now)  # healthy
    # live channel, frozen step: NOT a stall (compiling) — check passes
    mon.consume(0, make_heartbeat(0, step=1))
    mon.check(now + 8.0)
    # silent channel past the budget: hung
    with pytest.raises(StallError, match="rank 0"):
        mon.check(now + 11.0)


def test_health_monitor_startup_grace():
    from ray_lightning_tpu.resilience.health import (
        HealthMonitor,
        make_heartbeat,
    )

    mon = HealthMonitor(num_workers=2, stall_timeout_s=30.0,
                        startup_grace_s=20.0)
    now = time.monotonic()
    mon.consume(0, make_heartbeat(0, step=0))
    mon.check(now + 19.0)  # rank 1 silent but inside the startup grace
    with pytest.raises(StallError, match="never reached"):
        mon.check(now + 21.0)  # rank 0 (21s < 30s budget) is fine;
        #                        rank 1 never started -> grace expired


# ------------------------------------------- deterministic resume (fast)


class _MetricRecorder:
    """Collects per-batch id sums so replay/skip is provable."""

    def __init__(self):
        from ray_lightning_tpu import Callback

        class _CB(Callback):
            def __init__(cb):
                cb.id_sums = []

            def on_train_batch_end(cb, trainer, module, metrics, batch_idx):
                cb.id_sums.append(float(np.asarray(metrics["id_sum"])))

        self.cb = _CB()


def _idsum_loader():
    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    x = np.zeros((64, 8), np.float32)
    x[:, 0] = np.arange(64)
    y = rng.integers(0, 2, 64).astype(np.int32)
    return DataLoader({"x": x, "y": y}, batch_size=8, shuffle=True, seed=3)


def test_kill_at_step_j_resume_is_deterministic(tmp_path):
    """Train 16 steps straight vs raise-at-step-3 (faults.py) + resume
    from latest_checkpoint: final params BITWISE identical, every batch
    trained exactly once (id accounting) — pins _resume_skip_batches
    under a real restart-shaped interruption."""
    import jax

    from ray_lightning_tpu import SingleDevice, Trainer
    from ray_lightning_tpu.checkpoint import latest_checkpoint
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from ray_lightning_tpu.resilience.faults import Fault, FaultInjector
    from tests.utils import IdSumModel

    def trainer(root, extra):
        return Trainer(strategy=SingleDevice(), max_epochs=2,
                       enable_checkpointing=False,
                       enable_progress_bar=False, seed=7,
                       default_root_dir=str(root), callbacks=extra)

    # --- run A: uninterrupted
    rec_a = _MetricRecorder()
    mod_a = IdSumModel(lr=1e-2)
    trainer(tmp_path / "a", [rec_a.cb]).fit(mod_a, _idsum_loader())
    assert len(rec_a.cb.id_sums) == 16  # 8 batches/epoch x 2

    # --- run B: checkpoint every step, die at step 3, auto-resume
    ck = str(tmp_path / "ck")
    state = str(tmp_path / "fault_state")
    rec_b = _MetricRecorder()
    mc = ModelCheckpoint(dirpath=ck, monitor=None,
                         every_n_train_steps=1, save_top_k=-1)
    inj = FaultInjector([Fault("raise", None, 3, {}, index=0)], state)
    mod_b1 = IdSumModel(lr=1e-2)
    with pytest.raises(RuntimeError, match="injected fatal failure"):
        trainer(tmp_path / "b1", [mc, rec_b.cb, inj]).fit(
            mod_b1, _idsum_loader())
    resume_from = latest_checkpoint(ck)
    assert resume_from is not None and resume_from.endswith("step=3")

    mod_b2 = IdSumModel(lr=1e-2)
    inj2 = FaultInjector([Fault("raise", None, 3, {}, index=0)], state)
    t_b2 = trainer(tmp_path / "b2", [rec_b.cb, inj2])
    t_b2.fit(mod_b2, _idsum_loader(), ckpt_path=resume_from)

    # no batch replayed, none skipped: 3 + 13 = 16 sums, totals equal
    assert len(rec_b.cb.id_sums) == 16
    assert sum(rec_b.cb.id_sums) == sum(rec_a.cb.id_sums) \
        == 2 * sum(range(64))
    # the two halves cover the same batch sequence as the straight run
    assert rec_b.cb.id_sums == rec_a.cb.id_sums
    # final params identical, bitwise
    for a, b in zip(jax.tree.leaves(jax.device_get(mod_a.params)),
                    jax.tree.leaves(jax.device_get(mod_b2.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert t_b2.global_step == 16


# -------------------------------------------------------- sweep retry


def test_sweep_trial_retry_resumes_on_infra_failure(tmp_path):
    """Trial-level retry (same taxonomy): an infra-classified failure
    re-runs the trial; a FATAL user exception still fails it."""
    from ray_lightning_tpu import sweep

    flaky_marker = str(tmp_path / "first_attempt_done")

    def flaky(config):
        if not os.path.exists(flaky_marker):
            with open(flaky_marker, "w") as f:
                f.write("1")
            raise TimeoutError("transient infra loss")
        sweep.report(loss=0.1)
        return {"ok": True}

    analysis = sweep.run(
        flaky, {}, executor="inline", metric="loss", mode="min",
        storage_dir=str(tmp_path / "s1"), total_chips=1,
        retry_policy=RetryPolicy(max_restarts=2, backoff_base_s=0.0,
                                 jitter=0.0),
    )
    [trial] = analysis.trials
    assert trial.status == "done" and trial.restarts == 1

    def fatal(config):
        raise ValueError("a real bug")

    analysis = sweep.run(
        fatal, {}, executor="inline", storage_dir=str(tmp_path / "s2"),
        total_chips=1, raise_on_failed_trial=False,
        retry_policy=RetryPolicy(max_restarts=2, backoff_base_s=0.0,
                                 jitter=0.0),
    )
    [trial] = analysis.trials
    assert trial.status == "error" and trial.restarts == 0


# ----------------------------------------- supervised SPMD runs (slow)


def _sup_module():
    from tests.utils import IdSumModel

    return IdSumModel(lr=1e-2)


def _sup_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(strategy=DataParallel(), max_epochs=2,
                   enable_progress_bar=False, enable_checkpointing=False,
                   seed=0)


def _sup_data():
    import jax

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    x = np.zeros((64, 8), np.float32)
    x[:, 0] = np.arange(64)
    y = rng.integers(0, 2, 64).astype(np.int32)
    return DataLoader({"x": x, "y": y}, batch_size=8,
                      num_shards=jax.process_count(),
                      shard_index=jax.process_index())


def _resilience(tmp_path, name, faults=None, max_restarts=2):
    from ray_lightning_tpu import ResilienceConfig

    return ResilienceConfig(
        checkpoint_dir=str(tmp_path / name),
        policy=RetryPolicy(max_restarts=max_restarts, backoff_base_s=0.2,
                           jitter=0.0),
        save_every_n_steps=1,
        heartbeat_interval_s=1.0,
        stall_timeout_s=0.0,  # liveness covers these tests; the stall
        #                       path has its own unit coverage
        faults=faults,
    )


_SPMD = dict(num_processes=2, platform="cpu",
             num_cpu_devices_per_process=1, timeout=420)


def _supervised_params(tmp_path, name, faults):
    from ray_lightning_tpu import fit_supervised

    module = _sup_module()
    supervised = fit_supervised(
        _sup_module, _sup_trainer, _sup_data, module=module,
        resilience=_resilience(tmp_path, name, faults),
        log_dir=str(tmp_path / f"logs_{name}"), **_SPMD,
    )
    assert module.params is not None
    return supervised, module


@pytest.mark.slow
@pytest.mark.parametrize("victim", [1, 0])  # 0 = the coordinator rank
def test_supervise_worker_kill_autoresumes(tmp_path, victim):
    """A SIGKILL'd worker (rank 1) / the dropped coordinator (rank 0) at
    step 2: the supervisor relaunches and resumes; the final params are
    IDENTICAL to an uninterrupted supervised run — nothing replayed,
    nothing skipped, optimizer state included."""
    import jax

    base, base_mod = _supervised_params(tmp_path, "base", faults=None)
    assert base.total_attempts == 1

    killed, killed_mod = _supervised_params(
        tmp_path, f"kill{victim}", faults=f"kill:rank={victim},step=2")
    assert killed.total_attempts == 2
    [failure] = killed.failures
    assert failure["kind"] == "retryable"   # SIGKILL = OOM-kill/host loss
    assert "SIGKILL" in failure["cause"]
    for a, b in zip(jax.tree.leaves(base_mod.params),
                    jax.tree.leaves(killed_mod.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_supervise_skips_corrupt_latest_checkpoint(tmp_path):
    """corrupt-latest + kill in the same step: resume must come from the
    last VALID checkpoint (step=1), and the run still converges to the
    uninterrupted result."""
    import jax

    base, base_mod = _supervised_params(tmp_path, "base", faults=None)
    hurt, hurt_mod = _supervised_params(
        tmp_path, "corrupt",
        faults="corrupt_latest:rank=0,step=2,dir={d};kill:rank=0,step=2"
        .format(d=str(tmp_path / "corrupt")))
    assert hurt.total_attempts == 2
    for a, b in zip(jax.tree.leaves(base_mod.params),
                    jax.tree.leaves(hurt_mod.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_supervise_sigterm_emergency_checkpoint_and_drain(tmp_path):
    """SIGTERM during training: flag-only handler, batch-boundary
    emergency save, PreemptedError drain, PREEMPTION-classified resume."""
    from ray_lightning_tpu.checkpoint import verify_checkpoint

    sup, _ = _supervised_params(tmp_path, "pre",
                                faults="preempt:rank=*,step=2")
    assert sup.preemptions == 1 and sup.restarts == 0
    [failure] = sup.failures
    assert failure["kind"] == "preemption"
    emergency = [d for d in os.listdir(tmp_path / "pre")
                 if d.startswith("preempt-step=")]
    assert emergency, "no emergency checkpoint was written"
    ok, reason = verify_checkpoint(str(tmp_path / "pre" / emergency[0]))
    assert ok, reason


@pytest.mark.slow
def test_supervise_fatal_fails_fast_with_classified_cause(tmp_path):
    """A deterministic user exception: NO restarts; the SupervisedFailure
    names the classification and chains the rank-tagged WorkerError."""
    from ray_lightning_tpu import fit_supervised
    from ray_lightning_tpu.resilience.supervisor import SupervisedFailure

    with pytest.raises(SupervisedFailure) as exc_info:
        fit_supervised(
            _sup_module, _sup_trainer, _sup_data,
            resilience=_resilience(tmp_path, "fatal",
                                   faults="raise:rank=0,step=2"),
            log_dir=str(tmp_path / "logs_fatal"), **_SPMD,
        )
    exc = exc_info.value
    assert exc.classified.kind == FailureKind.FATAL
    assert exc.attempts == 1
    cause = exc.__cause__
    assert isinstance(cause, WorkerError) and cause.rank == 0
    assert "injected fatal failure" in cause.traceback_str
    assert "worker log tail" in str(cause)  # rank-tagged log tail attached
