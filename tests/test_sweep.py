"""Sweep/HPO tests — parity with the reference's Tune suite
(reference tests/test_tune.py): nested HPO correctness (iterations ==
sampled max_epochs, :34-45), best_checkpoint exists (:60-74), plus the
rebuild's own surface: search spaces, integral resource accounting, ASHA
early stopping, process-isolated trials, and the nested
sweep-over-distributed-fit topology (SURVEY §3.3)."""
from __future__ import annotations

import os

import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, SingleDevice, sweep
from ray_lightning_tpu.sweep.analysis import Trial

from tests.utils import BoringModel, get_trainer, random_dataset


# ---------------------------------------------------------------- spaces


def test_space_expand_grid_and_samplers():
    space = {
        "lr": sweep.loguniform(1e-4, 1e-1),
        "bs": sweep.grid_search([16, 32]),
        "layers": sweep.grid_search([1, 2, 3]),
        "fixed": "adam",
    }
    configs = sweep.expand(space, num_samples=2, seed=0)
    assert len(configs) == 2 * 2 * 3
    assert {c["bs"] for c in configs} == {16, 32}
    assert all(1e-4 <= c["lr"] <= 1e-1 for c in configs)
    assert all(c["fixed"] == "adam" for c in configs)
    # deterministic under the same seed
    assert configs == sweep.expand(space, num_samples=2, seed=0)


def test_space_choice_randint():
    space = {"a": sweep.choice([1, 2, 3]), "b": sweep.randint(0, 10),
             "c": sweep.uniform(0.0, 1.0)}
    configs = sweep.expand(space, num_samples=20, seed=1)
    assert len(configs) == 20
    assert all(c["a"] in (1, 2, 3) for c in configs)
    assert all(0 <= c["b"] < 10 for c in configs)


# -------------------------------------------------------------- resources


def test_resource_pool_integral_blocks():
    pool = sweep.ResourcePool(total_chips=8)
    per_trial = sweep.TpuResources(chips=4)
    assert pool.max_concurrent(per_trial) == 2
    assert pool.try_acquire(per_trial)
    assert pool.try_acquire(per_trial)
    assert not pool.try_acquire(per_trial)  # 8/8 in use
    pool.release(per_trial)
    assert pool.try_acquire(per_trial)
    with pytest.raises(ValueError):
        pool.try_acquire(sweep.TpuResources(chips=16))  # > slice


# ------------------------------------------------- inline trials + ASHA


def _fake_trainable(config):
    """Pure-python trainable: loss is config-determined, 12 iterations."""
    for _ in range(12):
        sweep.report(loss=float(config["q"]))
    return "done"


def test_resource_pool_mixed_chip_cpu_constraints():
    """Joint chip+cpu accounting (reference per-worker CPU reservation,
    examples/ray_ddp_example.py:107-112): packing is bounded by whichever
    budget runs out first."""
    pool = sweep.ResourcePool(total_chips=8, total_cpus=8)
    per = sweep.TpuResources(chips=2, cpus=4)
    # chips alone would allow 4 concurrent; cpus cap it at 2
    assert pool.max_concurrent(per) == 2
    assert pool.try_acquire(per)
    assert pool.try_acquire(per)
    assert not pool.try_acquire(per)  # cpus exhausted (8/8), chips at 4/8
    assert pool.in_use == 4
    assert pool.cpus_in_use == 8
    pool.release(per)
    assert pool.try_acquire(per)
    with pytest.raises(ValueError):
        pool.try_acquire(sweep.TpuResources(chips=1, cpus=99))
    # chips-only trials are unaffected by the cpu budget
    assert pool.max_concurrent(sweep.TpuResources(chips=4)) == 2


def test_fifo_runs_all_trials_to_completion(tmp_path):
    analysis = sweep.run(
        _fake_trainable,
        config={"q": sweep.grid_search([0.1, 0.5, 0.9])},
        metric="loss",
        mode="min",
        executor="inline",
        total_chips=8,
        storage_dir=str(tmp_path),
    )
    assert all(t.status == Trial.DONE for t in analysis.trials)
    assert all(t.iterations == 12 for t in analysis.trials)
    assert analysis.best_config == {"q": 0.1}
    assert analysis.best_trial.last_result["training_iteration"] == 12


def test_asha_stops_bad_trials_early(tmp_path):
    analysis = sweep.run(
        _fake_trainable,
        config={"q": sweep.grid_search([0.1, 0.2, 0.8, 0.9])},
        metric="loss",
        mode="min",
        scheduler=sweep.ASHAScheduler(grace_period=1, reduction_factor=2,
                                      max_t=12),
        executor="inline",
        total_chips=8,
        storage_dir=str(tmp_path),
    )
    by_q = {t.config["q"]: t for t in analysis.trials}
    assert by_q[0.1].status == Trial.DONE  # the best survives
    stopped = [t for t in analysis.trials if t.status == Trial.STOPPED]
    assert stopped, "ASHA stopped nothing"
    assert all(t.iterations < 12 for t in stopped)
    assert analysis.best_config == {"q": 0.1}


def test_median_stopping_rule():
    rule = sweep.MedianStoppingRule(metric="loss", mode="min",
                                    grace_period=2, min_samples=2)
    # two good peers establish the median
    for step in range(1, 6):
        assert rule.on_result("good_a", step, 0.1) == "continue"
        assert rule.on_result("good_b", step, 0.2) == "continue"
    # a clearly-worse trial gets cut after grace
    assert rule.on_result("bad", 1, 5.0) == "continue"  # grace
    assert rule.on_result("bad", 2, 5.0) == "stop"


def test_trial_error_recorded_and_raised(tmp_path):
    def boom(config):
        if config["x"] == 1:
            raise RuntimeError("kaboom")
        sweep.report(loss=1.0)

    analysis = sweep.run(
        boom, config={"x": sweep.grid_search([0, 1])},
        metric="loss", executor="inline", total_chips=8,
        storage_dir=str(tmp_path / "a"), raise_on_failed_trial=False,
    )
    statuses = {t.config["x"]: t.status for t in analysis.trials}
    assert statuses == {0: Trial.DONE, 1: Trial.ERROR}
    assert "kaboom" in analysis.errors()["trial_00001"]

    with pytest.raises(sweep.SweepError, match="kaboom"):
        sweep.run(
            boom, config={"x": sweep.grid_search([0, 1])},
            metric="loss", executor="inline", total_chips=8,
            storage_dir=str(tmp_path / "b"),
        )


# ------------------------------------- trainer-in-the-loop (ref parity)


def _trainer_trainable(root_dir, with_checkpoint=False):
    data = random_dataset(n=128)

    def trainable(config):
        cb_cls = (sweep.TuneReportCheckpointCallback if with_checkpoint
                  else sweep.TuneReportCallback)
        cb = cb_cls(metrics={"loss": "val_loss", "acc": "val_acc"})
        module = BoringModel(lr=config["lr"])
        trainer = get_trainer(
            os.path.join(root_dir, sweep.get_trial_id()),
            strategy=SingleDevice(),
            max_epochs=config["max_epochs"],
            callbacks=[cb],
            checkpoint_callback=False,
        )
        train = DataLoader(data, batch_size=32)
        val = DataLoader(data, batch_size=32)
        trainer.fit(module, train, val)

    return trainable


def test_sweep_iterations_match_max_epochs(tmp_path):
    """Reference parity: trial iteration count == sampled max_epochs
    (reference tests/test_tune.py:34-45)."""
    analysis = sweep.run(
        _trainer_trainable(str(tmp_path)),
        config={"lr": 1e-2, "max_epochs": sweep.grid_search([1, 2])},
        metric="loss",
        mode="min",
        executor="inline",
        total_chips=8,
        storage_dir=str(tmp_path / "sweep"),
    )
    for t in analysis.trials:
        assert t.status == Trial.DONE
        assert t.last_result["training_iteration"] == t.config["max_epochs"]
        assert "loss" in t.last_result and "acc" in t.last_result


def test_sweep_best_checkpoint_exists(tmp_path):
    """Reference parity: analysis.best_checkpoint exists and is loadable
    (reference tests/test_tune.py:60-74) — but as an in-place sharded
    checkpoint path, not a queue-shipped dict (SURVEY §2.4)."""
    from ray_lightning_tpu.checkpoint.io import read_meta

    analysis = sweep.run(
        _trainer_trainable(str(tmp_path), with_checkpoint=True),
        config={"lr": sweep.grid_search([1e-2, 1e-1]), "max_epochs": 2},
        metric="loss",
        mode="min",
        executor="inline",
        total_chips=8,
        storage_dir=str(tmp_path / "sweep"),
    )
    best = analysis.best_checkpoint
    assert best and os.path.exists(best)
    meta = read_meta(best)
    assert meta["global_step"] > 0
    # every trial registered one checkpoint per epoch
    assert all(len(t.checkpoints) == 2 for t in analysis.trials)


# --------------------------------------- process-isolated trial actors


def test_process_trials_and_concurrency(tmp_path):
    """Trials run in their own processes (the reference's trial-actor
    isolation) with integral-chip accounting capping concurrency."""
    analysis = sweep.run(
        _fake_trainable,
        config={"q": sweep.grid_search([0.3, 0.6, 0.9])},
        metric="loss",
        mode="min",
        executor="process",
        total_chips=8,
        resources_per_trial=sweep.TpuResources(chips=4),  # => 2 concurrent
        storage_dir=str(tmp_path),
        trial_timeout=120.0,
    )
    assert all(t.status == Trial.DONE for t in analysis.trials)
    assert all(t.iterations == 12 for t in analysis.trials)
    assert analysis.best_config == {"q": 0.3}
    # per-trial process isolation leaves per-trial logs behind
    for t in analysis.trials:
        assert os.path.isdir(os.path.join(t.trial_dir, "logs"))


def test_process_trial_failure_is_fail_fast(tmp_path):
    def boom(config):
        raise ValueError("process kaboom")

    analysis = sweep.run(
        boom, config={}, metric="loss", executor="process",
        total_chips=8, storage_dir=str(tmp_path),
        raise_on_failed_trial=False, trial_timeout=120.0,
    )
    [t] = analysis.trials
    assert t.status == Trial.ERROR
    assert "process kaboom" in t.error


def test_trials_placed_on_cluster_hosts(tmp_path):
    """Cross-host trial placement: each process-executor trial borrows a
    host from the pool (via the remote transport's bootstrap path) and
    returns it — the reference's 'Tune schedules trial actors on any
    node' capability. 3 trials over 2 fake hosts forces reuse."""
    from ray_lightning_tpu.runtime import LoopbackTransport

    transport = LoopbackTransport()
    analysis = sweep.run(
        _fake_trainable,
        config={"q": sweep.grid_search([0.2, 0.5, 0.8])},
        metric="loss",
        mode="min",
        executor="process",
        total_chips=8,
        storage_dir=str(tmp_path),
        trial_timeout=180.0,
        hosts=["host-a", "host-b"],
        transport=transport,
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert all(t.status == Trial.DONE for t in analysis.trials)
    assert all(t.iterations == 12 for t in analysis.trials)
    spawned_hosts = [h for h, _ in transport.spawned]
    assert len(spawned_hosts) == 3
    assert set(spawned_hosts) == {"host-a", "host-b"}  # pool reuse

    with pytest.raises(sweep.SweepError, match="hosts"):
        sweep.run(
            _fake_trainable, config={}, metric="loss", executor="process",
            total_chips=8, storage_dir=str(tmp_path / "x"),
            resources_per_trial=sweep.TpuResources(chips=1, hosts=3),
            hosts=["only-one"], transport=LoopbackTransport(),
        )
    # hosts without a remote transport must fail fast, not deadlock the
    # scheduling loop
    with pytest.raises(sweep.SweepError, match="remote transport"):
        sweep.run(
            _fake_trainable, config={}, metric="loss", executor="process",
            total_chips=8, storage_dir=str(tmp_path / "y"),
            hosts=["host-a"],
        )


def _hosts_aware_trainable(config):
    from ray_lightning_tpu.sweep import get_trial_hosts

    sweep.report(loss=0.0)
    return {"hosts": get_trial_hosts()}


def test_trial_sees_its_borrowed_host_set(tmp_path):
    """A trial reserving N hosts runs its driver on the first and can
    discover the full set (for nested cross-host fit_distributed)."""
    from ray_lightning_tpu.runtime import LoopbackTransport

    transport = LoopbackTransport()
    analysis = sweep.run(
        _hosts_aware_trainable,
        config={},
        metric="loss",
        executor="process",
        total_chips=8,
        resources_per_trial=sweep.TpuResources(chips=2, hosts=2),
        storage_dir=str(tmp_path),
        trial_timeout=180.0,
        hosts=["host-a", "host-b"],
        transport=transport,
        env={"JAX_PLATFORMS": "cpu"},
    )
    [t] = analysis.trials
    assert t.status == Trial.DONE
    assert t.result["hosts"] == ["host-a", "host-b"]
    # the driver process itself was spawned on the first borrowed host
    assert [h for h, _ in transport.spawned] == ["host-a"]


def _retention_trainable(config):
    from ray_lightning_tpu import DataLoader, Trainer
    from ray_lightning_tpu.sweep import TuneReportCheckpointCallback
    from tests.utils import BoringModel, random_dataset

    trainer = Trainer(
        max_epochs=4,
        callbacks=[TuneReportCheckpointCallback(on="train_epoch_end",
                                                keep_last_n=2)],
        enable_checkpointing=False,
        enable_progress_bar=False,
        seed=0,
    )
    trainer.fit(BoringModel(), DataLoader(random_dataset(64), batch_size=32))
    return "ok"


def test_sweep_checkpoint_retention(tmp_path):
    """keep_last_n prunes the callback's older sweep checkpoints so long
    sweeps don't fill the disk; the newest (the resume source,
    trial.checkpoints[-1]) always survives."""
    analysis = sweep.run(
        _retention_trainable, config={}, metric="loss", executor="inline",
        total_chips=8, storage_dir=str(tmp_path),
    )
    [t] = analysis.trials
    assert t.status == Trial.DONE
    assert len(t.checkpoints) == 4  # all four were registered...
    import os as _os

    existing = [c for c in t.checkpoints if _os.path.isdir(c)]
    assert existing == t.checkpoints[-2:]  # ...but only the newest 2 kept
    assert t.last_checkpoint in existing


def test_report_server_survives_stalled_and_resetting_peers():
    """The report channel may face a network (host-placed trials): a peer
    that connects and stalls mid-challenge, or resets, must not wedge or
    kill the acceptor — legitimate trials keep reporting."""
    import socket
    from multiprocessing.connection import Client

    from ray_lightning_tpu.sweep.tuner import _ReportServer

    server = _ReportServer(lambda tid, m, c: "continue")
    try:
        # stalled peer: connects, never answers the auth challenge
        stall = socket.create_connection(server.address)
        # a real client must still hand-shake and report
        conn = Client(tuple(server.address),
                      authkey=bytes.fromhex(server.authkey_hex))
        conn.send(("report", "t1", {"m": 1.0}, None))
        assert conn.recv() == "continue"
        # resetting peer: connect + immediate close (RST mid-challenge)
        socket.create_connection(server.address).close()
        conn.send(("report", "t1", {"m": 2.0}, None))
        assert conn.recv() == "continue"
        conn.close()
        stall.close()
    finally:
        server.close()


# ------------------------------------------------------- trial resume


def _resumable_trainable(config):
    """Trains 4 epochs, hard-killing its own process after epoch 1 unless
    a resume checkpoint is supplied (kill -> rerun -> resume pattern)."""
    from ray_lightning_tpu import DataLoader, Trainer
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.sweep import (
        TuneReportCheckpointCallback,
        get_checkpoint,
    )
    from tests.utils import BoringModel, random_dataset

    ckpt = get_checkpoint()

    class CrashAfterEpoch1(Callback):
        def on_train_epoch_end(self, trainer, module):
            if ckpt is None and trainer.current_epoch >= 1:
                os._exit(1)  # simulate a mid-sweep kill/preemption

    trainer = Trainer(
        max_epochs=4,
        callbacks=[
            # fires BEFORE the crash callback: epochs 0-1 get registered
            TuneReportCheckpointCallback(on="train_epoch_end"),
            CrashAfterEpoch1(),
        ],
        enable_checkpointing=False,
        enable_progress_bar=False,
        seed=0,
    )
    module = BoringModel()
    trainer.fit(module, DataLoader(random_dataset(64), batch_size=32),
                ckpt_path=ckpt)
    return {"final_step": trainer.global_step, "resumed": ckpt is not None}


@pytest.mark.slow
def test_sweep_trial_resume_after_kill(tmp_path):
    """VERDICT r3 task 6: kill a trial mid-run, rerun sweep.run over the
    same storage_dir, and see it complete FROM THE SAVED STEP (extends
    reference tune.py:128-142 with the restore direction). Slow-marked:
    three sweep.run invocations, each a fresh trial subprocess with its
    own jax import + cold compile; the generous trial_timeout absorbs
    loaded single-core boxes where an epoch can take minutes."""
    kw = dict(
        config={}, metric="loss", executor="process",
        total_chips=2, storage_dir=str(tmp_path), trial_timeout=600.0,
    )
    analysis = sweep.run(_resumable_trainable, raise_on_failed_trial=False,
                         **kw)
    [t] = analysis.trials
    assert t.status == Trial.ERROR  # the process died mid-run
    assert t.checkpoints, "epochs 0-1 must have registered checkpoints"
    # durable record for the rerun
    assert os.path.exists(os.path.join(t.trial_dir, "trial_state.json"))

    analysis2 = sweep.run(_resumable_trainable, **kw)
    [t2] = analysis2.trials
    assert t2.status == Trial.DONE
    assert t2.result["resumed"] is True
    # 64/32 = 2 steps/epoch x 4 epochs = 8 total; a non-resumed rerun
    # would also end at 8 but with history 2 + 4 = 6 reports — resumed
    # history is exactly 4 (epochs 0-1 from run 1, 2-3 from run 2)
    assert t2.result["final_step"] == 8
    assert t2.iterations == 4

    # third run: everything DONE, nothing re-executed — and the recorded
    # trainable return value survives the rerun
    analysis3 = sweep.run(_resumable_trainable, **kw)
    [t3] = analysis3.trials
    assert t3.status == Trial.DONE
    assert t3.iterations == 4
    assert t3.result == {"final_step": 8, "resumed": True}


# ------------------------------ nested: sweep over distributed SPMD fit


@pytest.mark.slow
def test_sweep_over_fit_distributed(tmp_path):
    """The signature three-level topology (SURVEY §3.3): sweep driver →
    trial → SPMD worker group. Worker rank 0's report closure trampolines
    through the runtime queue into the trial session (reference
    tune.py:97-101 + util.py:88-93 rebuilt)."""
    from ray_lightning_tpu.runtime import fit_distributed

    root = str(tmp_path)

    def trainable(config):
        def module_factory():
            return BoringModel(lr=config["lr"])

        def trainer_factory():
            from ray_lightning_tpu import DataParallel

            return get_trainer(
                os.path.join(root, "inner"),
                strategy=DataParallel(),
                max_epochs=config["max_epochs"],
                callbacks=[sweep.TuneReportCallback(
                    metrics={"loss": "val_loss"})],
                checkpoint_callback=False,
            )

        def data_factory():
            data = random_dataset(n=128)
            return (DataLoader(data, batch_size=32),
                    DataLoader(data, batch_size=32))

        fit_distributed(
            module_factory, trainer_factory, data_factory,
            num_processes=2, platform="cpu",
            num_cpu_devices_per_process=2,
            return_weights=False,
            log_dir=os.path.join(root, "workers"),
        )

    analysis = sweep.run(
        trainable,
        config={"lr": 1e-2, "max_epochs": 2},
        metric="loss",
        mode="min",
        executor="inline",
        total_chips=8,
        resources_per_trial=sweep.TpuResources(chips=4),
        storage_dir=os.path.join(root, "sweep"),
    )
    [t] = analysis.trials
    assert t.status == Trial.DONE
    assert t.last_result["training_iteration"] == 2
    assert t.last_result["loss"] > 0
