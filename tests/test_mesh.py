"""Mesh construction and multi-slice (DCN) layout: the `data` axis must be
the only thing that spans slices (the layout contract of parallel/mesh.py;
gradient all-reduce rides DCN, tensor/seq/fsdp collectives stay on ICI)."""
import dataclasses

import jax
import pytest

from ray_lightning_tpu.parallel.mesh import (
    MeshSpec,
    batch_size_divisor,
    dp_axis_names,
    order_devices_for_slices,
)


@dataclasses.dataclass(frozen=True)
class FakeDev:
    """Stand-in for a multi-slice TPU device (CPU devices carry no
    slice_index, so multi-slice layout is tested with fakes)."""

    id: int
    slice_index: int


def test_meshspec_resolve_wildcard():
    spec = MeshSpec(data=-1, tensor=2).resolve(8)
    assert spec.data == 4 and spec.tensor == 2
    with pytest.raises(ValueError):
        MeshSpec(data=-1, fsdp=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)


def test_meshspec_build_and_dp_axes(devices8):
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build(devices8)
    assert dict(mesh.shape) == {"data": 2, "pipe": 1, "fsdp": 2,
                                "expert": 1, "seq": 1, "tensor": 2}
    assert dp_axis_names(mesh) == ("data", "fsdp")
    assert batch_size_divisor(mesh) == 4


def test_single_slice_order_unchanged(devices8):
    spec = MeshSpec(data=8)
    assert order_devices_for_slices(devices8, spec) == list(devices8)


def test_multislice_orders_slice_major():
    # interleaved arrival order (as jax.devices() can present them)
    devs = [FakeDev(i, slice_index=i % 2) for i in range(8)]
    spec = MeshSpec(data=2, fsdp=2, tensor=2)
    out = order_devices_for_slices(devs, spec)
    # slice 0's four devices first, then slice 1's — so reshape(data=2, ...)
    # puts each whole slice under one `data` coordinate
    assert [d.slice_index for d in out] == [0, 0, 0, 0, 1, 1, 1, 1]
    # stable within a slice
    assert [d.id for d in out] == [0, 2, 4, 6, 1, 3, 5, 7]


def test_multislice_data_must_cover_slices():
    devs = [FakeDev(i, slice_index=i % 2) for i in range(8)]
    with pytest.raises(ValueError, match="multiple of the slice count"):
        order_devices_for_slices(devs, MeshSpec(data=1, tensor=8))
    # data=4 over 2 slices: fine (2 data groups per slice)
    out = order_devices_for_slices(devs, MeshSpec(data=4, tensor=2))
    assert len(out) == 8


def test_multislice_uneven_slices_rejected():
    devs = [FakeDev(i, slice_index=0) for i in range(5)]
    devs += [FakeDev(5 + i, slice_index=1) for i in range(3)]
    with pytest.raises(ValueError, match="uneven"):
        order_devices_for_slices(devs, MeshSpec(data=2, tensor=4))


def test_build_with_multislice_fakes():
    """End-to-end: a mesh built from interleaved multi-slice devices has
    whole slices under each data coordinate."""
    devs = [FakeDev(i, slice_index=i % 2) for i in range(8)]
    spec = MeshSpec(data=2, tensor=4).resolve(8)
    ordered = order_devices_for_slices(devs, spec)
    import numpy as np

    arr = np.asarray(ordered, dtype=object).reshape(2, 1, 1, 1, 1, 4)
    for data_coord in range(2):
        slices = {d.slice_index for d in arr[data_coord].flat}
        assert len(slices) == 1, "a data row must live in ONE slice"


def test_jax_devices_have_no_fake_attrs(devices8):
    # guard: the getattr default path (CPU devices) stays on the
    # single-slice fast path
    assert all(getattr(d, "slice_index", None) in (None, 0)
               for d in devices8)
    mesh = MeshSpec(data=4, tensor=2).build(devices8)
    assert jax.device_count() >= 8
    assert mesh.devices.shape == (4, 1, 1, 1, 1, 2)
