"""The 8B dress rehearsal — no hardware required (VERDICT r3 #4).

BASELINE.json config 4 is "Llama-3-8B, FSDP-on-XLA across v5p-64". These
tests make that north star checkable on a CPU box:

  1. the EXACT param/opt/grad footprint of the full 8B TrainState under
     the proposed ShardedMesh, via eval_shape + the strategy's own
     sharding composition over an AbstractMesh (parallel/plan.py) —
     asserted to fit v5p HBM with the activation bound included;
  2. the planner must also be able to say NO (the same model on a
     too-small topology does not fit — a planner that always passes
     proves nothing);
  3. the true-8B-config train step (remat + scan + fused CE at
     dim 4096 / 32 layers / V=128256) AOT-COMPILES over a REAL 8-device
     virtual mesh — pure FSDP and the Megatron-TP x FSDP composition —
     running the SPMD partitioner and buffer assignment, with XLA's own
     memory_analysis asserted against the planner's byte arithmetic;
  4. the planner never initializes a jax backend (it must work on a box
     whose accelerator is unreachable).
"""
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
from ray_lightning_tpu.parallel.plan import (
    HBM_BYTES_BY_KIND,
    llama_activation_bytes,
    plan_train_memory,
)
from ray_lightning_tpu.parallel.strategy import ShardedMesh

GIB = 1024**3


def _cfg_8b(**kw):
    # the flagship path: remat+scan (the only class that holds at 8B),
    # fused CE (materialized [B,S,V] logits provably OOM at V=128256)
    return LlamaConfig.llama3_8b(
        remat=True, scan_layers=True, fused_ce=True, **kw
    )


def _batch_struct(batch, seq):
    return {"tokens": np.zeros((batch, seq + 1), np.int32)}


def test_hbm_bytes_override_accepts_unknown_hardware():
    """ISSUE-1 satellite: an unknown device_kind errors helpfully
    (listing known kinds — never a bare KeyError), and an explicit
    hbm_bytes override plans hardware the table doesn't know."""
    from ray_lightning_tpu.parallel.plan import hbm_bytes_for_kind

    with pytest.raises(ValueError, match="known"):
        hbm_bytes_for_kind("TPU v99")
    with pytest.raises(ValueError, match="positive"):
        hbm_bytes_for_kind("TPU v99", hbm_bytes=0)
    assert hbm_bytes_for_kind("TPU v99", 7 * GIB) == 7 * GIB
    assert hbm_bytes_for_kind("TPU v5p") == HBM_BYTES_BY_KIND["TPU v5p"]

    cfg = LlamaConfig.tiny()
    plan = plan_train_memory(
        LlamaModule(cfg), ShardedMesh(fsdp=8), n_devices=8,
        example_batch=_batch_struct(8, 256),
        device_kind="research-chip-x1",
        hbm_bytes_per_device=8 * GIB,
    )
    assert plan.hbm_bytes_per_device == 8 * GIB
    assert plan.fits


def test_plan_cli_hbm_bytes_override(capsys):
    """--hbm-bytes flows through the plan subcommand, unlocking
    free-form --device-kind strings."""
    import json

    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "tiny", "--fsdp", "8", "--batch", "8",
               "--seq", "128", "--device-kind", "research-chip-x1",
               "--hbm-bytes", str(8 * GIB), "--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and info["fits"] is True
    assert info["budget_bytes"] == int(8 * GIB * 0.9)

    # without the override the unknown kind is a structured exit-2 error
    rc = main(["plan", "--preset", "tiny", "--fsdp", "8", "--batch", "8",
               "--seq", "128", "--device-kind", "research-chip-x1",
               "--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 2 and "research-chip-x1" in info["error"]


def test_8b_fits_v5p_64_under_fsdp():
    """The north-star plan: Llama-3-8B, FSDP over 64 v5p chips,
    global batch 64 x S=8192."""
    cfg = _cfg_8b(max_seq_len=8192)
    n_dev, global_batch, seq = 64, 64, 8192
    acts = llama_activation_bytes(cfg, local_batch=global_batch // n_dev,
                                  seq=seq)
    plan = plan_train_memory(
        LlamaModule(cfg),
        ShardedMesh(fsdp=n_dev),
        n_devices=n_dev,
        example_batch=_batch_struct(global_batch, seq),
        activation_bytes_per_device=acts,
        device_kind="TPU v5p",
    )
    # the plan's param accounting IS Llama-3-8B: ~8.03B f32 params
    n_params = plan.params_bytes_global / 4
    assert 7.9e9 < n_params < 8.1e9, f"{n_params:.3e} params"
    # adamw: mu + nu, param-shaped -> ~2x params (+ tiny schedule scalars)
    assert plan.opt_bytes_global == pytest.approx(
        2 * plan.params_bytes_global, rel=0.01)
    # FSDP actually sharded the big state ~evenly over 64 devices
    assert plan.params_bytes_per_device < plan.params_bytes_global / 48
    assert plan.fits, plan.summary()


def test_8b_plan_rejects_undersized_topology():
    """Same model, 8 v5e chips (16 GiB): params+opt alone are ~12 GiB per
    device before activations — the planner must refuse."""
    cfg = _cfg_8b(max_seq_len=8192)
    plan = plan_train_memory(
        LlamaModule(cfg),
        ShardedMesh(fsdp=8),
        n_devices=8,
        example_batch=_batch_struct(8, 8192),
        activation_bytes_per_device=llama_activation_bytes(cfg, 1, 8192),
        device_kind="TPU v5e",
    )
    assert not plan.fits, plan.summary()
    assert plan.hbm_bytes_per_device == HBM_BYTES_BY_KIND["TPU v5e"]


def test_plan_respects_tensor_axis_specs():
    """Megatron tensor specs from the module overlay the fsdp auto-spec:
    a tensor=8 mesh splits the qkv projection's output dim 8-ways."""
    cfg = _cfg_8b(max_seq_len=2048)
    plan_t = plan_train_memory(
        LlamaModule(cfg), ShardedMesh(tensor=8), n_devices=8,
        example_batch=_batch_struct(8, 2048), device_kind="TPU v5p",
    )
    plan_r = plan_train_memory(
        LlamaModule(cfg), ShardedMesh(data=8), n_devices=8,
        example_batch=_batch_struct(8, 2048), device_kind="TPU v5p",
    )
    # pure DP replicates everything; TP cuts per-device param bytes hard
    assert plan_r.params_bytes_per_device == plan_r.params_bytes_global
    assert plan_t.params_bytes_per_device < 0.2 * plan_t.params_bytes_global


def test_planner_input_validation():
    """Library-API edges: unknown device kinds name the escape hatch
    instead of KeyError-ing, and dp_degree refuses unresolved specs
    (a -1 wildcard would silently undercount the batch divisor)."""
    from ray_lightning_tpu.parallel.mesh import MeshSpec
    from ray_lightning_tpu.parallel.plan import dp_degree

    cfg = LlamaConfig.tiny()
    with pytest.raises(ValueError, match="hbm_bytes_per_device"):
        plan_train_memory(
            LlamaModule(cfg), ShardedMesh(fsdp=8), n_devices=8,
            example_batch={"tokens": np.zeros((8, 257), np.int32)},
            device_kind="TPU v99",
        )
    assert dp_degree(MeshSpec(data=2, fsdp=4, tensor=2)) == 8
    with pytest.raises(ValueError, match="resolved"):
        dp_degree(MeshSpec(fsdp=-1))


def test_planner_initializes_no_backend():
    """The planner's contract: NO jax backend is ever initialized — it
    must work on a box whose accelerator is unreachable (the exact
    situation where you need a pre-flight plan). Regression for two
    traps: a concrete PRNG key, and the pallas dispatch probing
    jax.default_backend() at trace time."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import numpy as np\n"
        "from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule\n"
        "from ray_lightning_tpu.parallel.plan import plan_train_memory\n"
        "from ray_lightning_tpu.parallel.strategy import ShardedMesh\n"
        "cfg = LlamaConfig.tiny(remat=True, fused_ce=True)\n"
        "plan = plan_train_memory(LlamaModule(cfg), ShardedMesh(fsdp=8),\n"
        "    n_devices=8,\n"
        "    example_batch={'tokens': np.zeros((8, 257), np.int32)})\n"
        "assert plan.fits\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), \\\n"
        "    'planning initialized a backend'\n"
        "print('NO-BACKEND-OK')\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    # the adversarial setting: RLT_PALLAS=1 pushes every op toward the
    # kernel path, whose interpret-mode probe queries the backend —
    # force_xla() must pin ALL of them off during the plan trace
    env["RLT_PALLAS"] = "1"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=180, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "NO-BACKEND-OK" in out.stdout


@pytest.mark.slow
@pytest.mark.parametrize("fsdp,tensor", [(8, 1), (4, 2)])
def test_8b_program_compiles_on_virtual_mesh(devices8, fsdp, tensor):
    """AOT-compile the REAL 8B training step (value_and_grad + adamw
    update, donated state — the bench/Trainer step shape) over an
    8-device mesh with its real shardings — pure FSDP, and the
    Megatron-TP x FSDP composition: tracing, StableHLO lowering, the XLA
    SPMD partitioner AND buffer assignment all run (compiling plans
    buffers, it does not allocate them — ~12s per config on one CPU
    core), and the executable's own memory_analysis must agree with the
    planner's per-device param+opt arithmetic. This is the strongest
    no-hardware proof that the north-star program BUILDS."""
    import jax
    import optax
    from functools import partial

    cfg = _cfg_8b(max_seq_len=8192)
    module = LlamaModule(cfg)
    strategy = ShardedMesh(fsdp=fsdp, tensor=tensor, devices=devices8)
    strategy.setup(module)
    module.setup()  # the Trainer's fit() ordering: mesh first, then model

    batch, seq = 8, 8192
    tokens_sds = jax.ShapeDtypeStruct((batch, seq + 1), np.int32)
    a_params = jax.eval_shape(
        module.init_params, jax.random.key(0),
        {"tokens": tokens_sds},
    )
    p_shardings = strategy.param_shardings(a_params)
    # the module's REAL optimizer — the same transformation the planner
    # measures, so the byte cross-check below compares like with like
    tx = module.configure_optimizers()
    a_opt = jax.eval_shape(tx.init, a_params)
    o_shardings = strategy.opt_state_shardings(a_opt, a_params)

    def loss_fn(params, tokens):
        return module._loss(params, tokens[:, :-1], tokens[:, 1:], None)

    @partial(jax.jit, donate_argnums=(0, 1),
             in_shardings=(p_shardings, o_shardings,
                           strategy.batch_sharding()),
             out_shardings=(p_shardings, o_shardings, None))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    lowered = step.lower(
        jax.tree.map(lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                       sharding=s),
                     a_params, p_shardings),
        jax.tree.map(lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                       sharding=s),
                     a_opt, o_shardings),
        jax.ShapeDtypeStruct((batch, seq + 1), np.int32,
                             sharding=strategy.batch_sharding()),
    )
    hlo = lowered.as_text()
    assert "sharding" in hlo  # the program carries real shardings
    # loss out is a replicated f32 scalar — shapes flowed end to end
    out_avals = jax.tree.leaves(lowered.out_info)
    assert any(getattr(o, "shape", None) == () for o in out_avals)

    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    # XLA's buffer assignment must agree with the planner's arithmetic:
    # per-device arguments = sharded params (f32) + adamw mu/nu
    # (~12.05 GB at fsdp=8; the ~32 KiB/device token buffer and any
    # layout padding live inside the 2%+1MiB slack). A fresh
    # module+strategy per plan_train_memory's contract.
    plan = plan_train_memory(
        LlamaModule(cfg), ShardedMesh(fsdp=fsdp, tensor=tensor),
        n_devices=8,
        example_batch={"tokens": np.zeros((batch, seq + 1), np.int32)},
        device_kind="TPU v5p",
    )
    expected_args = (plan.params_bytes_per_device
                    + plan.opt_bytes_per_device)
    assert abs(mem.argument_size_in_bytes - expected_args) \
        < 0.02 * expected_args + 2**20, (
        mem.argument_size_in_bytes, expected_args)
    # donation wired through: outputs alias the donated state
    assert mem.alias_size_in_bytes > 0.9 * expected_args
    assert mem.temp_size_in_bytes > 0  # activations/workspace planned

def test_activation_bytes_counts_inline_ce_residuals():
    """ce_inline_bwd trades recompute for residual memory (dx + f32 dW);
    the planner must charge for it, or an inline-CE plan could read FITS
    on a chip the dW accumulator alone would overflow."""
    from ray_lightning_tpu.models.llama import LlamaConfig

    base = LlamaConfig.llama3_8b(remat=True, scan_layers=True,
                                 fused_ce=True, max_seq_len=8192)
    inline = LlamaConfig.llama3_8b(remat=True, scan_layers=True,
                                   fused_ce=True, max_seq_len=8192,
                                   ce_inline_bwd=True)
    a = llama_activation_bytes(base, local_batch=1, seq=8192)
    b = llama_activation_bytes(inline, local_batch=1, seq=8192)
    # at least the f32 [D, V] accumulator (x1.5 slack), ~3 GB at 8B scale
    assert b - a >= 1.5 * base.dim * base.vocab_size * 4


def test_find_max_local_batch_exact_boundary():
    """The search returns EXACTLY the largest batch whose activation
    bound fits the post-weights headroom — including non-power-of-2
    optima the exponential bracket alone would miss."""
    from ray_lightning_tpu.parallel.plan import find_max_local_batch

    cfg = LlamaConfig.tiny()
    per_batch = 64 * 1024**2  # 64 MiB per local-batch row, linear

    local, plan = find_max_local_batch(
        LlamaModule(cfg), ShardedMesh(data=8), n_devices=8,
        example_batch=_batch_struct(8, cfg.max_seq_len),
        activation_bytes_fn=lambda b: b * per_batch,
        device_kind="TPU v5e",
    )
    assert local >= 1
    # exactness: the found batch fits, the next one does not
    headroom_wo_acts = plan.headroom_bytes + plan.activation_bytes_per_device
    assert local * per_batch <= headroom_wo_acts
    assert (local + 1) * per_batch > headroom_wo_acts
    assert plan.activation_bytes_per_device == local * per_batch
    assert plan.fits, plan.summary()
    # a 16 GiB chip minus tiny-model weights leaves a non-trivial,
    # non-power-of-2 count of 64 MiB rows — guard the bisection path
    assert local not in (0, 1)


def test_find_max_local_batch_no_fit_returns_zero():
    """When even local_batch=1 exceeds the headroom the caller gets
    (0, activation-free plan) — the model/mesh is the problem, not the
    batch, and the summary says what the weights alone cost."""
    from ray_lightning_tpu.parallel.plan import find_max_local_batch

    cfg = LlamaConfig.tiny()
    local, plan = find_max_local_batch(
        LlamaModule(cfg), ShardedMesh(data=1), n_devices=1,
        example_batch=_batch_struct(1, cfg.max_seq_len),
        activation_bytes_fn=lambda b: 10**15,
        device_kind="TPU v5e",
    )
    assert local == 0
    assert plan.activation_bytes_per_device == 0


def test_find_max_local_batch_ceiling_clamps():
    """A free activation function saturates at the ceiling rather than
    spinning the growth loop forever."""
    from ray_lightning_tpu.parallel.plan import find_max_local_batch

    cfg = LlamaConfig.tiny()
    local, _ = find_max_local_batch(
        LlamaModule(cfg), ShardedMesh(data=1), n_devices=1,
        example_batch=_batch_struct(1, cfg.max_seq_len),
        activation_bytes_fn=lambda b: 0,
        device_kind="TPU v5p", ceiling=100,
    )
    assert local == 100


def test_find_max_batch_8b_north_star():
    """The north-star mesh (8B FSDP on v5p-64, S=8192) must admit at
    least the BASELINE global batch of 64 (local 1) — and the finder's
    answer must itself plan as FITS under the real flagship bound."""
    from ray_lightning_tpu.parallel.plan import find_max_local_batch

    cfg = _cfg_8b(max_seq_len=8192)
    local, plan = find_max_local_batch(
        LlamaModule(cfg), ShardedMesh(fsdp=64), n_devices=64,
        example_batch=_batch_struct(64, 8192),
        activation_bytes_fn=lambda b: llama_activation_bytes(
            cfg, b, 8192, weight_shard_degree=64),
        device_kind="TPU v5p",
    )
    assert local >= 1, plan.summary()
    assert plan.fits, plan.summary()


def test_activation_bytes_attnout_gated_on_remat_and_dtype():
    """ADVICE r5: the attn_out residual charge applies only when remat
    is actually on (the model documents the policy as ignored with
    remat=False), and is charged at cfg.dtype's width, not an assumed
    2 B/elem."""
    import jax.numpy as jnp

    base = _cfg_8b(remat_policy="nothing")
    attn = _cfg_8b(remat_policy="attn_out")
    plain = llama_activation_bytes(base, local_batch=1, seq=8192)
    saved = llama_activation_bytes(attn, local_batch=1, seq=8192)
    assert saved > plain  # remat=True + attn_out charges the residuals

    # remat off: policy documented as ignored -> identical charge
    import dataclasses

    attn_no_remat = dataclasses.replace(attn, remat=False)
    base_no_remat = dataclasses.replace(base, remat=False)
    assert (llama_activation_bytes(attn_no_remat, 1, 8192)
            == llama_activation_bytes(base_no_remat, 1, 8192))

    # f32 compute dtype: the residual share doubles vs bf16 (4 B vs 2 B
    # per element; the f32 logsumexp term is dtype-independent)
    attn_f32 = _cfg_8b(remat_policy="attn_out", dtype=jnp.float32)
    delta_bf16 = saved - plain
    delta_f32 = (llama_activation_bytes(attn_f32, 1, 8192)
                 - llama_activation_bytes(
                     _cfg_8b(remat_policy="nothing", dtype=jnp.float32),
                     1, 8192))
    hd = attn.head_dim
    lse = attn.n_layers * 8192 * attn.n_heads * 4
    resid_bf16 = attn.n_layers * 8192 * (
        (2 * attn.n_heads + 2 * attn.n_kv_heads) * hd * 2)
    resid_f32 = resid_bf16 * 2
    assert delta_bf16 == int(1.5 * (resid_bf16 + lse))
    assert delta_f32 == int(1.5 * (resid_f32 + lse))
