"""numcheck (analysis/numcheck.py): the RLT8xx precision layer.

Fire/sanction matrix per rule over real jaxprs, the RLT804 collective
check over fabricated event streams, precision-ledger byte identities
against the audit's own memory accounting, the shared dtype-width table
(no drift vs RLT105), repo-audits-clean pins for every bundled trace
target, and CLI smoke for `lint --numerics` / `trace --no-numerics`.

The matrix convention: each `fire_*` test must produce EXACTLY the
named finding(s) — an injected bug yields one finding, not a spray —
and each `sanction_*` test must be silent. That exactness is the
contract that keeps the format.sh gate (zero RLT801/805 across the
examples) meaningful.
"""
import json
import subprocess
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from ray_lightning_tpu.analysis import costmodel
from ray_lightning_tpu.analysis.numcheck import (
    LOW_PRECISION_EXTENT,
    check_gradient_collectives,
    check_numerics_sources,
    numcheck_jaxpr,
    summarize,
)


def _audit(fn, *args, loss_index=None):
    closed = jax.make_jaxpr(fn)(*args)
    return numcheck_jaxpr(closed, loss_index=loss_index)


def _rules(fn, *args):
    findings, _ = _audit(fn, *args)
    return [f.rule for f in findings]


BF = jnp.ones((512, 512), jnp.bfloat16)
F32 = jnp.ones((512, 512), jnp.float32)
Q8 = jnp.ones((512, 512), jnp.int8)
SMALL = jnp.ones((64, 64), jnp.bfloat16)


# --------------------------------------------------------------------------
# RLT801 — low-precision accumulation
# --------------------------------------------------------------------------


class TestRLT801:
    def test_fire_bf16_dot(self):
        assert _rules(lambda a, b: a @ b, BF, BF) == ["RLT801"]

    def test_fire_raw_bf16_reduce_sum(self):
        # raw reduce_sum at bf16 (jnp.sum would auto-widen — see below)
        fn = lambda a: lax.reduce_sum_p.bind(a, axes=(0,))  # noqa: E731
        assert _rules(fn, BF) == ["RLT801"]

    def test_sanction_preferred_f32_round_once(self):
        # the rule's own prescription: f32 accumulator, one rounding
        def fn(a, b):
            out = lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return out.astype(jnp.bfloat16)
        assert _rules(fn, BF, BF) == []

    def test_sanction_jnp_sum_auto_widens(self):
        # jnp.sum(bf16) inserts convert->f32->reduce->convert itself
        assert _rules(lambda a: a.sum(axis=0), BF) == []

    def test_sanction_small_extent(self):
        # K <= LOW_PRECISION_EXTENT costs < 1 decimal digit — sanctioned
        assert SMALL.shape[0] <= LOW_PRECISION_EXTENT
        assert _rules(lambda a, b: a @ b, SMALL, SMALL) == []
        fn = lambda a: lax.reduce_sum_p.bind(a, axes=(0,))  # noqa: E731
        assert _rules(fn, SMALL) == []

    def test_injected_bug_exactly_one_finding(self):
        # acceptance: an injected bf16-accumulating dot produces ONE
        # finding, not a cascade from its downstream uses
        def fn(a, b):
            y = a @ b
            return (y + 1.0).sum()
        findings, _ = _audit(fn, BF, BF)
        assert [f.rule for f in findings] == ["RLT801"]


# --------------------------------------------------------------------------
# RLT802 — transcendental on low-precision operand
# --------------------------------------------------------------------------


class TestRLT802:
    @pytest.mark.parametrize("fn", [jnp.exp, jnp.log, lax.rsqrt],
                             ids=["exp", "log", "rsqrt"])
    def test_fire_bf16_transcendental(self, fn):
        assert _rules(lambda a: fn(a), BF) == ["RLT802"]

    def test_sanction_f32_operand(self):
        assert _rules(lambda a: jnp.exp(a), F32) == []

    def test_sanction_softmax_submax(self):
        # exp(x - max(x)) is the numerically-sanctioned shape
        assert _rules(lambda a: jax.nn.softmax(a, axis=-1), BF) == []


# --------------------------------------------------------------------------
# RLT803 — cast churn (f32 -> bf16 -> f32 with no compute between)
# --------------------------------------------------------------------------


class TestRLT803:
    def test_fire_inline_round_trip(self):
        fn = lambda a: (a + 1.0).astype(jnp.bfloat16).astype(jnp.float32) * 2.0  # noqa: E731,E501
        assert _rules(fn, F32) == ["RLT803"]

    def test_sanction_compute_between_casts(self):
        # real bf16 arithmetic between the casts: that is mixed
        # precision working as designed, not churn
        def fn(a):
            h = (a + 1.0).astype(jnp.bfloat16) * 2.0
            return h.astype(jnp.float32) + 1.0
        assert _rules(fn, F32) == []

    def test_fire_scan_carried_cast(self):
        # the downcast rides a scan carry; the re-widen after the loop
        # still closes the round trip (fixpoint carry merge)
        def fn(a):
            h = (a + 1.0).astype(jnp.bfloat16)

            def body(c, _):
                return c, ()

            c, _ = lax.scan(body, h, None, length=3)
            return c.astype(jnp.float32) * 2.0
        assert _rules(fn, F32) == ["RLT803"]

    def test_sanction_rounding_fresh_accumulator(self):
        # downcasting a dot's WIDE accumulator is RLT801's own
        # prescription — re-widening later must not read as churn
        def fn(a, b):
            y = lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.bfloat16)
            return y.astype(jnp.float32).sum()
        assert _rules(fn, BF, BF) == []

    def test_sanction_cross_file_seam(self):
        # downcast here, re-widen inside ops/norms.py: a module-
        # boundary contract (the callee computes at f32 by design),
        # not a churn bug in either file
        from ray_lightning_tpu.ops.norms import rms_norm

        w = jnp.ones((512,), jnp.float32)

        def fn(a, w):
            h = (a + 1.0).astype(jnp.bfloat16)
            return rms_norm(h, w)
        assert _rules(fn, F32, w) == []


# --------------------------------------------------------------------------
# RLT805 — quantized payload consumed without a dequant scale
# --------------------------------------------------------------------------


class TestRLT805:
    def test_fire_scale_free_consume_exactly_one(self):
        # acceptance: int8 pushed straight into float math — one RLT805
        # (plus the bf16 dot's own RLT801, a distinct defect)
        findings, _ = _audit(lambda a, b: a.astype(jnp.bfloat16) @ b,
                             Q8, BF)
        assert sorted(f.rule for f in findings) == ["RLT801", "RLT805"]
        assert sum(f.rule == "RLT805" for f in findings) == 1

    def test_sanction_f32_scale(self):
        def fn(a, b):
            deq = a.astype(jnp.float32) * jnp.float32(0.02)
            return (deq.astype(jnp.bfloat16) @ b).astype(jnp.float32)
        findings, _ = _audit(fn, Q8, BF)
        assert all(f.rule != "RLT805" for f in findings)

    def test_narrow_scale_fires_then_clears(self):
        # a bf16 scale IS a scale (quant flag clears, the dot does not
        # re-fire) but re-quantizes the payload — its own RLT805
        def fn(a, b):
            return (a.astype(jnp.bfloat16) * jnp.bfloat16(0.02)) @ b
        findings, _ = _audit(fn, Q8, BF)
        narrow = [f for f in findings if f.rule == "RLT805"]
        assert len(narrow) == 1
        assert "narrower than f32" in narrow[0].message

    def test_sanction_int8_dot_int32(self):
        # integer-domain contraction (the int8-KV plan's inner product)
        # never enters float math — nothing to scale yet
        def fn(a, b):
            return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.int32)
        assert _rules(fn, Q8, Q8) == []


# --------------------------------------------------------------------------
# pallas: kernels audit like plain arrays; f32 scratch is the sanction
# --------------------------------------------------------------------------


class TestPallasSanction:
    def test_rmsnorm_pallas_bf16_clean(self):
        # the kernel reads bf16 tiles but squares/sums in an f32
        # scratch — numcheck recurses into pallas_call and must see
        # that, not flag the bf16 refs
        from ray_lightning_tpu.ops.pallas.rmsnorm import rms_norm_pallas

        x = jnp.ones((8, 512), jnp.bfloat16)
        w = jnp.ones((512,), jnp.float32)
        assert _rules(lambda x, w: rms_norm_pallas(x, w), x, w) == []


# --------------------------------------------------------------------------
# model pins — the satellite-1 fixes stay fixed
# --------------------------------------------------------------------------


class TestModelPins:
    def test_fused_ce_accumulates_f32(self):
        # the chunked loop must carry f32 partials and dot with
        # preferred_element_type=f32 even on bf16 hidden/weights
        from ray_lightning_tpu.ops.fused_ce import fused_cross_entropy

        h = jnp.ones((4, 128, 64), jnp.bfloat16)
        W = jnp.ones((64, 512), jnp.float32)
        t = jnp.zeros((4, 128), jnp.int32)

        def loss(h, W):
            return fused_cross_entropy(h, W, t, chunk_tokens=128).mean()

        closed = jax.make_jaxpr(
            lambda h, W: jax.value_and_grad(loss, argnums=(0, 1))(h, W)
        )(h, W)
        findings, info = numcheck_jaxpr(closed, loss_index=0)
        assert findings == []
        assert info["loss_widest_dtype"] == "float32"

    def test_moe_mlp_bf16_grad_clean(self):
        # router logits, dispatch/combine einsums and expert matmuls
        # all accumulate f32 (preferred_element_type) at dtype=bf16
        from ray_lightning_tpu.models.moe import MoEMLP

        m = MoEMLP(n_experts=4, hidden_dim=128, top_k=2,
                   dtype=jnp.bfloat16)
        x = jnp.ones((2, 64, 32), jnp.bfloat16)
        params = m.init(jax.random.PRNGKey(0), x)["params"]

        def loss(p, x):
            y, aux = m.apply({"params": p}, x)
            return (y.astype(jnp.float32) ** 2).mean() + aux.mean()

        closed = jax.make_jaxpr(
            lambda p, x: jax.value_and_grad(loss)(p, x))(params, x)
        findings, _ = numcheck_jaxpr(closed)
        assert findings == []


# --------------------------------------------------------------------------
# RLT804 — gradient collectives vs optimizer-state width
# --------------------------------------------------------------------------


def _ev(**kw):
    return types.SimpleNamespace(**kw)


_PARAMS = {"layers/w/kernel": types.SimpleNamespace(
    shape=(8, 8), dtype=np.dtype("float32"))}
_OPT = {"mu/layers/w/kernel": types.SimpleNamespace(
    shape=(8, 8), dtype=np.dtype("float32"))}


class TestRLT804:
    def test_fire_bf16_grad_reduce_scatter(self):
        events = [_ev(kind="reduce_scatter", dtype="bfloat16",
                      param_path="params/layers/w/kernel", axes=("data",),
                      source="reduce_scatter @ x.py:1")]
        findings = check_gradient_collectives(events, _PARAMS, _OPT)
        assert [f.rule for f in findings] == ["RLT804"]
        assert findings[0].symbol == "params/layers/w/kernel"
        assert "data" in findings[0].message

    def test_dedupe_by_site_and_path(self):
        ev = _ev(kind="reduce_scatter", dtype="bfloat16",
                 param_path="params/layers/w/kernel", axes=("data",),
                 source="reduce_scatter @ x.py:1")
        assert len(check_gradient_collectives([ev, ev], _PARAMS, _OPT)) == 1

    def test_silent_cases(self):
        events = [
            # f32 payload: already as wide as the opt state
            _ev(kind="psum", dtype="float32",
                param_path="params/layers/w/kernel", axes=("data",),
                source="psum @ x.py:2"),
            # all_gather is a weight fetch, not a gradient reduction
            _ev(kind="all_gather", dtype="bfloat16",
                param_path="params/layers/w/kernel", axes=("data",),
                source="ag @ x.py:3"),
            # non-param payload (a metric psum)
            _ev(kind="psum", dtype="bfloat16", param_path="loss",
                axes=("data",), source="psum @ x.py:4"),
        ]
        assert check_gradient_collectives(events, _PARAMS, _OPT) == []

    def test_silent_when_opt_state_is_not_wider(self):
        opt = {"mu/layers/w/kernel": types.SimpleNamespace(
            shape=(8, 8), dtype=np.dtype(jnp.bfloat16))}
        events = [_ev(kind="reduce_scatter", dtype="bfloat16",
                      param_path="params/layers/w/kernel", axes=("data",),
                      source="reduce_scatter @ x.py:1")]
        assert check_gradient_collectives(events, _PARAMS, opt) == []


# --------------------------------------------------------------------------
# shared width table — RLT105 and RLT804 must not drift
# --------------------------------------------------------------------------


class TestWidthTable:
    def test_numcheck_width_is_costmodel_width(self):
        from ray_lightning_tpu.analysis import numcheck

        for dt in ("float32", "bfloat16", "float16", "int8", "float64"):
            assert numcheck._width(dt) == costmodel.dtype_width(dt)
        assert numcheck._width("bfloat16") == 2.0
        assert numcheck._width("int8") == 1.0

    def test_rlt105_and_rlt804_single_source(self):
        # both passes import THE costmodel symbol — a width tweak in
        # one place moves both rules together (no copied tables)
        import inspect

        import ray_lightning_tpu.analysis.numcheck as numcheck
        import ray_lightning_tpu.analysis.plan_checker as plan_checker

        assert numcheck.dtype_width is costmodel.dtype_width
        imp = "from ray_lightning_tpu.analysis.costmodel import dtype_width"
        for mod in (numcheck, plan_checker):
            src = inspect.getsource(mod)
            assert imp in src
            # no privately copied width table
            assert "DTYPE_WIDTHS = {" not in src


# --------------------------------------------------------------------------
# summarize — bench JSON block shape
# --------------------------------------------------------------------------


def test_summarize_counts_by_rule():
    findings, _ = _audit(lambda a, b: a.astype(jnp.bfloat16) @ b, Q8, BF)
    s = summarize(findings)
    assert s == {"total": 2, "by_rule": {"RLT801": 1, "RLT805": 1}}
    assert summarize([]) == {"total": 0, "by_rule": {}}


# --------------------------------------------------------------------------
# precision ledger — byte identities against the audit's own accounting
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mnist_report():
    from ray_lightning_tpu.analysis.cli import resolve_trace_target
    from ray_lightning_tpu.analysis.costmodel import parse_topology
    from ray_lightning_tpu.analysis.tracecheck import audit_step

    topo = parse_topology("v5p-8")
    module, strategy, batch, label = resolve_trace_target(
        "mnist_dp_example.py", topo)
    return audit_step(module, strategy, batch, topology="v5p-8",
                      label=label)


class TestPrecisionLedger:
    def test_ledger_sums_match_plan_bytes(self, mnist_report):
        p = mnist_report.precision
        assert sum(p["params"].values()) == \
            mnist_report.params_bytes_per_device
        assert sum(p["opt_state"].values()) == \
            mnist_report.opt_bytes_per_device
        assert all(v > 0 for by in
                   (p["params"], p["opt_state"], p["activations"])
                   for v in by.values())

    def test_ledger_classes_and_loss_dtype(self, mnist_report):
        p = mnist_report.precision
        assert set(p) == {"params", "opt_state", "activations",
                          "kv_pool", "loss_widest_dtype"}
        assert p["kv_pool"] == {}  # training step holds no KV pool
        assert p["loss_widest_dtype"] == "float32"

    def test_ledger_in_to_dict(self, mnist_report):
        d = mnist_report.to_dict()
        assert d["precision"] == mnist_report.precision

    def test_numerics_off_means_no_ledger(self):
        from ray_lightning_tpu.analysis.cli import resolve_trace_target
        from ray_lightning_tpu.analysis.costmodel import parse_topology
        from ray_lightning_tpu.analysis.tracecheck import audit_step

        topo = parse_topology("v5p-8")
        module, strategy, batch, label = resolve_trace_target(
            "mnist_dp_example.py", topo)
        rep = audit_step(module, strategy, batch, topology="v5p-8",
                         label=label, numerics=False)
        assert rep.precision is None


# --------------------------------------------------------------------------
# repo audits clean — every bundled trace target is RLT8xx-free
# --------------------------------------------------------------------------

_RLT8XX = {"RLT801", "RLT802", "RLT803", "RLT804", "RLT805"}


def _trace_rules(target, topo_name):
    from ray_lightning_tpu.analysis.cli import resolve_trace_target
    from ray_lightning_tpu.analysis.costmodel import parse_topology
    from ray_lightning_tpu.analysis.tracecheck import audit_step

    topo = parse_topology(topo_name)
    module, strategy, batch, label = resolve_trace_target(target, topo)
    rep = audit_step(module, strategy, batch, topology=topo_name,
                     label=label)
    return rep, sorted({f.rule for f in rep.findings} & _RLT8XX)


@pytest.mark.parametrize("target", [
    "mnist_dp_example.py", "pod_launch_example.py",
    "cifar_resnet_example.py", "bert_finetune_example.py",
])
def test_bundled_targets_numerics_clean(target):
    _, rules = _trace_rules(target, "v5p-8")
    assert rules == []


@pytest.mark.slow
def test_llama3_8b_flagship_numerics_clean():
    rep, rules = _trace_rules("llama3-8b", "v5p-64")
    assert rules == []
    assert rep.precision["loss_widest_dtype"] == "float32"
    assert rep.precision["params"]  # the ledger is populated


# --------------------------------------------------------------------------
# AST mini-pass — `lint --numerics`
# --------------------------------------------------------------------------


class TestASTPass:
    def test_inline_bf16_astype_in_dot_fires(self):
        src = ("import jax.numpy as jnp\n"
               "def f(a, b):\n"
               "    return jnp.dot(a.astype(jnp.bfloat16), b)\n")
        findings = check_numerics_sources([("m.py", src)])
        assert [f.rule for f in findings] == ["RLT801"]
        assert findings[0].line == 3

    def test_preferred_element_type_sanctions(self):
        src = ("import jax.numpy as jnp\n"
               "def f(a, b):\n"
               "    return jnp.einsum('ij,jk->ik', a.astype(jnp.bfloat16),"
               " b, preferred_element_type=jnp.float32)\n")
        assert check_numerics_sources([("m.py", src)]) == []

    def test_inline_int8_astype_fires_805(self):
        src = ("import jax.numpy as jnp\n"
               "def f(a, b):\n"
               "    return jnp.matmul(a.astype(jnp.int8), b)\n")
        findings = check_numerics_sources([("m.py", src)])
        assert [f.rule for f in findings] == ["RLT805"]

    def test_disable_comment_suppresses(self):
        src = ("import jax.numpy as jnp\n"
               "def f(a, b):\n"
               "    return jnp.dot(a.astype(jnp.bfloat16), b)"
               "  # rlt: disable=RLT801\n")
        assert check_numerics_sources([("m.py", src)]) == []


# --------------------------------------------------------------------------
# CLI smoke
# --------------------------------------------------------------------------


class TestCLISmoke:
    def test_lint_numerics_flag(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax.numpy as jnp\n"
                       "def f(a, b):\n"
                       "    return jnp.dot(a.astype(jnp.bfloat16), b)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_lightning_tpu", "lint",
             "--numerics", str(bad)],
            capture_output=True, text=True)
        assert proc.returncode == 1
        assert "RLT801" in proc.stdout
        proc = subprocess.run(
            [sys.executable, "-m", "ray_lightning_tpu", "lint",
             "--no-numerics", str(bad)],
            capture_output=True, text=True)
        assert proc.returncode == 0
        assert "RLT801" not in proc.stdout

    def test_trace_no_numerics_json(self):
        proc = subprocess.run(
            [sys.executable, "-m", "ray_lightning_tpu", "trace",
             "mnist_dp_example.py", "--topo", "v5p-8", "--no-numerics",
             "--json"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        d = json.loads(proc.stdout)
        assert d["precision"] is None

    def test_trace_numerics_json_has_ledger(self):
        proc = subprocess.run(
            [sys.executable, "-m", "ray_lightning_tpu", "trace",
             "mnist_dp_example.py", "--topo", "v5p-8", "--json"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        d = json.loads(proc.stdout)
        assert d["precision"]["loss_widest_dtype"] == "float32"
        assert sum(d["precision"]["params"].values()) == \
            d["params_bytes_per_device"]
