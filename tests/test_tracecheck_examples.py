"""tracecheck over every bundled example's train step, on CPU (tier-1).

The ISSUE-2 acceptance bar: all six examples' steps audit with zero
RESHARD-IMPLICIT (RLT301) and zero RING-DEADLOCK (RLT303) findings, and
the Llama-8B FSDP example reports a sane peak-HBM estimate on v5p-64 —
positive, within the chip budget, and dominated by more than just the
weights (liveness, not arithmetic on params alone)."""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from ray_lightning_tpu.analysis.cli import (
    _TRACE_BUILDERS, resolve_trace_target,
)
from ray_lightning_tpu.analysis.costmodel import parse_topology
from ray_lightning_tpu.analysis.tracecheck import audit_step

EXAMPLES = sorted(set(_TRACE_BUILDERS) - {"llama3-8b"})

#: subprocess CLI invocations must be hermetic: the autouse fixture
#: chdirs every test into a tmp dir, so the repo root (package import +
#: repo-relative example paths) is pinned explicitly rather than
#: inherited from whatever cwd/PYTHONPATH the runner happened to have.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": _REPO + os.pathsep + os.environ.get(
                "PYTHONPATH", "")}

#: the flagship example audits at its BASELINE.json topology; the
#: data-parallel examples at a small pod slice
_TOPO = {"llama_fsdp_example.py": "v5p-64"}


@pytest.mark.parametrize("example", EXAMPLES)
def test_example_step_audits_clean(example):
    topo = parse_topology(_TOPO.get(example, "v5p-8"))
    module, strategy, batch, label = resolve_trace_target(example, topo)
    report = audit_step(module, strategy, batch, topology=topo,
                        label=label)
    bad = [f for f in report.findings if f.rule in ("RLT301", "RLT303")]
    assert not bad, "\n".join(f.format() for f in bad)


def test_llama_fsdp_v5p64_hbm_estimate_sane():
    topo = parse_topology("v5p-64")
    module, strategy, batch, label = resolve_trace_target(
        "llama_fsdp_example.py", topo)
    report = audit_step(module, strategy, batch, topology=topo,
                        label=label)
    gib = 1024**3
    # weights alone: ~0.5 GiB params + ~0.9 GiB opt per device; the
    # estimate must include live intermediates on top, and fit the chip
    floor = (report.params_bytes_per_device
             + report.opt_bytes_per_device)
    assert floor > 1 * gib
    assert report.peak_hbm_bytes > floor
    assert report.peak_hbm_bytes <= report.hbm_budget_bytes, \
        report.summary()
    assert report.fits
    # the ZeRO schedule is present: weight all-gathers AND gradient
    # reduce-scatters over fsdp, with real traffic behind them
    kinds = {e.kind for e in report.collectives}
    assert {"all_gather", "reduce_scatter"} <= kinds
    assert all(e.axes == ("fsdp",) for e in report.collectives)
    assert report.ici_bytes_per_step > 10 * gib


def test_trace_cli_json_llama(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "ray_lightning_tpu", "trace",
         "examples/llama_fsdp_example.py", "--topo", "v5p-64", "--json"],
        capture_output=True, text=True, timeout=300, cwd=_REPO,
        env=_CLI_ENV,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    d = json.loads(out.stdout.strip().splitlines()[-1])
    assert d["ok"] is True
    assert d["topology"]["name"] == "v5p-64"
    assert d["ici_bytes_per_step"] > 0
    assert d["peak_hbm_bytes"] > 0
    assert d["fits"] is True
    # the un-overlapped ZeRO scan legitimately draws RLT305 advisories
    # (exposed per-trip weight gathers — the overlap knob's pointer);
    # anything else is a regression
    assert all(f["rule"] == "RLT305" for f in d["findings"]), d["findings"]
    # ...but only for PER-TRIP gathers: the lm_head gather is
    # loop-invariant in the CE chunk scan and hoisted — the knob could
    # not hide it, so flagging it would be a false advisory
    assert not any("lm_head" in (f.get("symbol") or "")
                   for f in d["findings"]), d["findings"]


def test_trace_cli_unknown_target_exits_2():
    out = subprocess.run(
        [sys.executable, "-m", "ray_lightning_tpu", "trace",
         "no_such_example.py", "--json"],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
        env=_CLI_ENV,
    )
    assert out.returncode == 2
    assert "error" in json.loads(out.stdout.strip().splitlines()[-1])
