"""HF interop tests: random-initialized `transformers` models (built
offline from configs — no downloads) converted to framework params must
reproduce the HF forward pass numerically."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from ray_lightning_tpu.models.bert import (  # noqa: E402
    BertConfig,
    BertEncoder,
    BertForSequenceClassification,
)
from ray_lightning_tpu.models.hf_interop import (  # noqa: E402
    bert_classifier_params_from_hf,
    bert_params_from_hf,
    llama_params_from_hf,
)
from ray_lightning_tpu.models.llama import Llama, LlamaConfig  # noqa: E402


def _hf_bert(cfg: BertConfig):
    hf_cfg = transformers.BertConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.dim,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        intermediate_size=cfg.hidden_dim,
        max_position_embeddings=cfg.max_seq_len,
        type_vocab_size=cfg.type_vocab_size, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=cfg.norm_eps,
    )
    torch.manual_seed(0)
    model = transformers.BertModel(hf_cfg)
    model.eval()
    return model


@pytest.mark.slow  # ~18s compile; HF-bert parity stays in tier-1 via
#                    the classifier/pooler test (encoder + head on top)
def test_bert_encoder_matches_hf():
    cfg = BertConfig.tiny(dtype=jnp.float32, dropout=0.0, use_flash=False)
    hf = _hf_bert(cfg)
    ids = np.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)),
        dtype=np.int32,
    )
    mask = np.ones_like(ids)
    mask[1, 10:] = 0

    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long))
    params = bert_params_from_hf(hf.state_dict(), cfg)
    ours = BertEncoder(cfg).apply({"params": params}, ids, mask,
                                  deterministic=True)
    # only compare unmasked positions (HF leaves masked rows defined but
    # downstream-irrelevant; our mask keeps them from attending at all)
    ref_np = ref.last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(ours)[0], ref_np[0],
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(ours)[1, :10], ref_np[1, :10],
                               atol=2e-4, rtol=2e-4)


def test_bert_classifier_pooler_matches_hf():
    cfg = BertConfig.tiny(dtype=jnp.float32, dropout=0.0, use_flash=False)
    hf = _hf_bert(cfg)
    ids = np.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 12)),
        dtype=np.int32,
    )
    with torch.no_grad():
        ref_pooled = hf(torch.tensor(ids, dtype=torch.long)).pooler_output
    params = bert_classifier_params_from_hf(hf.state_dict(), cfg,
                                            num_classes=2)
    logits = BertForSequenceClassification(cfg, 2).apply(
        {"params": params}, ids, deterministic=True)
    assert logits.shape == (2, 2)
    # check the converted pooler directly: tanh(W @ h_cls + b) must match
    # HF's pooler_output
    enc = BertEncoder(cfg).apply({"params": params["encoder"]}, ids,
                                 deterministic=True)
    pooled_ours = np.tanh(
        np.asarray(enc[:, 0]) @ np.asarray(params["pooler"]["kernel"])
        + np.asarray(params["pooler"]["bias"])
    )
    np.testing.assert_allclose(pooled_ours, ref_pooled.numpy(),
                               atol=1e-3, rtol=1e-3)


def test_llama_matches_hf():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, use_flash=False)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.dim,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        intermediate_size=cfg.hidden_dim,
        max_position_embeddings=cfg.max_seq_len,
        rope_theta=cfg.rope_theta, rms_norm_eps=cfg.norm_eps,
        attention_bias=False, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    ids = np.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 16)),
        dtype=np.int32,
    )
    with torch.no_grad():
        ref = hf(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    params = llama_params_from_hf(hf.state_dict(), cfg)
    ours = np.asarray(Llama(cfg).apply({"params": params}, ids))
    np.testing.assert_allclose(ours, ref, atol=5e-4, rtol=5e-4)


def test_missing_key_raises_helpfully():
    cfg = BertConfig.tiny()
    with pytest.raises(KeyError, match="missing"):
        bert_params_from_hf({"bogus": np.zeros(3)}, cfg)
