"""GPipe pipeline parallelism (ops/pipeline.py): numerical equivalence
with sequential layer application (fwd + grads), composition with data
parallelism, and end-to-end training through the Trainer on a
data×pipe mesh. Beyond-parity capability (SURVEY §2.3 lists PP as absent
from the reference)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer
from ray_lightning_tpu.models.pipelined import PipelinedMLPModule
from ray_lightning_tpu.ops import gpipe_apply
from ray_lightning_tpu.parallel.mesh import make_mesh


def _stage_fn(lp, h):
    return jnp.tanh(h @ lp["w"] + lp["b"])


def _stacked_params(L=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.standard_normal((L, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((L, d)) * 0.1, jnp.float32),
    }


def _sequential(params, x):
    def body(h, lp):
        return _stage_fn(lp, h), None

    return jax.lax.scan(body, x, params)[0]


@pytest.mark.parametrize("microbatches", [2, 4])
def test_gpipe_matches_sequential(devices8, microbatches):
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    params = _stacked_params(L=4)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)),
                    jnp.float32)
    ref = _sequential(params, x)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh,
                          microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_gpipe_multiple_layers_per_stage(devices8):
    # L=8 over pipe=2: each stage owns a 4-layer block
    mesh = make_mesh(data=2, pipe=2, tensor=2, devices=devices8)
    params = _stacked_params(L=8)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 16)),
                    jnp.float32)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=2e-5, atol=1e-6)


def test_gpipe_pipe1_degrades_to_scan(devices8):
    mesh = make_mesh(data=8, devices=devices8)
    params = _stacked_params(L=3)
    x = jnp.ones((8, 16), jnp.float32)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


@pytest.mark.parametrize("remat", [False, True])
def test_gpipe_grads_match_sequential(devices8, remat):
    """Backward through the pipeline (AD of scan+ppermute) must equal the
    sequential gradients — GPipe is a schedule, not a different model."""
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    params = _stacked_params(L=4)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 16)),
                    jnp.float32)

    def loss_seq(p, x):
        return (_sequential(p, x) ** 2).mean()

    def loss_pipe(p, x):
        return (gpipe_apply(_stage_fn, p, x, mesh, microbatches=4,
                            remat=remat) ** 2).mean()

    g_seq = jax.grad(loss_seq, argnums=(0, 1))(params, x)
    with mesh:
        g_pipe = jax.grad(loss_pipe, argnums=(0, 1))(params, x)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=1e-6)


def test_gpipe_validates_divisibility(devices8):
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    with pytest.raises(ValueError, match="not divisible by pipe"):
        with mesh:
            gpipe_apply(_stage_fn, _stacked_params(L=3),
                        jnp.ones((8, 16), jnp.float32), mesh,
                        microbatches=2)


# ---------------------------------------------------- Trainer integration


def test_pipeline_trains_through_trainer(devices8, tmp_path):
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=128)
    x = (centers[y] + rng.normal(size=(128, 8)) * 0.1).astype(np.float32)

    module = PipelinedMLPModule(d=16, n_layers=4, microbatches=2)
    strategy = ShardedMesh(data=2, pipe=4, devices=devices8,
                           min_shard_size=1)
    trainer = Trainer(strategy=strategy, max_epochs=6,
                      default_root_dir=str(tmp_path),
                      enable_checkpointing=False, enable_progress_bar=False,
                      seed=0)
    trainer.fit(module, DataLoader({"x": x, "y": y}, batch_size=32,
                                   shuffle=True),
                DataLoader({"x": x, "y": y}, batch_size=32))
    assert dict(trainer.strategy.mesh.shape)["pipe"] == 4
    # stacked layer weights are stage-sharded on the pipe axis
    spec = trainer.state.params["layers"]["w"].sharding.spec
    assert "pipe" in str(spec)
    assert float(trainer.callback_metrics["val_acc"]) > 0.9
