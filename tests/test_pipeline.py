"""GPipe pipeline parallelism (ops/pipeline.py): numerical equivalence
with sequential layer application (fwd + grads), composition with data
parallelism, and end-to-end training through the Trainer on a
data×pipe mesh. Beyond-parity capability (SURVEY §2.3 lists PP as absent
from the reference)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer
from ray_lightning_tpu.models.pipelined import PipelinedMLPModule
from ray_lightning_tpu.ops import gpipe_apply
from ray_lightning_tpu.parallel.mesh import make_mesh


def _stage_fn(lp, h):
    return jnp.tanh(h @ lp["w"] + lp["b"])


def _stacked_params(L=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.standard_normal((L, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((L, d)) * 0.1, jnp.float32),
    }


def _sequential(params, x):
    def body(h, lp):
        return _stage_fn(lp, h), None

    return jax.lax.scan(body, x, params)[0]


@pytest.mark.parametrize("microbatches", [2, 4])
def test_gpipe_matches_sequential(devices8, microbatches):
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    params = _stacked_params(L=4)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)),
                    jnp.float32)
    ref = _sequential(params, x)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh,
                          microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_gpipe_multiple_layers_per_stage(devices8):
    # L=8 over pipe=2: each stage owns a 4-layer block
    mesh = make_mesh(data=2, pipe=2, tensor=2, devices=devices8)
    params = _stacked_params(L=8)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 16)),
                    jnp.float32)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=2e-5, atol=1e-6)


def test_gpipe_pipe1_degrades_to_scan(devices8):
    mesh = make_mesh(data=8, devices=devices8)
    params = _stacked_params(L=3)
    x = jnp.ones((8, 16), jnp.float32)
    with mesh:
        out = gpipe_apply(_stage_fn, params, x, mesh, microbatches=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


@pytest.mark.parametrize("remat", [False, True])
def test_gpipe_grads_match_sequential(devices8, remat):
    """Backward through the pipeline (AD of scan+ppermute) must equal the
    sequential gradients — GPipe is a schedule, not a different model."""
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    params = _stacked_params(L=4)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 16)),
                    jnp.float32)

    def loss_seq(p, x):
        return (_sequential(p, x) ** 2).mean()

    def loss_pipe(p, x):
        return (gpipe_apply(_stage_fn, p, x, mesh, microbatches=4,
                            remat=remat) ** 2).mean()

    g_seq = jax.grad(loss_seq, argnums=(0, 1))(params, x)
    with mesh:
        g_pipe = jax.grad(loss_pipe, argnums=(0, 1))(params, x)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pipe)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=1e-6)


def test_gpipe_validates_divisibility(devices8):
    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    with pytest.raises(ValueError, match="not divisible by pipe"):
        with mesh:
            gpipe_apply(_stage_fn, _stacked_params(L=3),
                        jnp.ones((8, 16), jnp.float32), mesh,
                        microbatches=2)


# ------------------------------------------------- Llama pipeline path


def _llama_cfg(**kw):
    from ray_lightning_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=128, dim=32, n_layers=4, n_heads=2, n_kv_heads=1,
        hidden_dim=64, max_seq_len=64, use_flash=False, dtype=jnp.float32,
        remat=False, **kw)


@pytest.mark.slow  # ~50-100s compile each: double-compile (pipe + scan)
@pytest.mark.parametrize("fused", [False, True])
def test_llama_pipeline_matches_scan_path(devices8, fused):
    """The GPipe decoder path trains the SAME stacked params as the scan
    path: losses and grads must agree (pipeline is a schedule)."""
    from ray_lightning_tpu.models.llama import LlamaModule

    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    batch = {"tokens": (np.arange(8 * 17, dtype=np.int32)
                        .reshape(8, 17) % 128)}

    m_pipe = LlamaModule(_llama_cfg(pipeline_microbatches=2,
                                    fused_ce=fused, ce_chunk_tokens=16))
    m_pipe.mesh = mesh
    m_pipe.setup()
    params = m_pipe.init_params(jax.random.key(0), batch)
    i, t, msk = m_pipe._split(batch)

    m_scan = LlamaModule(_llama_cfg(fused_ce=fused, ce_chunk_tokens=16))
    m_scan.setup()

    with mesh:
        assert m_pipe._use_pipeline()
        loss_p, grads_p = jax.value_and_grad(
            lambda p: m_pipe._loss(p, i, t, msk))(params)
    loss_s, grads_s = jax.value_and_grad(
        lambda p: m_scan._loss(p, i, t, msk))(params)
    np.testing.assert_allclose(np.asarray(loss_p), np.asarray(loss_s),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(grads_p), jax.tree.leaves(grads_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-6)


def test_llama_pipeline_tied_bf16_matches_scan(devices8):
    """Tied embeddings at bf16: the pipeline head must use the same
    cfg.dtype matmul as flax's Embed.attend (an f32 head would silently
    diverge — and be slower)."""
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule

    mesh = make_mesh(data=2, pipe=4, devices=devices8)
    base = dict(vocab_size=128, dim=32, n_layers=4, n_heads=2, n_kv_heads=1,
                hidden_dim=64, max_seq_len=64, use_flash=False, remat=False,
                dtype=jnp.bfloat16, tie_embeddings=True, fused_ce=False)
    batch = {"tokens": (np.arange(8 * 17, dtype=np.int32)
                        .reshape(8, 17) % 128)}

    m_pipe = LlamaModule(LlamaConfig(**base, pipeline_microbatches=2))
    m_pipe.mesh = mesh
    m_pipe.setup()
    params = m_pipe.init_params(jax.random.key(0), batch)
    i, t, msk = m_pipe._split(batch)
    with mesh:
        loss_p = float(m_pipe._loss(params, i, t, msk))
    m_scan = LlamaModule(LlamaConfig(**base))
    m_scan.setup()
    loss_s = float(m_scan._loss(params, i, t, msk))
    np.testing.assert_allclose(loss_p, loss_s, rtol=2e-2)


def test_llama_pipeline_trains_through_trainer(devices8, tmp_path):
    from ray_lightning_tpu.models.llama import LlamaModule

    cfg = _llama_cfg(pipeline_microbatches=2)
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=4)
    rng = np.random.default_rng(0)
    data = {"tokens": rng.integers(0, cfg.vocab_size, (16, 33))
            .astype(np.int32)}
    strategy = ShardedMesh(data=2, pipe=4, devices=devices8,
                           min_shard_size=1)
    trainer = Trainer(strategy=strategy, max_epochs=1,
                      limit_train_batches=2,
                      default_root_dir=str(tmp_path),
                      enable_checkpointing=False,
                      enable_progress_bar=False, seed=0)
    trainer.fit(module, DataLoader(data, batch_size=8))
    assert trainer.global_step == 2
    # the scanned layer stack is stage-sharded over pipe
    spec = trainer.state.params["layers"]["wqkv"]["kernel"].sharding.spec
    assert "pipe" in str(spec)
    assert float(trainer.callback_metrics["loss"]) > 0


def test_llama_pipeline_config_validation():
    from ray_lightning_tpu.models.llama import LlamaConfig

    with pytest.raises(ValueError, match="scan_layers"):
        _llama_cfg(pipeline_microbatches=2, scan_layers=False)
    with pytest.raises(ValueError, match="mutually exclusive"):
        LlamaConfig.tiny(pipeline_microbatches=2, seq_parallel=True)


# ---------------------------------------------------- Trainer integration


def test_pipeline_trains_through_trainer(devices8, tmp_path):
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 8)) * 3
    y = rng.integers(0, 4, size=128)
    x = (centers[y] + rng.normal(size=(128, 8)) * 0.1).astype(np.float32)

    module = PipelinedMLPModule(d=16, n_layers=4, microbatches=2)
    strategy = ShardedMesh(data=2, pipe=4, devices=devices8,
                           min_shard_size=1)
    trainer = Trainer(strategy=strategy, max_epochs=6,
                      default_root_dir=str(tmp_path),
                      enable_checkpointing=False, enable_progress_bar=False,
                      seed=0)
    trainer.fit(module, DataLoader({"x": x, "y": y}, batch_size=32,
                                   shuffle=True),
                DataLoader({"x": x, "y": y}, batch_size=32))
    assert dict(trainer.strategy.mesh.shape)["pipe"] == 4
    # stacked layer weights are stage-sharded on the pipe axis
    spec = trainer.state.params["layers"]["w"].sharding.spec
    assert "pipe" in str(spec)
    assert float(trainer.callback_metrics["val_acc"]) > 0.9
