"""lockwatch (analysis/lockwatch.py) — the runtime half of threadcheck.

These tests drive ``_SanLock`` directly (armed-ness is decided at
``san_lock()`` call time from the env, so the wrapper class is the
deterministic unit) and snapshot/restore the process-global order graph
around each test: the suite-wide sanitizer verdict in conftest's
``pytest_sessionfinish`` must keep seeing the REAL package's
acquisitions, not the synthetic cycles built here.
"""
import threading
import time

import pytest

from ray_lightning_tpu.analysis import lockwatch
from ray_lightning_tpu.analysis.lockwatch import (
    _SanLock,
    assert_lockwatch_clean,
    lockwatch_armed,
    lockwatch_cycles,
    lockwatch_findings,
    san_lock,
)


@pytest.fixture
def fresh_watch():
    """Run against an empty order graph; restore the suite's real
    observations (and this thread's held-stack) afterwards."""
    with lockwatch._META:
        order = {k: dict(v) for k, v in lockwatch._ORDER.items()}
        findings = list(lockwatch._FINDINGS)
        cycles = list(lockwatch._CYCLES)
    lockwatch.reset_lockwatch()
    try:
        yield
    finally:
        with lockwatch._META:
            lockwatch._ORDER.clear()
            lockwatch._ORDER.update(order)
            lockwatch._FINDINGS[:] = findings
            lockwatch._CYCLES[:] = cycles
        if getattr(lockwatch._TLS, "stack", None):
            lockwatch._TLS.stack = []


def test_armed_factory_returns_wrapper(monkeypatch):
    monkeypatch.setenv("RLT_LOCKWATCH", "1")
    assert lockwatch_armed()
    assert isinstance(san_lock("lwt.factory"), _SanLock)


def test_disarmed_factory_returns_plain_lock(monkeypatch):
    monkeypatch.setenv("RLT_LOCKWATCH", "0")
    assert not lockwatch_armed()
    lk = san_lock("lwt.plain")
    assert not isinstance(lk, _SanLock)
    with lk:  # a real lock, zero wrapper
        pass
    rlk = san_lock("lwt.plain.r", reentrant=True)
    with rlk:
        with rlk:
            pass


def test_cycle_detected_from_one_execution_order(fresh_watch):
    """A->B then B->A in ONE thread: the opposite interleaving never
    runs, the cycle is still diagnosed."""
    a, b = _SanLock("LWT_A"), _SanLock("LWT_B")
    with a:
        with b:
            pass
    assert lockwatch_cycles() == []
    with b:
        with a:
            pass
    cycles = lockwatch_cycles()
    assert len(cycles) == 1
    assert set(cycles[0]) == {"LWT_A", "LWT_B"}
    f = [f for f in lockwatch_findings() if f.rule == "RLT702"]
    assert len(f) == 1 and "cycle" in f[0].message
    with pytest.raises(AssertionError, match="lock-order cycle"):
        assert_lockwatch_clean()


def test_consistent_order_stays_clean(fresh_watch):
    a, b, c = _SanLock("LWT_1"), _SanLock("LWT_2"), _SanLock("LWT_3")
    for _ in range(3):
        with a:
            with b:
                with c:
                    pass
    assert lockwatch_cycles() == []
    assert lockwatch_findings() == []
    assert_lockwatch_clean()


def test_identity_is_the_name_not_the_instance(fresh_watch):
    """Two different instances of the same name are one lockdep class:
    the cycle is caught even though no single PAIR of instances was
    ever taken in both orders."""
    with _SanLock("LWT_N1"):
        with _SanLock("LWT_N2"):
            pass
    with _SanLock("LWT_N2"):  # fresh instances, same names
        with _SanLock("LWT_N1"):
            pass
    assert len(lockwatch_cycles()) == 1


def test_self_deadlock_raises_instead_of_hanging(fresh_watch):
    a = _SanLock("LWT_SELF")
    a.acquire()
    with pytest.raises(RuntimeError, match="re-acquired non-reentrant"):
        a.acquire()
    a.release()
    f = [f for f in lockwatch_findings() if f.rule == "RLT702"]
    assert len(f) == 1 and "re-acquire" in f[0].message


def test_reentrant_nesting_is_legal(fresh_watch):
    r = _SanLock("LWT_R", reentrant=True)
    with r:
        with r:
            assert r._is_owned()
    assert not r._is_owned()
    assert lockwatch_findings() == []


def test_held_too_long_reports_rlt705(fresh_watch, monkeypatch):
    monkeypatch.setenv("RLT_LOCKWATCH_MAX_HOLD_S", "0.05")
    slow = _SanLock("LWT_SLOW")  # threshold read at construction
    with slow:
        time.sleep(0.08)
    f = [f for f in lockwatch_findings() if f.rule == "RLT705"]
    assert len(f) == 1
    assert "LWT_SLOW" in f[0].message and f[0].severity == "warning"
    # held-too-long is report-only: never a cycle, never a hard failure
    assert lockwatch_cycles() == []
    assert_lockwatch_clean()


def test_condition_protocol_over_san_lock(fresh_watch):
    """threading.Condition(san_lock(...)) — wait() fully releases the
    watched lock (another thread can notify) and restores it after."""
    lk = _SanLock("LWT_CV")
    cv = threading.Condition(lk)
    hit = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            hit.append(lk._is_owned())

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with cv:
            if cv._waiters:
                cv.notify()
                break
        time.sleep(0.01)
    t.join(5)
    assert hit == [True]
    assert not lk._is_owned()
    assert lockwatch_findings() == []
