"""Checkpoint round-trips (SURVEY §3.4): best_model_path, load, resume.

The reference's two paths were (1) rank-0 best_model_path + state_dict
round-trip (ray_ddp.py:186-193,280-291) and (2) Tune queue-shipped dicts
(tune.py:128-142). Here checkpoints are written sharded in place and only
paths travel; these tests cover path (1) plus full resume, which the
reference delegated to PTL.
"""
import os

import jax
import numpy as np
import pytest

from ray_lightning_tpu import (
    DataLoader,
    DataParallel,
    FSDP,
    ModelCheckpoint,
    SingleDevice,
    Trainer,
)
from ray_lightning_tpu.checkpoint import load_checkpoint, save_checkpoint
from tests.utils import BoringModel, get_trainer, random_dataset


def test_best_model_path_and_load(tmp_path):
    module = BoringModel()
    mc = ModelCheckpoint(monitor="val_loss", mode="min",
                         dirpath=str(tmp_path / "ckpts"))
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=2,
                          callbacks=[mc], checkpoint_callback=False)
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))
    assert mc.best_model_path and os.path.isdir(mc.best_model_path)
    assert mc.best_model_score is not None
    loaded = BoringModel.load_from_checkpoint(mc.best_model_path)
    assert loaded.hparams["lr"] == module.hparams["lr"]
    assert "on_load_checkpoint" in loaded.hook_calls


def test_save_top_k_prunes(tmp_path):
    module = BoringModel(lr=0.05)
    mc = ModelCheckpoint(monitor="val_loss", save_top_k=1,
                         dirpath=str(tmp_path / "ckpts"))
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=4,
                          callbacks=[mc], checkpoint_callback=False)
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))
    kept = os.listdir(tmp_path / "ckpts")
    assert len(kept) == 1, f"top-k pruning failed: {kept}"


def test_resume_from_checkpoint(tmp_path):
    data = random_dataset()

    module = BoringModel(lr=0.05)
    trainer = get_trainer(tmp_path / "a", SingleDevice(), max_epochs=2,
                          checkpoint_callback=False, seed=7)
    trainer.fit(module, DataLoader(data, batch_size=32, shuffle=True, seed=3))
    ckpt = trainer.save_checkpoint(str(tmp_path / "mid"))
    steps_a = trainer.global_step

    # resume: epoch counter continues, params identical at restore point
    module_b = BoringModel(lr=0.05)
    trainer_b = get_trainer(tmp_path / "b", SingleDevice(), max_epochs=4,
                            checkpoint_callback=False, seed=7)
    trainer_b.fit(module_b, DataLoader(data, batch_size=32, shuffle=True,
                                       seed=3), ckpt_path=ckpt)
    assert trainer_b.current_epoch >= 2
    assert trainer_b.global_step > steps_a
    assert "on_load_checkpoint" in module_b.hook_calls


def test_sharded_roundtrip_preserves_values(tmp_path):
    """FSDP-sharded state saves and restores identically."""
    module = BoringModel()
    trainer = get_trainer(tmp_path, DataParallel(num_workers=8), max_epochs=1,
                          checkpoint_callback=False)
    trainer.fit(module, DataLoader(random_dataset(), batch_size=32))
    path = trainer.save_checkpoint(str(tmp_path / "ck"))
    restored = load_checkpoint(path)
    for a, b in zip(jax.tree.leaves(jax.device_get(trainer.state.params)),
                    jax.tree.leaves(restored["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert restored["hparams"]["lr"] == module.hparams["lr"]
    assert int(restored["step"]) == trainer.global_step


def test_async_step_checkpointing(devices8, tmp_path):
    """Step-cadence async saves: non-blocking writes joined at fit end,
    all checkpoints restorable."""
    from ray_lightning_tpu import DataLoader, ModelCheckpoint, SingleDevice, Trainer
    from ray_lightning_tpu.checkpoint import load_checkpoint

    from tests.utils import BoringModel, random_dataset

    data = random_dataset(n=128)
    cb = ModelCheckpoint(dirpath=str(tmp_path / "ck"),
                         every_n_train_steps=2, async_save=True,
                         save_top_k=-1)
    module = BoringModel()
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=1,
        callbacks=[cb], default_root_dir=str(tmp_path),
        enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=32))  # 4 steps
    import os as _os

    step_ckpts = sorted(p for p in _os.listdir(tmp_path / "ck")
                        if p.startswith("step="))
    assert step_ckpts == ["step=2", "step=4"]
    for name in step_ckpts:
        restored = load_checkpoint(str(tmp_path / "ck" / name))
        assert "params" in restored and restored["global_step"] > 0


def test_step_cadence_pruned_and_exclusive(devices8, tmp_path):
    """Step-based saves respect save_top_k and suppress epoch saves —
    even for a monitored callback (step cadence ignores monitor)."""
    import os as _os

    from ray_lightning_tpu import DataLoader, ModelCheckpoint, SingleDevice, Trainer

    from tests.utils import BoringModel, random_dataset

    data = random_dataset(n=128)
    cb = ModelCheckpoint(dirpath=str(tmp_path / "ck"), monitor="val_loss",
                         every_n_train_steps=1, save_top_k=2)
    trainer = Trainer(strategy=SingleDevice(), max_epochs=1,
                      callbacks=[cb], default_root_dir=str(tmp_path),
                      enable_progress_bar=False)
    trainer.fit(BoringModel(), DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))  # 4 steps + val epoch
    names = sorted(_os.listdir(tmp_path / "ck"))
    assert names == ["step=3", "step=4"]  # pruned to 2, no epoch dirs
    assert cb.best_model_path.endswith("step=4")


def test_mid_epoch_resume_replays_rest_of_epoch(tmp_path):
    """A checkpoint saved mid-epoch (step cadence) resumes the SAME epoch
    at the saved batch offset — the remainder of the epoch is trained, not
    silently skipped."""
    data = random_dataset(n=128)  # 4 batches/epoch at bs=32

    module = BoringModel(lr=0.05)
    trainer = Trainer(strategy=SingleDevice(), max_epochs=1, max_steps=2,
                      enable_checkpointing=False, enable_progress_bar=False,
                      default_root_dir=str(tmp_path / "a"), seed=7)
    trainer.fit(module, DataLoader(data, batch_size=32, shuffle=True, seed=3))
    assert trainer.global_step == 2  # stopped mid-epoch (2 of 4 batches)
    ckpt = trainer.save_checkpoint(str(tmp_path / "mid"))

    module_b = BoringModel(lr=0.05)
    trainer_b = Trainer(strategy=SingleDevice(), max_epochs=2,
                        enable_checkpointing=False, enable_progress_bar=False,
                        default_root_dir=str(tmp_path / "b"), seed=7)
    trainer_b.fit(module_b,
                  DataLoader(data, batch_size=32, shuffle=True, seed=3),
                  ckpt_path=ckpt)
    # same-epoch resume: 2 remaining batches of epoch 0 + 4 of epoch 1
    assert trainer_b.current_epoch == 1
    assert trainer_b.global_step == 8


def test_epoch_boundary_resume_advances_epoch(tmp_path):
    """A checkpoint saved at an epoch boundary resumes at the NEXT epoch."""
    data = random_dataset(n=128)
    module = BoringModel(lr=0.05)
    trainer = Trainer(strategy=SingleDevice(), max_epochs=1,
                      enable_checkpointing=False, enable_progress_bar=False,
                      default_root_dir=str(tmp_path / "a"), seed=7)
    trainer.fit(module, DataLoader(data, batch_size=32))
    ckpt = trainer.save_checkpoint(str(tmp_path / "end"))

    module_b = BoringModel(lr=0.05)
    trainer_b = Trainer(strategy=SingleDevice(), max_epochs=2,
                        enable_checkpointing=False, enable_progress_bar=False,
                        default_root_dir=str(tmp_path / "b"), seed=7)
    trainer_b.fit(module_b, DataLoader(data, batch_size=32), ckpt_path=ckpt)
    assert trainer_b.global_step == 8  # exactly one more epoch


def test_async_meta_deferred_until_finalized(tmp_path):
    """block=False must not write meta.json (the completeness marker)
    over a still-streaming state dir. The background finalizer publishes
    it EAGERLY once the state write commits (a crash between cadences
    must not cost a finished checkpoint its marker), so the invariant is
    ordering, not absence: meta present ⇒ state finalized + verifiable;
    wait_for_checkpoints() guarantees it afterwards."""
    import jax.numpy as jnp

    from ray_lightning_tpu.checkpoint import (
        verify_checkpoint,
        wait_for_checkpoints,
    )
    from ray_lightning_tpu.checkpoint.io import read_meta

    path = str(tmp_path / "ck")
    save_checkpoint(path, {"w": jnp.ones((4,))}, {"epoch": 3}, block=False)
    if os.path.exists(os.path.join(path, "meta.json")):
        # the eager finalizer won the race — then the state MUST already
        # be complete and the digest must check out
        ok, reason = verify_checkpoint(path)
        assert ok, reason
    wait_for_checkpoints()
    assert os.path.exists(os.path.join(path, "meta.json"))
    assert read_meta(path)["epoch"] == 3
    ok, reason = verify_checkpoint(path)
    assert ok, reason


def test_async_save_with_top_k_prune(tmp_path):
    """Async step-cadence saves with save_top_k pruning: pruned dirs stay
    deleted (no resurrection by background finalize), kept dirs have
    meta.json, and fit succeeds."""
    data = random_dataset(n=256)  # 8 steps at bs=32
    cb = ModelCheckpoint(dirpath=str(tmp_path / "ck"),
                         every_n_train_steps=1, async_save=True,
                         save_top_k=1)
    trainer = Trainer(strategy=SingleDevice(), max_epochs=1,
                      callbacks=[cb], default_root_dir=str(tmp_path),
                      enable_progress_bar=False)
    trainer.fit(BoringModel(), DataLoader(data, batch_size=32))
    kept = sorted(os.listdir(tmp_path / "ck"))
    assert kept == ["step=8"], kept
    assert os.path.exists(tmp_path / "ck" / "step=8" / "meta.json")


def test_monitored_absent_keeps_tracking(tmp_path):
    """A validation epoch without the monitored metric must not drop the
    existing checkpoint's save_top_k tracking entry."""
    cb = ModelCheckpoint(dirpath=str(tmp_path / "ck"), monitor="val_loss",
                         save_top_k=1, filename="best")
    cb.best_model_path = str(tmp_path / "ck" / "best")
    cb.best_model_score = 1.0
    cb._saved = [(1.0, cb.best_model_path)]

    class _T:
        current_epoch = 1
        every_n_epochs = 1
        global_step = 4
        val_check_interval = None
        default_root_dir = str(tmp_path)

    cb._maybe_save(_T(), None, {"other": 2.0})  # metric absent: no save
    assert cb._saved == [(1.0, cb.best_model_path)]
