"""Forced data sharding in distributed jobs.

The reference *forces* a DistributedSampler(num_replicas=num_workers,
rank=global_rank) onto every loader so users cannot accidentally train on
duplicated data (reference ray_ddp.py:293-303, asserted per-stage at
reference tests/test_ddp.py:44-76). Here the same guarantee is
`ensure_sharded` (core/data.py), injected by `_job_remote`
(runtime/fit.py) for train/val and the eval family alike: forgetting
shard arguments is impossible — the launcher injects them, and
unshardable inputs are a hard error, never silently-duplicated per-host
batches.
"""
import numpy as np
import pytest

from ray_lightning_tpu.core.data import (
    DataLoader,
    DataModule,
    ensure_sharded,
)

from tests.utils import IdSumModel


def _ids_loader(n=64, batch_size=8, **kw):
    x = np.arange(n, dtype=np.float32)[:, None] * np.ones(
        (1, 4), np.float32)
    y = (np.arange(n) % 2).astype(np.int32)
    return DataLoader({"x": x, "y": y}, batch_size=batch_size, **kw)


# ------------------------------------------------------- unit: the forcing


def test_injects_into_unsharded_loader():
    loader = _ids_loader()
    assert loader.num_shards == 1
    out = ensure_sharded(loader, 4, 2)
    assert out is loader
    assert (out.num_shards, out.shard_index) == (4, 2)
    assert len(out) == 2  # 64 rows / 4 shards / batch 8


def test_matching_manual_shards_are_idempotent():
    loader = _ids_loader(num_shards=4, shard_index=2)
    out = ensure_sharded(loader, 4, 2)
    assert (out.num_shards, out.shard_index) == (4, 2)


def test_mismatched_manual_shards_raise():
    loader = _ids_loader(num_shards=2, shard_index=0)
    with pytest.raises(ValueError, match="sharded 0/2"):
        ensure_sharded(loader, 4, 1)


def test_plain_iterable_raises():
    batches = [{"x": np.zeros((8, 4), np.float32)}]
    with pytest.raises(TypeError, match="plain iterable"):
        ensure_sharded(batches, 2, 0)


def test_sharded_externally_honored_for_array_loaders():
    """A loader declared externally sharded (each host loaded ITS OWN
    rows already) is left alone — injecting num_shards on top would
    silently train on a 1/world slice of each host's local data."""
    loader = _ids_loader(sharded_externally=True)
    out = ensure_sharded(loader, 4, 2)
    assert out is loader
    assert (out.num_shards, out.shard_index) == (1, 0)


def test_streaming_requires_external_sharding():
    stream = DataLoader(lambda epoch: iter([]), batch_size=8)
    with pytest.raises(ValueError, match="sharded_externally"):
        ensure_sharded(stream, 2, 0)
    marked = DataLoader(lambda epoch: iter([]), batch_size=8,
                        sharded_externally=True)
    assert ensure_sharded(marked, 2, 0) is marked


def test_single_process_and_none_untouched():
    loader = _ids_loader()
    assert ensure_sharded(loader, 1, 0) is loader
    assert loader.num_shards == 1
    assert ensure_sharded(None, 4, 0) is None


def test_shards_are_disjoint_and_cover_everything():
    """The loader-level guarantee the forcing relies on: the per-rank
    shards partition the dataset (pairwise disjoint, union == all rows
    modulo drop_last equal-size truncation)."""
    world, seen = 4, []
    for rank in range(world):
        loader = ensure_sharded(_ids_loader(shuffle=True, seed=7),
                                world, rank)
        ids = np.concatenate(
            [b["x"][:, 0].astype(np.int64) for b in loader])
        seen.append(set(ids.tolist()))
        assert len(ids) == len(set(ids.tolist()))
    union = set().union(*seen)
    assert len(union) == sum(len(s) for s in seen)  # pairwise disjoint
    assert len(union) == 64  # full coverage (64 divides evenly)


# ------------------------------------- end-to-end: duplicated rows CANNOT
# happen through the distributed round-trip (the regression VERDICT r3 #2)


def _make_module():
    return IdSumModel()


def _make_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=1,
        enable_progress_bar=False,
        enable_checkpointing=False,
        seed=0,
    )


def _make_unsharded_data():
    """Deliberately NO num_shards/shard_index — the launcher must inject
    them (the reference's forcing, ray_ddp.py:293-303)."""
    n = 256
    x = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 4), np.float32)
    y = (np.arange(n) % 2).astype(np.int32)
    train = DataLoader({"x": x, "y": y}, batch_size=16)
    val = DataLoader({"x": x, "y": y}, batch_size=16)
    return train, val


@pytest.mark.slow
def test_distributed_fit_auto_shards_unsharded_loaders(tmp_path):
    from ray_lightning_tpu.runtime import fit_distributed

    result = fit_distributed(
        _make_module,
        _make_trainer,
        _make_unsharded_data,
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path),
        timeout=420,
    )
    # train leg: every global batch held distinct rows...
    assert result.metrics["dup_rows"] == 0.0
    # ...and the LAST global batch was {112..127} ∪ {240..255} — exactly
    # the contiguous-shard split (8 steps/epoch, not the 16 duplicated
    # ones an unsharded loader would produce):
    assert result.metrics["id_sum"] == float(
        sum(range(112, 128)) + sum(range(240, 256)))
    # val leg (forced per-stage, like the reference's val sampler):
    assert result.metrics["val_dup_rows"] == 0.0


class _IdsDataModule(DataModule):
    """DataModule with deliberately UNSHARDED loaders — the launcher must
    resolve per-stage loaders and inject shard semantics into each."""

    def setup(self):
        n = 256
        self._x = np.arange(n, dtype=np.float32)[:, None] * np.ones(
            (1, 4), np.float32)
        self._y = (np.arange(n) % 2).astype(np.int32)

    def train_dataloader(self):
        return DataLoader({"x": self._x, "y": self._y}, batch_size=16)

    def val_dataloader(self):
        return DataLoader({"x": self._x, "y": self._y}, batch_size=16)


@pytest.mark.slow
def test_distributed_fit_auto_shards_datamodule(tmp_path):
    """The DataModule path through _job_remote: per-stage loaders are
    resolved worker-side and each gets forced shard semantics."""
    from ray_lightning_tpu.runtime import fit_distributed

    result = fit_distributed(
        _make_module,
        _make_trainer,
        _IdsDataModule,
        num_processes=2,
        platform="cpu",
        num_cpu_devices_per_process=2,
        log_dir=str(tmp_path),
        timeout=420,
    )
    assert result.metrics["dup_rows"] == 0.0
    assert result.metrics["val_dup_rows"] == 0.0
    assert result.metrics["id_sum"] == float(
        sum(range(112, 128)) + sum(range(240, 256)))


def _make_plain_iterable_data():
    return [{"x": np.zeros((8, 4), np.float32),
             "y": np.zeros((8,), np.int32)}]


@pytest.mark.slow
def test_distributed_fit_rejects_plain_iterables(tmp_path):
    """An unshardable input is a hard error naming the fix — not silent
    duplicated training."""
    from ray_lightning_tpu.runtime import fit_distributed
    from ray_lightning_tpu.runtime.group import WorkerError

    with pytest.raises(WorkerError, match="no shard handle"):
        fit_distributed(
            _make_module,
            _make_trainer,
            _make_plain_iterable_data,
            num_processes=2,
            platform="cpu",
            num_cpu_devices_per_process=2,
            log_dir=str(tmp_path),
            timeout=420,
        )
