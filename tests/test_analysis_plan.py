"""shardcheck plan-checker unit tests: each rule fires on a minimal bad
plan and stays quiet on a good one — all over AbstractMesh + eval_shape,
zero devices (the checker must run on a box with NO accelerator, like
the memory planner it complements)."""
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.analysis import (
    check_donation,
    check_opt_state_dtypes,
    check_param_specs,
    check_plan,
    spec_findings,
)
from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
from ray_lightning_tpu.parallel.strategy import FSDP, ShardedMesh

MESH = {"data": 1, "pipe": 1, "fsdp": 8, "expert": 1, "seq": 1,
        "tensor": 1}


class _Leaf:
    def __init__(self, shape, dtype=np.float32):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)


def rules_of(findings):
    return sorted(f.rule for f in findings)


# ---- spec_findings: the structural core ----------------------------------


def test_unknown_axis_rlt101():
    fs = spec_findings(P("fdsp", None), (64, 64), MESH,  # rlt: disable=RLT101
                       path="w")
    assert rules_of(fs) == ["RLT101"]
    assert "fdsp" in fs[0].message and fs[0].symbol == "w"


def test_uneven_shard_rlt102():
    fs = spec_findings(P("fsdp", None), (63, 64), MESH, path="w")
    assert rules_of(fs) == ["RLT102"]
    assert "partitioned" in fs[0].message


def test_duplicate_axis_rlt103():
    # across two dims
    fs = spec_findings(P("fsdp", "fsdp"), (64, 64), MESH)  # rlt: disable=RLT103
    assert "RLT103" in rules_of(fs)
    # within one dim's tuple entry
    fs = spec_findings(P(("fsdp", "fsdp"), None), (64, 64),  # rlt: disable=RLT103
                       MESH)
    assert "RLT103" in rules_of(fs)


def test_rank_mismatch_rlt104():
    fs = spec_findings(P(None, None, "fsdp"), (64, 64), MESH, path="w")
    assert rules_of(fs) == ["RLT104"]


def test_good_specs_quiet():
    assert spec_findings(P("fsdp", None), (64, 64), MESH) == []
    assert spec_findings(P(("data", "fsdp"), "tensor"), (64, 64),
                         MESH) == []
    assert spec_findings(P(), (64, 64), MESH) == []
    # size-1 axis on an indivisible dim is fine (divisor 1)
    assert spec_findings(P("tensor", None), (63, 64), MESH) == []


# ---- check_param_specs: the overlay audit --------------------------------


def test_stale_spec_path_rlt107():
    params = {"layers/wqkv/kernel": _Leaf((2, 64, 128))}
    fs = check_param_specs(
        {"layers/renamed/kernel": P()}, params, MESH)
    assert rules_of(fs) == ["RLT107"]


def test_overlay_good_and_none_quiet():
    params = {"w": _Leaf((64, 64))}
    assert check_param_specs({"w": P("fsdp", None)}, params, MESH) == []
    assert check_param_specs(None, params, MESH) == []


# ---- RLT105 dtype widening -----------------------------------------------


def test_opt_dtype_widening_rlt105():
    params = {"w": _Leaf((64, 64), np.dtype(jnp.bfloat16))}
    opt = {"0/mu/w": _Leaf((64, 64), np.float32),
           "0/nu/w": _Leaf((64, 64), np.dtype(jnp.bfloat16)),
           "1/count": _Leaf((), np.int32)}
    fs = check_opt_state_dtypes(params, opt)
    assert rules_of(fs) == ["RLT105"]
    assert fs[0].symbol == "0/mu/w"


def test_opt_dtype_same_or_narrower_quiet():
    params = {"w": _Leaf((64, 64), np.float32)}
    opt = {"0/mu/w": _Leaf((64, 64), np.float32),
           "0/nu/w": _Leaf((64, 64), np.dtype(jnp.bfloat16))}
    assert check_opt_state_dtypes(params, opt) == []


# ---- RLT106 donation -----------------------------------------------------


def test_donation_mismatch_rlt106():
    donated = {"params/w": (_Leaf((8, 8)), P("fsdp", None))}
    # output exists but at a different sharding: nothing to alias
    outputs = {"params/w": (_Leaf((8, 8)), P(None, "fsdp"))}
    fs = check_donation(donated, outputs)
    assert rules_of(fs) == ["RLT106"]

    # dtype change breaks aliasing too
    fs = check_donation(
        {"p/w": (_Leaf((8, 8), np.float32), P())},
        {"p/w": (_Leaf((8, 8), np.dtype(jnp.bfloat16)), P())})
    assert rules_of(fs) == ["RLT106"]


def test_donation_match_quiet_and_consumed_once():
    leaf, spec = _Leaf((8, 8)), P("fsdp", None)
    assert check_donation({"a": (leaf, spec)}, {"a": (leaf, spec)}) == []
    # two donated buffers, one matching output: exactly one finding
    fs = check_donation({"a": (leaf, spec), "b": (leaf, spec)},
                        {"a": (leaf, spec)})
    assert rules_of(fs) == ["RLT106"]


# ---- check_plan: the full engine, no devices -----------------------------


def _batch():
    return {"tokens": np.zeros((8, 129), np.int32)}


def test_check_plan_clean_on_bundled_llama():
    fs = check_plan(LlamaModule(LlamaConfig.tiny()), ShardedMesh(fsdp=8),
                    8, _batch())
    assert fs == [], "\n".join(f.format() for f in fs)


def test_check_plan_clean_on_bundled_moe():
    """The expert-parallel bundled model audits clean too — the
    self-check covers more than the flagship."""
    from ray_lightning_tpu.models.moe import MoEClassifierModule

    fs = check_plan(
        MoEClassifierModule(), ShardedMesh(data=2, expert=4), 8,
        {"x": np.zeros((8, 16), np.float32),
         "y": np.zeros((8,), np.int32)})
    assert fs == [], "\n".join(f.format() for f in fs)


def test_check_plan_reports_typo_and_stale_path():
    class Bad(LlamaModule):
        def param_specs(self, params):
            sp = dict(super().param_specs(params))
            sp["final_norm"] = P("fdsp")  # rlt: disable=RLT101
            sp["layers/renamed/kernel"] = P()
            return sp

    fs = check_plan(Bad(LlamaConfig.tiny()), ShardedMesh(fsdp=8), 8,
                    _batch())
    assert "RLT101" in rules_of(fs) and "RLT107" in rules_of(fs)


def test_check_plan_reports_uneven_tensor_split():
    # tiny cfg: dim=64, qkv out dim 128; tensor=5 divides neither
    fs = check_plan(LlamaModule(LlamaConfig.tiny()),
                    ShardedMesh(data=1, fsdp=1, tensor=5), 5, _batch())
    assert "RLT102" in rules_of(fs)


def test_check_plan_flags_widened_opt_state():
    """bf16 params with f32 Adam moments: each moment buffer is 2x the
    weights it tracks — exactly the silent-optimizer-HBM hazard RLT105
    names (the planner charges it correctly; the checker makes it
    visible)."""
    import jax
    import optax

    class Bf16Params(LlamaModule):
        def configure_optimizers(self):
            return optax.adam(1e-3, mu_dtype=jnp.float32)

        def init_params(self, rng, batch):
            params = super().init_params(rng, batch)
            return jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == np.dtype(np.float32) else x, params)

    fs = check_plan(Bf16Params(LlamaConfig.tiny()), ShardedMesh(fsdp=8),
                    8, _batch())
    assert "RLT105" in rules_of(fs)


def test_check_plan_flags_dtype_drifting_optimizer_donation():
    """check_plan's donation audit eval_shapes the REAL optimizer update
    tail: an optimizer whose update returns state at a different dtype
    than init breaks in/out buffer aliasing — the donated opt-state
    memory cannot be reused and peak exceeds the plan (RLT106)."""
    import jax
    import optax

    class DriftingOpt(LlamaModule):
        def configure_optimizers(self):
            def init(params):
                return jax.tree.map(jnp.zeros_like, params)  # f32

            def update(grads, state, params=None):
                # dtype drift: the returned state no longer matches the
                # donated input buffers
                new_state = jax.tree.map(
                    lambda s: s.astype(jnp.bfloat16), state)
                return jax.tree.map(jnp.zeros_like, grads), new_state

            return optax.GradientTransformation(init, update)

    fs = check_plan(DriftingOpt(LlamaConfig.tiny()), ShardedMesh(fsdp=8),
                    8, _batch())
    assert "RLT106" in rules_of(fs)
    assert any("opt_state/" in (f.symbol or "") for f in fs
               if f.rule == "RLT106")


# ---- strategy-level eager guard (the live Trainer path) ------------------


class _RawModule:
    """Minimal param_specs carrier for the strategy-level guard tests
    (the strategy only reads .param_specs and assignment of .mesh)."""

    def __init__(self, specs, shapes):
        self._specs = specs
        self._shapes = shapes
        self.mesh = None

    def param_specs(self, params):
        return self._specs

    def params(self):
        return {k: np.zeros(s, np.float32)
                for k, s in self._shapes.items()}


def test_strategy_raises_on_unknown_axis_eagerly(devices8):
    """A typo'd axis used to be SILENTLY DROPPED by _adapt_spec (the
    leaf replicated — the motivating OOM-at-scale); now it raises at
    setup, by name, citing the shardcheck rule."""
    module = _RawModule({"w": P("fdsp", None)},  # rlt: disable=RLT101
                        {"w": (64, 64)})
    strategy = ShardedMesh(fsdp=8)
    strategy.setup(module)
    with pytest.raises(ValueError, match="RLT101"):
        strategy.param_shardings(module.params())


def test_strategy_raises_on_uneven_composed_spec(devices8):
    """An overlay forcing an indivisible split fails eagerly with the
    parameter's name, not deep inside an XLA compile."""
    module = _RawModule({"w": P("fsdp", None)}, {"w": (6, 4)})
    strategy = ShardedMesh(fsdp=8)
    strategy.setup(module)
    with pytest.raises(ValueError, match="partitioned"):
        strategy.param_shardings(module.params())


def test_strategy_quiet_on_wellformed_overlay(devices8):
    import jax

    module = _RawModule({"w": P("fsdp", None)}, {"w": (64, 64)})
    strategy = ShardedMesh(fsdp=8)
    strategy.setup(module)
    shardings = strategy.param_shardings(module.params())
    assert jax.tree.leaves(shardings)


class _NestedOptModule(TpuModule):
    """Custom optimizer stashing param-shaped slots inside nested
    dict/list containers — the donation audit must walk ALL of it and
    report full pytree paths, not top-level keys (ISSUE-2 satellite)."""

    def __init__(self, break_alias: bool = False):
        super().__init__()
        self.break_alias = break_alias

    def init_params(self, rng, batch):
        import jax.numpy as jnp

        return {"dense": {"kernel": jnp.zeros((1024, 64), jnp.bfloat16)}}

    def configure_model(self):
        return None

    def configure_optimizers(self):
        import jax
        import jax.numpy as jnp
        import optax

        break_alias = self.break_alias

        def init(params):
            return {"slots": [
                jax.tree.map(jnp.zeros_like, params),
                jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                             params),
            ], "count": jnp.zeros((), jnp.int32)}

        def update(grads, state, params=None):
            slot1 = state["slots"][1]
            if break_alias:
                # dtype drift on the NESTED leaf: its donated f32
                # buffer can no longer alias any output
                slot1 = jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16), slot1)
            return grads, {"slots": [state["slots"][0], slot1],
                           "count": state["count"] + 1}

        return optax.GradientTransformation(init, update)

    def training_step(self, params, batch, rng):
        import jax.numpy as jnp

        return jnp.float32(0)


def test_donation_audit_walks_nested_opt_state():
    """Clean nested state: the only finding is the deliberate f32-nu
    dtype widening (RLT105), reported with the FULL nested path."""
    findings = check_plan(
        _NestedOptModule(break_alias=False), FSDP(), 4,
        {"x": np.zeros((8, 1024), np.float32)})
    assert [f.rule for f in findings] == ["RLT105"]
    assert findings[0].symbol == "slots/1/dense/kernel"


def test_donation_mismatch_reports_full_nested_path():
    findings = check_plan(
        _NestedOptModule(break_alias=True), FSDP(), 4,
        {"x": np.zeros((8, 1024), np.float32)})
    rlt106 = [f for f in findings if f.rule == "RLT106"]
    assert len(rlt106) == 1
    f = rlt106[0]
    # full nested dict/list path, not a top-level key
    assert f.symbol == "opt_state/slots/1/dense/kernel"
    # and the near-miss diagnosis names the drifted output
    assert "Nearest same-shape output" in f.message
    assert "bfloat16" in f.message
