"""Core Trainer behavior on a single device (the loop the reference
delegated to PTL; coverage modeled on reference tests/test_ddp.py)."""
import jax
import numpy as np
import pytest

from ray_lightning_tpu import (
    DataLoader,
    EarlyStopping,
    SingleDevice,
    Trainer,
)
from tests.utils import BoringModel, get_trainer, random_dataset


def test_fit_changes_weights(tmp_path):
    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=2)
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32, shuffle=True),
                DataLoader(data, batch_size=32))
    assert module.params is not None
    assert trainer.global_step > 0
    assert "loss" in trainer.callback_metrics
    assert "train_loss" in trainer.callback_metrics  # self.log inside jit
    assert "val_loss" in trainer.callback_metrics


def test_hooks_fire_in_order(tmp_path):
    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=1)
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))
    calls = module.hook_calls
    assert calls[0] == "on_fit_start"
    assert calls[-1] == "on_fit_end"
    for h in ("on_train_epoch_start", "on_train_epoch_end",
              "on_validation_epoch_end", "on_save_checkpoint"):
        assert h in calls, f"{h} never fired"


def test_max_steps(tmp_path):
    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=10,
                          limit_train_batches=None, max_steps=7)
    trainer.fit(module, DataLoader(random_dataset(), batch_size=32))
    assert trainer.global_step == 7


def test_grad_accumulation_matches_big_batch(tmp_path):
    """accum=4 over micro-batches == one batch of 4x size (SGD linearity)."""
    data = random_dataset(n=128)

    def run(accum, bs):
        module = BoringModel(lr=0.1)
        trainer = get_trainer(
            tmp_path / f"a{accum}", SingleDevice(), max_epochs=1,
            limit_train_batches=2, accumulate_grad_batches=accum,
            checkpoint_callback=False, seed=0,
        )
        trainer.fit(module, DataLoader(data, batch_size=bs))
        return jax.device_get(module.params)

    p1 = run(1, 128)
    p4 = run(4, 128)
    flat1, flat4 = jax.tree.leaves(p1), jax.tree.leaves(p4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_early_stopping(tmp_path):
    """EarlyStopping halts the run (reference tests/test_ddp.py:116-132)."""
    module = BoringModel(lr=0.0)  # loss can never improve
    es = EarlyStopping(monitor="val_loss", patience=1, min_delta=1e9)
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=50,
                          callbacks=[es])
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32),
                DataLoader(data, batch_size=32))
    assert trainer.should_stop
    assert trainer.current_epoch < 49


def test_seed_determinism(tmp_path):
    def run():
        module = BoringModel()
        trainer = get_trainer(tmp_path / "d", SingleDevice(), max_epochs=1,
                              checkpoint_callback=False, seed=123)
        trainer.fit(module, DataLoader(random_dataset(), batch_size=32,
                                       shuffle=True, seed=1))
        return jax.device_get(module.params)

    a, b = run(), run()
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_predict_one_shot_iterator_keeps_first_batch(tmp_path):
    """Eval entrypoints on an UNFITTED module peek batch 0 to init params;
    with a one-shot iterator (generator) the peeked batch must still be
    predicted — the re-stitched loader from _ensure_state is the one
    iterated, not the half-consumed original."""
    from ray_lightning_tpu import SingleDevice, Trainer

    module = BoringModel()
    data = random_dataset(n=64)
    batches = list(DataLoader(data, batch_size=16))  # 4 batches
    trainer = Trainer(
        strategy=SingleDevice(), enable_progress_bar=False,
        enable_checkpointing=False, default_root_dir=str(tmp_path), seed=0,
    )
    preds = trainer.predict(module, (b for b in batches))
    assert len(preds) == 4  # batch 0 not swallowed by the init peek
    assert all(np.asarray(p).shape == (16,) for p in preds)


def test_validate_and_test_apis(tmp_path):
    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=1)
    data = random_dataset()
    trainer.fit(module, DataLoader(data, batch_size=32))
    metrics = trainer.validate(module, DataLoader(data, batch_size=32))
    assert "val_loss" in metrics and "val_acc" in metrics
    tmetrics = trainer.test(module, DataLoader(data, batch_size=32))
    assert "val_loss" in tmetrics  # test_step defaults to validation_step


def test_limit_test_batches_is_independent(tmp_path):
    """PTL parity: test() has its own eval-limit knob — limit_val_batches
    must not silently cap the test epoch (VERDICT r3 weak #6). The metric
    is the mean row id over PROCESSED batches: with unshuffled batches of
    16 ids, stopping after k batches gives exactly 8k - 0.5."""
    import jax.numpy as jnp

    from ray_lightning_tpu import SingleDevice, Trainer

    class IdMeanModel(BoringModel):
        def validation_step(self, params, batch):
            return {"id_mean": jnp.mean(batch["x"][:, 0])}

    n = 128
    data = {
        "x": np.arange(n, dtype=np.float32)[:, None] * np.ones(
            (1, 32), np.float32),
        "y": (np.arange(n) % 2).astype(np.int32),
    }
    module = IdMeanModel()
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=1,
        limit_val_batches=2, limit_test_batches=5,
        enable_progress_bar=False, enable_checkpointing=False,
        default_root_dir=str(tmp_path), seed=0,
    )
    trainer.fit(module, DataLoader(data, batch_size=16))

    def id_mean_after(k):  # mean of ids 0..16k-1
        return 8.0 * k - 0.5

    loader = DataLoader(data, batch_size=16)  # 8 batches, unshuffled
    assert trainer.validate(module, loader)["id_mean"] == id_mean_after(2)
    assert trainer.test(module, loader)["id_mean"] == id_mean_after(5)
    trainer.limit_test_batches = None  # unset -> the whole loader
    assert trainer.test(module, loader)["id_mean"] == id_mean_after(8)


def test_memory_monitor(tmp_path):
    """MemoryMonitor reports HBM stats when the backend exposes them and is
    silently inert otherwise (CPU may or may not implement memory_stats)."""
    from ray_lightning_tpu import MemoryMonitor

    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=1,
                          callbacks=[MemoryMonitor(log_stats=False)])
    trainer.fit(module, DataLoader(random_dataset(), batch_size=32))
    stats = MemoryMonitor._stats()
    if stats and "bytes_in_use" in stats:
        assert trainer.callback_metrics["hbm_bytes_in_use"] > 0
    else:
        assert "hbm_bytes_in_use" not in trainer.callback_metrics


def test_eval_epoch_single_host_sync(tmp_path, monkeypatch):
    """Eval totals accumulate on device: exactly ONE host fetch per eval
    epoch regardless of batch count (VERDICT r2 weak #6 — a per-batch
    device_get is a stall machine at 8B scale)."""
    from ray_lightning_tpu.core import trainer as trainer_mod

    module = BoringModel()
    trainer = get_trainer(tmp_path, SingleDevice(), max_epochs=1)
    data = random_dataset(n=256)
    trainer.fit(module, DataLoader(data, batch_size=32))

    calls = []
    real = trainer_mod._to_host

    def counting(tree):
        calls.append(1)
        return real(tree)

    monkeypatch.setattr(trainer_mod, "_to_host", counting)
    metrics = trainer.validate(module, DataLoader(data, batch_size=32))
    assert "val_loss" in metrics
    assert len(calls) == 1, f"expected 1 host sync for 8 batches, got {len(calls)}"


def test_bad_batch_divisibility_raises(tmp_path):
    from ray_lightning_tpu import DataParallel

    module = BoringModel()
    trainer = get_trainer(tmp_path, DataParallel(num_workers=8), max_epochs=1)
    loader = DataLoader(random_dataset(n=60), batch_size=30, drop_last=True)
    with pytest.raises(ValueError, match="not divisible"):
        trainer.fit(module, loader)


def test_val_check_interval(devices8, tmp_path):
    """Mid-epoch validation fires every N steps (long-epoch LLM runs)."""
    from ray_lightning_tpu import DataLoader, SingleDevice, Trainer

    from tests.utils import BoringModel, random_dataset

    data = random_dataset(n=256)
    module = BoringModel()
    seen = []
    module.on_validation_epoch_end = (
        lambda trainer, metrics: seen.append(trainer.global_step))
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=1, val_check_interval=3,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=32),   # 8 steps
                DataLoader(data, batch_size=32))
    # steps 3 and 6 mid-epoch, plus the end-of-epoch validation
    assert seen == [3, 6, 8]


def test_val_check_interval_no_double_at_boundary(devices8, tmp_path):
    """Interval dividing the epoch length must not validate twice on the
    same step at the epoch boundary."""
    from ray_lightning_tpu import DataLoader, SingleDevice, Trainer

    from tests.utils import BoringModel, random_dataset

    data = random_dataset(n=256)
    module = BoringModel()
    seen = []
    module.on_validation_epoch_end = (
        lambda trainer, metrics: seen.append(trainer.global_step))
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=1, val_check_interval=4,
        default_root_dir=str(tmp_path), enable_checkpointing=False,
        enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=32),   # 8 steps
                DataLoader(data, batch_size=32))
    assert seen == [4, 8]
