"""Examples as smoke tests — the reference ran every example with
--smoke-test as a dedicated CI job (reference
.github/workflows/test.yaml:70-77, examples/ray_ddp_example.py:144-158);
same mechanism here, in subprocesses so each example controls its own
JAX platform config."""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(script: str, *args: str, cwd: str = EXAMPLES) -> str:
    env = dict(os.environ)
    # examples pick their own platform/device-count in --smoke-test mode;
    # don't leak the harness's (conftest sets an 8-device XLA_FLAGS)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), "--smoke-test",
         *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=cwd,
    )
    assert out.returncode == 0, (
        f"{script} failed:\n--- stdout ---\n{out.stdout[-3000:]}"
        f"\n--- stderr ---\n{out.stderr[-3000:]}"
    )
    return out.stdout


@pytest.mark.slow
def test_mnist_dp_example(tmp_path):
    out = _run("mnist_dp_example.py", cwd=str(tmp_path))
    assert "final val accuracy" in out


@pytest.mark.slow
def test_mnist_dp_example_tune(tmp_path):
    out = _run("mnist_dp_example.py", "--tune", "--num-samples", "2",
               cwd=str(tmp_path))
    assert "Best hyperparameters" in out


@pytest.mark.slow
def test_mnist_sweep_example(tmp_path):
    out = _run("mnist_sweep_example.py", cwd=str(tmp_path))
    assert "Best checkpoint" in out


@pytest.mark.slow
def test_llama_fsdp_example(tmp_path):
    out = _run("llama_fsdp_example.py", cwd=str(tmp_path))
    assert "tokens/sec" in out


@pytest.mark.slow
def test_cifar_resnet_example(tmp_path):
    out = _run("cifar_resnet_example.py", "--prefetch", cwd=str(tmp_path))
    assert "val_acc=" in out


@pytest.mark.slow
def test_bert_finetune_example(tmp_path):
    out = _run("bert_finetune_example.py", cwd=str(tmp_path))
    assert "val_acc=" in out


@pytest.mark.slow
def test_pod_launch_example(tmp_path):
    out = _run("pod_launch_example.py", cwd=str(tmp_path))
    assert "pod launch round-trip OK" in out
