"""threadcheck (analysis/concurrency.py) — the RLT7xx fixture matrix.

Every rule gets a fire case AND its sanction cases (the sanctions are
the rule's contract as much as the fire is: a race detector that flags
queue handoffs would be unusable). Sources go through
``check_concurrency_sources`` exactly as the CLI feeds files, so the
suppression syntax and the package-level finalization passes (the
dedicated-I/O-lock sanction, the cross-file order graph) are all on the
hook here.

The last tests pin the two self-referential guarantees: the package
itself lints clean (``lint --concurrency`` is default-on for self-lint),
and the tuner-shaped write-under-lock defect fixed in this PR stays
detectable — reintroducing it anywhere trips RLT705 via the repo-clean
pin.
"""
import os

from ray_lightning_tpu.analysis.concurrency import (
    check_concurrency_paths,
    check_concurrency_sources,
    summarize,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(src, name="fixture.py", extra=()):
    pairs = [(name, src)] + list(extra)
    return sorted({f.rule for f in check_concurrency_sources(pairs)})


def _findings(src, name="fixture.py"):
    return check_concurrency_sources([(name, src)])


# ---- RLT701 unguarded-shared-mutation --------------------------------------

_SRC_701_FIRE = """
import threading

class Pump:
    def __init__(self):
        self.buf = []
        self.t = threading.Thread(target=self._run, daemon=True)
        self.t.start()

    def _run(self):
        self.buf.append(1)

    def read(self):
        return len(self.buf)
"""


def test_rlt701_fires_on_unguarded_shared_list():
    fs = _findings(_SRC_701_FIRE)
    assert [f.rule for f in fs] == ["RLT701"], fs
    assert "self.buf" in fs[0].message
    assert "_run" in fs[0].message and "read" in fs[0].message


def test_rlt701_common_lock_sanctions():
    src = """
import threading

class Pump:
    def __init__(self):
        self.lock = threading.Lock()
        self.buf = []
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        with self.lock:
            self.buf.append(1)

    def read(self):
        with self.lock:
            return len(self.buf)
"""
    assert _rules(src) == [], _findings(src)


def test_rlt701_queue_handoff_sanctions():
    src = """
import queue
import threading

class Pump:
    def __init__(self):
        self.q = queue.Queue()
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        self.q.put_nowait(1)

    def read(self):
        return self.q.get(timeout=1)
"""
    assert _rules(src) == [], _findings(src)


def test_rlt701_event_flag_sanctions():
    src = """
import threading

class Pump:
    def __init__(self):
        self.done = threading.Event()
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        self.done.set()

    def poll(self):
        return self.done.is_set()
"""
    assert _rules(src) == [], _findings(src)


def test_rlt701_bounded_deque_sanctions():
    src = """
import collections
import threading

class Pump:
    def __init__(self):
        self.buf = collections.deque(maxlen=8)
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        self.buf.append(1)

    def read(self):
        return list(self.buf)
"""
    assert _rules(src) == [], _findings(src)


def test_rlt701_inline_suppression():
    src = _SRC_701_FIRE.replace(
        "self.buf.append(1)",
        "self.buf.append(1)  # rlt: disable=RLT701")
    assert _rules(src) == [], _findings(src)


# ---- RLT702 lock-order-inversion -------------------------------------------

_SRC_702_FIRE = """
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()

def one():
    with lock_a:
        with lock_b:
            pass

def two():
    with lock_b:
        with lock_a:
            pass
"""


def test_rlt702_fires_on_opposite_nesting():
    fs = _findings(_SRC_702_FIRE)
    assert [f.rule for f in fs] == ["RLT702"], fs
    assert "cycle" in fs[0].message


def test_rlt702_consistent_order_sanctions():
    src = """
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()

def one():
    with lock_a:
        with lock_b:
            pass

def two():
    with lock_a:
        with lock_b:
            pass
"""
    assert _rules(src) == [], _findings(src)


def test_rlt702_cycle_detected_across_files():
    """san_lock names are the package-wide lock identity: file one nests
    A under B, file two nests B under A — neither file alone has a
    cycle."""
    f1 = """
from ray_lightning_tpu.analysis.lockwatch import san_lock

la = san_lock("x.alpha")
lb = san_lock("x.beta")

def fwd():
    with la:
        with lb:
            pass
"""
    f2 = """
from ray_lightning_tpu.analysis.lockwatch import san_lock

la = san_lock("x.alpha")
lb = san_lock("x.beta")

def rev():
    with lb:
        with la:
            pass
"""
    fs = check_concurrency_sources([("one.py", f1), ("two.py", f2)])
    assert [f.rule for f in fs] == ["RLT702"], fs
    assert "x.alpha" in fs[0].message and "x.beta" in fs[0].message
    # each file alone is clean
    assert check_concurrency_sources([("one.py", f1)]) == []
    assert check_concurrency_sources([("two.py", f2)]) == []


# ---- RLT703 thread-leak ----------------------------------------------------

_SRC_703_FIRE = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
"""


def test_rlt703_fires_on_unjoined_nondaemon():
    fs = _findings(_SRC_703_FIRE)
    assert [f.rule for f in fs] == ["RLT703"], fs
    assert "join" in fs[0].message


def test_rlt703_join_sanctions():
    src = """
import threading

def run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
"""
    assert _rules(src) == [], _findings(src)


def test_rlt703_daemon_sanctions():
    src = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
"""
    assert _rules(src) == [], _findings(src)


# ---- RLT704 signal-unsafe-handler ------------------------------------------

_SRC_704_FIRE = """
import signal

def _handler(signum, frame):
    print("caught", signum)

signal.signal(signal.SIGTERM, _handler)
"""


def test_rlt704_fires_on_print_in_handler():
    fs = _findings(_SRC_704_FIRE)
    assert [f.rule for f in fs] == ["RLT704"], fs
    assert "_handler" in fs[0].message


def test_rlt704_flag_only_discipline_sanctions():
    src = """
import os
import signal

FLAG = {"stop": False}

def _handler(signum, frame):
    FLAG["stop"] = True
    os.write(2, b"sig\\n")

signal.signal(signal.SIGTERM, _handler)
"""
    assert _rules(src) == [], _findings(src)


# ---- RLT705 blocking-call-under-lock ---------------------------------------

_SRC_705_FIRE = """
import threading
import time

_lock = threading.Lock()

def slow():
    with _lock:
        time.sleep(1.0)
"""


def test_rlt705_fires_on_sleep_under_lock():
    fs = _findings(_SRC_705_FIRE)
    assert [f.rule for f in fs] == ["RLT705"], fs
    assert "sleep" in fs[0].message


def test_rlt705_dedicated_io_lock_sanctions():
    """A lock whose EVERY critical section is the same I/O exists to
    serialize that I/O — the append-ledger pattern, not a hazard."""
    src = """
import threading

_append_lock = threading.Lock()

def append_line(path, line):
    with _append_lock:
        with open(path, "a") as fh:
            fh.write(line)
"""
    assert _rules(src) == [], _findings(src)


def test_rlt705_timeout_queue_op_sanctions():
    src = """
import queue
import threading

_lock = threading.Lock()
_q = queue.Queue()

def poll():
    with _lock:
        return _q.get(timeout=0.1)
"""
    assert _rules(src) == [], _findings(src)


def test_rlt705_tuner_shaped_write_under_lock_fires():
    """The defect class fixed in sweep/tuner.py this PR: file write
    reached THROUGH a helper called under a state lock. Cross-call
    attribution must still see it — and the lock is NOT io-dedicated
    because its section also mutates in-memory state."""
    src = """
import threading

class Runner:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}

    def _write(self, path):
        with open(path, "w") as fh:
            fh.write("x")

    def handle(self, path):
        with self._lock:
            self.state["n"] = 1
            self._write(path)
"""
    fs = _findings(src)
    assert "RLT705" in [f.rule for f in fs], fs
    assert any("_write" in f.message for f in fs if f.rule == "RLT705")


# ---- the package self-lint pin ---------------------------------------------

def test_repo_lints_clean_under_threadcheck():
    """`python -m ray_lightning_tpu lint --concurrency` exits clean on
    the package — the regression pin for every concurrency fix this
    analyzer forced (tuner snapshot/write split, native suppression,
    the san_lock migrations)."""
    pkg = os.path.join(_REPO, "ray_lightning_tpu")
    fs = check_concurrency_paths([pkg])
    assert fs == [], "\n".join(f.format() for f in fs)


def test_summarize_counts_by_rule():
    fs = _findings(_SRC_701_FIRE) + _findings(_SRC_703_FIRE)
    s = summarize(fs)
    assert s == {"total": 2, "by_rule": {"RLT701": 1, "RLT703": 1}}
    assert summarize([]) == {"total": 0, "by_rule": {}}
