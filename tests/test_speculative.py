"""Speculative decoding on the paged engine (docs/SERVING.md
"speculative decoding").

The contract is exact: greedy accept/reject makes a draft-armed engine
TOKEN-IDENTICAL to plain greedy decode for every k — the draft only
changes how many verified tokens land per tick, never which tokens.
Covered here: token-identity at k=1/3/4 against an independent draft,
the full-acceptance ceiling with the target drafting for itself
(accepted_tokens_per_step == k), the one-compile pin, admission-time
gates (greedy-only, k-1 slot headroom), construction gates, the audit
cost model, and the driver's inline-only arming rules.
"""
import dataclasses

import jax
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, generate
from ray_lightning_tpu.serve.engine import (DecodeEngine, DraftConfig,
                                            EngineConfig)
from ray_lightning_tpu.serve.scheduler import (Request, Scheduler,
                                               validate_request)


def _drain(sched, reqs):
    out = {}
    for r in reqs:
        sched.submit(r)
    while sched.busy():
        for comp in sched.tick():
            out[comp.rid] = comp
    return out


def _prompts(cfg, n=6):
    prompts = []
    for i in range(n):
        size = 9 + 2 + (i % 3)
        prompts.append(np.asarray(
            jax.random.randint(jax.random.key(60 + i), (size,), 0,
                               cfg.vocab_size), np.int32))
    return prompts


@pytest.fixture(scope="module")
def draft_llama(tiny_llama_f32):
    """An INDEPENDENT draft: same tiny architecture, different init key
    — so acceptance is partial and the reject path actually runs."""
    cfg, _, _, tokens = tiny_llama_f32
    draft = Llama(cfg)
    draft_params = jax.jit(draft.init)(jax.random.key(2),
                                       tokens)["params"]
    return draft, draft_params


@pytest.mark.parametrize("k", [1, 3, 4])
def test_speculative_greedy_token_identical(tiny_llama_f32, draft_llama,
                                            k):
    cfg, model, params, _ = tiny_llama_f32
    draft, draft_params = draft_llama
    prompts = _prompts(cfg)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4, draft=DraftConfig(k=k))
    eng = DecodeEngine(model, params, ecfg, draft_model=draft,
                       draft_params=draft_params)
    eng.warmup()
    sched = Scheduler(eng)
    reqs = [Request(rid=f"s{i}", prompt=p, max_new_tokens=6, seed=3 + i)
            for i, p in enumerate(prompts)]
    out = _drain(sched, reqs)
    for i, r in enumerate(reqs):
        ref = np.asarray(generate(model, params, prompts[i][None],
                                  r.max_new_tokens, temperature=0.0,
                                  seed=r.seed))[0]
        got = np.array(out[r.rid].tokens, np.int32)
        assert np.array_equal(ref, got), (k, i, ref, got)
    # every tick emits the carried token at minimum; k=1 IS plain
    # greedy (the chunk holds only t0), so the rate pins to exactly 1
    rate = sched.accepted_tokens_per_step
    assert rate >= 1.0
    if k == 1:
        assert rate == 1.0
    assert eng.compile_count == 1  # verify chunk rides the ONE step


def test_self_draft_reaches_full_acceptance(tiny_llama_f32):
    # target drafting for itself agrees with every proposal: each
    # decode slot-step emits the full k-token chunk
    cfg, model, params, _ = tiny_llama_f32
    prompt = _prompts(cfg, n=1)[0]
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4, draft=DraftConfig(k=4))
    eng = DecodeEngine(model, params, ecfg, draft_model=model,
                       draft_params=params)
    eng.warmup()
    sched = Scheduler(eng)
    out = _drain(sched, [Request(rid="x0", prompt=prompt,
                                 max_new_tokens=8, seed=9)])
    ref = np.asarray(generate(model, params, prompt[None], 8,
                              temperature=0.0, seed=9))[0]
    assert np.array_equal(ref, np.array(out["x0"].tokens))
    assert sched.accepted_tokens_per_step == 4.0
    assert eng.compile_count == 1


# ---------------------------------------------------------------------------
# Gates
# ---------------------------------------------------------------------------


def test_validate_request_speculative_is_greedy_only():
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=4, draft=DraftConfig(k=4))
    prompt = np.arange(6, dtype=np.int32)
    with pytest.raises(ValueError, match="greedy-only"):
        validate_request(ecfg, ecfg.pool_spec,
                         Request(rid="t", prompt=prompt,
                                 max_new_tokens=4, seed=0,
                                 temperature=0.7))
    # the verify chunk writes k positions from the last decode pos:
    # k-1 headroom must be charged against the slot span
    fits_plain = Request(rid="h", prompt=prompt, max_new_tokens=10,
                         seed=0)
    validate_request(dataclasses.replace(ecfg, draft=None),
                     ecfg.pool_spec, fits_plain)
    with pytest.raises(ValueError, match="max_slot_len"):
        validate_request(ecfg, ecfg.pool_spec, fits_plain)


def test_engine_config_draft_gates():
    with pytest.raises(ValueError, match="prefill_batch"):
        EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                     prefill_chunk=4, prefill_batch=2,
                     draft=DraftConfig(k=2))
    with pytest.raises(ValueError, match="draft k"):
        EngineConfig(capacity=2, block_size=4, blocks_per_slot=2,
                     prefill_chunk=4, draft=DraftConfig(k=9))
    with pytest.raises(ValueError, match="k must be >= 1"):
        DraftConfig(k=0)
    # dict form coerces (the driver's JSON config path)
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=4, draft={"k": 3})
    assert ecfg.draft == DraftConfig(k=3)


def test_engine_requires_draft_weights(tiny_llama_f32):
    cfg, model, params, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=4, draft=DraftConfig(k=2))
    with pytest.raises(ValueError, match="draft model"):
        DecodeEngine(model, params, ecfg)


def test_driver_speculative_arming_gates(tiny_llama_f32):
    from ray_lightning_tpu.serve.driver import (ReplicaGroupConfig,
                                                ServeDriver)

    cfg, _, params, _ = tiny_llama_f32
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=4, draft=DraftConfig(k=2))
    with pytest.raises(ValueError, match="inline-only"):
        ReplicaGroupConfig(backend="process", engine=ecfg,
                           draft_model_cfg=cfg)
    with pytest.raises(ValueError, match="arm together"):
        ReplicaGroupConfig(backend="inline", engine=ecfg)
    good = ReplicaGroupConfig(backend="inline", engine=ecfg,
                              draft_model_cfg=cfg)
    with pytest.raises(ValueError, match="arm together"):
        ServeDriver(cfg, params, good)  # draft_params missing


# ---------------------------------------------------------------------------
# Audit cost model
# ---------------------------------------------------------------------------


def test_speculative_plan_cost_model(tiny_llama_f32):
    from ray_lightning_tpu.serve.audit import speculative_plan

    cfg, _, _, _ = tiny_llama_f32
    draft_cfg = dataclasses.replace(cfg, n_layers=max(
        1, cfg.n_layers // 4))
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=4,
                        prefill_chunk=4, draft=DraftConfig(k=4))
    plan = speculative_plan(cfg, draft_cfg, ecfg, accept_rate=0.5)
    # the k-wide verify prices exactly k target decode steps of FLOPs
    assert plan["verify_step_flops"] == (
        plan["k"] * plan["base_decode_flops_per_token"])
    # expected emission: the carried token plus accepted proposals
    assert plan["expected_tokens_per_tick"] == 1 + 0.5 * (4 - 1)
    assert plan["draft_params"] < plan["target_params"]
    # memory-bound speedup only beats 1.0 when the extra draft reads
    # cost less than the tokens they buy — the dict must price both
    assert plan["hbm_read_bytes_per_tick_spec"] > \
        plan["hbm_read_bytes_per_tick_base"]
    assert plan["memory_bound_speedup_x"] > 0.0
    with pytest.raises(ValueError, match="accept_rate"):
        speculative_plan(cfg, draft_cfg, ecfg, accept_rate=1.5)
