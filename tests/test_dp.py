"""Data-parallel strategy over the virtual 8-device mesh.

Coverage modeled on reference tests/test_ddp.py: train/load/predict matrix
over worker counts (:79-113), sharding wiring (:44-76), and the
num_workers=actor-count invariant (:29-41) recast as mesh-shape asserts.
"""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu import DataLoader, DataParallel, RayXlaPlugin, Trainer
from tests.utils import (
    BoringModel,
    MNISTClassifier,
    get_trainer,
    load_test,
    predict_test,
    synthetic_mnist,
    random_dataset,
)


@pytest.mark.parametrize("num_workers", [2, 8])
def test_mesh_matches_num_workers(tmp_path, num_workers):
    strategy = DataParallel(num_workers=num_workers)
    trainer = get_trainer(tmp_path, strategy, max_epochs=1)
    trainer.fit(BoringModel(), DataLoader(random_dataset(), batch_size=32))
    assert strategy.mesh is not None
    assert strategy.mesh.shape["data"] == num_workers
    assert strategy.dp_size == num_workers


def test_batch_is_sharded_params_replicated(tmp_path):
    strategy = DataParallel(num_workers=8)
    trainer = get_trainer(tmp_path, strategy, max_epochs=1,
                          limit_train_batches=2)
    module = BoringModel()
    trainer.fit(module, DataLoader(random_dataset(), batch_size=32))
    # params replicated across the mesh
    for leaf in jax.tree.leaves(trainer.state.params):
        assert leaf.sharding.is_fully_replicated
    # batch sharding: leading dim over 'data'
    batch = strategy.shard_batch({"x": np.zeros((32, 4), np.float32)})
    assert batch["x"].sharding.spec == P(("data",))


@pytest.mark.parametrize("num_workers", [1, 2])
def test_train_load_predict(tmp_path, num_workers):
    """The reference's canonical matrix (test_ddp.py:79-113)."""
    data = synthetic_mnist()
    module = MNISTClassifier(lr=1e-2)
    trainer = get_trainer(
        tmp_path, DataParallel(num_workers=num_workers), max_epochs=3,
        limit_train_batches=None, seed=0,
    )
    train = DataLoader(data, batch_size=64, shuffle=True)
    val = DataLoader(data, batch_size=64)
    trainer.fit(module, train, val)
    loaded = load_test(trainer, MNISTClassifier)
    acc = predict_test(trainer, module, data)
    assert acc >= 0.5
    # loaded params match trained params
    for a, b in zip(jax.tree.leaves(jax.device_get(module.params)),
                    jax.tree.leaves(loaded.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_dp_matches_single_device(tmp_path):
    """DP over 8 devices computes the same update as 1 device (allreduce
    correctness — replaces the reference's trust in NCCL)."""

    def run(strategy, tag):
        module = BoringModel(lr=0.1)
        trainer = get_trainer(tmp_path / tag, strategy, max_epochs=1,
                              limit_train_batches=4, seed=0,
                              checkpoint_callback=False)
        trainer.fit(module, DataLoader(random_dataset(), batch_size=64))
        return jax.device_get(module.params)

    from ray_lightning_tpu import SingleDevice

    p1 = run(SingleDevice(), "one")
    p8 = run(DataParallel(num_workers=8), "eight")
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ray_xla_plugin_alias(tmp_path):
    """RayXlaPlugin(num_workers=..., use_gpu=False) is a drop-in ctor
    (reference RayPlugin signature, ray_ddp.py:89-94)."""
    called = {}

    def init_hook():
        called["hook"] = True

    strategy = RayXlaPlugin(num_workers=2, num_cpus_per_worker=1,
                            use_gpu=False, init_hook=init_hook)
    trainer = get_trainer(tmp_path, strategy, max_epochs=1)
    trainer.fit(BoringModel(), DataLoader(random_dataset(), batch_size=32))
    assert called.get("hook"), "init_hook did not run (ray_ddp.py:118-119)"
    assert strategy.mesh.shape["data"] == 2


def test_ray_xla_plugin_cpu_budget(tmp_path, monkeypatch):
    """num_cpus_per_worker is honored: exported as the per-worker CPU
    budget and consumed as the data pipeline's thread-pool size
    (reference per-worker CPU reservation, ray_ddp.py:89-111)."""
    import os

    monkeypatch.delenv("RLT_NUM_CPUS_PER_WORKER", raising=False)
    # the DEFAULT ctor must not inject a budget (it would retune every
    # DataLoader in the process, not just this strategy's)
    assert "RLT_NUM_CPUS_PER_WORKER" not in RayXlaPlugin(num_workers=2).env
    # loader built BEFORE fit/setup — the budget must still apply (the
    # pool size is resolved lazily, not at construction)
    early_loader = DataLoader(random_dataset(), batch_size=32)
    strategy = RayXlaPlugin(num_workers=2, num_cpus_per_worker=3)
    assert strategy.num_cpus_per_worker == 3
    try:
        strategy.setup()
        assert os.environ["RLT_NUM_CPUS_PER_WORKER"] == "3"
        assert early_loader.num_workers == 3
        assert DataLoader(random_dataset(), batch_size=32,
                          num_workers=5).num_workers == 5
    finally:
        # strategy.setup writes os.environ directly; monkeypatch has no
        # undo registered for a key that was absent
        os.environ.pop("RLT_NUM_CPUS_PER_WORKER", None)
    assert DataLoader(random_dataset(), batch_size=32).num_workers == 2
