"""Ring attention / sequence parallelism tests.

Exactness: ring attention over a seq-sharded mesh must match full
attention to float tolerance (it is the same math, blockwise). Then the
full stack: a Llama with seq_parallel=True on a (data, seq, tensor) mesh
produces the same logits as the unsharded model with identical params,
and trains end-to-end.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer, make_mesh
from ray_lightning_tpu.ops import dot_product_attention, ring_attention


def _qkv(B=2, S=32, H=4, Hkv=None, D=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    Hkv = Hkv or H
    q = jax.random.normal(ks[0], (B, S, H, D), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize(
    "seq",
    # ring=4 covers the multi-hop protocol per-PR; the 2- and 8-way
    # variants (same code path, ~15s compile each) run in the slow job
    [pytest.param(2, marks=pytest.mark.slow), 4,
     pytest.param(8, marks=pytest.mark.slow)],
)
def test_ring_matches_full_attention(devices8, causal, seq):
    mesh = make_mesh(seq=seq, devices=devices8[:seq])
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_gqa_and_mixed_mesh(devices8):
    """GQA (kv heads < q heads) on a full data×seq×tensor mesh."""
    mesh = make_mesh(data=2, seq=2, tensor=2, devices=devices8)
    q, k, v = _qkv(B=4, S=16, H=4, Hkv=2, D=8)
    ref = dot_product_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_under_jit(devices8):
    """The manual island composes with an outer jit (the Trainer's shape)."""
    mesh = make_mesh(seq=4, devices=devices8[:4])
    q, k, v = _qkv(S=16)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True)

    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(dot_product_attention(q, k, v, causal=True)),
        atol=2e-5, rtol=2e-5,
    )


# ------------------------------------------------ llama integration


def _llama_logits(cfg, params, tokens, mesh=None):
    from ray_lightning_tpu.models.llama import Llama

    model = Llama(cfg, mesh=mesh)
    return model.apply({"params": params}, tokens)


def test_llama_seq_parallel_matches_dense(devices8):
    """Same params, same tokens: the ring path must reproduce the plain
    attention path's logits."""
    import dataclasses

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (2, 32), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(Llama(cfg).init)(jax.random.key(1), tokens)["params"]
    ref = _llama_logits(cfg, params, tokens)

    mesh = make_mesh(data=2, seq=4, devices=devices8)
    sp_cfg = dataclasses.replace(cfg, seq_parallel=True)
    out = _llama_logits(sp_cfg, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_llama_trains_with_seq_parallel(devices8, tmp_path):
    """Full training step over a data×seq mesh: strategy binds the mesh,
    configure_model builds the ring path, loss decreases machinery runs."""
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule

    cfg = LlamaConfig.tiny(use_flash=False, seq_parallel=True)
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=4)
    rng = np.random.default_rng(0)
    data = {"tokens": rng.integers(
        0, cfg.vocab_size, (16, 33)).astype(np.int32)}

    trainer = Trainer(
        strategy=ShardedMesh(data=2, seq=4, devices=devices8,
                             min_shard_size=1),
        max_epochs=1,
        limit_train_batches=2,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False,
        enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=8))
    assert trainer.global_step == 2
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
    assert module.model.mesh is not None  # the ring path was built


# ------------------------------------------------------ ulysses variant


def test_ulysses_matches_full_attention(devices8):
    from ray_lightning_tpu.ops import ulysses_attention

    mesh = make_mesh(data=2, seq=4, devices=devices8)
    q, k, v = _qkv(B=4, S=32, H=4, Hkv=4, D=8)
    for causal in (True, False):
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(devices8):
    import pytest as _pytest

    from ray_lightning_tpu.ops import ulysses_attention

    mesh = make_mesh(seq=4, devices=devices8[:4])
    q, k, v = _qkv(H=4, Hkv=2)  # Hkv=2 not divisible by seq=4
    with _pytest.raises(ValueError, match="ring"):
        ulysses_attention(q, k, v, mesh)


def test_llama_ulysses_mode_matches_dense(devices8):
    import dataclasses

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32,
                           n_heads=8, n_kv_heads=4)
    tokens = np.asarray(
        jax.random.randint(jax.random.key(0), (2, 32), 0, cfg.vocab_size),
        dtype=np.int32,
    )
    params = jax.jit(Llama(cfg).init)(jax.random.key(1), tokens)["params"]
    ref = _llama_logits(cfg, params, tokens)

    mesh = make_mesh(data=2, seq=2, tensor=2, devices=devices8)
    sp_cfg = dataclasses.replace(cfg, seq_parallel=True,
                                 seq_parallel_mode="ulysses")
    out = _llama_logits(sp_cfg, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
