"""shardcheck code-linter unit tests: every rule fires on a minimal bad
snippet and stays quiet on the matching good one, suppression works, and
the bundled models/ops self-lint clean (the framework is held to its own
bar — docs/STATIC_ANALYSIS.md)."""
import os

from ray_lightning_tpu.analysis import lint_paths, lint_source

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ray_lightning_tpu")


def rules_of(findings):
    return sorted({f.rule for f in findings})


def lint(src: str):
    return lint_source(src, "<test>")


# ---- RLT201 host transfer ------------------------------------------------


def test_host_transfer_fires_in_training_step():
    fs = lint(
        "import numpy as np\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return np.asarray(batch['x']).sum()\n")
    assert rules_of(fs) == ["RLT201"]
    assert fs[0].symbol == "M.training_step"


def test_host_transfer_method_forms():
    fs = lint(
        "class M:\n"
        "    def validation_step(self, params, batch):\n"
        "        a = loss.item()\n"
        "        b = loss.tolist()\n"
        "        c = loss.block_until_ready()\n")
    assert [f.rule for f in fs] == ["RLT201"] * 3


def test_host_transfer_quiet_outside_traced_code():
    fs = lint(
        "import numpy as np\n"
        "def collate(batch):\n"
        "    return np.asarray(batch)\n"
        "class M:\n"
        "    def on_fit_end(self, trainer):\n"
        "        return float(np.asarray(1.0))\n")
    assert fs == []


def test_host_transfer_found_through_helper_calls():
    """Fixpoint propagation: a transfer two helpers deep under a step
    hook is still a per-step transfer."""
    fs = lint(
        "import jax\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return self._loss(params, batch)\n"
        "    def _loss(self, params, batch):\n"
        "        return _fetch(params)\n"
        "def _fetch(p):\n"
        "    return jax.device_get(p)\n")
    assert rules_of(fs) == ["RLT201"]
    assert fs[0].symbol == "_fetch"


def test_call_form_jit_marks_local_function():
    fs = lint(
        "import jax\n"
        "def make_step():\n"
        "    def step(p):\n"
        "        return p.item()\n"
        "    return jax.jit(step)\n")
    assert rules_of(fs) == ["RLT201"]


# ---- RLT202 python rng ---------------------------------------------------


def test_python_rng_fires_jax_rng_quiet():
    fs = lint(
        "import random\n"
        "import numpy as np\n"
        "import jax\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        a = random.random()\n"
        "        b = np.random.normal()\n"
        "        c = jax.random.normal(rng, (2,))\n"
        "        return a + b + c.sum()\n")
    assert [f.rule for f in fs] == ["RLT202", "RLT202"]


# ---- RLT203 / RLT204 wallclock + print -----------------------------------


def test_wallclock_and_print_warn():
    fs = lint(
        "import time\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        t = time.time()\n"
        "        print('step at', t)\n"
        "        return t\n")
    assert rules_of(fs) == ["RLT203", "RLT204"]
    assert all(f.severity == "warning" for f in fs)


# ---- RLT205 static args --------------------------------------------------


def test_unhashable_static_default_fires():
    fs = lint(
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def step(params, opts=[1, 2]):\n"
        "    return params\n")
    assert rules_of(fs) == ["RLT205"]


def test_static_argnames_typo_fires():
    fs = lint(
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('cfgg',))\n"
        "def step(params, cfg=None):\n"
        "    return params\n")
    assert rules_of(fs) == ["RLT205"]
    assert "cfgg" in fs[0].message


def test_wellformed_static_args_quiet():
    fs = lint(
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(1,), static_argnames=('cfg',))\n"
        "def step(params, cfg=None):\n"
        "    return params\n")
    assert fs == []


# ---- RLT206 unordered iteration ------------------------------------------


def test_set_iteration_warns_sorted_quiet():
    bad = lint(
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        out = {}\n"
        "        for k in set(batch):\n"
        "            out[k] = batch[k]\n"
        "        return out\n")
    assert rules_of(bad) == ["RLT206"]
    good = lint(
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        out = {}\n"
        "        for k in sorted(set(batch)):\n"
        "            out[k] = batch[k]\n"
        "        return out\n")
    assert good == []


def test_set_comprehension_iteration_warns():
    fs = lint(
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return [batch[k] for k in {'a', 'b'}]\n")
    assert rules_of(fs) == ["RLT206"]


# ---- RLT101 / RLT103 mesh-axis literals ----------------------------------


def test_partition_spec_typo_fires_anywhere():
    fs = lint(
        "from jax.sharding import PartitionSpec as P\n"
        "SPEC = P('fdsp', None)\n")
    assert rules_of(fs) == ["RLT101"]
    assert "fdsp" in fs[0].message


def test_partition_spec_duplicate_axis_fires():
    fs = lint(
        "from jax.sharding import PartitionSpec\n"
        "SPEC = PartitionSpec('tensor', 'tensor')\n")
    assert rules_of(fs) == ["RLT103"]


def test_partition_spec_good_axes_quiet():
    fs = lint(
        "from jax.sharding import PartitionSpec as P\n"
        "A = P('data', None)\n"
        "B = P(('data', 'fsdp'), 'tensor')\n"
        "C = P()\n")
    assert fs == []


def test_extra_axes_extend_vocabulary():
    src = ("from jax.sharding import PartitionSpec as P\n"
           "SPEC = P('stage', None)\n")
    assert rules_of(lint_source(src, "<t>")) == ["RLT101"]
    assert lint_source(src, "<t>", extra_axes=("stage",)) == []


# ---- RLT001 + suppression ------------------------------------------------


def test_parse_error_reported_not_raised():
    fs = lint("def broken(:\n")
    assert rules_of(fs) == ["RLT001"]


def test_line_suppression():
    fs = lint(
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        print('x')  # rlt: disable=RLT204\n"
        "        return 0\n")
    assert fs == []


def test_file_suppression():
    fs = lint(
        "# rlt: disable-file=RLT204\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        print('a')\n"
        "        print('b')\n"
        "        return 0\n")
    assert fs == []


def test_bare_suppression_disables_all_on_line():
    fs = lint(
        "import numpy as np\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return np.asarray(batch)  # rlt: disable\n")
    assert fs == []


# ---- self-lint: the framework passes its own analyzer --------------------


def test_bundled_models_and_ops_self_lint_clean():
    """ISSUE-1 acceptance: llama, moe, and all of ops/ are clean under
    the default severity (every finding, not only errors)."""
    targets = [
        os.path.join(PKG, "models", "llama.py"),
        os.path.join(PKG, "models", "moe.py"),
        os.path.join(PKG, "ops"),
    ]
    findings = lint_paths(targets)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_whole_package_self_lint_clean():
    """The bar format.sh enforces: the entire package lints clean."""
    findings = lint_paths([PKG])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_tpumodule_lint_classmethod():
    from ray_lightning_tpu.models.llama import LlamaModule

    assert LlamaModule.lint() == []


# ---- RLT401 resilience anti-patterns (ISSUE 3 satellite) -----------------


def test_rlt401_swallowed_worker_error_fires():
    fs = lint(
        "from ray_lightning_tpu.runtime import fit_distributed\n"
        "def train(mf, tf, df):\n"
        "    try:\n"
        "        fit_distributed(mf, tf, df, 4)\n"
        "    except Exception:\n"
        "        pass\n")
    assert rules_of(fs) == ["RLT401"]
    assert "swallows" in fs[0].message


def test_rlt401_bare_except_and_group_method_forms():
    fs = lint(
        "def run(group):\n"
        "    try:\n"
        "        group.run(lambda: 1)\n"
        "    except:\n"
        "        pass\n")
    assert rules_of(fs) == ["RLT401"]
    fs = lint(
        "from ray_lightning_tpu.runtime import WorkerError\n"
        "def run(g):\n"
        "    try:\n"
        "        g.do_stuff()\n"
        "    except WorkerError:\n"
        "        continue_anyway = None\n"
        "        pass\n")
    # handler body is NOT trivial (assignment) -> quiet
    assert fs == []


def test_rlt401_quiet_on_handled_or_unrelated_excepts():
    # re-raised: not swallowed
    fs = lint(
        "from ray_lightning_tpu.runtime import fit_distributed\n"
        "def train(mf, tf, df):\n"
        "    try:\n"
        "        fit_distributed(mf, tf, df, 4)\n"
        "    except Exception:\n"
        "        log.error('boom')\n"
        "        raise\n")
    assert fs == []
    # broad except-pass around NON-worker code: quiet (that is ruff's
    # turf, not a supervision defeat)
    fs = lint(
        "def parse(x):\n"
        "    try:\n"
        "        return int(x)\n"
        "    except Exception:\n"
        "        pass\n")
    assert fs == []


def test_rlt401_worker_group_without_shutdown_fires():
    fs = lint(
        "from ray_lightning_tpu.runtime import WorkerGroup\n"
        "def launch_all():\n"
        "    g = WorkerGroup(4)\n"
        "    g.start()\n"
        "    return g.run(lambda: 1)\n")
    assert rules_of(fs) == ["RLT401"]
    assert "shutdown" in fs[0].message
    # chained start form
    fs = lint(
        "def launch_all():\n"
        "    g = WorkerGroup(4).start()\n"
        "    g.run(lambda: 1)\n")
    assert rules_of(fs) == ["RLT401"]


def test_rlt401_quiet_on_managed_worker_groups():
    # with-managed
    fs = lint(
        "def a(tmp):\n"
        "    g = WorkerGroup(2)\n"
        "    with g:\n"
        "        g.run(fn)\n")
    assert fs == []
    # try/finally shutdown (even conditional, the repo's tuner idiom)
    fs = lint(
        "def b():\n"
        "    g = None\n"
        "    try:\n"
        "        g = WorkerGroup(2)\n"
        "        g.start()\n"
        "        g.run(fn)\n"
        "    finally:\n"
        "        if g is not None:\n"
        "            g.shutdown()\n")
    assert fs == []
    # ownership handed away: factory returns the started group
    fs = lint(
        "def make():\n"
        "    g = WorkerGroup(2)\n"
        "    g.start()\n"
        "    return g\n")
    assert fs == []
    # never started: nothing leaked
    fs = lint(
        "def c():\n"
        "    g = WorkerGroup(2)\n")
    assert fs == []


def test_rlt401_suppressible():
    fs = lint(
        "def launch_all():\n"
        "    g = WorkerGroup(4)  # rlt: disable=RLT401\n"
        "    g.start()\n")
    assert fs == []


# ---- RLT304 host sync in hot loop ----------------------------------------


def test_rlt304_host_syncs_on_step_outputs():
    fs = lint(
        "import jax, numpy as np\n"
        "def train(loader, train_step, state):\n"
        "    for batch in loader:\n"
        "        state, metrics = train_step(state, batch)\n"
        "        a = float(metrics['loss'])\n"
        "        b = np.asarray(metrics['acc'])\n"
        "        metrics['loss'].block_until_ready()\n"
        "        c = metrics['loss'].item()\n")
    assert rules_of(fs) == ["RLT304"]
    assert len(fs) == 4
    assert all(f.symbol == "train" for f in fs)


def test_rlt304_unprefetched_device_put():
    fs = lint(
        "import jax\n"
        "def train(dataloader, step, state):\n"
        "    for batch in dataloader:\n"
        "        db = jax.device_put(batch)\n"
        "        state, _ = step(state, db)\n")
    assert rules_of(fs) == ["RLT304"]
    assert "device_put" in fs[0].message


def test_rlt304_log_cadence_exempt():
    fs = lint(
        "def train(loader, train_step, state, step_no):\n"
        "    for batch in loader:\n"
        "        state, metrics = train_step(state, batch)\n"
        "        if step_no % 50 == 0:\n"
        "            print(float(metrics['loss']))\n")
    assert fs == []


def test_rlt304_quiet_outside_loader_loops_and_after_loop():
    # non-loader iteration: not a hot loop
    fs = lint(
        "def f(xs, step):\n"
        "    for x in xs:\n"
        "        y = step(x)\n"
        "        z = float(y)\n")
    assert fs == []
    # sync AFTER the loop (the trainer's own pending-metrics pattern)
    fs = lint(
        "def train(loader, train_step, state):\n"
        "    pending = None\n"
        "    for batch in loader:\n"
        "        state, pending = train_step(state, batch)\n"
        "    return float(pending['loss'])\n")
    assert fs == []
    # non-step values inside a loader loop: not flagged
    fs = lint(
        "def show(loader):\n"
        "    for batch in loader:\n"
        "        n = float(batch['x'][0])\n")
    assert fs == []


def test_rlt304_module_level_script_and_enumerate():
    fs = lint(
        "for i, batch in enumerate(val_loader):\n"
        "    m = eval_step(params, batch)\n"
        "    t = m.item()\n")
    assert rules_of(fs) == ["RLT304"]


def test_rlt304_not_in_traced_code():
    # inside a traced step the per-step sync is RLT201's business
    fs = lint(
        "import numpy as np\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        for b in batch_loader:\n"
        "            x = np.asarray(b)\n"
        "        return x\n")
    assert "RLT304" not in rules_of(fs)


def test_rlt304_suppressible():
    fs = lint(
        "def train(loader, train_step, state):\n"
        "    for batch in loader:\n"
        "        state, m = train_step(state, batch)\n"
        "        loss = float(m['loss'])  # rlt: disable=RLT304\n")
    assert fs == []


def test_rlt304_nested_hot_loops_report_once():
    # a loader loop inside a loader loop: each finding belongs to
    # exactly ONE loop's pass — never doubled
    fs = lint(
        "def train(loader, batch_loader, step, s):\n"
        "    for batch in loader:\n"
        "        for b2 in batch_loader:\n"
        "            s, m = step(s, b2)\n"
        "            x = float(m)\n")
    assert rules_of(fs) == ["RLT304"]
    assert len(fs) == 1, [f.format() for f in fs]


# ---- RLT402 nan-through-where (trainguard, ISSUE 5) ------------------------


def test_rlt402_where_with_risky_branch():
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        x = batch['x']\n"
        "        return jnp.where(x > 0, jnp.log(x), 0.0).sum()\n")
    assert "RLT402" in rules_of(fs)


def test_rlt402_division_and_power_in_branch():
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        r = jnp.where(m, a / b, 0.0)\n"
        "        p = jnp.where(m, x ** 0.5, 1.0)\n"
        "        return r + p\n")
    assert [f.rule for f in fs] == ["RLT402", "RLT402"]


def test_rlt402_safe_division_and_power_are_clean():
    # the sanctioned clamp on the denominator, and integer powers
    # (finite gradient everywhere), must not be flagged
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        r = jnp.where(m, x / jnp.maximum(d, 1e-6), 0.0)\n"
        "        q = jnp.where(m, x ** 2, 0.0)\n"
        "        s = jnp.where(m, jnp.maximum(x, 0.0) ** 0.5, 0.0)\n"
        "        return r + q + s\n")
    assert "RLT402" not in rules_of(fs)


def test_rlt402_masked_input_is_clean():
    # the FIX the rule recommends must not itself be flagged
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        x = batch['x']\n"
        "        safe = jnp.log(jnp.where(x > 0, x, 1.0))\n"
        "        clamped = jnp.where(x > 0, jnp.log(jnp.maximum(x, 1e-6)),\n"
        "                            0.0)\n"
        "        return (safe + clamped).sum()\n")
    assert "RLT402" not in rules_of(fs)


def test_rlt402_unguarded_log_of_batch():
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return jnp.log(batch['x']).sum()\n")
    assert "RLT402" in rules_of(fs)
    # epsilon-shifted input is the sanctioned pattern
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        return jnp.log(batch['x'] + 1e-6).sum()\n")
    assert "RLT402" not in rules_of(fs)


def test_rlt402_only_in_traced_code():
    # host-side code may where/log freely — the cotangent trap is a
    # property of differentiated traced code
    fs = lint(
        "import jax.numpy as jnp\n"
        "def report(batch):\n"
        "    return jnp.where(batch > 0, jnp.log(batch), 0.0)\n")
    assert "RLT402" not in rules_of(fs)


def test_rlt402_suppressible():
    fs = lint(
        "import jax.numpy as jnp\n"
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        x = batch['x']\n"
        "        y = jnp.where(x > 0, jnp.log(x), 0.0)"
        "  # rlt: disable=RLT402\n"
        "        return y.sum()\n")
    assert "RLT402" not in rules_of(fs)


# ---- RLT502 serve-loop recompile ----------------------------------------


def test_rlt502_growing_concat_fires():
    fs = lint(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "step = jax.jit(lambda p, t: t)\n"
        "def serve(params, prompt):\n"
        "    toks = prompt\n"
        "    for t in range(16):\n"
        "        logits = step(params, toks)\n"
        "        toks = jnp.concatenate([toks, logits[:, None]], axis=1)\n"
        "    return toks\n")
    assert "RLT502" in rules_of(fs)


def test_rlt502_unbucketed_slice_fires():
    fs = lint(
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=())\n"
        "def prefill(params, toks):\n"
        "    return toks\n"
        "def serve(params, toks, lens):\n"
        "    for i, l in enumerate(lens):\n"
        "        out = prefill(params, toks[:, :l])\n")
    assert "RLT502" in rules_of(fs)


def test_rlt502_while_loop_fires():
    fs = lint(
        "import jax\n"
        "import numpy as np\n"
        "decode = jax.jit(lambda p, t: t)\n"
        "def serve(params, toks):\n"
        "    done = False\n"
        "    while not done:\n"
        "        out = decode(params, toks)\n"
        "        toks = np.concatenate([toks, out])\n")
    assert "RLT502" in rules_of(fs)


def test_rlt502_fixed_shapes_clean():
    # position-indexed cache writes + integer indexing: shapes constant
    fs = lint(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "step = jax.jit(lambda p, t: t)\n"
        "def serve(params, toks):\n"
        "    out = jnp.zeros((4, 16), jnp.int32)\n"
        "    for t in range(16):\n"
        "        tok = step(params, toks)\n"
        "        out = out.at[:, t].set(tok)\n"
        "        x = step(params, out[t])\n"
        "    return out\n")
    assert "RLT502" not in rules_of(fs)


def test_rlt502_quiet_in_traced_code_and_nonjit_callees():
    # inside jit, loop shapes are static by construction; and a plain
    # (unjitted) python callee retraces nothing
    fs = lint(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def traced(params, toks):\n"
        "    for t in range(4):\n"
        "        toks = jnp.concatenate([toks, toks], axis=1)\n"
        "    return toks\n"
        "def plain(params, toks):\n"
        "    for t in range(4):\n"
        "        toks = jnp.concatenate([toks, helper(params, toks)])\n")
    assert "RLT502" not in rules_of(fs)


def test_rlt502_suppressible():
    fs = lint(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "step = jax.jit(lambda p, t: t)\n"
        "def serve(params, toks):\n"
        "    for t in range(4):\n"
        "        out = step(params, toks)  # rlt: disable=RLT502\n"
        "        toks = jnp.concatenate([toks, out])\n")
    assert "RLT502" not in rules_of(fs)


def test_rlt502_outer_loop_variable_in_nested_loop_fires():
    # review regression: the canonical per-request-outer /
    # per-token-inner serve loop — the slice varies with the OUTER
    # loop's un-bucketed length
    fs = lint(
        "import jax\n"
        "step = jax.jit(lambda p, t: t)\n"
        "def serve(params, toks, lens):\n"
        "    for l in lens:\n"
        "        done = False\n"
        "        while not done:\n"
        "            out = step(params, toks[:, :l])\n")
    assert "RLT502" in rules_of(fs)


# ---- RLT601 pinned-world-size (elastic/, docs/ELASTIC.md) ------------------


def test_rlt601_pinned_count_assert_fires():
    fs = lint(
        "import jax\n"
        "def setup():\n"
        "    assert jax.device_count() == 8\n")
    assert "RLT601" in rules_of(fs)


def test_rlt601_len_devices_fires():
    fs = lint(
        "import jax\n"
        "def setup():\n"
        "    if len(jax.devices()) != 16:\n"
        "        raise RuntimeError('need 16')\n")
    assert "RLT601" in rules_of(fs)


def test_rlt601_batch_div_literal_fires():
    fs = lint(
        "def shard(global_batch, rank):\n"
        "    per_host = global_batch // 8\n"
        "    lane = rank % 4\n"
        "    return per_host, lane\n")
    assert len([f for f in fs if f.rule == "RLT601"]) == 2


def test_rlt601_capability_checks_sanctioned():
    # == 1 / > 1 are capability gates, not world-size pins; mesh-derived
    # divisors are names/calls, never literals
    fs = lint(
        "import jax\n"
        "from ray_lightning_tpu.parallel import mesh as mesh_lib\n"
        "def shard(batch, mesh, accum, seq):\n"
        "    if jax.process_count() == 1:\n"
        "        pass\n"
        "    if jax.process_count() > 1:\n"
        "        pass\n"
        "    per = batch // mesh_lib.batch_size_divisor(mesh)\n"
        "    micro = batch // accum\n"
        "    half = seq // 2\n"
        "    odd = batch // 3\n"
        "    return per, micro, half, odd\n")
    assert "RLT601" not in rules_of(fs)


def test_rlt601_suppressible():
    fs = lint(
        "def shard(global_batch):\n"
        "    return global_batch // 8  # rlt: disable=RLT601\n")
    assert "RLT601" not in rules_of(fs)


# ---- RLT504 per-token channel chatter (serve/channel.py, -------------------
# ---- docs/SERVING.md "the request channel") --------------------------------


def test_rlt504_per_token_send_fires():
    # the anti-pattern the batched side channel exists to prevent: one
    # channel send per emitted token instead of one item per tick
    fs = lint(
        "def worker_loop(chan, engine):\n"
        "    while True:\n"
        "        emitted = engine.tick()\n"
        "        for tok in emitted:\n"
        "            chan.send(('tok', tok))\n")
    assert "RLT504" in rules_of(fs)


def test_rlt504_per_token_queue_put_fires():
    fs = lint(
        "def worker_loop(out_queue, engine):\n"
        "    while True:\n"
        "        toks = engine.tick()\n"
        "        for i, t in enumerate(toks):\n"
        "            out_queue.put_nowait(t)\n")
    assert "RLT504" in rules_of(fs)


def test_rlt504_per_token_recv_and_writer_forms_fire():
    # the driver-side mirror (a recv/poll per expected token), and the
    # channel writer spelling
    fs = lint(
        "def drain(conn, writer, emitted_tokens):\n"
        "    for t in emitted_tokens:\n"
        "        conn.recv()\n"
        "    for t in emitted_tokens:\n"
        "        writer.send('submit', tok=t)\n")
    assert "RLT504" in rules_of(fs)


def test_rlt504_batched_send_quiet():
    # the sanctioned discipline: accumulate the tick's emissions, ONE
    # side-channel item per iteration
    fs = lint(
        "def worker_loop(chan, engine):\n"
        "    while True:\n"
        "        emitted = engine.tick()\n"
        "        batch = []\n"
        "        for tok in emitted:\n"
        "            batch.append(tok)\n"
        "        chan.send(('toks', batch))\n")
    assert "RLT504" not in rules_of(fs)


def test_rlt504_quiet_on_non_channel_and_non_token_loops():
    # a per-token loop touching no channel, and a channel loop not over
    # tokens (command replay iterates COMMANDS — epoch-bounded, fine)
    fs = lint(
        "def decode(emitted, writer, replay):\n"
        "    out = []\n"
        "    for tok in emitted:\n"
        "        out.append(tok)\n"
        "    for cmd in replay:\n"
        "        writer.send(cmd['op'])\n")
    assert "RLT504" not in rules_of(fs)


def test_rlt504_quiet_in_traced_code():
    # inside jit there is no channel to chatter on — same scope rule as
    # the other serve-loop lints
    fs = lint(
        "import jax\n"
        "@jax.jit\n"
        "def step(tokens, chan_like):\n"
        "    for t in tokens:\n"
        "        chan_like.send(t)\n"
        "    return tokens\n")
    assert "RLT504" not in rules_of(fs)


def test_rlt504_suppressible():
    fs = lint(
        "def worker_loop(chan, toks):\n"
        "    for t in toks:\n"
        "        chan.send(t)  # rlt: disable=RLT504\n")
    assert "RLT504" not in rules_of(fs)


# ---- RLT309 redundant prefix prefill (serve/kv_cache.py PrefixCache, -------
# ---- docs/SERVING.md "prefix cache") ---------------------------------------


def test_rlt309_constant_prefix_submit_fires():
    # the anti-pattern the prefix cache exists to prevent: every
    # request re-prefills the same system prompt
    fs = lint(
        "import numpy as np\n"
        "def fleet(sched, tails, sys_prompt):\n"
        "    for i, tail in enumerate(tails):\n"
        "        sched.submit(Request(rid=str(i),\n"
        "            prompt=np.concatenate([sys_prompt, tail])))\n")
    assert "RLT309" in rules_of(fs)


def test_rlt309_assigned_prompt_and_addition_forms_fire():
    # the prompt built on its own line, and the list-concatenation
    # spelling — both still a constant prefix per request
    fs = lint(
        "import numpy as np\n"
        "def fleet(sched, tails, sys_prompt):\n"
        "    for tail in tails:\n"
        "        prompt = np.concatenate([sys_prompt, tail])\n"
        "        sched.submit(Request(rid='x', prompt=prompt))\n")
    assert "RLT309" in rules_of(fs)
    fs = lint(
        "def fleet(driver, tails, sys_tokens):\n"
        "    for tail in tails:\n"
        "        driver.submit(Request(rid='x',\n"
        "                              prompt=sys_tokens + tail))\n")
    assert "RLT309" in rules_of(fs)


def test_rlt309_quiet_when_prefix_cache_armed():
    # prefix_cache=True anywhere in the file sanctions the loop — the
    # cache prefills the common prefix once, the loop is intended usage
    fs = lint(
        "import numpy as np\n"
        "def fleet(engine, tails, sys_prompt):\n"
        "    sched = Scheduler(engine, prefix_cache=True)\n"
        "    for i, tail in enumerate(tails):\n"
        "        sched.submit(Request(rid=str(i),\n"
        "            prompt=np.concatenate([sys_prompt, tail])))\n")
    assert "RLT309" not in rules_of(fs)


def test_rlt309_quiet_on_variant_prefix_and_plain_prompts():
    # a prefix that changes per iteration shares nothing; a prompt
    # submitted as-is concatenates nothing
    fs = lint(
        "import numpy as np\n"
        "def fleet(sched, pairs):\n"
        "    for head, tail in pairs:\n"
        "        sched.submit(Request(rid='x',\n"
        "            prompt=np.concatenate([head, tail])))\n")
    assert "RLT309" not in rules_of(fs)
    fs = lint(
        "def fleet(sched, prompts):\n"
        "    for i, p in enumerate(prompts):\n"
        "        sched.submit(Request(rid=str(i), prompt=p))\n")
    assert "RLT309" not in rules_of(fs)


def test_rlt309_quiet_in_traced_code():
    # inside jit there is no scheduler to submit to — same scope rule
    # as the other serve-loop lints
    fs = lint(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def step(sched_like, tails, sys_prompt):\n"
        "    for tail in tails:\n"
        "        sched_like.submit(Request(rid='x',\n"
        "            prompt=jnp.concatenate([sys_prompt, tail])))\n"
        "    return tails\n")
    assert "RLT309" not in rules_of(fs)


def test_rlt309_suppressible():
    fs = lint(
        "import numpy as np\n"
        "def fleet(sched, tails, sys_prompt):\n"
        "    for tail in tails:\n"
        "        sched.submit(  # rlt: disable=RLT309\n"
        "            Request(rid='x',\n"
        "                    prompt=np.concatenate([sys_prompt, "
        "tail])))\n")
    assert "RLT309" not in rules_of(fs)


# ---- RLT505 silent request drop (docs/SERVING.md ---------------------------
# ---- "traffic & SLO classes") ----------------------------------------------


def test_rlt505_except_pass_around_submit_fires():
    # the request vanishes with no terminal status, no shed record
    fs = lint(
        "def pump(driver, reqs):\n"
        "    for r in reqs:\n"
        "        try:\n"
        "            driver.submit(r)\n"
        "        except Exception:\n"
        "            pass\n")
    assert "RLT505" in rules_of(fs)


def test_rlt505_bare_except_continue_around_enqueue_fires():
    fs = lint(
        "def pump(sched, reqs):\n"
        "    for r in reqs:\n"
        "        try:\n"
        "            sched.enqueue(r, 0)\n"
        "        except:\n"
        "            continue\n")
    assert "RLT505" in rules_of(fs)


def test_rlt505_handled_submit_quiet():
    # recording a terminal outcome (or re-raising) is the contract
    fs = lint(
        "def pump(driver, reqs, meta):\n"
        "    for r in reqs:\n"
        "        try:\n"
        "            driver.submit(r)\n"
        "        except Exception as exc:\n"
        "            meta[r.rid] = {'finish_reason': 'error',\n"
        "                           'error': str(exc)}\n")
    assert "RLT505" not in rules_of(fs)


def test_rlt505_narrow_except_quiet():
    # a typed, expected exception is a decision, not a swallow
    fs = lint(
        "def pump(driver, req):\n"
        "    try:\n"
        "        driver.submit(req)\n"
        "    except ValueError:\n"
        "        pass\n")
    assert "RLT505" not in rules_of(fs)


def test_rlt505_bare_take_sheds_fires():
    # records produced and immediately discarded
    fs = lint(
        "def tick(sched):\n"
        "    sched.tick()\n"
        "    sched.take_sheds()\n")
    assert "RLT505" in rules_of(fs)


def test_rlt505_consumed_take_sheds_quiet():
    fs = lint(
        "def tick(sched, meta):\n"
        "    sched.tick()\n"
        "    for rec in sched.take_sheds():\n"
        "        meta[rec['rid']] = {'finish_reason': 'shed', **rec}\n")
    assert "RLT505" not in rules_of(fs)


def test_rlt505_buffer_clear_fires():
    fs = lint(
        "def reset(sched):\n"
        "    sched.last_sheds.clear()\n"
        "    sched.last_preemptions.clear()\n")
    assert "RLT505" in rules_of(fs)


def test_rlt505_quiet_in_traced_code():
    # under jit there is no scheduler to drop from — same scope rule
    # as the other serve-loop lints
    fs = lint(
        "import jax\n"
        "@jax.jit\n"
        "def step(sched_like, x):\n"
        "    try:\n"
        "        sched_like.submit(x)\n"
        "    except Exception:\n"
        "        pass\n"
        "    return x\n")
    assert "RLT505" not in rules_of(fs)


def test_rlt505_suppressible():
    # the lockstep follower discards on purpose: the leader owns
    # shed emission (serve/driver.py _replica_session_main)
    fs = lint(
        "def follower(sched):\n"
        "    sched.take_sheds()  # rlt: disable=RLT505\n")
    assert "RLT505" not in rules_of(fs)
