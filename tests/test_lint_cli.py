"""`python -m ray_lightning_tpu lint` CLI contract tests (ISSUE-1
acceptance): exit 0 on the bundled models with no TPU present, exit
non-zero — with rule ids in --json output — on a fixture module carrying
a mesh-axis typo and a training_step host transfer. One subprocess smoke
proves the real `python -m` path; the rest run in-process."""
import json
import os
import subprocess
import sys

from ray_lightning_tpu.__main__ import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODELS = os.path.join(REPO, "ray_lightning_tpu", "models")

BAD_FIXTURE = """\
import numpy as np
from jax.sharding import PartitionSpec as P

SPECS = {"w": P("fdsp", None)}       # mesh-axis typo (RLT101)


class FixtureModule:
    def training_step(self, params, batch, rng):
        loss = (params["w"] * batch["x"]).sum()
        host = np.asarray(loss)      # host transfer (RLT201)
        return loss
"""


def test_lint_bundled_models_exit_0(capsys):
    assert main(["lint", MODELS]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_lint_bad_fixture_nonzero_with_rule_ids_json(tmp_path, capsys):
    bad = tmp_path / "bad_module.py"
    bad.write_text(BAD_FIXTURE)
    rc = main(["lint", str(bad), "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 1
    assert report["ok"] is False
    rules = {f["rule"] for f in report["findings"]}
    assert {"RLT101", "RLT201"} <= rules
    sym = {f.get("symbol") for f in report["findings"]}
    assert "FixtureModule.training_step" in sym


def test_lint_json_before_subcommand(tmp_path, capsys):
    """--json BEFORE the subcommand must work (same namespace-sharing
    contract as the plan subparser)."""
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_FIXTURE)
    rc = main(["--json", "lint", str(bad)])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and report["ok"] is False


def test_lint_severity_and_fail_on_gates(tmp_path, capsys):
    warn_only = tmp_path / "warn.py"
    warn_only.write_text(
        "class M:\n"
        "    def training_step(self, params, batch, rng):\n"
        "        print('x')\n"
        "        return 0\n")
    # default gate (error): warnings are reported but don't fail
    rc = main(["lint", str(warn_only), "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and report["counts"]["warning"] == 1
    # tightened gate fails on the warning
    assert main(["lint", str(warn_only), "--fail-on", "warning"]) == 1
    capsys.readouterr()
    # severity filter hides it entirely
    rc = main(["lint", str(warn_only), "--severity", "error", "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and report["findings"] == []


def test_lint_disable_drops_rule(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_FIXTURE)
    rc = main(["lint", str(bad), "--disable", "RLT101,RLT201", "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and report["findings"] == []


def test_lint_dotted_module_target(capsys):
    assert main(["lint", "ray_lightning_tpu.models.llama"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_lint_missing_target_exit_2(capsys):
    rc = main(["lint", "no/such/path.py", "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == 2 and "no such" in report["error"]


def test_lint_list_rules(capsys):
    assert main(["lint", "--list-rules", "--json"]) == 0
    rules = json.loads(capsys.readouterr().out.strip())
    assert "RLT101" in rules and "RLT201" in rules


def test_lint_cli_subprocess_smoke(tmp_path):
    """The real `python -m ray_lightning_tpu lint --json` path, on CPU
    with JAX_PLATFORMS pinned — the acceptance-criteria invocation."""
    bad = tmp_path / "bad_module.py"
    bad.write_text(BAD_FIXTURE)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    ok = subprocess.run(
        [sys.executable, "-m", "ray_lightning_tpu", "lint", MODELS,
         "--json"],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    assert ok.returncode == 0, ok.stderr[-2000:]
    assert json.loads(ok.stdout.strip())["ok"] is True

    fail = subprocess.run(
        [sys.executable, "-m", "ray_lightning_tpu", "lint", str(bad),
         "--json"],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    assert fail.returncode == 1, fail.stderr[-2000:]
    report = json.loads(fail.stdout.strip())
    assert {"RLT101", "RLT201"} <= {f["rule"] for f in report["findings"]}
