"""Flagship model: tiny-Llama end-to-end on sharded meshes."""
import dataclasses

import jax
import numpy as np
import pytest

from ray_lightning_tpu import (
    DataLoader,
    DataParallel,
    FSDP,
    ShardedMesh,
    Trainer,
)
from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
    llama_param_specs,
)


def _data(cfg, n=64, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"tokens": rng.integers(
        0, cfg.vocab_size, (n, seq + 1)).astype(np.int32)}


def _fit(strategy, cfg=None, max_epochs=1, **tkw):
    cfg = cfg or LlamaConfig.tiny(use_flash=False)
    module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=50)
    data = _data(cfg)
    train = DataLoader(data, batch_size=16, shuffle=True)
    val = DataLoader(data, batch_size=16)
    trainer = Trainer(strategy=strategy, max_epochs=max_epochs,
                      enable_progress_bar=False, enable_checkpointing=False,
                      **tkw)
    trainer.fit(module, train, val)
    return trainer, module


class TestLlamaForward:
    def test_logits_shape_and_finite(self):
        cfg = LlamaConfig.tiny(use_flash=False)
        model = Llama(cfg)
        tokens = np.zeros((2, 16), dtype=np.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert bool(np.isfinite(np.asarray(logits)).all())

    def test_scan_matches_unrolled(self):
        """Numerical equivalence: unrolled per-layer weights restacked into
        the scan layout must give identical logits."""
        import jax.numpy as jnp

        base = dict(vocab_size=64, dim=32, n_layers=3, n_heads=2,
                    n_kv_heads=1, hidden_dim=64, max_seq_len=64,
                    remat=False, use_flash=False, dtype=jnp.float32)
        tokens = np.arange(32, dtype=np.int32).reshape(2, 16) % 64

        cfg_u = LlamaConfig(**base, scan_layers=False)
        model_u = Llama(cfg_u)
        params_u = model_u.init(jax.random.key(0), tokens)["params"]
        out_u = model_u.apply({"params": params_u}, tokens)

        # restack layer_i subtrees along a leading layer axis
        layer_trees = [params_u[f"layer_{i}"] for i in range(base["n_layers"])]
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *layer_trees)
        params_s = {k: v for k, v in params_u.items()
                    if not k.startswith("layer_")}
        params_s["layers"] = stacked

        cfg_s = LlamaConfig(**base, scan_layers=True)
        out_s = Llama(cfg_s).apply({"params": params_s}, tokens)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u),
                                   atol=2e-5)

    @pytest.mark.slow  # 3 full forward compiles of the same model
    def test_remat_policies_equivalent(self):
        """remat off / full / dots-saveable / attn_out-saveable are
        schedule choices, not math: losses and grads must agree."""
        import jax.numpy as jnp

        tokens = {"tokens": (np.arange(34, dtype=np.int32).reshape(2, 17)
                             % 64)}
        outs = []
        for remat, policy in ((False, "nothing"), (True, "nothing"),
                              (True, "dots"), (True, "attn_out")):
            cfg = LlamaConfig(
                vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=1,
                hidden_dim=64, max_seq_len=64, use_flash=False,
                dtype=jnp.float32, remat=remat, remat_policy=policy)
            m = LlamaModule(cfg)
            m.setup()
            params = m.init_params(jax.random.key(0), tokens)
            i, t, msk = m._split(tokens)
            loss, grads = jax.value_and_grad(
                lambda p: m._loss(p, i, t, msk))(params)
            outs.append((np.asarray(loss), grads))
        for loss, grads in outs[1:]:
            np.testing.assert_allclose(loss, outs[0][0], rtol=1e-5)
            for a, b in zip(jax.tree.leaves(grads),
                            jax.tree.leaves(outs[0][1])):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-6)

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        cfg = LlamaConfig.tiny(use_flash=False)
        model = Llama(cfg)
        t1 = np.zeros((1, 16), dtype=np.int32)
        t2 = t1.copy()
        t2[0, -1] = 5
        params = model.init(jax.random.key(0), t1)["params"]
        l1 = model.apply({"params": params}, t1)
        l2 = model.apply({"params": params}, t2)
        np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
        assert not np.allclose(l1[:, -1], l2[:, -1], atol=1e-5)


class TestLlamaTraining:
    def test_dp_loss_decreases(self):
        from ray_lightning_tpu import Callback

        class FirstLoss(Callback):
            value = None

            def on_train_batch_end(self, trainer, module, metrics, batch_idx):
                if self.value is None and "loss" in metrics:
                    self.value = float(metrics["loss"])

        first = FirstLoss()
        trainer, _ = _fit(DataParallel(num_workers=4), max_epochs=3,
                          callbacks=[first], log_every_n_steps=1)
        final = float(trainer.callback_metrics["val_loss"])
        assert first.value is not None
        # A genuine decrease from the recorded step-1 loss — not just
        # "below some constant" (chance level for vocab 256 is ln(256)≈5.55).
        assert final < first.value - 0.2, (first.value, final)

    @pytest.mark.slow  # two full compiles with the interpret-mode kernel
    def test_remat_attn_out_with_pallas_flash(self, monkeypatch):
        """The production combination — scanned layers + nn.remat with
        remat_policy='attn_out' + the pallas flash kernel (whose
        custom_vjp is defined with optimize_remat=True, the mechanism
        the policy saves through) — must match the no-remat gradients.
        RLT_PALLAS=1 runs the real kernel in interpret mode on CPU;
        shapes sized to pass the kernel's tiling gate (head_dim 64,
        S multiple of 128)."""
        import jax.numpy as jnp

        monkeypatch.setenv("RLT_PALLAS", "1")
        tokens = {"tokens": (np.arange(2 * 129, dtype=np.int32)
                             .reshape(2, 129) % 64)}
        outs = []
        for remat in (False, True):
            cfg = LlamaConfig(
                vocab_size=64, dim=256, n_layers=2, n_heads=4,
                n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                use_flash=True, dtype=jnp.float32, remat=remat,
                remat_policy="attn_out" if remat else "nothing")
            m = LlamaModule(cfg)
            m.setup()
            params = m.init_params(jax.random.key(0), tokens)
            i, t, msk = m._split(tokens)
            loss, grads = jax.value_and_grad(
                lambda p: m._loss(p, i, t, msk))(params)
            outs.append((np.asarray(loss), grads))
        np.testing.assert_allclose(outs[1][0], outs[0][0], rtol=1e-5)
        for a, b in zip(jax.tree.leaves(outs[1][1]),
                        jax.tree.leaves(outs[0][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=2e-5)

    def test_mu_dtype_bf16_trains_and_halves_mu(self):
        """mu_dtype=bfloat16: the Adam first moment is stored bf16 (the
        memory lever that buys batch on a capped chip), nu stays f32,
        and training still decreases the loss."""
        import jax.numpy as jnp
        import optax

        from ray_lightning_tpu import Callback

        class FirstLoss(Callback):
            value = None

            def on_train_batch_end(self, trainer, module, metrics,
                                   batch_idx):
                if self.value is None and "loss" in metrics:
                    self.value = float(metrics["loss"])

        first = FirstLoss()
        cfg = LlamaConfig.tiny(use_flash=False)
        module = LlamaModule(cfg, lr=1e-3, warmup_steps=1, total_steps=50,
                             mu_dtype=jnp.bfloat16)
        data = _data(cfg)
        trainer = Trainer(strategy=DataParallel(num_workers=4),
                          max_epochs=2, enable_progress_bar=False,
                          enable_checkpointing=False, callbacks=[first],
                          log_every_n_steps=1)
        trainer.fit(module, DataLoader(data, batch_size=16, shuffle=True),
                    DataLoader(data, batch_size=16))
        adam = [s for s in jax.tree.leaves(
            trainer.state.opt_state,
            is_leaf=lambda s: isinstance(s, optax.ScaleByAdamState))
            if isinstance(s, optax.ScaleByAdamState)]
        assert adam, "no ScaleByAdamState found in opt_state"
        for s in adam:
            assert all(m.dtype == jnp.bfloat16
                       for m in jax.tree.leaves(s.mu))
            assert all(n.dtype == jnp.float32
                       for n in jax.tree.leaves(s.nu))
        # a genuine decrease from the recorded step-1 loss (the adjacent
        # dp test's discipline): the bf16 moment must not stop learning,
        # not merely avoid divergence
        final = float(trainer.callback_metrics["val_loss"])
        assert first.value is not None and final < first.value - 0.2, (
            first.value, final)

    def test_fsdp_sharding_applied(self, devices8):
        trainer, module = _fit(FSDP(min_shard_size=1))
        leaf = module.params["layers"]["w_gate_up"]["kernel"]
        assert "fsdp" in str(leaf.sharding.spec)

    def test_3d_mesh(self, devices8):
        trainer, module = _fit(ShardedMesh(data=2, fsdp=2, tensor=2,
                                           min_shard_size=1))
        spec = str(module.params["layers"]["wqkv"]["kernel"].sharding.spec)
        assert "tensor" in spec and "fsdp" in spec

    def test_param_specs_cover_all_leaves(self):
        cfg = LlamaConfig.tiny()
        module = LlamaModule(cfg)
        module.setup()
        tokens = np.zeros((1, 8), dtype=np.int32)
        params = module.init_params(jax.random.key(0), {"tokens": tokens})
        specs = llama_param_specs(cfg)
        from ray_lightning_tpu.utils.pytree import named_leaves

        paths = {p for p, _ in named_leaves(params)}
        assert paths == set(specs.keys())

    def test_tied_embeddings(self):
        cfg = LlamaConfig.tiny(tie_embeddings=True, use_flash=False)
        module = LlamaModule(cfg)
        module.setup()
        tokens = np.zeros((1, 8), dtype=np.int32)
        params = module.init_params(jax.random.key(0), {"tokens": tokens})
        assert "lm_head" not in params
        specs = llama_param_specs(cfg)
        from ray_lightning_tpu.utils.pytree import named_leaves

        assert {p for p, _ in named_leaves(params)} == set(specs.keys())

    def test_grad_accumulation(self):
        cfg = LlamaConfig.tiny(use_flash=False)
        trainer, _ = _fit(DataParallel(num_workers=2), cfg,
                          accumulate_grad_batches=2)
        assert trainer.global_step > 0

    def test_num_params(self):
        cfg = LlamaConfig.tiny()
        module = LlamaModule(cfg)
        module.setup()
        tokens = np.zeros((1, 8), dtype=np.int32)
        module.params = module.init_params(jax.random.key(0),
                                           {"tokens": tokens})
        n = module.num_params()
        # embed 256*64 + head 64*256 + final 64 + 2 layers of
        # (wqkv 64*(4+2+2)*16=8192, wo 64*64, gate_up 64*256, down 128*64, norms 128)
        assert n > 50_000


class TestGraftEntry:
    @pytest.mark.slow  # the already-initialized-backend branch of the
    # dryrun; the self-provisioning branch stays in the fast suite
    # (tests/test_graft_entry.py), which keeps the driver contract covered
    def test_dryrun_multichip(self, devices8):
        import importlib.util, os

        spec = importlib.util.spec_from_file_location(
            "__graft_entry__",
            os.path.join(os.path.dirname(os.path.dirname(__file__)),
                         "__graft_entry__.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)
