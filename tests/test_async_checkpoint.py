"""Async checkpointing (checkpoint/io.py background commit): eager
finalize, snapshot isolation from donation, stall accounting, and
atomicity under an injected kill mid-save with supervised-style resume
reproducing bitwise-identical parameters."""
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import DataLoader, ModelCheckpoint, SingleDevice, Trainer
from ray_lightning_tpu.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
    wait_for_checkpoints,
)
from ray_lightning_tpu.checkpoint.io import device_snapshot, io_stats, read_meta

from tests.utils import BoringModel, random_dataset

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestAsyncCommit:
    def test_background_finalize_without_join(self, tmp_path):
        """meta.json + digest must be published by the FINALIZER thread
        once the state write commits — no wait_for_checkpoints() needed
        (a crash between checkpoint cadences must not cost a fully
        written checkpoint its completeness marker)."""
        path = str(tmp_path / "ck")
        save_checkpoint(path, {"w": jnp.arange(1024.0)}, {"epoch": 7},
                        block=False)
        deadline = time.time() + 30
        meta = os.path.join(path, "meta.json")
        while not os.path.exists(meta) and time.time() < deadline:
            time.sleep(0.02)
        assert os.path.exists(meta), "finalizer never published meta.json"
        ok, reason = verify_checkpoint(path)
        assert ok, reason
        assert read_meta(path)["epoch"] == 7
        wait_for_checkpoints()  # idempotent after eager finalize

    def test_async_state_matches_blocking(self, tmp_path):
        state = {"w": jnp.asarray(np.random.default_rng(0)
                                  .standard_normal(512, dtype=np.float32))}
        a = str(tmp_path / "a")
        b = str(tmp_path / "b")
        save_checkpoint(a, state, {}, block=True)
        save_checkpoint(b, state, {}, block=False)
        wait_for_checkpoints()
        ra = restore_checkpoint(a, state)
        rb = restore_checkpoint(b, state)
        np.testing.assert_array_equal(np.asarray(ra["w"]),
                                      np.asarray(rb["w"]))
        for p in (a, b):
            ok, reason = verify_checkpoint(p)
            assert ok, (p, reason)

    def test_snapshot_survives_donation(self, tmp_path):
        """The async path snapshots via the no-donation identity: the
        caller may donate the live buffers into a jitted step immediately
        after save returns, and the checkpoint still holds the
        at-save-time values."""
        w0 = jnp.arange(4096, dtype=jnp.float32)
        state = {"w": w0}
        path = str(tmp_path / "ck")
        save_checkpoint(path, state, {}, block=False)
        # donate + overwrite the live buffer while the write streams
        bump = jax.jit(lambda t: jax.tree.map(lambda x: x * 0 - 1.0, t),
                       donate_argnums=(0,))
        state = bump(state)
        jax.block_until_ready(state)
        wait_for_checkpoints()
        restored = restore_checkpoint(path, {"w": jnp.zeros(4096)})
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(4096, dtype=np.float32))

    def test_stall_accounting(self, tmp_path):
        before = io_stats()["ckpt_async_saves"]
        big = {"w": jnp.ones((256, 256))}
        save_checkpoint(str(tmp_path / "a"), big, {}, block=False)
        save_checkpoint(str(tmp_path / "b"), big, {}, block=False)
        wait_for_checkpoints()
        stats = io_stats()
        assert stats["ckpt_async_saves"] >= before + 2
        assert stats["ckpt_stall_s"] >= 0.0

    def test_device_snapshot_is_fresh_buffers(self):
        x = jnp.ones((16,))
        snap = device_snapshot({"x": x})
        assert snap["x"].unsafe_buffer_pointer() != x.unsafe_buffer_pointer()
        np.testing.assert_array_equal(np.asarray(snap["x"]), np.asarray(x))


def _run_to_completion(root, data, seed, max_steps, ckpt_path=None):
    trainer = Trainer(
        strategy=SingleDevice(), max_epochs=50, max_steps=max_steps,
        default_root_dir=str(root), enable_checkpointing=False,
        enable_progress_bar=False, seed=seed,
    )
    module = BoringModel()
    trainer.fit(module, DataLoader(data, batch_size=32),
                ckpt_path=ckpt_path)
    return trainer, module


@pytest.mark.slow  # subprocess + SIGKILL mid-write
def test_kill_mid_async_save_atomicity_and_bitwise_resume(tmp_path):
    """The acceptance matrix for async checkpoints: a SIGKILL landing
    while an async save streams (injected via resilience/faults.py on the
    exact save step) must leave only checkpoints that either VERIFY or
    are skipped by latest_checkpoint — and resuming from the survivor
    reproduces bitwise-identical final params vs an uninterrupted run."""
    ckdir = tmp_path / "ck"
    script = f"""
import os, sys
sys.path.insert(0, {_REPO!r})
from tests.utils import BoringModel, random_dataset
from ray_lightning_tpu import DataLoader, ModelCheckpoint, SingleDevice, Trainer
from ray_lightning_tpu.resilience.faults import maybe_install_faults

data = random_dataset(n=192, seed=5)
cb = ModelCheckpoint(dirpath={str(ckdir)!r}, every_n_train_steps=2,
                     save_top_k=-1, async_save=True)
trainer = Trainer(strategy=SingleDevice(), max_epochs=50, max_steps=40,
                  default_root_dir={str(tmp_path / "killed")!r},
                  enable_checkpointing=False, enable_progress_bar=False,
                  seed=9, callbacks=[cb])
maybe_install_faults(trainer)
trainer.fit(BoringModel(), DataLoader(data, batch_size=32))
print("SHOULD NOT REACH HERE")
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # kill on the same batch-end the step-6 async save enqueues:
           # the injector callback runs right after ModelCheckpoint's
           "RLT_FAULTS": "kill:rank=0,step=6"}
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                proc.stdout, proc.stderr)
    assert "SHOULD NOT REACH HERE" not in proc.stdout

    # every surviving candidate is either complete+verified or skipped
    survivors = sorted(os.listdir(ckdir)) if ckdir.is_dir() else []
    assert survivors, "no checkpoint dirs at all — saves never ran"
    verdicts = {d: verify_checkpoint(str(ckdir / d)) for d in survivors}
    best = latest_checkpoint(str(ckdir))
    assert best is not None, f"no valid checkpoint survived: {verdicts}"
    ok, reason = verify_checkpoint(best)
    assert ok, reason
    resumed_meta = read_meta(best)
    assert 0 < int(resumed_meta["global_step"]) <= 6

    # bitwise acceptance: resume the killed run to 40 steps and compare
    # against one uninterrupted 40-step run with the same seed/data
    data = random_dataset(n=192, seed=5)
    _, m_resumed = _run_to_completion(tmp_path / "resume", data, seed=9,
                                      max_steps=40, ckpt_path=best)
    _, m_full = _run_to_completion(tmp_path / "full", data, seed=9,
                                   max_steps=40)
    for a, b in zip(jax.tree.leaves(jax.device_get(m_resumed.params)),
                    jax.tree.leaves(jax.device_get(m_full.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # supervised single-process run with async cadence
def test_supervised_resume_from_async_checkpoint_bitwise(tmp_path):
    """Supervisor-level acceptance: a supervised fit whose step-cadence
    checkpoints are ASYNC, killed by an injected fault and auto-resumed,
    must converge to bitwise-identical params vs an uninterrupted run."""
    from ray_lightning_tpu import ResilienceConfig, fit_supervised
    from ray_lightning_tpu.resilience import RetryPolicy

    def module_factory():
        return BoringModel()

    def data_factory():
        return DataLoader(random_dataset(n=192, seed=5), batch_size=32)

    def trainer_factory():
        return Trainer(strategy=SingleDevice(), max_epochs=50,
                       max_steps=24, enable_checkpointing=False,
                       enable_progress_bar=False, seed=9)

    cfg = ResilienceConfig(
        checkpoint_dir=str(tmp_path / "sup_ck"),
        policy=RetryPolicy(max_restarts=2, backoff_base_s=0.2, jitter=0.0),
        save_every_n_steps=2, async_save=True,
        faults="kill:rank=0,step=7",
        stall_timeout_s=0.0,
    )
    module = BoringModel()
    result = fit_supervised(
        module_factory, trainer_factory, data_factory, module=module,
        num_processes=1, platform="cpu", num_cpu_devices_per_process=1,
        timeout=420, log_dir=str(tmp_path / "logs"), resilience=cfg)
    assert result.restarts >= 1
    assert module.params is not None

    _, m_full = _run_to_completion(tmp_path / "full",
                                   random_dataset(n=192, seed=5),
                                   seed=9, max_steps=24)
    for a, b in zip(jax.tree.leaves(jax.device_get(module.params)),
                    jax.tree.leaves(jax.device_get(m_full.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
