"""MoE / expert-parallel tests: routing math, capacity behavior, and
end-to-end training with the expert mesh axis active."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu import DataLoader, ShardedMesh, Trainer
from ray_lightning_tpu.models.moe import MoEClassifierModule, MoEMLP


def _apply(layer, x, seed=0):
    params = layer.init(jax.random.key(seed), x)["params"]
    return params, layer.apply({"params": params}, x)


def test_single_expert_equals_dense_swiglu():
    """E=1, k=1, ample capacity: the MoE must reduce to one plain SwiGLU
    FFN — same math, dispatch is the identity."""
    x = jax.random.normal(jax.random.key(0), (2, 8, 16), jnp.float32)
    layer = MoEMLP(n_experts=1, hidden_dim=32, top_k=1,
                   capacity_factor=2.0, dtype=jnp.float32)
    params, (y, aux) = _apply(layer, x)
    w_gate_up = params["w_gate_up"][0]
    w_down = params["w_down"][0]
    h = x.reshape(-1, 16) @ w_gate_up
    gate, up = jnp.split(h, 2, axis=-1)
    ref = (jax.nn.silu(gate) * up) @ w_down
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)
    assert float(aux) == 1.0  # one expert carries everything


def test_combine_weights_and_capacity():
    x = jax.random.normal(jax.random.key(1), (4, 16, 32), jnp.float32)
    layer = MoEMLP(n_experts=4, hidden_dim=64, top_k=2,
                   capacity_factor=1.5, dtype=jnp.float32)
    _, (y, aux) = _apply(layer, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert 0.5 < float(aux) < 4.0  # load-balance loss is O(1)

    # starving capacity drops tokens but never produces NaNs
    tight = MoEMLP(n_experts=4, hidden_dim=64, top_k=2,
                   capacity_factor=0.1, dtype=jnp.float32)
    _, (y2, _) = _apply(tight, x)
    assert np.isfinite(np.asarray(y2)).all()
    # with almost no capacity most outputs are zero (dropped tokens)
    assert (np.abs(np.asarray(y2)) < 1e-6).mean() > 0.5


def test_moe_trains_expert_parallel(devices8, tmp_path):
    """End-to-end on a data×expert×tensor mesh: the expert axis really
    shards the stacked expert weights."""
    rng = np.random.default_rng(0)
    n, C = 256, 4
    y = rng.integers(0, C, n).astype(np.int32)
    centers = rng.standard_normal((C, 32)).astype(np.float32) * 3
    data = {"x": centers[y] + rng.standard_normal((n, 32)).astype(np.float32),
            "y": y}

    module = MoEClassifierModule(dim=64, n_experts=4, hidden_dim=128,
                                 num_classes=C, lr=3e-3)
    trainer = Trainer(
        strategy=ShardedMesh(data=2, expert=2, tensor=2,
                             devices=devices8, min_shard_size=1),
        max_epochs=6,
        default_root_dir=str(tmp_path),
        enable_checkpointing=False, enable_progress_bar=False,
    )
    trainer.fit(module, DataLoader(data, batch_size=64, shuffle=True),
                DataLoader(data, batch_size=64))
    assert float(trainer.callback_metrics["val_acc"]) >= 0.5
    # the stacked expert weights are actually sharded over `expert`
    leaf = trainer.state.params["moe"]["w_gate_up"]
    spec = leaf.sharding.spec
    assert "expert" in str(spec), spec
