"""SLO watch rules + automatic incident capture (telemetry/watch.py,
telemetry/incidents.py, docs/OBSERVABILITY.md "watch rules &
incidents"): rule/threshold/sustain/burn-window semantics, episode
fire-once, metric surfaces over real persisted fixtures, the incident
record contract (evidence + timeline excerpt + capture actions), the
controller/driver wiring (forced flight persist), the watch-off
program pin, lint rule RLT503, and the bench/bench_gate incident
surfaces."""
from __future__ import annotations

import dataclasses
import json
import os
import time

import pytest

from ray_lightning_tpu.telemetry import watch as watch_mod
from ray_lightning_tpu.telemetry.incidents import (
    append_incident,
    capture_evidence,
    read_incidents,
)
from ray_lightning_tpu.telemetry.watch import (
    BUILTIN_RULES,
    MetricSurfaces,
    WatchConfig,
    WatchEngine,
    WatchRule,
)


def _tdir(run_dir: str) -> str:
    return os.path.join(run_dir, "telemetry")


# ------------------------------------------------------------- rule units


def test_rule_validation():
    with pytest.raises(ValueError, match="unknown op"):
        WatchRule("r", "load.pressure", "~", 1.0)
    with pytest.raises(ValueError, match="sustain"):
        WatchRule("r", "load.pressure", ">", 1.0, sustain=0)
    with pytest.raises(ValueError, match="could never fire"):
        WatchRule("r", "load.pressure", ">", 1.0, sustain=3, window=2)
    with pytest.raises(ValueError, match="severity"):
        WatchRule("r", "load.pressure", ">", 1.0, severity="meh")
    r = WatchRule("r", "load.pressure", ">=", 2.0)
    assert r.breached(2.0) and not r.breached(1.9)


def test_watch_config_coerce():
    assert WatchConfig.coerce(None) is None
    assert WatchConfig.coerce(False) is None
    assert WatchConfig.coerce(True).rules == BUILTIN_RULES
    rules = (WatchRule("r", "load.pressure", ">", 1.0),)
    assert WatchConfig.coerce(rules).rules == rules
    cfg = WatchConfig(excerpt_events=3)
    assert WatchConfig.coerce(cfg) is cfg
    with pytest.raises(TypeError):
        WatchConfig.coerce("yes")


class _ScriptedSurfaces:
    """MetricSurfaces stand-in: scripted values per selector, popped
    one per poll."""

    script: dict = {}

    def __init__(self, run_dir, tail_bytes=0, telemetry_dir=None):
        pass

    def value(self, selector):
        seq = self.script.get(selector)
        if not seq:
            return None
        return seq.pop(0)

    def evidence(self, selector):
        return {"scripted": True}


@pytest.fixture
def scripted(monkeypatch, tmp_path):
    def make(script):
        _ScriptedSurfaces.script = {k: list(v)
                                    for k, v in script.items()}
        monkeypatch.setattr(watch_mod, "MetricSurfaces",
                            _ScriptedSurfaces)
        return str(tmp_path)
    return make


def test_sustain_consecutive(scripted):
    run = scripted({"load.pressure": [3.0, 1.0, 3.0, 3.0, 3.0]})
    rule = WatchRule("qp", "load.pressure", ">", 2.0, sustain=2)
    eng = WatchEngine(run, WatchConfig(rules=(rule,), capture=False))
    # breach, clear, breach, breach(sustained -> fire), breach(open)
    assert [len(eng.poll()) for _ in range(5)] == [0, 0, 0, 1, 0]
    assert eng.fired == 1


def test_burn_rate_window(scripted):
    run = scripted({"load.pressure": [3.0, 1.0, 3.0]})
    rule = WatchRule("qp", "load.pressure", ">", 2.0, sustain=2,
                     window=4)
    eng = WatchEngine(run, WatchConfig(rules=(rule,), capture=False))
    # 2 breaches anywhere in the last 4 evaluations fire — NOT
    # consecutive (the K-in-window burn-rate form)
    assert [len(eng.poll()) for _ in range(3)] == [0, 0, 1]


def test_episode_fire_once_and_rearm(scripted):
    run = scripted({"load.pressure": [3.0, 3.0, 3.0, 1.0, 3.0]})
    rule = WatchRule("qp", "load.pressure", ">", 2.0)
    eng = WatchEngine(run, WatchConfig(rules=(rule,), capture=False))
    fired = [len(eng.poll()) for _ in range(5)]
    # one incident per EPISODE: sustained breach fires once; clearing
    # re-arms; the next breach is a new episode
    assert fired == [1, 0, 0, 0, 1]


def test_none_signal_holds_state(scripted):
    run = scripted({"load.pressure": [3.0, None, 3.0]})
    rule = WatchRule("qp", "load.pressure", ">", 2.0, sustain=2)
    eng = WatchEngine(run, WatchConfig(rules=(rule,), capture=False))
    # None neither clears nor counts: the streak survives the gap
    assert [len(eng.poll()) for _ in range(3)] == [0, 0, 1]


# ------------------------------------------------ metric surfaces (real)


def _serving_fixture(run_dir, ttft=(0.01, 0.02, 3.0)):
    from ray_lightning_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry(_tdir(run_dir), replica=0,
                          flush_every_n_ticks=1)
    for v in ttft:
        reg.observe("ttft_s", v)
    reg.gauge("queue_depth", 8.0)
    reg.gauge("decoding_slots", 2.0)
    reg.gauge("free_slots", 0.0)
    reg.tick_end()
    reg.close()


def test_surface_serving_quantile(tmp_path):
    run = str(tmp_path)
    _serving_fixture(run)
    s = MetricSurfaces(run)
    p99 = s.value("serving.ttft_p99_s")
    assert p99 == pytest.approx(3.0, rel=0.25)
    assert s.value("serving.ttft_p50_s") < p99
    ev = s.evidence("serving.ttft_p99_s")
    assert ev["n"] == 3 and ev["sketch"]
    assert s.value("serving.nosuch_p99_s") is None


def test_surface_load(tmp_path):
    run = str(tmp_path)
    _serving_fixture(run)
    s = MetricSurfaces(run)
    assert s.value("load.queue_depth_p50") == 8.0
    assert s.value("load.pressure") == pytest.approx(8.0 / 2.0)
    assert "load_signal" in s.evidence("load.pressure")


def test_surface_goodput(tmp_path):
    from ray_lightning_tpu.telemetry.goodput import write_goodput

    run = str(tmp_path)
    write_goodput(_tdir(run), {
        "wall_s": 10.0, "goodput_fraction": 0.4,
        "buckets": {"backoff_s": 2.0},
        "events": {"restarts": 2}})
    s = MetricSurfaces(run)
    assert s.value("goodput.goodput_fraction") == 0.4
    assert s.value("goodput.backoff_s") == 2.0
    assert s.value("goodput.restarts") == 2.0
    assert MetricSurfaces(str(tmp_path / "none")).value(
        "goodput.goodput_fraction") is None


def test_surface_guard_from_ckpt_meta(tmp_path):
    run = str(tmp_path)
    for step, streak in ((10, 1), (20, 4)):
        d = os.path.join(run, f"step{step}")
        os.makedirs(os.path.join(d, "state"))
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump({"global_step": step, "blessed": streak < 3,
                       "guard": {"skipped_steps": streak,
                                 "streak": streak,
                                 "last_anomaly": step}}, f)
    s = MetricSurfaces(run)
    # the NEWEST checkpoint's counters win
    assert s.value("guard.streak") == 4.0
    assert s.value("guard.skipped_steps") == 4.0
    assert s.evidence("guard.streak")["guard"]["global_step"] == 20


def test_surface_restarts(tmp_path):
    run = str(tmp_path)
    os.makedirs(_tdir(run))
    for uid in ("100-0", "101-0", "102-0"):
        with open(os.path.join(_tdir(run),
                               f"ledger.rank0.{uid}.json"), "w") as f:
            json.dump({"version": "rlt-ledger-v1", "rank": 0}, f)
    with open(os.path.join(run, "flight.json"), "w") as f:
        json.dump({"version": "rlt-flight-v1",
                   "dumps": [{"replica": 0, "death": {}}]}, f)
    s = MetricSurfaces(run)
    # 3 attempts -> 2 restarts, + 1 serving replica death
    assert s.value("restarts.count") == 3.0
    assert s.value("restarts.replica_deaths") == 1.0


# -------------------------------------------- incidents + evidence hooks


def test_incident_fires_with_record_contract(tmp_path):
    run = str(tmp_path)
    _serving_fixture(run)   # p99 ~ 3s
    rule = next(r for r in BUILTIN_RULES if r.name == "ttft_p99")
    eng = WatchEngine(run, WatchConfig(rules=(rule,)))
    fired = eng.poll()
    assert [i["rule"] for i in fired] == ["ttft_p99"]
    assert eng.poll() == []   # episode stays open: no re-fire
    parsed = read_incidents(run)
    assert parsed["header"]["version"] == "rlt-incidents-v1"
    assert parsed["header"]["t0_wall"] > 0
    [inc] = parsed["incidents"]
    ev = inc["evidence"]
    assert ev["metric"] == "serving.ttft_p99_s"
    assert ev["value"] > rule.threshold and ev["sketch"]
    assert inc["severity"] == "page" and inc["window"]
    # the evidence hooks actuated: one profiler CAPTURE marker
    marker = inc["actions"]["profiler_marker"]
    assert os.path.exists(marker)
    assert os.path.basename(marker) == "CAPTURE"
    # timeline excerpt rides along (the metrics ticks at minimum)
    assert isinstance(inc["timeline_excerpt"], list)


def test_capture_marker_consumed_once(tmp_path):
    run = str(tmp_path)
    a1 = capture_evidence(run)
    assert os.path.exists(a1["profiler_marker"])
    a2 = capture_evidence(run)
    # an unconsumed marker from an earlier incident is left alone —
    # one marker = one profiler capture
    assert "profiler_marker" not in a2
    assert a2["profiler_marker_pending"] == a1["profiler_marker"]


def test_capture_forces_flight_persist(tmp_path):
    class _Drv:
        persisted = 0

        def force_flight_persist(self):
            self.persisted += 1
            return 2

    drv = _Drv()
    actions = capture_evidence(str(tmp_path), driver=drv)
    assert actions["flight_persisted"] == 2 and drv.persisted == 1

    class _Broken:
        def force_flight_persist(self):
            raise RuntimeError("dead")

    actions = capture_evidence(str(tmp_path), driver=_Broken())
    assert "flight_persist_error" in actions  # best-effort, no raise


def test_incident_ledger_append_and_garbage(tmp_path):
    run = str(tmp_path)
    append_incident(run, {"rule": "a", "severity": "warn", "wall": 1.0})
    append_incident(run, {"rule": "b", "severity": "page", "wall": 2.0})
    with open(os.path.join(run, "incidents.jsonl"), "a") as f:
        f.write("{torn")
    parsed = read_incidents(run)
    assert [i["rule"] for i in parsed["incidents"]] == ["a", "b"]
    assert parsed["unparseable_lines"] == 1


# ------------------------------------- driver / controller / supervisor


@pytest.fixture(scope="module")
def tiny_serve():
    from ray_lightning_tpu.serve.cli import _tiny_setup
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg, model, params, prompts, reqs = _tiny_setup(4, 6)
    ecfg = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    return cfg, model, params, prompts, reqs, ecfg


def test_force_flight_persist_seam(tmp_path, tiny_serve):
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig,
        ServeDriver,
    )
    from ray_lightning_tpu.telemetry.metrics import read_flight

    cfg, model, params, prompts, reqs, ecfg = tiny_serve
    run = str(tmp_path)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, engine=ecfg, run_dir=run,
        # a persist cadence far beyond this test: without the forced
        # persist the recorded events would NOT be on disk
        flight_persist_every=10_000,
        metrics_flush_every_n_ticks=2))
    drv.start()
    drv.submit(reqs[0])
    for _ in range(3):
        drv.tick()
    fpath = os.path.join(_tdir(run), "replica0.flight.json")
    before = read_flight(fpath)
    assert not before["events"]   # construction-time empty ring only
    persisted = drv.force_flight_persist()
    assert persisted == 2         # replica ring + driver ring
    after = read_flight(fpath)
    assert after["events"]        # the breach window's ticks landed
    drv.stop()


def test_controller_watch_wiring_fires_and_persists(tmp_path,
                                                    tiny_serve):
    """ControllerConfig(watch=...): the controller's poll cadence IS
    the watch cadence; a breach lands in <run_dir>/incidents.jsonl
    with the driver's forced flight persist in its actions."""
    from ray_lightning_tpu.autoscale import (
        AutoscaleController,
        ControllerConfig,
        PolicyConfig,
    )
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig,
        ServeDriver,
    )

    cfg, model, params, prompts, reqs, ecfg = tiny_serve
    run = str(tmp_path)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, engine=ecfg, run_dir=run,
        metrics_flush_every_n_ticks=2))
    drv.start()
    # any completed request breaches a 0-second TTFT bound — the rule
    # exists to drive the wiring, not to be a sane SLO
    rule = WatchRule("ttft_p99", "serving.ttft_p99_s", ">", 0.0)
    ctl = AutoscaleController(drv, ControllerConfig(
        policy=PolicyConfig(min_replicas=1, max_replicas=1),
        watch=WatchConfig(rules=(rule,))), run_dir=run)
    assert ctl.watch is not None
    for req in reqs[:2]:
        drv.submit(req)
    tick = 0
    while drv.busy():
        drv.tick()
        tick += 1
        if tick % 2 == 0:
            ctl.step(now=float(tick))
    ctl.step(now=float(tick + 1))
    drv.stop()
    parsed = read_incidents(run)
    assert len(parsed["incidents"]) == 1   # episode: exactly one
    inc = parsed["incidents"][0]
    assert inc["rule"] == "ttft_p99"
    # the driver seam actuated: replica + driver rings persisted
    assert inc["actions"]["flight_persisted"] >= 2


def test_watch_off_program_pin(tmp_path, tiny_serve):
    """The acceptance pin: watch on vs off is a byte-identical lowered
    decode program and ONE compile — the watch layer reads files, it
    never touches the engine (same discipline as telemetry=off)."""
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig,
        ServeDriver,
    )
    from ray_lightning_tpu.serve.engine import DecodeEngine, idle_prefill

    cfg, model, params, prompts, reqs, ecfg = tiny_serve

    def lowered_text(engine):
        C = ecfg.capacity
        spec = ecfg.pool_spec
        pslot, ptoks, ppos, plast = idle_prefill(ecfg)
        return engine._step.lower(
            engine.params, engine.pool_k, engine.pool_v,
            engine.last_logits,
            jnp.asarray(np.zeros((C, spec.blocks_per_slot), np.int32)),
            jnp.asarray(np.zeros(C, np.int32)),
            jnp.asarray(np.zeros(C, bool)),
            jnp.asarray(np.zeros(C, np.float32)),
            jnp.asarray(np.zeros(C, np.int32)),
            jnp.asarray(np.zeros((C, 2), np.uint32)),
            jnp.asarray(pslot), jnp.asarray(ptoks), jnp.asarray(ppos),
            jnp.asarray(plast)).as_text()

    baseline = DecodeEngine(model, params, ecfg)
    run = str(tmp_path)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, engine=ecfg, run_dir=run,
        metrics_flush_every_n_ticks=2))
    drv.start()
    eng = WatchEngine(run, WatchConfig(rules=BUILTIN_RULES))
    drv.submit(reqs[0])
    tick = 0
    while drv.busy():
        drv.tick()
        tick += 1
        if tick % 2 == 0:
            eng.poll(driver=drv)
    eng.poll(driver=drv)
    watched_engine = drv.replicas[0].engine
    assert lowered_text(watched_engine) == lowered_text(baseline)
    assert watched_engine.compile_count == 1
    drv.stop()


def test_supervised_result_incidents_field():
    from ray_lightning_tpu.resilience.supervisor import (
        ResilienceConfig,
        SupervisedResult,
    )

    r = SupervisedResult(result=None, restarts=0, preemptions=0,
                         failures=[])
    assert r.incidents == []
    cfg = ResilienceConfig(checkpoint_dir="/tmp/x", watch=True)
    assert cfg.watch is True


@pytest.mark.slow
def test_supervisor_watch_arming(tmp_path):
    """End to end: a supervised run with an injected worker death and
    watch armed fires the restart rule (the surviving rank's attempt
    ledgers carry the count — the SIGKILLed rank writes none) and
    surfaces the incidents in SupervisedResult +
    <checkpoint_dir>/incidents.jsonl."""
    from ray_lightning_tpu.resilience.cli import (
        _smoke_data,
        _smoke_module,
        _smoke_trainer,
    )
    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import (
        ResilienceConfig,
        fit_supervised,
    )

    base = str(tmp_path / "ckpts")
    rule = WatchRule("restart_rate", "restarts.count", ">=", 1,
                     severity="warn")
    cfg = ResilienceConfig(
        checkpoint_dir=base,
        policy=RetryPolicy(max_restarts=2, backoff_base_s=0.2,
                           jitter=0.0),
        save_every_n_steps=5,
        heartbeat_interval_s=1.0,
        stall_timeout_s=0.0,
        faults="kill:rank=0,step=3",
        watch=WatchConfig(rules=(rule,)))
    supervised = fit_supervised(
        _smoke_module, _smoke_trainer, _smoke_data, 2,
        resilience=cfg, platform="cpu",
        num_cpu_devices_per_process=1, return_weights=False,
        timeout=300)
    assert supervised.restarts >= 1
    assert [i["rule"] for i in supervised.incidents] == ["restart_rate"]
    parsed = read_incidents(base)
    assert len(parsed["incidents"]) == 1
    assert parsed["incidents"][0]["evidence"]["restarts"]["attempts"] >= 2


# --------------------------------------------------------- RLT503 lint


def _rlt503(src):
    from ray_lightning_tpu.analysis.linter import lint_source

    return [f for f in lint_source(src) if f.rule == "RLT503"]


def test_rlt503_fires_on_unbounded_follow_loop():
    fs = _rlt503("""
import time
from ray_lightning_tpu.telemetry.spans import read_spans

def follow(path):
    while True:
        data = read_spans(path)
        time.sleep(5)
""")
    assert len(fs) == 1 and "tail" in fs[0].message


def test_rlt503_propagates_through_helpers():
    fs = _rlt503("""
import time
from ray_lightning_tpu.telemetry.metrics import read_metrics

def _view(path):
    return read_metrics(path)

def follow(path):
    while True:
        _view(path)
        time.sleep(5)
""")
    assert len(fs) == 1


def test_rlt503_propagates_through_methods():
    fs = _rlt503("""
import time

class Controller:
    def _signal(self):
        from ray_lightning_tpu.serve.driver import load_signal
        return load_signal(self.run_dir)

    def step(self):
        return self._signal()

    def run_wall(self):
        while True:
            self.step()
            time.sleep(5)
""")
    assert len(fs) == 1


def test_rlt503_sanctions():
    # a threaded bound sanctions — the caller owns the window
    assert not _rlt503("""
import time
from ray_lightning_tpu.telemetry.spans import read_spans

def follow(path, tail):
    while True:
        data = read_spans(path, tail_bytes=tail)
        time.sleep(5)
""")
    # window= counts as a bound (load_signal derives its tail from it)
    assert not _rlt503("""
import time
from ray_lightning_tpu.serve.driver import load_signal

def follow(run):
    while True:
        sig = load_signal(run, window=16)
        time.sleep(5)
""")
    # not cadence-polled: one-shot reads stay free to read everything
    assert not _rlt503("""
from ray_lightning_tpu.telemetry.spans import read_spans

def report(path):
    return read_spans(path)
""")
    # a loop WITHOUT a sleep is a drain loop, not a poll
    assert not _rlt503("""
from ray_lightning_tpu.telemetry.spans import read_spans

def drain(paths):
    for p in paths:
        read_spans(p)
""")
    # an explicit tail_bytes=None is NOT a bound
    assert len(_rlt503("""
import time
from ray_lightning_tpu.telemetry.spans import read_spans

def follow(path):
    while True:
        read_spans(path, tail_bytes=None)
        time.sleep(5)
""")) == 1


def test_rlt503_suppression():
    assert not _rlt503("""
import time
from ray_lightning_tpu.telemetry.spans import read_spans

def follow(path):
    while True:
        data = read_spans(path)  # rlt: disable=RLT503
        time.sleep(5)
""")


def test_repo_lints_clean_of_rlt503():
    import ray_lightning_tpu
    from ray_lightning_tpu.analysis.linter import lint_paths

    root = os.path.dirname(ray_lightning_tpu.__file__)
    findings = [f for f in lint_paths([root])
                if f.rule == "RLT503"]
    assert findings == []


# ------------------------------------------------- bench / gate surfaces


def test_bench_watch_schema_on_every_line():
    import bench

    summary = bench._watch_summary()
    assert "incidents" in summary["watch"]["schema"]
    assert "ttft_p99" in summary["watch"]["rules"]
    assert summary["watch"]["source"] == "static-schema"


def test_bench_gate_incidents_bound():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "bench_gate.py"))
    bg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bg)

    line = {"metric": "m", "value": 1.0}
    assert bg.gate({**line, "incidents": 0}, {}, 0.05) == []
    fails = bg.gate({**line, "incidents": 1}, {}, 0.05)
    assert fails and "incidents" in fails[0]
    # skip lines + absent/null counts waive
    assert bg.gate({**line, "skipped": "backend unavailable",
                    "incidents": 3}, {}, 0.05) == []
    assert bg.gate({**line, "incidents": None}, {}, 0.05) == []
    assert bg.gate(line, {}, 0.05) == []


def test_watch_cli_one_shot(tmp_path, capsys):
    from ray_lightning_tpu.__main__ import main

    run = str(tmp_path)
    _serving_fixture(run)
    assert main(["watch", run, "--ttft-max", "0.001"]) == 0
    out = capsys.readouterr().out
    assert "ttft_p99" in out and "1 new incident" in out
    assert read_incidents(run)["incidents"]
    assert main(["watch", str(tmp_path / "missing")]) == 2
