"""Replica-group driver tests (serve/driver.py): inline multiplexing,
params round-trip, telemetry spans + the report serving section, and —
slow, real processes — the injected-SIGKILL respawn/replay drill."""
from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import Llama, LlamaConfig, generate
from ray_lightning_tpu.serve.driver import (
    ReplicaGroupConfig,
    ServeDriver,
    load_params_npz,
    save_params_npz,
)
from ray_lightning_tpu.serve.engine import EngineConfig
from ray_lightning_tpu.serve.scheduler import Request


@pytest.fixture(scope="module")
def setup(tiny_llama_f32):
    # params from the session-scope canonical build (tests/conftest.py);
    # every driver test threads them by value (ServeDriver arg / npz
    # round-trip), so the exact key only has to be consistent within
    # the fixture — sharing the session init skips a per-module compile
    cfg, model, params, _ = tiny_llama_f32
    prompts = [
        np.array(jax.random.randint(
            jax.random.key(60 + i), (1, 3 + (i % 4)), 0,
            cfg.vocab_size), dtype=np.int32)
        for i in range(8)
    ]
    return cfg, model, params, prompts


ECFG = EngineConfig(capacity=2, block_size=4, blocks_per_slot=8,
                    prefill_chunk=4)


def _requests(prompts, max_new=6):
    return [Request(rid=f"r{i}", prompt=p[0], max_new_tokens=max_new,
                    temperature=0.6 if i % 2 else 0.0,
                    top_k=3 if i % 2 else None, seed=5 + i)
            for i, p in enumerate(prompts)]


def _refs(model, params, prompts, reqs):
    return {r.rid: np.asarray(generate(
        model, params, prompts[i], r.max_new_tokens,
        temperature=r.temperature, top_k=r.top_k, seed=r.seed))[0]
        for i, r in enumerate(reqs)}


def test_params_npz_roundtrip_exact(setup, tmp_path):
    cfg, model, params, _ = setup
    path = str(tmp_path / "p.npz")
    save_params_npz(params, path)
    loaded = load_params_npz(path)
    flat_a = jax.tree.leaves(params)
    flat_b = jax.tree.leaves(loaded)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_inline_two_replicas_parity_and_summary(setup, tmp_path):
    cfg, model, params, prompts = setup
    reqs = _requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    run_dir = str(tmp_path / "run")
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=2, backend="inline", engine=ECFG, run_dir=run_dir))
    res = drv.run(reqs)
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(res.outputs[rid]), ref,
                                      err_msg=rid)
    assert res.stats["n_tokens"] == sum(len(v) for v in
                                        res.outputs.values())
    assert res.stats["compile_count"] in (1, -1)
    # summary + spans on disk, and the report CLI surfaces them
    assert os.path.exists(os.path.join(run_dir, "serving.json"))
    with open(os.path.join(run_dir, "serving.json")) as f:
        summary = json.load(f)
    assert summary["stats"]["n_requests"] == 8
    from ray_lightning_tpu.telemetry.report import build_serving_section

    section = build_serving_section(run_dir)
    assert section is not None
    assert section["requests"] == 8
    assert section["ttft_p95_s"] >= section["ttft_p50_s"] >= 0


def test_run_does_not_mutate_caller_requests(setup):
    """Review regression: run() copies requests before stamping
    arrival, so the same list serves two runs with sane queue_wait
    both times (a stale first-run stamp used to inflate the second
    run's queue_wait by the whole first run's wall)."""
    cfg, model, params, prompts = setup
    reqs = _requests(prompts[:2], max_new=3)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ECFG))
    res1 = drv.run(reqs)
    assert all(r.arrival == 0.0 for r in reqs), "caller objects mutated"
    res2 = drv.run(reqs)
    for rid in res1.outputs:
        assert res1.outputs[rid] == res2.outputs[rid]
        # queue_wait is per-run: bounded by THIS run's wall, never the
        # inter-run gap a stale stamp would add
        assert (0.0 <= res2.meta[rid]["queue_wait_s"]
                <= res2.stats["wall_s"] + 0.5)


def test_inline_rejects_fault(setup):
    cfg, model, params, prompts = setup
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ECFG))
    with pytest.raises(ValueError, match="process"):
        drv.run(_requests(prompts[:1]),
                fault={"replica": 0, "kill_after_tokens": 1})


def test_process_backend_requires_params_path(setup):
    cfg, model, params, _ = setup
    with pytest.raises(ValueError, match="npz"):
        ServeDriver(cfg, params, ReplicaGroupConfig(
            n_replicas=1, backend="process", engine=ECFG))


def test_serving_spans_flushed(setup, tmp_path):
    """Per-request serving spans land in the recorder files with the
    request meta the report aggregates."""
    from ray_lightning_tpu.telemetry.spans import (
        PH_DECODE, PH_PREFILL, PH_QUEUE_WAIT, read_spans,
    )

    cfg, model, params, prompts = setup
    run_dir = str(tmp_path / "run")
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ECFG, run_dir=run_dir))
    drv.run(_requests(prompts[:3], max_new=4))
    import glob

    files = glob.glob(os.path.join(run_dir, "telemetry",
                                   "rank*.spans.jsonl"))
    assert files
    spans = [s for f in files for s in read_spans(f)["spans"]]
    phases = {s["phase"] for s in spans}
    assert {PH_QUEUE_WAIT, PH_PREFILL, PH_DECODE} <= phases
    decode = [s for s in spans if s["phase"] == PH_DECODE]
    assert len(decode) == 3
    assert all("ttft_s" in (s.get("meta") or {}) for s in decode)


@pytest.mark.slow
def test_process_replica_kill_respawns_and_replays(setup, tmp_path):
    """The recovery drill with real processes: SIGKILL replica 1 after
    6 tokens -> classified RETRYABLE -> respawn reloads weights from
    the npz and re-warms via the persistent compile cache -> the lost
    streams replay bitwise; the surviving replica never restarts."""
    cfg, model, params, prompts = setup
    reqs = _requests(prompts)
    refs = _refs(model, params, prompts, reqs)
    pp = str(tmp_path / "params.npz")
    save_params_npz(params, pp)
    drv = ServeDriver(cfg, pp, ReplicaGroupConfig(
        n_replicas=2, backend="process", engine=ECFG,
        run_dir=str(tmp_path / "run"),
        compile_cache_dir=str(tmp_path / "cc"),
        env={"JAX_PLATFORMS": "cpu"}))
    res = drv.run(reqs, fault={"replica": 1, "kill_after_tokens": 6})
    assert res.restarts[1] >= 1, "kill did not trigger a respawn"
    assert res.restarts[0] == 0, "the surviving replica restarted"
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(res.outputs[rid]), ref,
                                      err_msg=rid)
    assert res.stats["warmup_respawn_s"] is not None
    # the flight-recorder postmortem (telemetry/metrics.py): the driver
    # finalized the dead replica's last persisted ring into flight.json
    # with the resilience classification stamped on
    with open(str(tmp_path / "run" / "flight.json")) as f:
        doc = json.load(f)
    dump = doc["dumps"][0]
    assert dump["replica"] == 1
    assert dump["death"]["kind"] == "retryable"
    assert dump["death"]["respawning"] is True
    assert any(e.get("kind") == "tick" for e in dump["events"])
