"""elastic/reshard.py — cross-topology checkpoint restore (ISSUE 9).

The contract under test (docs/ELASTIC.md "resharding restore"): a
checkpoint written on ANY mesh restores onto ANY other mesh's
shardings — bitwise, leaf for leaf, opt-state and extra (guard/EMA)
slots included — validated against the provenance the writer stamped;
legacy (provenance-free) checkpoints refuse the move with a clear
error instead of restoring a fiction.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.checkpoint.io import (
    read_meta,
    save_checkpoint,
    sharding_provenance,
    verify_checkpoint,
    wait_for_checkpoints,
)
from ray_lightning_tpu.elastic import (
    ElasticBudget,  # noqa: F401 — re-export sanity
    ReshardError,
    checkpoint_provenance,
    reshard_restore,
    validate_reshard,
)
from ray_lightning_tpu.parallel.strategy import (
    DataParallel,
    FSDP,
    ShardedMesh,
)


def _state(strategy):
    """A small but multi-leaf state on `strategy`'s mesh: params with
    shardable dims, a nested opt-state inheriting param layouts, and a
    guard/EMA-style scalar slot."""
    params = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
              "b": jnp.arange(8.0),
              "deep": {"k": jnp.arange(32, dtype=jnp.float32)
                       .reshape(4, 8)}}
    params = strategy.shard_params(params)
    opt = {"mu": jax.tree.map(lambda x: x * 2.0, params),
           "nu": jax.tree.map(lambda x: x * 3.0, params)}
    return {
        "params": params,
        "opt_state": opt,
        "guard": {"loss_ema": jax.device_put(
            jnp.float32(1.25), strategy.replicated())},
        "step": jax.device_put(jnp.int32(11), strategy.replicated()),
    }


def _target_like(strategy, host_state):
    params = strategy.shard_params(
        jax.tree.map(jnp.zeros_like, host_state["params"]))
    opt = jax.tree.map(jnp.zeros_like, host_state["opt_state"])
    opt = jax.device_put(opt, strategy.opt_state_shardings(
        jax.eval_shape(lambda t: t, opt), params))
    return {
        "params": params,
        "opt_state": opt,
        "guard": {"loss_ema": jax.device_put(
            jnp.zeros((), jnp.float32), strategy.replicated())},
        "step": jax.device_put(jnp.zeros((), jnp.int32),
                               strategy.replicated()),
    }


def _save(tmp_path, strategy, state, name="ck", extra_meta=None):
    path = os.path.join(str(tmp_path), name)
    meta = {"global_step": 11,
            **sharding_provenance(strategy.mesh, state)}
    meta.update(extra_meta or {})
    save_checkpoint(path, state, meta)
    wait_for_checkpoints()
    return path


def _assert_bitwise(src_state, restored):
    a = jax.device_get(src_state)
    b = jax.device_get(restored)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.parametrize("dst_factory", [
    lambda: FSDP(num_workers=4, min_shard_size=8),        # fsdp 8 -> 4
    lambda: DataParallel(num_workers=8),                  # fsdp -> dp swap
    lambda: ShardedMesh(data=2, fsdp=4, min_shard_size=8),  # hybrid
    lambda: FSDP(num_workers=2, min_shard_size=8),        # world 8 -> 2
], ids=["fsdp8to4", "fsdp-to-dp", "hsdp", "world8to2"])
def test_mesh_to_mesh_bitwise(tmp_path, dst_factory):
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)

    dst = dst_factory()
    dst.setup()
    restored = reshard_restore(path, _target_like(dst, jax.device_get(state)))
    _assert_bitwise(state, restored)
    assert int(jax.device_get(restored["step"])) == 11
    # the restored tree really lives on the TARGET mesh
    tgt_mesh = jax.tree.leaves(restored["params"])[0].sharding.mesh
    assert int(tgt_mesh.size) == dst.world_size


def test_reverse_move_dp_to_fsdp(tmp_path):
    src = DataParallel(num_workers=4)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)
    dst = FSDP(min_shard_size=8)
    dst.setup()
    restored = reshard_restore(path, _target_like(dst, jax.device_get(state)))
    _assert_bitwise(state, restored)


def test_provenance_stamped_and_verified(tmp_path):
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)
    meta = read_meta(path)
    assert meta["mesh_spec"]["fsdp"] == 8
    assert meta["topology"]["n_devices"] == 8
    assert meta["topology"]["platform"] == "cpu"
    # per-leaf specs recorded for every param leaf
    assert set(meta["param_specs"]) == {"w", "b", "deep/k"}
    prov = checkpoint_provenance(path)
    assert set(prov) == {"mesh_spec", "topology", "param_specs"}
    ok, reason = verify_checkpoint(path)
    assert ok, reason


def test_verify_rejects_contradictory_provenance(tmp_path):
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)
    # tamper: mesh product no longer matches recorded device count
    import json

    mp = os.path.join(path, "meta.json")
    with open(mp) as f:
        meta = json.load(f)
    meta["topology"]["n_devices"] = 3
    with open(mp, "w") as f:
        json.dump(meta, f)
    ok, reason = verify_checkpoint(path)
    assert not ok and "provenance mismatch" in reason
    # and the reshard path refuses it too
    with pytest.raises(ReshardError, match="provenance is invalid"):
        validate_reshard(meta, {"fsdp": 4})


def test_verify_rejects_alien_axis_in_param_specs(tmp_path):
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)
    import json

    mp = os.path.join(path, "meta.json")
    with open(mp) as f:
        meta = json.load(f)
    meta["param_specs"]["w"] = [None, "bogus_axis"]
    with open(mp, "w") as f:
        json.dump(meta, f)
    ok, reason = verify_checkpoint(path)
    assert not ok and "bogus_axis" in reason


def test_legacy_meta_refuses_reshard(tmp_path):
    """A checkpoint without provenance restores legacy-style only —
    reshard_restore names the gap instead of moving it."""
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = os.path.join(str(tmp_path), "legacy")
    save_checkpoint(path, state, {"global_step": 11})  # no provenance
    wait_for_checkpoints()
    ok, reason = verify_checkpoint(path)
    assert ok, reason  # legacy checkpoints still VERIFY fine
    dst = FSDP(num_workers=4, min_shard_size=8)
    dst.setup()
    with pytest.raises(ReshardError, match="no sharding provenance"):
        reshard_restore(path, _target_like(dst, jax.device_get(state)))
    # ...but the legacy same-sharding path still works
    from ray_lightning_tpu.checkpoint.io import restore_checkpoint

    same = FSDP(min_shard_size=8)
    same.setup()
    restored = restore_checkpoint(
        path, _target_like(same, jax.device_get(state)))
    _assert_bitwise(state, restored)


def test_validate_reshard_move_summary():
    meta = {"mesh_spec": {"data": 1, "fsdp": 8, "tensor": 1},
            "topology": {"n_devices": 8},
            "param_specs": {"w": [None, "fsdp"]}}
    move = validate_reshard(meta, {"data": 2, "fsdp": 2})
    assert move["from_mesh"] == {"fsdp": 8}
    assert move["to_mesh"] == {"data": 2, "fsdp": 2}
    assert move["from_world"] == 8 and move["to_world"] == 4
    assert move["world_change"] is True
    assert move["changed_axes"] == ["data", "fsdp"]
    # identical live mesh: legal, no world change
    move = validate_reshard(meta, {"fsdp": 8})
    assert move["world_change"] is False and move["changed_axes"] == []


def test_reshard_restore_refuses_torn_checkpoint(tmp_path):
    src = FSDP(min_shard_size=8)
    src.setup()
    state = _state(src)
    path = _save(tmp_path, src, state)
    os.remove(os.path.join(path, "meta.json"))  # torn: no completeness
    dst = FSDP(num_workers=4, min_shard_size=8)
    dst.setup()
    with pytest.raises(ReshardError, match="invalid checkpoint"):
        reshard_restore(path, _target_like(dst, jax.device_get(state)))


def test_trainer_restore_reshards_across_meshes(tmp_path):
    """End to end through the Trainer: fit on fsdp=8, checkpoint, then
    a FRESH trainer on fsdp=4 resumes from it — the cross-topology
    restore path (`_reshard_move`) validates the move and training
    continues with bitwise-equal restored params."""
    from ray_lightning_tpu import DataLoader, Trainer
    from tests.utils import BoringModel, random_dataset

    data = random_dataset()

    m1 = BoringModel()
    t1 = Trainer(strategy=FSDP(min_shard_size=8), max_epochs=1,
                 enable_progress_bar=False, enable_checkpointing=False,
                 default_root_dir=str(tmp_path), seed=0)
    t1.fit(m1, DataLoader(data, batch_size=16),
           DataLoader(data, batch_size=16))
    ck = t1.save_checkpoint(str(tmp_path / "ck"))
    wait_for_checkpoints()
    saved = jax.device_get({"params": t1.state.params,
                            "opt_state": t1.state.opt_state,
                            "step": t1.state.step})
    meta = read_meta(ck)
    assert meta["mesh_spec"]["fsdp"] == 8

    # the move the fsdp=4 trainer will perform, validated standalone
    move = validate_reshard(meta, {"fsdp": 4})
    assert move["from_mesh"] == {"fsdp": 8}
    assert move["to_mesh"] == {"fsdp": 4}

    # standalone full-tree reshard restore: bitwise vs the saved state
    dst = FSDP(num_workers=4, min_shard_size=8)
    dst.setup()
    tgt_params = dst.shard_params(
        jax.tree.map(jnp.zeros_like, saved["params"]))
    tgt_opt = jax.tree.map(jnp.zeros_like, saved["opt_state"])
    tgt_opt = jax.device_put(tgt_opt, dst.opt_state_shardings(
        jax.eval_shape(lambda t: t, tgt_opt), tgt_params))
    restored = reshard_restore(ck, {
        "params": tgt_params, "opt_state": tgt_opt,
        "step": jax.device_put(jnp.zeros((), jnp.int32),
                               dst.replicated())})
    for a, b in zip(jax.tree.leaves(saved),
                    jax.tree.leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # end to end: a FRESH trainer on the 4-device mesh resumes from the
    # 8-device checkpoint (the Trainer's _reshard_move path) and trains
    m2 = BoringModel()
    t2 = Trainer(strategy=FSDP(num_workers=4, min_shard_size=8),
                 max_epochs=2, enable_progress_bar=False,
                 enable_checkpointing=False,
                 default_root_dir=str(tmp_path), seed=0)
    metrics = t2.fit(m2, DataLoader(data, batch_size=16),
                     DataLoader(data, batch_size=16), ckpt_path=ck)
    assert t2.global_step > int(saved["step"])
    assert "ptl/val_accuracy" in metrics or metrics  # trained through
