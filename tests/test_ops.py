"""Kernel correctness: flash attention + rmsnorm vs jnp references.

Pallas kernels run in interpret mode on the CPU test harness, so the same
kernel code the TPU executes is what's checked here.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.ops.attention import (
    dot_product_attention,
    flash_attention,
)
from ray_lightning_tpu.ops.norms import rms_norm
from ray_lightning_tpu.ops.pallas.flash import (
    flash_attention_pallas,
    shapes_supported,
)
from ray_lightning_tpu.ops.pallas.rmsnorm import rms_norm_pallas
from ray_lightning_tpu.ops.rope import apply_rope, rope_frequencies


def _qkv(B=2, S=256, H=4, Hk=2, D=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, S, H, D), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((B, S, Hk, D), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((B, S, Hk, D), dtype=np.float32))
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention_pallas(q, k, v, causal=causal,
                                     block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_backward_matches_reference(self):
        q, k, v = _qkv()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v) ** 2).sum()

        def loss_flash(q, k, v):
            return (flash_attention_pallas(
                q, k, v, block_q=128, block_k=128) ** 2).sum()

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            scale = float(jnp.abs(a).max())
            np.testing.assert_allclose(b, a, atol=3e-5 * max(scale, 1.0))

    def test_attn_out_policy_saves_kernel_residuals(self):
        """remat_policy='attn_out' must (a) keep grads identical to
        no-remat, and (b) actually save the flash kernel's VJP residuals
        so the backward skips the forward recompute. The mechanism is
        optimize_remat=True on the kernel's custom_vjp: its fwd rule
        becomes a `remat_opt` call whose outputs the policy saves —
        without it a custom_vjp is opaque to checkpoint policies and a
        name-based policy verifiably saved nothing."""
        import contextlib
        import io

        from jax.ad_checkpoint import print_saved_residuals

        from ray_lightning_tpu.models.llama import _remat_policy

        def saved_residuals_report(fn, *args) -> str:
            # public-API capture (saved_residuals lives in jax._src)
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                print_saved_residuals(fn, *args)
            return buf.getvalue()

        q, k, v = _qkv(S=128)
        policy = _remat_policy("attn_out")

        def loss(q, k, v):
            o = flash_attention_pallas(q, k, v, block_q=64, block_k=64)
            return (o ** 2).sum()

        g_plain = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_remat = jax.grad(jax.checkpoint(loss, policy=policy),
                           argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_plain, g_remat):
            scale = float(jnp.abs(a).max())
            np.testing.assert_allclose(b, a, atol=3e-5 * max(scale, 1.0))
        # residual proof: the saved set must include the remat_opt
        # (= kernel fwd-rule) outputs — 5 tensors (q, k, v, o, lse);
        # under nothing_saveable none of them appear
        res = saved_residuals_report(
            jax.checkpoint(loss, policy=policy), q, k, v)
        assert res.count("remat_opt") >= 5, res
        res0 = saved_residuals_report(jax.checkpoint(loss), q, k, v)
        assert "remat_opt" not in res0, res0

    def test_mha_no_gqa(self):
        q, k, v = _qkv(H=4, Hk=4)
        ref = dot_product_attention(q, k, v)
        out = flash_attention_pallas(q, k, v, block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_q_offset_decode_shard(self):
        """A query shard starting mid-sequence masks correctly."""
        q, k, v = _qkv(S=256)
        q_half = q[:, 128:]
        ref = dot_product_attention(q_half, k, v, causal=True, q_offset=128)
        out = flash_attention_pallas(q_half, k, v, causal=True, q_offset=128,
                                     block_q=128, block_k=128)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_shapes_supported_gate(self):
        assert shapes_supported((2, 256, 4, 128), (2, 256, 4, 128))
        assert shapes_supported((2, 256, 4, 64), (2, 256, 2, 64))
        assert not shapes_supported((2, 250, 4, 128), (2, 250, 4, 128))
        assert not shapes_supported((2, 256, 4, 100), (2, 256, 4, 100))
        assert not shapes_supported((2, 256, 3, 128), (2, 256, 2, 128))

    def test_dispatch_falls_back_off_tpu(self):
        """flash_attention auto-dispatch returns reference results on CPU."""
        q, k, v = _qkv(S=64)
        out = flash_attention(q, k, v)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_padding_mask(self):
        q, k, v = _qkv(S=64)
        mask = jnp.asarray(
            np.random.default_rng(1).integers(0, 2, (2, 64)).astype(bool)
        )
        mask = mask.at[:, 0].set(True)  # row 0 visible so no all-masked rows
        out = dot_product_attention(q, k, v, causal=True, mask=mask)
        assert out.shape == q.shape
        assert bool(jnp.isfinite(out).all())

    def test_fully_masked_rows_yield_zeros_not_nan(self):
        """A sequence whose padding mask is all-False (or padding ∩ causal
        leaving a query row with no visible key) must produce zeros, not
        NaN from softmax over all -inf."""
        q, k, v = _qkv(S=16)
        mask = jnp.zeros((2, 16), dtype=bool).at[1].set(True)  # batch 0 fully padded
        out = dot_product_attention(q, k, v, causal=True, mask=mask)
        assert bool(jnp.isfinite(out).all())
        np.testing.assert_allclose(np.asarray(out[0]), 0.0)
        assert bool((jnp.abs(out[1]) > 0).any())


class TestRMSNorm:
    def test_forward(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 128, 256), dtype=np.float32))
        w = jnp.asarray(rng.standard_normal(256, dtype=np.float32))
        ref = rms_norm(x, w, use_pallas=False)
        out = rms_norm_pallas(x, w)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_backward(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 64, 128), dtype=np.float32))
        w = jnp.asarray(rng.standard_normal(128, dtype=np.float32))
        g1 = jax.grad(lambda x, w: (rms_norm(x, w, use_pallas=False) ** 2).sum(),
                      argnums=(0, 1))(x, w)
        g2 = jax.grad(lambda x, w: (rms_norm_pallas(x, w) ** 2).sum(),
                      argnums=(0, 1))(x, w)
        np.testing.assert_allclose(g2[0], g1[0], atol=1e-4)
        np.testing.assert_allclose(g2[1], g1[1], atol=1e-3)

    def test_bf16(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 128)), dtype=jnp.bfloat16)
        w = jnp.ones(128, jnp.bfloat16)
        out = rms_norm_pallas(x, w)
        assert out.dtype == jnp.bfloat16


class TestRope:
    def test_norm_preserved(self):
        """Rotation preserves pairwise norms."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 16, 4, 64), dtype=np.float32))
        cos, sin = rope_frequencies(64, 32)
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(y, axis=-1), jnp.linalg.norm(x, axis=-1),
            rtol=1e-5,
        )

    def test_position_zero_identity(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((1, 1, 2, 32), dtype=np.float32))
        cos, sin = rope_frequencies(32, 8)
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(y, x, atol=1e-6)

    def test_explicit_positions(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((1, 4, 2, 32), dtype=np.float32))
        cos, sin = rope_frequencies(32, 16)
        shifted = apply_rope(x, cos, sin, positions=jnp.arange(4) + 8)
        full = apply_rope(
            jnp.concatenate([jnp.zeros((1, 8, 2, 32), x.dtype), x], axis=1),
            cos, sin,
        )[:, 8:]
        np.testing.assert_allclose(shifted, full, atol=1e-5)

    def test_relative_property(self):
        """Attention scores depend only on relative positions."""
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 1, 1, 64), dtype=np.float32))
        k = jnp.asarray(rng.standard_normal((1, 1, 1, 64), dtype=np.float32))
        cos, sin = rope_frequencies(64, 64)

        def score(qpos, kpos):
            qr = apply_rope(q, cos, sin, positions=jnp.array([qpos]))
            kr = apply_rope(k, cos, sin, positions=jnp.array([kpos]))
            return float(jnp.sum(qr * kr))

        assert abs(score(5, 3) - score(10, 8)) < 1e-4


def test_flash_block_env_malformed_falls_back(monkeypatch):
    """A malformed RLT_FLASH_BLOCK_Q/K must fall back to the tuned
    default with a warning, not raise at trace time and fail the whole
    training step (ADVICE r4 — same policy as the bench watchdog env)."""
    from ray_lightning_tpu.ops.pallas import flash as flash_mod

    monkeypatch.setenv("RLT_FLASH_BLOCK_Q", "not-a-number")
    with pytest.warns(UserWarning, match="RLT_FLASH_BLOCK_Q"):
        assert flash_mod._env_block(
            "RLT_FLASH_BLOCK_Q", flash_mod.DEFAULT_BLOCK_Q
        ) == flash_mod.DEFAULT_BLOCK_Q
    monkeypatch.setenv("RLT_FLASH_BLOCK_K", "256")
    assert flash_mod._env_block("RLT_FLASH_BLOCK_K", 128) == 256


def test_flash_block_env_nonpositive_falls_back(monkeypatch):
    """0/negative block sizes are malformed too: 0 divides-by-zero in the
    grid math at trace time — same fallback-with-warning path."""
    from ray_lightning_tpu.ops.pallas import flash as flash_mod

    for bad in ("0", "-128"):
        monkeypatch.setenv("RLT_FLASH_BLOCK_Q", bad)
        with pytest.warns(UserWarning, match="RLT_FLASH_BLOCK_Q"):
            assert flash_mod._env_block(
                "RLT_FLASH_BLOCK_Q", flash_mod.DEFAULT_BLOCK_Q
            ) == flash_mod.DEFAULT_BLOCK_Q


class TestAttnOutPolicyScope:
    def test_foreign_remat_opt_not_saved(self):
        """ADVICE r5: remat_policy='attn_out' is scoped to the FLASH
        kernel's hoisted fwd rule (fingerprinted by its
        'flash_residuals' checkpoint_name). Any other custom_vjp defined
        with optimize_remat=True must keep its default remat fate, not
        silently have its residuals saved."""
        import contextlib
        import io

        from jax.ad_checkpoint import print_saved_residuals

        from ray_lightning_tpu.models.llama import _remat_policy

        @jax.custom_vjp
        def f(x):
            return jnp.sin(x)

        def f_fwd(x):
            return jnp.sin(x), (x,)

        def f_bwd(res, g):
            return (g * jnp.cos(res[0]),)

        f.defvjp(f_fwd, f_bwd, optimize_remat=True)

        def loss(x):
            return f(x * 2).sum()

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            print_saved_residuals(
                jax.checkpoint(loss, policy=_remat_policy("attn_out")),
                jnp.ones(16))
        assert "remat_opt" not in buf.getvalue(), buf.getvalue()

    def test_pallas_branch_skips_redundant_attn_out_name(self, monkeypatch):
        """On the pallas flash path the block-level checkpoint_name is
        dropped (the kernel's own residual set already saves o — naming
        it again would double-save a [B,S,H*hd] tensor per layer); the
        XLA-reference path keeps the name as its only save point."""
        import numpy as np

        from ray_lightning_tpu.models.llama import Llama, LlamaConfig

        def jaxpr_names(use_flash, env):
            if env:
                monkeypatch.setenv("RLT_PALLAS", "1")
            else:
                monkeypatch.delenv("RLT_PALLAS", raising=False)
            cfg = LlamaConfig(
                vocab_size=64, dim=256, n_layers=1, n_heads=4,
                n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                use_flash=use_flash, dtype=jnp.float32, remat=False,
                scan_layers=False)
            model = Llama(cfg)
            tokens = np.zeros((2, 128), np.int32)
            params = jax.eval_shape(
                lambda: model.init(jax.random.key(0), tokens))
            jaxpr = jax.make_jaxpr(
                lambda p, t: model.apply(p, t))(params, tokens)
            return str(jaxpr)

        # pallas path (forced in interpret mode): no block-level name
        assert "name=attn_out" not in jaxpr_names(True, env=True)
        # XLA reference path: the name is the policy's save point
        assert "name=attn_out" in jaxpr_names(False, env=False)
