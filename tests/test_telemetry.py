"""Telemetry subsystem (ISSUE 7, docs/OBSERVABILITY.md): span recorder,
goodput accounting, profiler triggers, drift report, heartbeat phases,
the RLT501 lint rule, the ThroughputMonitor compile-skew fix, and the
bench_gate goodput/overhead legs.

The load-bearing pins:
  * telemetry=off vs on train BITWISE-identically and lower
    byte-identical step programs (telemetry is host bookkeeping, never
    program content);
  * telemetry=on performs the SAME number of host transfers as off
    (device_get counted) — zero new host syncs;
  * goodput buckets sum to wall (worker ledgers exactly; assembled
    reports within tolerance) and replay attribution reclassifies
    re-trained steps.
"""
import contextlib
import importlib.util
import json
import logging
import os
import time

import numpy as np
import pytest


@contextlib.contextmanager
def _capture_logs(name):
    """The package logger sets propagate=False (utils/logging.py), so
    caplog never sees it — attach a list handler directly."""
    records = []

    class _H(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _H()
    logger = logging.getLogger(name)
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)

from ray_lightning_tpu.telemetry import (
    TelemetryConfig,
    TelemetryRecorder,
    assemble_goodput,
    buckets_consistent,
)
from ray_lightning_tpu.telemetry.goodput import (
    read_ledgers,
    worker_ledger,
    write_ledger,
)
from ray_lightning_tpu.telemetry.spans import (
    NULL_RECORDER,
    PH_COMPILE,
    PH_DISPATCH,
    PH_STEP,
    THREAD_PRODUCER,
    read_spans,
)


def _mlp_fit(tmp_path, telemetry, steps=4, name="run", **trainer_kw):
    from ray_lightning_tpu import DataLoader, Trainer
    from ray_lightning_tpu.models.mlp import MLPClassifier

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(64,))
    trainer = Trainer(max_epochs=2, max_steps=steps, seed=0,
                      enable_checkpointing=False,
                      enable_progress_bar=False,
                      default_root_dir=str(tmp_path / name),
                      telemetry=telemetry, log_every_n_steps=2,
                      **trainer_kw)
    module = MLPClassifier(features=(16,), num_classes=4, lr=1e-2)
    trainer.fit(module, DataLoader({"x": x, "y": y}, batch_size=16))
    return trainer


# --------------------------------------------------------------------------
# recorder
# --------------------------------------------------------------------------


class TestRecorder:
    def test_span_totals_and_ring(self, tmp_path):
        rec = TelemetryRecorder(directory=str(tmp_path), rank=3,
                                ring_size=8)
        with rec.span(PH_DISPATCH, step=7):
            pass
        rec.record(PH_STEP, time.perf_counter(), 0.5, step=7)
        totals = rec.phase_totals()
        assert totals[PH_STEP] == pytest.approx(0.5)
        assert PH_DISPATCH in totals
        # ring bound: 20 records into a size-8 ring drop 12
        for i in range(20):
            rec.record("x", 0.0, 0.001, step=i)
        assert rec.dropped == 20 + 2 - 8
        rec.close()
        import glob as _glob

        [path] = _glob.glob(os.path.join(str(tmp_path),
                                         "rank3.*.spans.jsonl"))
        parsed = read_spans(path)
        assert parsed["header"]["rank"] == 3
        assert parsed["dropped"] == rec.dropped
        assert len(parsed["spans"]) == 8  # what survived the ring

    def test_producer_spans_excluded_from_totals(self):
        rec = TelemetryRecorder()
        rec.record("h2d", 0.0, 1.0, thread=THREAD_PRODUCER)
        rec.record(PH_STEP, 0.0, 0.25)
        assert "h2d" not in rec.phase_totals()
        assert rec.phase_totals()[PH_STEP] == pytest.approx(0.25)

    def test_current_phase_tracks_main_spans_only(self):
        rec = TelemetryRecorder()
        assert rec.current_phase() == "setup"
        with rec.span(PH_COMPILE):
            assert rec.current_phase() == PH_COMPILE
            with rec.span("h2d", thread=THREAD_PRODUCER):
                assert rec.current_phase() == PH_COMPILE
        assert rec.current_phase() == PH_STEP
        assert rec.last_span()["phase"] == PH_COMPILE

    def test_nested_main_spans_charge_exclusively(self):
        # a lazy compile INSIDE the eval span: totals must not count
        # that second twice (the goodput buckets sum to wall)
        rec = TelemetryRecorder()
        with rec.span("eval"):
            with rec.span(PH_COMPILE):
                time.sleep(0.05)
            assert rec.current_phase() == "eval"  # restored, not "step"
        totals = rec.phase_totals()
        assert totals[PH_COMPILE] >= 0.05
        assert totals["eval"] < totals[PH_COMPILE]  # exclusive remainder
        # the span ENTRY keeps the full duration for the timeline
        evals = [s for s in rec._ring if s["phase"] == "eval"]
        assert evals[0]["dur"] >= 0.05

    def test_null_recorder_is_inert(self):
        with NULL_RECORDER.span("anything"):
            pass
        NULL_RECORDER.record("x", 0.0, 1.0)
        assert NULL_RECORDER.phase_totals() == {}
        assert NULL_RECORDER.flush() == 0
        assert not NULL_RECORDER.enabled

    def test_config_coerce(self, tmp_path):
        assert TelemetryConfig.coerce(None) is None
        assert TelemetryConfig.coerce(False) is None
        assert TelemetryConfig.coerce(True).dir is None
        assert TelemetryConfig.coerce(str(tmp_path)).dir == str(tmp_path)
        cfg = TelemetryConfig(dir="x")
        assert TelemetryConfig.coerce(cfg) is cfg
        with pytest.raises(TypeError):
            TelemetryConfig.coerce(3)
        assert TelemetryConfig().resolved_dir("/r") == "/r/telemetry"


# --------------------------------------------------------------------------
# trainer integration
# --------------------------------------------------------------------------


class TestTrainerTelemetry:
    def test_fit_writes_spans_and_ledger(self, tmp_path):
        trainer = _mlp_fit(tmp_path, telemetry=True, steps=6)
        tdir = str(tmp_path / "run" / "telemetry")
        import glob as _glob

        [spans_path] = _glob.glob(
            os.path.join(tdir, "rank0.*.spans.jsonl"))
        parsed = read_spans(spans_path)
        phases = {s["phase"] for s in parsed["spans"]}
        assert {"dispatch", "step", "compile", "h2d"} <= phases
        # producer-thread H2D spans are tagged so goodput never
        # double-charges overlapped time
        assert any(s.get("thread") == THREAD_PRODUCER
                   for s in parsed["spans"] if s["phase"] == "h2d")
        ledgers = read_ledgers(tdir, rank=0)
        assert ledgers and ledgers[-1]["completed"]
        led = ledgers[-1]
        assert led["end_step"] == 6
        # worker ledger books close exactly: productive is wall minus
        # the measured stalls
        assert sum(led["buckets"].values()) == pytest.approx(
            led["wall_s"], rel=1e-6)
        # surfaced in callback_metrics
        assert "goodput_fraction" in trainer.callback_metrics
        assert trainer.callback_metrics["telemetry_compile_s"] > 0

    def test_off_is_bitwise_and_program_identical(self, tmp_path):
        import jax

        t_off = _mlp_fit(tmp_path, telemetry=False, name="off")
        t_on = _mlp_fit(tmp_path, telemetry=True, name="on")
        for a, b in zip(jax.tree.leaves(t_off.state.params),
                        jax.tree.leaves(t_on.state.params)):
            assert jax.numpy.array_equal(a, b)

        def lowered(tr):
            batch = tr._place_train_batch(
                {"x": np.zeros((16, 8), np.float32),
                 "y": np.zeros((16,), np.int64)})[1]
            return tr._train_step._jitted.lower(
                tr.state, batch, tr._base_rng).as_text()

        assert lowered(t_off) == lowered(t_on)

    def test_on_adds_zero_host_transfers(self, tmp_path, monkeypatch):
        import jax

        counts = {}

        real_device_get = jax.device_get

        def counting_device_get(x):
            counts["n"] = counts.get("n", 0) + 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_device_get)
        counts["n"] = 0
        _mlp_fit(tmp_path, telemetry=False, name="cnt_off")
        off_n = counts["n"]
        counts["n"] = 0
        _mlp_fit(tmp_path, telemetry=True, name="cnt_on")
        assert counts["n"] == off_n

    def test_telemetry_off_by_default(self, tmp_path):
        trainer = _mlp_fit(tmp_path, telemetry=None, name="default")
        assert trainer.telemetry_recorder is NULL_RECORDER
        assert not (tmp_path / "default" / "telemetry").exists()


# --------------------------------------------------------------------------
# goodput
# --------------------------------------------------------------------------


def _fake_ledger(tdir, wall, start, end, t0, productive=None,
                 compile_s=0.0, pid=None):
    rec = TelemetryRecorder()
    if compile_s:
        rec.record("compile", 0.0, compile_s)
    led = worker_ledger(rec, wall, rank=0, start_step=start,
                        end_step=end, completed=True)
    led["t0_wall"] = t0
    path = write_ledger(tdir, led)
    if pid is not None:  # distinct filenames for same-process "attempts"
        os.replace(path, os.path.join(tdir, f"ledger.rank0.{pid}.json"))
    return led


class TestGoodput:
    def test_ledger_books_close_exactly(self):
        rec = TelemetryRecorder()
        rec.record("compile", 0.0, 2.0)
        rec.record("data_wait", 0.0, 1.0)
        rec.record("h2d", 0.0, 5.0, thread=THREAD_PRODUCER)  # overlapped
        led = worker_ledger(rec, 10.0, rank=0, start_step=0, end_step=8)
        b = led["buckets"]
        assert b["compile_s"] == 2.0
        assert b["data_wait_s"] == 1.0
        assert b["productive_s"] == pytest.approx(7.0)
        assert sum(b.values()) == pytest.approx(10.0)

    def test_assemble_replay_attribution(self, tmp_path):
        tdir = str(tmp_path)
        # attempt 1: reached step 10, died; attempt 2: resumed at 4 —
        # 6 of its 16 steps are replay
        _fake_ledger(tdir, wall=10.0, start=0, end=10, t0=100.0, pid=11)
        _fake_ledger(tdir, wall=16.0, start=4, end=20, t0=200.0, pid=22)
        report = assemble_goodput(tdir, wall_s=30.0, backoff_s=2.0,
                                  restarts=1)
        b = report["buckets"]
        assert b["backoff_s"] == 2.0
        # replay share: 6/16 of attempt 2's productive time (== 16s,
        # no stalls recorded)
        assert b["rollback_replay_s"] == pytest.approx(6.0)
        assert b["productive_s"] == pytest.approx(10.0 + 16.0 - 6.0)
        assert report["buckets_sum_s"] == pytest.approx(30.0, rel=1e-3)
        assert buckets_consistent(report)
        assert report["attempts"][1]["replay_steps"] == 6

    def test_assemble_no_ledgers_still_structured(self, tmp_path):
        report = assemble_goodput(str(tmp_path), wall_s=5.0)
        assert report["ledgers"] == 0
        assert report["buckets"]["other_s"] == pytest.approx(5.0)
        assert buckets_consistent(report)

    def test_buckets_consistent_rejects_gap(self):
        assert not buckets_consistent(
            {"wall_s": 10.0, "buckets": {"productive_s": 5.0}})


# --------------------------------------------------------------------------
# profiler
# --------------------------------------------------------------------------


class _FakeProfiler:
    def __init__(self, fail=False):
        self.fail = fail
        self.starts = []
        self.stops = 0

    def start_trace(self, d):
        if self.fail:
            raise RuntimeError("no profiling on this backend")
        self.starts.append(d)

    def stop_trace(self):
        self.stops += 1


class TestProfiler:
    def _patch(self, monkeypatch, fake):
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace",
                            fake.start_trace)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)

    def test_step_window(self, tmp_path, monkeypatch):
        from ray_lightning_tpu.telemetry import (
            ProfileConfig, ProfilerController,
        )

        fake = _FakeProfiler()
        self._patch(monkeypatch, fake)
        ctl = ProfilerController(ProfileConfig(
            dir=str(tmp_path), start_step=3, num_steps=2), rank=0)
        for step in range(1, 8):
            ctl.on_step(step)
        assert fake.starts == [str(tmp_path)]
        assert fake.stops == 1
        assert ctl.captures == 1
        assert not ctl.capturing

    def test_marker_trigger_and_rank_scope(self, tmp_path, monkeypatch):
        from ray_lightning_tpu.telemetry import (
            ProfileConfig, ProfilerController,
        )

        fake = _FakeProfiler()
        self._patch(monkeypatch, fake)
        cfg = ProfileConfig(dir=str(tmp_path), num_steps=1,
                            poll_every_n_steps=1)
        # rank 1 is out of scope: the marker must not trigger there
        other = ProfilerController(cfg, rank=1)
        ctl = ProfilerController(cfg, rank=0)
        (tmp_path / "CAPTURE").touch()
        other.on_step(1)
        assert fake.starts == []
        ctl.on_step(1)
        assert fake.starts == [str(tmp_path)]
        # marker is consumed: one touch = one capture
        assert not (tmp_path / "CAPTURE").exists()
        ctl.on_step(2)
        assert fake.stops == 1

    def test_backend_failure_disables_loudly(self, tmp_path,
                                             monkeypatch):
        from ray_lightning_tpu.telemetry import (
            ProfileConfig, ProfilerController,
        )

        fake = _FakeProfiler(fail=True)
        self._patch(monkeypatch, fake)
        ctl = ProfilerController(ProfileConfig(
            dir=str(tmp_path), start_step=1, num_steps=1), rank=0)
        with _capture_logs(
                "ray_lightning_tpu.telemetry.profiler") as records:
            ctl.on_step(1)
        assert ctl.disabled_reason
        assert any("DISABLED" in m for m in records)
        # disarmed: later steps never retry into the same failure
        ctl.on_step(2)
        assert fake.stops == 0

    def test_trainer_profile_knob(self, tmp_path, monkeypatch):
        fake = _FakeProfiler()
        self._patch(monkeypatch, fake)
        from ray_lightning_tpu.telemetry import ProfileConfig

        _mlp_fit(tmp_path, telemetry=False, steps=6, name="prof",
                 profile=ProfileConfig(dir=str(tmp_path / "traces"),
                                       start_step=2, num_steps=2))
        assert fake.starts == [str(tmp_path / "traces")]
        assert fake.stops == 1


# --------------------------------------------------------------------------
# heartbeat phase + stall attribution
# --------------------------------------------------------------------------


class TestHeartbeatPhase:
    def test_heartbeat_carries_phase_and_span(self):
        from ray_lightning_tpu.resilience.health import make_heartbeat

        hb = make_heartbeat(1, step=12, phase="ckpt_stall",
                            span={"phase": "ckpt_stall", "dur": 1.5,
                                  "step": 12, "t": 9.0})
        assert hb["phase"] == "ckpt_stall"
        assert hb["span"] == {"phase": "ckpt_stall", "dur": 1.5,
                              "step": 12}

    def test_stall_error_names_phase_and_step(self):
        from ray_lightning_tpu.resilience.health import (
            HealthMonitor, make_heartbeat,
        )
        from ray_lightning_tpu.resilience.policy import StallError

        mon = HealthMonitor(num_workers=1, stall_timeout_s=5.0,
                            startup_grace_s=1.0)
        mon.consume(0, make_heartbeat(0, step=42, phase="ckpt_stall"))
        with pytest.raises(StallError) as err:
            mon.check(now=time.monotonic() + 60.0)
        assert "ckpt_stall" in str(err.value)
        assert "42" in str(err.value)
        assert err.value.phase == "ckpt_stall"

    def test_compile_phase_reads_span_not_counter(self):
        from ray_lightning_tpu.resilience.health import (
            HealthMonitor, make_heartbeat,
        )

        mon = HealthMonitor(num_workers=1, stall_timeout_s=1e9,
                            step_stall_note_s=5.0)
        t0 = time.monotonic()
        mon.consume(0, make_heartbeat(0, step=10, phase="compile"))
        # keep the channel live but the step frozen past the note budget
        with _capture_logs(
                "ray_lightning_tpu.resilience.health") as records:
            mon._last_seen[0] = t0 + 59.0
            mon.check(now=t0 + 60.0)
        assert any("XLA compile" in m for m in records)
        assert mon.snapshot()[0]["phase"] == "compile"


# --------------------------------------------------------------------------
# ThroughputMonitor compile-skew
# --------------------------------------------------------------------------


class TestThroughputMonitorSkew:
    def _run(self, intervals, skip_first=1):
        from ray_lightning_tpu.core.callbacks import ThroughputMonitor

        ticks = [0.0]
        for dt in intervals:
            ticks.append(ticks[-1] + dt)
        it = iter(ticks)
        mon = ThroughputMonitor(window=20, skip_first=skip_first,
                                clock=lambda: next(it))

        class T:
            callback_metrics = {}
            last_batch_size = 32

        t = T()
        mon.on_fit_start(t, None)
        mon.on_train_epoch_start(t, None)
        for i in range(len(intervals)):
            mon.on_train_batch_end(t, None, {}, i)
        return t.callback_metrics

    def test_cold_compile_interval_excluded(self):
        # first "step" is a 10s lazy compile against 0.1s warm steps —
        # the window mean must be the warm step time, not 2.575s
        metrics = self._run([10.0, 0.1, 0.1, 0.1])
        assert metrics["step_time_s"] == pytest.approx(0.1)
        assert metrics["examples_per_sec"] == pytest.approx(320.0)

    def test_skip_zero_reproduces_the_skew(self):
        metrics = self._run([10.0, 0.1, 0.1, 0.1], skip_first=0)
        assert metrics["step_time_s"] == pytest.approx(2.575)


# --------------------------------------------------------------------------
# report + drift
# --------------------------------------------------------------------------


class TestReportDrift:
    def test_build_drift_placeholder_when_unmeasured(self):
        from ray_lightning_tpu.telemetry.report import build_drift

        drift = build_drift({"step_us": 1000.0,
                             "overlap_hidden_fraction": 0.9},
                            timeline=None)
        assert drift["verdict"] == "not-measured"
        assert drift["measured"]["step_us"] is None
        assert "skipped" in drift["measured"]

    def test_build_drift_flags_slow_step(self):
        from ray_lightning_tpu.telemetry.report import build_drift

        timeline = {"step_stats": {"steps": 10, "mean_s": 2e-3,
                                   "p50_s": 2e-3, "max_s": 2e-3}}
        drift = build_drift({"step_us": 1000.0}, timeline)
        assert drift["step_time_ratio"] == pytest.approx(2.0)
        assert drift["verdict"] == "drift"
        assert drift["flags"]

    def test_build_drift_ok_within_threshold(self):
        from ray_lightning_tpu.telemetry.report import build_drift

        timeline = {"step_stats": {"steps": 10, "mean_s": 1.1e-3,
                                   "p50_s": 1.1e-3, "max_s": 1.2e-3}}
        drift = build_drift({"step_us": 1000.0}, timeline)
        assert drift["verdict"] == "ok"
        assert not drift["flags"]

    def test_report_on_real_run_dir(self, tmp_path):
        _mlp_fit(tmp_path, telemetry=True, steps=6, name="reported")
        from ray_lightning_tpu.telemetry.report import build_report

        out = build_report(str(tmp_path / "reported"))
        assert 0 in [int(r) for r in out["phase_totals"]]
        assert out["step_stats"]["steps"] >= 1
        json.dumps(out)  # the --json path must be serializable

    def test_report_cli_json(self, tmp_path, capsys):
        _mlp_fit(tmp_path, telemetry=True, steps=4, name="cli")
        from ray_lightning_tpu.__main__ import main

        rc = main(["report", str(tmp_path / "cli"), "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["step_stats"] is not None

    def test_monitor_cli_one_shot(self, tmp_path, capsys):
        _mlp_fit(tmp_path, telemetry=True, steps=4, name="mon")
        from ray_lightning_tpu.__main__ import main

        rc = main(["monitor", str(tmp_path / "mon"), "--json"])
        assert rc == 0
        view = json.loads(capsys.readouterr().out.strip())
        assert view["ranks"]["0"]["phase"] is not None

    def test_predicted_composition_tiny_topo(self):
        from ray_lightning_tpu.telemetry.report import (
            predicted_step_composition,
        )

        pred = predicted_step_composition("llama3-8b", "v5p-8")
        assert "error" not in pred
        assert pred["ici_time_us"] > 0
        assert pred["overlap_hidden_fraction"] >= 0.0


# --------------------------------------------------------------------------
# RLT501
# --------------------------------------------------------------------------


class TestRLT501:
    def _rules(self, src):
        from ray_lightning_tpu.analysis.linter import lint_source

        return [f for f in lint_source(src, "x.py")
                if f.rule == "RLT501"]

    def test_flush_per_batch_fires(self):
        src = ("def run(loader, telemetry):\n"
               "    for batch in loader:\n"
               "        telemetry.flush()\n")
        assert len(self._rules(src)) == 1

    def test_span_per_batch_fires(self):
        src = ("def run(loader, recorder):\n"
               "    for batch in loader:\n"
               "        with recorder.span('dispatch'):\n"
               "            pass\n")
        assert len(self._rules(src)) == 1

    def test_cadence_guard_sanctions(self):
        src = ("def run(loader, telemetry):\n"
               "    step = 0\n"
               "    for batch in loader:\n"
               "        step += 1\n"
               "        if step % 50 == 0:\n"
               "            telemetry.flush()\n")
        assert self._rules(src) == []

    def test_unbounded_callback_append_fires(self):
        src = ("class EventsCallback(Callback):\n"
               "    def __init__(self):\n"
               "        self.events = []\n"
               "    def on_train_batch_end(self, t, m, metrics, i):\n"
               "        self.events.append(metrics)\n")
        found = self._rules(src)
        assert len(found) == 1
        assert "EventsCallback" in found[0].message

    def test_bounded_callback_patterns_clean(self):
        src = ("import collections\n"
               "class RingCallback(Callback):\n"
               "    def __init__(self):\n"
               "        self.events = collections.deque(maxlen=8)\n"
               "    def on_train_batch_end(self, t, m, metrics, i):\n"
               "        self.events.append(metrics)\n"
               "class TruncCallback(Callback):\n"
               "    def __init__(self):\n"
               "        self.events = []\n"
               "    def on_train_batch_end(self, t, m, metrics, i):\n"
               "        self.events.append(metrics)\n"
               "        self.events = self.events[-10:]\n"
               "class FlushCallback(Callback):\n"
               "    def __init__(self):\n"
               "        self.events = []\n"
               "    def on_train_batch_end(self, t, m, metrics, i):\n"
               "        self.events.append(metrics)\n"
               "    def on_train_epoch_end(self, t, m):\n"
               "        self.events.clear()\n")
        assert self._rules(src) == []

    def test_repo_lints_clean(self):
        from ray_lightning_tpu.analysis.linter import lint_paths

        pkg = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "ray_lightning_tpu")
        assert [f for f in lint_paths([pkg])
                if f.rule == "RLT501"] == []


# --------------------------------------------------------------------------
# bench gate: goodput ratchet + overhead bound
# --------------------------------------------------------------------------


def _bench_gate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchGateTelemetry:
    def test_goodput_fraction_ratchets(self):
        bg = _bench_gate()
        best = {"goodput_fraction": (0.9, "r06")}
        assert bg.gate({"metric": "m", "value": 1.0,
                        "goodput_fraction": 0.92}, best, 0.05) == []
        bad = bg.gate({"metric": "m", "value": 1.0,
                       "goodput_fraction": 0.5}, best, 0.05)
        assert bad and "goodput_fraction" in bad[0]

    def test_goodput_waived_on_skip(self):
        bg = _bench_gate()
        best = {"goodput_fraction": (0.9, "r06")}
        line = {"metric": "m", "skipped": "backend unavailable",
                "goodput_fraction": 0.0}
        assert bg.gate(line, best, 0.05) == []

    def test_overhead_bound(self):
        bg = _bench_gate()
        ok = {"metric": "m", "value": 1.0,
              "telemetry_overhead_fraction": 0.003}
        bad = {"metric": "m", "value": 1.0,
               "telemetry_overhead_fraction": 0.03}
        absent = {"metric": "m", "value": 1.0}
        null = {"metric": "m", "value": 1.0,
                "telemetry_overhead_fraction": None}
        assert bg.gate(ok, {}, 0.05) == []
        assert bg.gate(absent, {}, 0.05) == []
        assert bg.gate(null, {}, 0.05) == []
        fail = bg.gate(bad, {}, 0.05)
        assert fail and "telemetry_overhead_fraction" in fail[0]

    def test_overhead_waived_on_skip(self):
        bg = _bench_gate()
        line = {"metric": "m", "skipped": "killed: SIGTERM",
                "telemetry_overhead_fraction": 0.5}
        assert bg.gate(line, {}, 0.05) == []

    def test_bench_overhead_measure_is_tiny(self):
        # the measured recorder cost against a realistic 10 ms step:
        # far under the 1% gate, or the bound is meaningless
        import bench

        frac = bench._telemetry_overhead_fraction(step_dt=0.010, n=500)
        assert frac < 0.01

    def test_bench_telemetry_summary_schema(self):
        import bench

        summary = bench._telemetry_summary()
        assert "telemetry_error" not in summary
        assert "buckets" in summary["goodput"]["schema"]
        assert "dispatch" in summary["telemetry"]["span_phases"]


# --------------------------------------------------------------------------
# supervised goodput (2-proc, fault-injected) — the satellite-3 pin
# --------------------------------------------------------------------------


@pytest.mark.slow
class TestSupervisedGoodput:
    def test_kill_restart_buckets_sum_and_replay(self, tmp_path):
        from ray_lightning_tpu.resilience.cli import (
            _smoke_data, _smoke_module, _smoke_trainer,
        )
        from ray_lightning_tpu.resilience.policy import RetryPolicy
        from ray_lightning_tpu.resilience.supervisor import (
            ResilienceConfig, fit_supervised,
        )
        from ray_lightning_tpu.telemetry import buckets_consistent

        cfg = ResilienceConfig(
            checkpoint_dir=str(tmp_path / "ckpts"),
            policy=RetryPolicy(max_restarts=2, backoff_base_s=0.5,
                               jitter=0.0),
            save_every_n_steps=5,
            heartbeat_interval_s=1.0, stall_timeout_s=0.0,
            faults="kill:rank=1,step=3")
        supervised = fit_supervised(
            _smoke_module, _smoke_trainer, _smoke_data, 2,
            resilience=cfg, platform="cpu",
            num_cpu_devices_per_process=1, return_weights=False,
            timeout=300.0)
        assert supervised.restarts >= 1
        report = supervised.goodput
        assert report is not None
        assert buckets_consistent(report, tolerance=0.05)
        assert report["buckets"]["backoff_s"] > 0
        assert report["buckets"]["rollback_replay_s"] > 0
        # persisted beside the checkpoints for the report CLI
        assert os.path.exists(os.path.join(
            str(tmp_path / "ckpts"), "telemetry", "goodput.json"))
