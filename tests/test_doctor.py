"""The `python -m ray_lightning_tpu` doctor: topology report correctness
(run in a subprocess so it controls its own backend)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_doctor_collect_reports_topology():
    code = (
        "from ray_lightning_tpu import simulate_cpu_devices\n"
        "simulate_cpu_devices(8)\n"
        "import json\n"
        "from ray_lightning_tpu.__main__ import collect\n"
        "print(json.dumps(collect()))\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=180, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    info = json.loads(out.stdout.strip().splitlines()[-1])
    assert info["backend"] == "cpu"
    assert info["local_devices"] == 8
    assert len(info["devices"]) == 8
    assert info["devices"][0]["platform"] == "cpu"
    assert info["process_count"] == 1


def test_doctor_main_human_output(capsys):
    from ray_lightning_tpu.__main__ import main

    assert main([]) == 0
    out = capsys.readouterr().out
    assert "ray_lightning_tpu" in out
    assert "devices" in out


def test_doctor_plan_subcommand(capsys):
    """`plan` sizes a model against a mesh/chip with no devices touched;
    exit status encodes fits (0) vs does-not-fit (1)."""
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "llama3-8b", "--fsdp", "64",
               "--batch", "64", "--seq", "8192",
               "--device-kind", "TPU v5p", "--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and info["fits"] is True
    assert info["mesh"] == {"fsdp": 64}

    # --json BEFORE the subcommand must work too (the subparser writes
    # into the same namespace; its default must not clobber the parent's)
    rc = main(["--json", "plan", "--preset", "llama3-8b", "--fsdp", "64",
               "--batch", "64", "--seq", "8192"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and info["fits"] is True

    rc = main(["plan", "--preset", "llama3-8b", "--fsdp", "8",
               "--batch", "8", "--seq", "8192",
               "--device-kind", "TPU v5e"])
    out = capsys.readouterr().out
    assert rc == 1 and "DOES NOT FIT" in out

    # unshardable batch: refused (exit 2, error on stderr / a JSON error
    # object with --json), never a bogus FITS
    rc = main(["plan", "--preset", "llama3-8b", "--data", "4",
               "--fsdp", "64", "--batch", "64"])
    captured = capsys.readouterr()
    assert rc == 2 and "not divisible" in captured.err
    rc = main(["plan", "--preset", "llama3-8b", "--data", "4",
               "--fsdp", "64", "--batch", "64", "--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 2 and "not divisible" in info["error"]


def test_doctor_plan_invalid_mesh_exits_2(capsys):
    """EVERY invalid configuration honors the documented exit-2 contract
    — not just batch divisibility: a mesh the model cannot shard
    (tensor=5 against dim=128) must exit 2 with a structured error, not
    escape as a traceback indistinguishable from exit-1 "does not fit"
    (ADVICE r4)."""
    from ray_lightning_tpu.__main__ import main

    args = ["plan", "--preset", "tiny", "--tensor", "5", "--fsdp", "1",
            "--data", "1", "--batch", "5", "--seq", "128"]
    rc = main(args + ["--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 2 and "partitioned" in info["error"]
    rc = main(args)
    captured = capsys.readouterr()
    assert rc == 2 and "error:" in captured.err


def test_doctor_plan_zero_axis_exits_2(capsys):
    """A zero/negative mesh axis must exit 2, not ZeroDivisionError into
    an exit-1 traceback a scripted consumer reads as 'does not fit'."""
    from ray_lightning_tpu.__main__ import main

    rc = main(["plan", "--preset", "tiny", "--data", "0", "--batch", "8",
               "--seq", "128", "--json"])
    info = json.loads(capsys.readouterr().out.strip())
    assert rc == 2 and "--data" in info["error"]


def test_doctor_plan_ce_inline_flag(capsys):
    """--ce-inline-bwd plans the inline-CE config: residuals charged
    (sharded dW — the fsdp x tensor degree divides the [D, V] term), and
    the 8B FSDP north-star still fits with it on."""
    from ray_lightning_tpu.__main__ import main

    base = ["plan", "--preset", "llama3-8b", "--fsdp", "64",
            "--batch", "64", "--seq", "8192", "--device-kind", "TPU v5p",
            "--json"]
    rc = main(base)
    a = json.loads(capsys.readouterr().out.strip())
    rc2 = main(base + ["--ce-inline-bwd"])
    b = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and rc2 == 0
    assert b["fits"] is True
    assert b["per_device_bytes"] > a["per_device_bytes"]


def test_doctor_plan_find_max_batch(capsys):
    """--find-max-batch reports the largest per-device batch for the
    mesh/chip (auto_scale_batch_size, plan-side): global = local x dp,
    and --batch is ignored entirely — an indivisible default must not
    trip the divisibility refusal."""
    from ray_lightning_tpu.__main__ import main

    # data=3: the default --batch 64 is NOT divisible by dp=3; the flag
    # ignores --batch so this must still plan (rc 0/1, never 2)
    rc = main(["plan", "--preset", "tiny", "--data", "3", "--fsdp", "1",
               "--seq", "128", "--device-kind", "TPU v5e",
               "--find-max-batch", "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0, out
    assert out["fits"] is True
    assert out["max_local_batch"] >= 1
    assert out["max_global_batch"] == out["max_local_batch"] * 3
    assert out["dp_degree"] == 3


def test_doctor_plan_find_max_batch_no_fit_labelled(capsys):
    """local==0 returns the activation-free plan, whose own summary can
    read FITS (the weights fit; no batch does) — the CLI must label it
    so neither a human nor a script reads a contradiction."""
    from ray_lightning_tpu.__main__ import main

    # 8B over 64 v3 chips: ~1.9 GiB/device of weights fit easily, but
    # one S=32768 row's activations alone overflow 16 GiB
    rc = main(["plan", "--preset", "llama3-8b", "--fsdp", "64",
               "--seq", "32768", "--device-kind", "TPU v3",
               "--find-max-batch", "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1
    assert out["fits"] is False and out["max_local_batch"] == 0
    assert out["summary"].startswith("no local batch fits")
