"""Fused chunked cross-entropy (ops/fused_ce.py) vs the materialized-logits
reference path: forward and gradients must agree; the LlamaModule loss must
ride it end-to-end."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import (
    LlamaConfig,
    LlamaModule,
    cross_entropy_loss,
)
from ray_lightning_tpu.ops import fused_cross_entropy


def _setup(B=2, S=32, D=16, V=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((B, S, D)), dtype)
    w = jnp.asarray(rng.standard_normal((D, V)) * 0.1, dtype)
    targets = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (B, S)), jnp.int32)
    return hidden, w, targets, mask


def _reference(hidden, w, targets, mask):
    logits = (hidden @ w).astype(jnp.float32)
    return cross_entropy_loss(logits, targets, mask)


@pytest.mark.parametrize("chunk_tokens", [8, 17, 64, 4096])
def test_fused_ce_matches_reference_forward(chunk_tokens):
    hidden, w, targets, mask = _setup()
    ref = _reference(hidden, w, targets, mask)
    fused = fused_cross_entropy(hidden, w, targets, mask,
                                chunk_tokens=chunk_tokens,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)


def test_fused_ce_prime_token_count_stays_tiled():
    """A prime T must pad to tiles, never collapse to one [T, V] tile
    (the memory bound must hold unconditionally)."""
    hidden, w, targets, mask = _setup(B=1, S=31)  # T=31, prime
    ref = _reference(hidden, w, targets, mask)
    fused = fused_cross_entropy(hidden, w, targets, mask, chunk_tokens=8,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)
    # no-mask variant: padded rows must not contribute to the mean
    ref2 = _reference(hidden, w, targets, None)
    fused2 = fused_cross_entropy(hidden, w, targets, None, chunk_tokens=8,
                                 compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused2), np.asarray(ref2),
                               rtol=1e-5)


def test_fused_ce_no_mask():
    hidden, w, targets, _ = _setup()
    ref = _reference(hidden, w, targets, None)
    fused = fused_cross_entropy(hidden, w, targets, None, chunk_tokens=16,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)


def test_fused_ce_grads_match():
    hidden, w, targets, mask = _setup()

    g_ref = jax.grad(lambda h, w_: _reference(h, w_, targets, mask),
                     argnums=(0, 1))(hidden, w)
    g_fused = jax.grad(
        lambda h, w_: fused_cross_entropy(
            h, w_, targets, mask, chunk_tokens=16,
            compute_dtype=jnp.float32),
        argnums=(0, 1),
    )(hidden, w)
    for a, b in zip(g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_llama_module_fused_vs_logits_loss():
    """The module's fused loss path equals its logits path on the same
    params/batch (tiny config, f32 so differences are reduction-order only)."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32, use_flash=False)
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, cfg.vocab_size, (2, 33)).astype(np.int32)}

    m_fused = LlamaModule(cfg, fused_ce=True, ce_chunk_tokens=16)
    m_fused.setup()
    params = m_fused.init_params(jax.random.key(0), batch)

    inputs, targets, mask = m_fused._split(batch)
    loss_fused = m_fused._loss(params, inputs, targets, mask)

    m_logits = LlamaModule(cfg, fused_ce=False)
    m_logits.setup()
    loss_logits = m_logits._loss(params, inputs, targets, mask)
    np.testing.assert_allclose(np.asarray(loss_fused),
                               np.asarray(loss_logits), rtol=1e-5)


def test_llama_module_fused_tied_embeddings():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, use_flash=False,
                           tie_embeddings=True)
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, cfg.vocab_size, (2, 17)).astype(np.int32)}
    m = LlamaModule(cfg, fused_ce=True, ce_chunk_tokens=8)
    m.setup()
    params = m.init_params(jax.random.key(0), batch)
    inputs, targets, mask = m._split(batch)
    loss = m._loss(params, inputs, targets, mask)
    m2 = LlamaModule(cfg, fused_ce=False)
    m2.setup()
    ref = m2._loss(params, inputs, targets, mask)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)
