"""Fused chunked cross-entropy (ops/fused_ce.py) vs the materialized-logits
reference path: forward and gradients must agree; the LlamaModule loss must
ride it end-to-end."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.models.llama import (
    LlamaConfig,
    LlamaModule,
    cross_entropy_loss,
)
from ray_lightning_tpu.ops import fused_cross_entropy


def _setup(B=2, S=32, D=16, V=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((B, S, D)), dtype)
    w = jnp.asarray(rng.standard_normal((D, V)) * 0.1, dtype)
    targets = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (B, S)), jnp.int32)
    return hidden, w, targets, mask


def _reference(hidden, w, targets, mask):
    logits = (hidden @ w).astype(jnp.float32)
    return cross_entropy_loss(logits, targets, mask)


@pytest.mark.parametrize("chunk_tokens", [8, 17, 64, 4096])
def test_fused_ce_matches_reference_forward(chunk_tokens):
    hidden, w, targets, mask = _setup()
    ref = _reference(hidden, w, targets, mask)
    fused = fused_cross_entropy(hidden, w, targets, mask,
                                chunk_tokens=chunk_tokens,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)


def test_fused_ce_prime_token_count_stays_tiled():
    """A prime T must pad to tiles, never collapse to one [T, V] tile
    (the memory bound must hold unconditionally)."""
    hidden, w, targets, mask = _setup(B=1, S=31)  # T=31, prime
    ref = _reference(hidden, w, targets, mask)
    fused = fused_cross_entropy(hidden, w, targets, mask, chunk_tokens=8,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)
    # no-mask variant: padded rows must not contribute to the mean
    ref2 = _reference(hidden, w, targets, None)
    fused2 = fused_cross_entropy(hidden, w, targets, None, chunk_tokens=8,
                                 compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused2), np.asarray(ref2),
                               rtol=1e-5)


def test_fused_ce_no_mask():
    hidden, w, targets, _ = _setup()
    ref = _reference(hidden, w, targets, None)
    fused = fused_cross_entropy(hidden, w, targets, None, chunk_tokens=16,
                                compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=1e-5)


def test_fused_ce_grads_match():
    hidden, w, targets, mask = _setup()

    g_ref = jax.grad(lambda h, w_: _reference(h, w_, targets, mask),
                     argnums=(0, 1))(hidden, w)
    g_fused = jax.grad(
        lambda h, w_: fused_cross_entropy(
            h, w_, targets, mask, chunk_tokens=16,
            compute_dtype=jnp.float32),
        argnums=(0, 1),
    )(hidden, w)
    for a, b in zip(g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_llama_module_fused_vs_logits_loss():
    """The module's fused loss path equals its logits path on the same
    params/batch (tiny config, f32 so differences are reduction-order only)."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32, use_flash=False)
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, cfg.vocab_size, (2, 33)).astype(np.int32)}

    m_fused = LlamaModule(cfg, fused_ce=True, ce_chunk_tokens=16)
    m_fused.setup()
    params = m_fused.init_params(jax.random.key(0), batch)

    inputs, targets, mask = m_fused._split(batch)
    loss_fused = m_fused._loss(params, inputs, targets, mask)

    m_logits = LlamaModule(cfg, fused_ce=False)
    m_logits.setup()
    loss_logits = m_logits._loss(params, inputs, targets, mask)
    np.testing.assert_allclose(np.asarray(loss_fused),
                               np.asarray(loss_logits), rtol=1e-5)


def test_llama_module_fused_tied_embeddings():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, use_flash=False,
                           tie_embeddings=True)
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, cfg.vocab_size, (2, 17)).astype(np.int32)}
    m = LlamaModule(cfg, fused_ce=True, ce_chunk_tokens=8)
    m.setup()
    params = m.init_params(jax.random.key(0), batch)
    inputs, targets, mask = m._split(batch)
    loss = m._loss(params, inputs, targets, mask)
    m2 = LlamaModule(cfg, fused_ce=False)
    m2.setup()
    ref = m2._loss(params, inputs, targets, mask)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)


class TestInlineBackward:
    """inline_backward=True computes (dx, dW) during the forward scan and
    the custom_vjp just scales by the upstream cotangent — must match the
    autodiff-through-remat path's loss AND grads exactly (f32 compute),
    for any cotangent scale, any mask, and padded tile counts."""

    @pytest.mark.parametrize("chunk_tokens", [8, 17, 4096])
    def test_loss_and_grads_match_reference(self, chunk_tokens):
        hidden, w, targets, mask = _setup()

        def ref_loss(h, w):
            return _reference(h, w, targets, mask)

        def inline_loss(h, w):
            return fused_cross_entropy(h, w, targets, mask,
                                       chunk_tokens=chunk_tokens,
                                       compute_dtype=jnp.float32,
                                       inline_backward=True)

        l_ref, g_ref = jax.value_and_grad(ref_loss, argnums=(0, 1))(hidden, w)
        l_inl, g_inl = jax.value_and_grad(inline_loss, argnums=(0, 1))(
            hidden, w)
        np.testing.assert_allclose(np.asarray(l_inl), np.asarray(l_ref),
                                   rtol=1e-5)
        for a, b in zip(g_ref, g_inl):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-5)

    def test_unrolled_and_scan_branches_agree(self, monkeypatch):
        """The inline forward unrolls the chunk chain when n_chunks <=
        RLT_CE_INLINE_UNROLL_MAX and falls back to lax.scan above it —
        the two lowerings must produce the same loss and grads (the
        unroll exists purely to sidestep the TPU compiler's pathological
        handling of a scan whose carry is the [D, V] dW accumulator)."""
        hidden, w, targets, mask = _setup()

        def loss(h, w):
            # chunk_tokens=16 over T=2*32 tokens -> n_chunks=4
            return fused_cross_entropy(h, w, targets, mask,
                                       chunk_tokens=16,
                                       compute_dtype=jnp.float32,
                                       inline_backward=True)

        # pin the ceiling explicitly: an ambient override <= 3 would send
        # BOTH calls down the scan branch and the test would pass
        # vacuously
        monkeypatch.setenv("RLT_CE_INLINE_UNROLL_MAX", "16")
        l_u, g_u = jax.value_and_grad(loss, argnums=(0, 1))(hidden, w)
        monkeypatch.setenv("RLT_CE_INLINE_UNROLL_MAX", "1")
        l_s, g_s = jax.value_and_grad(loss, argnums=(0, 1))(hidden, w)
        np.testing.assert_allclose(np.asarray(l_u), np.asarray(l_s),
                                   rtol=1e-6)
        for a, b in zip(g_u, g_s):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-6)
        # malformed env falls back to the default ceiling, not a crash
        monkeypatch.setenv("RLT_CE_INLINE_UNROLL_MAX", "not-an-int")
        l_m, _ = jax.value_and_grad(loss, argnums=(0, 1))(hidden, w)
        np.testing.assert_allclose(np.asarray(l_m), np.asarray(l_u),
                                   rtol=1e-6)

    def test_cotangent_scaling_exact(self):
        """The residuals are computed for g=1 and SCALED in bwd — a
        non-unit upstream cotangent (loss used inside a larger graph,
        grad accumulation) must scale grads exactly linearly."""
        hidden, w, targets, mask = _setup()

        def scaled(h, w):
            return 3.5 * fused_cross_entropy(h, w, targets, mask,
                                             chunk_tokens=16,
                                             compute_dtype=jnp.float32,
                                             inline_backward=True)

        def unscaled(h, w):
            return fused_cross_entropy(h, w, targets, mask,
                                       chunk_tokens=16,
                                       compute_dtype=jnp.float32,
                                       inline_backward=True)

        g_s = jax.grad(scaled, argnums=(0, 1))(hidden, w)
        g_u = jax.grad(unscaled, argnums=(0, 1))(hidden, w)
        for a, b in zip(g_u, g_s):
            np.testing.assert_allclose(np.asarray(b), 3.5 * np.asarray(a),
                                       rtol=1e-6)

    def test_no_mask_and_prime_token_count(self):
        """Padded rows (prime T) contribute nothing to loss or grads."""
        hidden, w, targets, _ = _setup(B=1, S=31)  # T=31, prime

        def ref_loss(h, w):
            return _reference(h, w, targets, None)

        def inline_loss(h, w):
            return fused_cross_entropy(h, w, targets,
                                       chunk_tokens=8,
                                       compute_dtype=jnp.float32,
                                       inline_backward=True)

        l_ref, g_ref = jax.value_and_grad(ref_loss, argnums=(0, 1))(hidden, w)
        l_inl, g_inl = jax.value_and_grad(inline_loss, argnums=(0, 1))(
            hidden, w)
        np.testing.assert_allclose(np.asarray(l_inl), np.asarray(l_ref),
                                   rtol=1e-5)
        for a, b in zip(g_ref, g_inl):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-5)

    def test_primal_only_path_no_grad(self):
        """Without differentiation the loss value still matches (the
        primal call takes the plain chunked path, no gradient work)."""
        hidden, w, targets, mask = _setup()
        a = fused_cross_entropy(hidden, w, targets, mask, chunk_tokens=16,
                                compute_dtype=jnp.float32)
        b = fused_cross_entropy(hidden, w, targets, mask, chunk_tokens=16,
                                compute_dtype=jnp.float32,
                                inline_backward=True)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-6)

    @pytest.mark.slow  # ~10s double train-step compile; the inline-bwd
    #                    grads stay pinned in tier-1 by
    #                    test_loss_and_grads_match_reference and the
    #                    module wiring by
    #                    test_llama_module_fused_vs_logits_loss
    def test_module_end_to_end_grads(self):
        """LlamaModule(ce_inline_bwd=True): full train-step grads match
        the default fused path's on the same params/batch."""
        def make(inline):
            cfg = LlamaConfig.tiny(fused_ce=True, ce_chunk_tokens=16,
                                   ce_inline_bwd=inline, dtype=jnp.float32)
            return LlamaModule(cfg)

        rng = np.random.default_rng(3)
        tokens = jnp.asarray(rng.integers(0, 256, (2, 33)), jnp.int32)
        mod_a, mod_b = make(False), make(True)
        mod_a.setup()
        mod_b.setup()
        params = jax.jit(mod_a.model.init)(jax.random.key(0),
                                           tokens[:, :-1])["params"]

        def loss_fn(module):
            def f(p):
                return module._loss(p, tokens[:, :-1], tokens[:, 1:], None)
            return f

        la, ga = jax.value_and_grad(loss_fn(mod_a))(params)
        lb, gb = jax.value_and_grad(loss_fn(mod_b))(params)
        np.testing.assert_allclose(float(lb), float(la), rtol=1e-5)
        flat_a = jax.tree.leaves(ga)
        flat_b = jax.tree.leaves(gb)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=2e-5)


def test_inline_without_fused_ce_is_refused():
    """ce_inline_bwd on a config whose fused CE resolves OFF must raise
    at construction — a silent no-op would let users believe they
    measured the inline path (and the planner charge for residuals that
    never exist)."""
    with pytest.raises(ValueError, match="ce_inline_bwd"):
        LlamaConfig.tiny(ce_inline_bwd=True)  # auto-off at vocab=256
    with pytest.raises(ValueError, match="ce_inline_bwd"):
        LlamaConfig.tiny(fused_ce=False, ce_inline_bwd=True)
    LlamaConfig.tiny(fused_ce=True, ce_inline_bwd=True)  # explicit: fine
