"""Trace-driven load harness + traffic & SLO classes (ISSUE 20,
docs/SERVING.md "traffic & SLO classes"): the versioned
byte-deterministic trace format, the seeded arrival generator, the
virtual-clock runner (and the autoscale/sim shim over it), the
traffic-aware scheduler seams — class-major admission,
strictly-lower-class admit-preemption (never peers), typed budget
sheds with capped-exponential retry-after hints, `slo=None`
byte-identical to the historical FIFO policy — per-class
metrics/watch accounting, the bench/bench_gate traffic fields, and
(slow) a mid-burst SIGKILL on a process replica replaying bitwise
with per-class accounting consistent across the channel epoch roll."""
from __future__ import annotations

import importlib
import json
import os
import sys
import time

import numpy as np
import pytest

from ray_lightning_tpu.loadgen.generator import (
    WorkloadConfig,
    generate_events,
)
from ray_lightning_tpu.loadgen.runner import run_trace
from ray_lightning_tpu.loadgen.trace import (
    TraceEvent,
    arrivals_by_tick,
    dump_trace,
    events_from_arrivals,
    read_trace,
    to_request,
    write_trace,
)
from ray_lightning_tpu.serve.scheduler import (
    ClassSLO,
    Request,
    Scheduler,
    SLOConfig,
)

# ---- trace format ----------------------------------------------------------


def test_trace_bytes_deterministic_and_seed_sensitive():
    wl = WorkloadConfig(seed=5, n_requests=12, process="mmpp")
    a = dump_trace(generate_events(wl), wl.meta())
    b = dump_trace(generate_events(wl), wl.meta())
    assert a == b, "same config must serialize byte-identically"
    wl2 = WorkloadConfig(seed=6, n_requests=12, process="mmpp")
    assert a != dump_trace(generate_events(wl2), wl2.meta())


def test_trace_round_trip_and_version_refusal(tmp_path):
    wl = WorkloadConfig(seed=3, n_requests=6)
    events = generate_events(wl)
    path = str(tmp_path / "t.jsonl")
    write_trace(path, events, wl.meta())
    header, back = read_trace(path)
    assert header["meta"]["seed"] == 3
    assert dump_trace(back, header["meta"]) == \
        dump_trace(events, wl.meta())
    # a future trace version must be refused, never misread
    lines = open(path).read().splitlines()
    doc = json.loads(lines[0])
    doc["version"] = 999
    with open(path, "w") as f:
        f.write("\n".join([json.dumps(doc)] + lines[1:]) + "\n")
    with pytest.raises(ValueError, match="version"):
        read_trace(path)


def test_trace_event_to_request_and_priority_default():
    ev = TraceEvent(tick=2, rid="x", prompt=(1, 2, 3), max_new_tokens=4,
                    priority="latency_critical", temperature=0.5,
                    top_k=3, seed=9)
    req = to_request(ev)
    assert isinstance(req, Request)
    assert req.priority == "latency_critical" and req.seed == 9
    np.testing.assert_array_equal(np.asarray(req.prompt),
                                  np.array([1, 2, 3], np.int32))
    # a pre-traffic-class trace line (no priority key) reads as standard
    d = ev.to_dict()
    del d["priority"]
    assert TraceEvent.from_dict(d).priority == "standard"
    # arrivals grouping + its inverse round-trip
    evs = generate_events(WorkloadConfig(seed=1, n_requests=5))
    assert events_from_arrivals(arrivals_by_tick(evs)) == \
        sorted(evs, key=lambda e: (e.tick, e.rid))


def test_generator_class_mix_and_process_shapes():
    wl = WorkloadConfig(seed=8, n_requests=40, process="poisson",
                        class_mix={"latency_critical": 0.5,
                                   "best_effort": 0.5})
    evs = generate_events(wl)
    assert len(evs) == 40
    assert {e.priority for e in evs} <= {"latency_critical",
                                         "best_effort"}
    for e in evs:
        assert wl.prompt_len_min <= len(e.prompt) <= wl.prompt_len_max
        assert wl.max_new_min <= e.max_new_tokens <= wl.max_new_max
    # the bursty process produces a different arrival pattern
    mm = generate_events(WorkloadConfig(seed=8, n_requests=40,
                                        process="mmpp"))
    assert [e.tick for e in mm] != [e.tick for e in evs]
    with pytest.raises(ValueError):
        WorkloadConfig(process="weibull")
    with pytest.raises(ValueError):
        WorkloadConfig(class_mix={"vip": 1.0}).mix()


# ---- runner + the autoscale/sim shim ---------------------------------------


class _StubDriver:
    """Records the runner's submit/tick schedule; drains after a fixed
    number of ticks per outstanding request."""

    def __init__(self):
        self.submitted = []
        self.ticks = 0
        self._outstanding = 0

    def submit(self, req):
        self.submitted.append((self.ticks, req.rid))
        self._outstanding += 1

    def tick(self):
        self.ticks += 1
        if self._outstanding and self.ticks % 2 == 0:
            self._outstanding -= 1

    def busy(self):
        return self._outstanding > 0


def test_runner_and_sim_shim_drive_the_same_schedule():
    from ray_lightning_tpu.autoscale.sim import ScriptedLoad, run_scripted

    evs = generate_events(WorkloadConfig(seed=4, n_requests=6))
    arrivals = arrivals_by_tick(evs)
    a, b, c = _StubDriver(), _StubDriver(), _StubDriver()
    ra = run_trace(a, arrivals, idle_ticks_after_drain=2)
    # the runner accepts the raw event sequence too
    rb = run_trace(b, evs, idle_ticks_after_drain=2)
    load = ScriptedLoad(
        arrivals={t: [to_request(e) for e in sorted(
            g, key=lambda e: e.rid)] for t, g in
            arrivals_by_tick(evs).items()},
        idle_ticks_after_drain=2)
    rc = run_scripted(c, None, load)
    assert a.submitted == b.submitted == c.submitted
    assert ra["submitted"] == rb["submitted"] == len(evs)
    assert ra["ticks"] == rb["ticks"] == rc["ticks"]
    assert ra["drained_at"] == rc["drained_at"] is not None


# ---- SLOConfig / Request validation ----------------------------------------


def test_sloconfig_validation_wire_and_retry_after():
    with pytest.raises(ValueError, match="unknown class"):
        SLOConfig(classes={"vip": ClassSLO()})
    with pytest.raises(ValueError, match="unknown shed class"):
        SLOConfig(shed_classes=("vip",))
    with pytest.raises(ValueError, match="priority"):
        Request(rid="r", prompt=np.array([1], np.int32),
                max_new_tokens=1, priority="vip")
    slo = SLOConfig(retry_after_base_s=0.5, retry_after_cap_s=4.0)
    assert [slo.retry_after(n) for n in (1, 2, 3, 4, 5)] == \
        [0.5, 1.0, 2.0, 4.0, 4.0], "hint must be capped-exponential"
    back = SLOConfig.from_wire(slo.to_wire())
    assert back == slo
    assert SLOConfig.from_wire(None) is None


def test_class_slo_rules_shapes():
    from ray_lightning_tpu.telemetry.watch import class_slo_rules

    rules = {r.name: r for r in class_slo_rules(SLOConfig())}
    assert rules["slo_ttft_latency_critical"].severity == "page"
    assert rules["slo_ttft_best_effort"].severity == "warn"
    assert rules["slo_tpot_standard"].metric == \
        "serving.tpot_standard_p95_s"
    shed = rules["shed_best_effort"]
    assert shed.metric == "load.sheds_best_effort"
    assert shed.severity == "warn"


# ---- traffic-aware scheduler seams (tiny engine) ---------------------------


@pytest.fixture(scope="module")
def cap1(tiny_llama_f32):
    """A capacity-1 engine — admission order IS the completion order."""
    import jax

    from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig

    cfg, model, params, _ = tiny_llama_f32
    eng = DecodeEngine(model, params, EngineConfig(
        capacity=1, block_size=4, blocks_per_slot=8, prefill_chunk=4))
    eng.warmup()
    prompt = np.array(jax.random.randint(
        jax.random.key(42), (1, 4), 0, cfg.vocab_size), dtype=np.int32)
    return cfg, model, params, eng, prompt


def _req(prompt, rid, priority, seed=0, max_new=3):
    return Request(rid=rid, prompt=prompt[0], max_new_tokens=max_new,
                   seed=seed, priority=priority)


def _drain(sched):
    out = []
    while sched.busy():
        out.extend(sched.tick())
    return out


def test_priority_off_keeps_historical_fifo(cap1):
    """slo=None: the priority label is inert — admission stays
    arrival-order FIFO exactly as the historical scheduler (the
    byte-identical compatibility pin)."""
    *_, eng, prompt = cap1
    sched = Scheduler(eng)
    sched.submit(_req(prompt, "a", "best_effort", seed=1))
    sched.submit(_req(prompt, "b", "standard", seed=2))
    sched.submit(_req(prompt, "c", "latency_critical", seed=3))
    done = _drain(sched)
    assert [c.rid for c in done] == ["a", "b", "c"]
    assert [c.priority for c in done] == \
        ["best_effort", "standard", "latency_critical"]
    assert sched.take_sheds() == [] and sched.last_preemptions == []


def test_class_major_admission_peer_age_order(cap1):
    """Armed: admission is class-major (latency_critical first), FIFO
    within a class — and a peer NEVER preempts a peer."""
    *_, eng, prompt = cap1
    sched = Scheduler(eng, slo=SLOConfig())
    sched.submit(_req(prompt, "be", "best_effort", seed=1))
    sched.submit(_req(prompt, "std", "standard", seed=2))
    sched.submit(_req(prompt, "lc1", "latency_critical", seed=3))
    sched.submit(_req(prompt, "lc2", "latency_critical", seed=4))
    done = _drain(sched)
    assert [c.rid for c in done] == ["lc1", "lc2", "std", "be"]
    assert all(c.preempted == 0 for c in done), \
        "no strictly-lower-class slot was running — nothing may preempt"


def test_admit_preempt_strictly_lower_class_and_bitwise_replay(cap1):
    """A latency-critical arrival against a full slot set preempts the
    running best-effort slot (strictly lower class), which replays
    bitwise — same seed, same tokens, just later."""
    from ray_lightning_tpu.models.llama import generate

    cfg, model, params, eng, prompt = cap1
    sched = Scheduler(eng, slo=SLOConfig())
    sched.submit(_req(prompt, "be", "best_effort", seed=7, max_new=8))
    for _ in range(3):  # admit + prefill + first decode steps
        sched.tick()
    assert sched.slots, "best_effort never admitted"
    sched.submit(_req(prompt, "lc", "latency_critical", seed=8,
                      max_new=3))
    done = {c.rid: c for c in _drain(sched)}
    assert done["be"].preempted >= 1, "arrival never preempted the slot"
    assert done["lc"].preempted == 0
    for rid, (max_new, seed) in {"be": (8, 7), "lc": (3, 8)}.items():
        ref = np.asarray(generate(model, params, prompt, max_new,
                                  seed=seed))[0]
        np.testing.assert_array_equal(np.array(done[rid].tokens), ref,
                                      err_msg=rid)


def test_queue_budget_shed_typed_records_and_counters(cap1):
    """A zero best-effort budget sheds EVERY best-effort arrival at
    enqueue with a typed record (reason, capped-exponential
    retry_after_s) and per-class counters; other classes admit."""
    from ray_lightning_tpu.telemetry.metrics import MetricsRegistry

    *_, eng, prompt = cap1
    slo = SLOConfig(classes={
        "best_effort": ClassSLO(queue_budget=0)})
    reg = MetricsRegistry()
    sched = Scheduler(eng, metrics=reg, slo=slo)
    sched.submit(_req(prompt, "be", "best_effort", seed=1))
    sched.submit(_req(prompt, "lc", "latency_critical", seed=2))
    recs = sched.take_sheds()
    assert [r["rid"] for r in recs] == ["be"]
    assert recs[0]["reason"] == "queue_budget"
    assert recs[0]["priority"] == "best_effort"
    assert recs[0]["retry_after_s"] == slo.retry_after(1) > 0
    assert sched.take_sheds() == [], "take_sheds must drain"
    # the resubmission's hint backs off exponentially
    sched.submit(_req(prompt, "be", "best_effort", seed=1))
    assert sched.take_sheds()[0]["retry_after_s"] == slo.retry_after(2)
    done = _drain(sched)
    assert [c.rid for c in done] == ["lc"]
    counters = reg.counters()
    assert counters.get("sheds") == 2
    assert counters.get("sheds_best_effort") == 2


def test_per_class_histograms_recorded(cap1):
    """Armed completions land in class-keyed TTFT/TPOT histograms —
    the surface `class_slo_rules` selectors resolve against."""
    from ray_lightning_tpu.telemetry.metrics import MetricsRegistry

    *_, eng, prompt = cap1
    reg = MetricsRegistry()
    sched = Scheduler(eng, metrics=reg, slo=SLOConfig())
    sched.submit(_req(prompt, "lc", "latency_critical", seed=5))
    sched.submit(_req(prompt, "be", "best_effort", seed=6))
    done = _drain(sched)
    assert len(done) == 2
    for cls in ("latency_critical", "best_effort"):
        for kind in ("ttft", "tpot"):
            h = reg.histogram(f"{kind}_{cls}_s")
            assert h is not None and h.n == 1, f"{kind}_{cls}_s"
    assert reg.histogram("ttft_standard_s") is None


# ---- bench + bench_gate traffic fields -------------------------------------


def _bench_gate():
    scripts = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    return importlib.import_module("bench_gate")


def test_bench_gate_ratchets_lc_attainment():
    """slo_attainment_latency_critical ratchets (measured: waived on
    environmental skip lines; a dropped field fails)."""
    bg = _bench_gate()
    assert bg.RATCHETED["slo_attainment_latency_critical"] == \
        "slo_attainment_latency_critical"
    best = {"slo_attainment_latency_critical": (1.0, "BENCH_r09.json")}
    ok = {"metric": "m", "value": 1.0,
          "slo_attainment_latency_critical": 1.0}
    assert bg.gate(ok, best, tolerance=0.05) == []
    worse = {"metric": "m", "value": 1.0,
             "slo_attainment_latency_critical": 0.5}
    assert any("slo_attainment_latency_critical" in f
               for f in bg.gate(worse, best, tolerance=0.05))
    skip = {"metric": "m", "value": 0.0,
            "skipped": "backend unavailable"}
    assert bg.gate(skip, best, tolerance=0.05) == []
    dropped = {"metric": "m", "value": 1.0}
    assert any("dropped the field" in f
               for f in bg.gate(dropped, best, tolerance=0.05))


def test_bench_serve_summary_carries_traffic_schema():
    """The static serving schema (carried even on backend-down skip
    lines) names the traffic-class fields the measured leg emits."""
    import bench

    s = bench._serve_summary()["serving"]
    for field in ("slo_attainment", "slo_attainment_latency_critical",
                  "shed_fraction"):
        assert field in s["schema"], field
        assert field in s["traffic_schema"] or \
            field == "slo_attainment_latency_critical"
    assert set(s["traffic_schema"]) == {
        "slo_attainment", "slo_attainment_latency_critical",
        "shed_fraction"}


# ---- slow: process-backend SIGKILL drill -----------------------------------


@pytest.mark.slow
def test_process_kill_mid_burst_replays_with_class_accounting(
        tiny_llama_f32, tmp_path):
    """A seeded mixed-class burst on a real process replica with a
    mid-burst SIGKILL: the respawn replays the lost streams bitwise,
    the zero-budget best-effort shed set stays exactly the best-effort
    arrivals, and the per-class accounting is consistent across the
    channel epoch roll — a dead epoch's shed records must not
    double-count the driver's shed counter."""
    import jax

    from ray_lightning_tpu.models.llama import generate
    from ray_lightning_tpu.serve.channel import channel_dir
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig,
        ServeDriver,
        save_params_npz,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg, model, params, _ = tiny_llama_f32
    rng = np.random.Generator(np.random.PCG64(55))
    classes = ["latency_critical", "standard", "best_effort",
               "standard", "best_effort", "latency_critical"]
    reqs = [Request(
        rid=f"k{i:02d}",
        prompt=np.asarray(rng.integers(0, cfg.vocab_size,
                                       size=3 + i % 3), np.int32),
        max_new_tokens=8, temperature=0.7 if i % 2 else 0.0,
        top_k=4 if i % 2 else None, seed=61 + i,
        priority=classes[i]) for i in range(len(classes))]
    slo = SLOConfig(classes={"best_effort": ClassSLO(queue_budget=0)})
    be_rids = sorted(r.rid for r in reqs
                     if r.priority == "best_effort")
    refs = {r.rid: np.asarray(generate(
        model, params, np.asarray(r.prompt)[None, :],
        r.max_new_tokens, temperature=r.temperature, top_k=r.top_k,
        seed=r.seed))[0] for r in reqs if r.rid not in be_rids}
    pp = str(tmp_path / "params.npz")
    save_params_npz(params, pp)
    drv = ServeDriver(cfg, pp, ReplicaGroupConfig(
        n_replicas=1, backend="process",
        engine=EngineConfig(capacity=2, block_size=4,
                            blocks_per_slot=8, prefill_chunk=4),
        run_dir=str(tmp_path / "run"),
        compile_cache_dir=str(tmp_path / "cc"),
        platform="cpu", cpu_devices_per_rank=1,
        env={"JAX_PLATFORMS": "cpu"}, max_restarts=2,
        metrics_flush_every_n_ticks=2, slo=slo))
    drv.start(fault={"replica": 0, "kill_after_tokens": 10})
    for r in reqs:
        drv.submit(r)
    while drv.busy():
        drv.tick()
        time.sleep(0.01)
    res = drv.stop()
    assert res.restarts[0] >= 1, "kill never triggered a respawn"
    # bitwise replay of every surviving stream
    for rid, ref in refs.items():
        np.testing.assert_array_equal(np.array(res.outputs[rid]), ref,
                                      err_msg=rid)
    # typed sheds: exactly the best-effort arrivals, once each
    shed_meta = sorted(r for r, m in res.meta.items()
                       if m.get("finish_reason") == "shed")
    assert shed_meta == be_rids
    assert res.stats.get("requests_shed") == len(be_rids), \
        "epoch-roll replay double-counted (or dropped) shed records"
    # zero silent drops: every rid has a terminal meta record
    assert sorted(res.meta) == sorted(r.rid for r in reqs)
    for rid, m in res.meta.items():
        cls = m.get("priority", "standard")
        want = next(r.priority for r in reqs if r.rid == rid)
        assert cls == want, f"{rid}: class lost across the channel"
        if rid in be_rids:
            assert m.get("retry_after_s", 0) > 0
        else:
            assert m.get("finish_reason") in ("eos", "length")
    # the respawn rolled the command log to a fresh epoch
    epochs = sorted(p.name for p in
                    channel_dir(str(tmp_path / "run"), 0).iterdir())
    assert "epoch1.jsonl" in epochs
    assert res.stats["compile_count"] in (1, -1)
