#!/usr/bin/env bash
# Lint/format entry point (reference analog: format.sh with yapf+flake8,
# reference format.sh:1-140). Two tools: ruff (style, both roles) and
# shardcheck (`python -m ray_lightning_tpu lint`, docs/STATIC_ANALYSIS.md)
# for the TPU/JAX-semantics rules ruff cannot know — host transfers in
# traced code, mesh-axis typos, unhashable static args.
#
#   ./format.sh           # fix in place (+ shardcheck)
#   ./format.sh --check   # CI mode: fail on violations
set -euo pipefail
cd "$(dirname "$0")"
SECONDS=0

RUFF_ARGS=(check ray_lightning_tpu tests examples bench.py __graft_entry__.py)

# ruff is optional tooling: skip (loudly) on boxes that don't ship it so
# the semantic gates below still run — shardcheck/tracecheck are the
# gates that need THIS repo's toolchain, ruff is style only. CI images
# that DO ship ruff should export RLT_REQUIRE_RUFF=1 so a PATH break
# cannot silently drop the style gate.
if command -v ruff > /dev/null 2>&1; then
    if [[ "${1:-}" == "--check" ]]; then
        ruff "${RUFF_ARGS[@]}"
    else
        ruff "${RUFF_ARGS[@]}" --fix
    fi
elif [[ "${RLT_REQUIRE_RUFF:-}" == "1" ]]; then
    echo "format.sh: ruff not installed but RLT_REQUIRE_RUFF=1" >&2
    exit 1
else
    echo "format.sh: ruff not installed — skipping style pass" >&2
fi

# shardcheck has no fix mode; it gates both invocations identically.
# examples/ ship user-facing step code, so they are held to the same bar.
# --concurrency folds threadcheck (analysis/concurrency.py, RLT7xx:
# races, lock-order inversions, thread leaks, signal-handler and
# blocking-under-lock discipline) into the same gate — the package's
# host-side threading is linted as strictly as its jit-side sharding.
# --numerics adds numcheck's AST arm (inline .astype(bf16/int8)
# operands in dot/einsum calls — the RLT801/805 copy-paste shapes).
JAX_PLATFORMS=cpu python -m ray_lightning_tpu lint --concurrency \
    --numerics ray_lightning_tpu examples bench.py __graft_entry__.py

# lockwatch smoke (docs/STATIC_ANALYSIS.md "threadcheck & lockwatch"):
# the runtime half of the concurrency gate. Arm the sanitizer BEFORE
# the package imports (armed-ness is decided at lock creation), drive a
# real threaded subsystem (telemetry recorder: a worker thread posting
# spans while the main thread snapshots), and require a clean order
# graph. The full suite runs armed too (tests/conftest.py) — this is
# the seconds-cheap standalone proof the wiring works.
RLT_LOCKWATCH=1 JAX_PLATFORMS=cpu python -c '
import threading

from ray_lightning_tpu.analysis.lockwatch import (
    assert_lockwatch_clean, lockwatch_armed, san_lock)

assert lockwatch_armed(), "RLT_LOCKWATCH=1 not seen by lockwatch"
from ray_lightning_tpu.analysis.lockwatch import _SanLock
assert isinstance(san_lock("format.smoke"), _SanLock)

from ray_lightning_tpu.telemetry.spans import (
    THREAD_PRODUCER, TelemetryRecorder)
rec = TelemetryRecorder()
def worker():
    for i in range(50):
        with rec.span("format.smoke", step=i, thread=THREAD_PRODUCER):
            pass
threads = [threading.Thread(target=worker) for _ in range(4)]
for t in threads:
    t.start()
for t in threads:
    t.join()
for _ in range(20):
    rec.phase_totals()
    rec.last_span()
assert_lockwatch_clean()
print("lockwatch smoke: armed, threaded spans clean")'

# tracecheck + numcheck gate: the flagship Llama-8B v5p-64 step must
# audit clean at the jaxpr level (no implicit resharding, no ring
# deadlocks, peak HBM within budget — docs/STATIC_ANALYSIS.md
# "tracecheck") AND numerics-clean: zero RLT8xx findings of ANY
# severity (the warning-grade cast churn and bf16 transcendentals
# gate too), an f32 loss widest path, and a populated precision
# ledger ("numcheck — the precision layer").
JAX_PLATFORMS=cpu python -m ray_lightning_tpu trace llama3-8b \
    --topo v5p-64 --json --fail-on error | python -c '
import json, sys
r = json.load(sys.stdin)
bad = [f for f in r["findings"] if f["rule"].startswith("RLT8")]
assert not bad, f"flagship not numerics-clean: {bad}"
p = r["precision"]
assert p and p["params"], "precision ledger missing/empty"
assert p["loss_widest_dtype"] == "float32", \
    "loss widest path is %r, not f32" % p["loss_widest_dtype"]
print("numcheck gate: flagship RLT8xx-clean, loss path f32, "
      "%d param dtype class(es) in ledger" % len(p["params"]))'

# numcheck examples sweep: every bundled example trace target must be
# free of RLT801 (bf16 accumulation) and RLT805 (scale-free quant
# consume) — the two rules whose regressions are always real numeric
# bugs, not style. One process, all targets (import cost paid once).
# The llama targets are excluded: both resolve to the flagship 8B
# build the gate above already holds to the STRICTER zero-RLT8xx bar,
# and tracing it twice would double the slowest gate for no coverage.
JAX_PLATFORMS=cpu python -c '
from ray_lightning_tpu.analysis.cli import _TRACE_BUILDERS, \
    resolve_trace_target
from ray_lightning_tpu.analysis.costmodel import parse_topology
from ray_lightning_tpu.analysis.tracecheck import audit_step

targets = sorted(
    set(_TRACE_BUILDERS) - {"llama3-8b", "llama_fsdp_example.py"})
topo = parse_topology("v5p-8")
for target in targets:
    module, strategy, batch, label = resolve_trace_target(target, topo)
    rep = audit_step(module, strategy, batch, topology="v5p-8",
                     label=label)
    bad = [f for f in rep.findings if f.rule in ("RLT801", "RLT805")]
    assert not bad, f"{target}: {[f.message for f in bad]}"
print("numcheck sweep: %d example targets free of RLT801/RLT805"
      % len(targets))'

# collective-overlap gate (docs/PERFORMANCE.md "collective overlap"):
# the same flagship step under the strategy's overlap="on" knob must
# audit clean AND hide >= 70% of its prefetchable ZeRO collective time
# behind compute per tracecheck's roofline model (ISSUE 6 acceptance).
JAX_PLATFORMS=cpu python -m ray_lightning_tpu trace llama3-8b \
    --topo v5p-64 --overlap on --json --fail-on error \
    | python -c '
import json, sys
r = json.load(sys.stdin)
frac = r.get("overlap_hidden_fraction", 0.0)
assert r.get("overlap", {}).get("scheduled"), "prefetch schedule missing"
assert frac >= 0.7, f"overlap_hidden_fraction {frac} < 0.7"
print(f"overlap gate: {frac:.0%} of prefetchable ICI time hidden")'

# bench regression ratchet (scripts/bench_gate.py): the freshest bench
# JSON line must not regress the best prior BENCH_r0*.json round on any
# ratcheted metric (tokens/sec/chip, mfu, overlap_hidden_fraction). On
# a box with no TPU the bench emits its structured backend-down skip
# line within seconds (retry budget pinned down here), which passes the
# gate by design — the ratchet gates merit, not machine availability.
# Running the REAL bench.py (not a cached trace JSON) is deliberate:
# this gate doubles as the end-to-end proof that bench.py's structured
# skip contract holds, which is itself a pinned behavior (BENCH_r05).
{ JAX_PLATFORMS=tpu RLT_BENCH_MAX_WAIT=10 RLT_BENCH_INIT_RETRIES=1 \
    python bench.py 2>/dev/null || true; } \
    | python scripts/bench_gate.py -

# resilience gate, three supervised CPU-SPMD legs: (1) an injected
# worker kill must auto-resume from the step-cadence checkpoint and
# converge (kill -> classify -> relaunch -> resume, end to end); (2) an
# injected NaN batch must be SKIPPED IN-JIT by the trainguard (zero
# restarts) and converge; (3) an injected parameter bit-flip on rank 1
# must be caught by the SDC fingerprint probe within one cadence, rank
# 1 quarantined, and the rolled-back run must converge — all on a box
# with no accelerator. docs/RESILIENCE.md "trainguard" +
# "fault-injection cookbook".
JAX_PLATFORMS=cpu python -m ray_lightning_tpu supervise --smoke > /dev/null

# observability gate (docs/OBSERVABILITY.md): telemetry=off must train
# bitwise-identically and lower a byte-identical step program; a 2-proc
# CPU-SPMD supervised run with an injected worker kill must produce a
# parseable goodput report whose buckets sum to supervised wall time
# (±5%) with the backoff + replay lost-time classes nonzero; and the
# flagship llama3-8b drift section must emit (structured-skip measured
# placeholder on a box with no TPU) against tracecheck's predicted step
# composition.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu monitor --smoke > /dev/null

# watch/incident gate (docs/OBSERVABILITY.md "watch rules &
# incidents"): a scripted serving run with an INJECTED latency stall
# must fire the built-in ttft_p99 rule EXACTLY ONCE (episode
# semantics: a sustained breach is one incident, not one per poll),
# land a parseable incident record carrying metric evidence (value +
# histogram sketch) and a timeline excerpt of the surrounding events,
# and trigger one profiler CAPTURE-marker evidence capture; and the
# run dir's unified timeline must export valid Chrome-trace JSON with
# events from >= 4 distinct source subsystems ordered by aligned time.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu watch --smoke > /dev/null

# serving gate (docs/SERVING.md): 8 concurrent staggered streams
# (ragged prompts, mixed greedy/temperature/top-k) through the
# continuous-batching engine must decode bitwise-identical to 8
# independent single-stream generate() runs; request churn must compile
# the step exactly ONCE (metrics armed — instrumentation must not
# retrace); with 2 process replicas an injected SIGKILL mid-stream must
# classify -> respawn -> reload weights -> replay the lost streams
# bitwise with the survivor untouched; the METRICS legs
# (docs/OBSERVABILITY.md "serving metrics") must hold: per-replica
# metrics JSONL on the tick cadence with histogram counts equal to the
# completed-request count, EXACT cross-replica histogram merge (counts
# sum, quantiles merge-order independent), a parseable flight.json
# postmortem with final ticks from the SIGKILL drill, and a live
# load_signal(); and the decode step must audit clean under tracecheck
# (no RLT301/RLT303).
JAX_PLATFORMS=cpu python -m ray_lightning_tpu serve --smoke > /dev/null

# autoscale gate (docs/AUTOSCALE.md): under a deterministic scripted
# load ramp (virtual-tick clock — no wall-clock flakiness) the
# closed-loop controller must scale 1 -> 2 on sustained pressure and
# back to 1 on idle, exactly once each (hysteresis + cooldowns honored
# across ~36 polls), record EVERY decision with its signal snapshot in
# a parseable autoscale.jsonl, and complete every stream
# bitwise-identical to single-stream generate() — a graceful drain
# drops nothing; a capacity-oracle probe file must clamp a wanted
# scale-up with the oracle's answer in the ledger; an injected
# SIGKILL-class spawn death mid-scale-up must be classified via the
# resilience taxonomy and retried within budget without dropping the
# scale target; and submit() with every replica draining must defer
# with a structured reason instead of routing onto a stopping replica.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu autoscale --smoke > /dev/null

# loadgen gate (docs/SERVING.md "traffic & SLO classes"): the seeded
# trace format must serialize byte-deterministically (same seed ->
# identical bytes, round-trip stable, wrong version refused); a bursty
# mixed-class trace replayed twice through the REAL driver must
# complete bitwise-identically with IDENTICAL per-class accounting and
# shed sets — best-effort sheds as typed records with retry-after
# hints while latency_critical stays un-shed, holds its TTFT target,
# and preempts lower-class slots; WatchEngine fires exactly one
# shed_best_effort incident; a process-backend leg with a zero
# best-effort queue budget must shed exactly the best-effort arrivals
# and stream the survivors bitwise; churn compiles the step ONCE.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu loadgen --smoke > /dev/null

# elastic gate (docs/ELASTIC.md): an 8-device fsdp=8 CPU-SPMD
# checkpoint must reshard-restore onto a 4-device fsdp=4 mesh with
# every param/opt-state leaf BITWISE-equal to the source, and training
# must continue from it; a supervised 2-proc run with an injected
# worker kill and the same-size relaunch budget exhausted
# (max_restarts=0) must consult its ElasticBudget, reshard onto the
# survivor (world 2 -> 1), resume, and converge — with the world
# change in the reshard ledger and the reshard_s goodput bucket.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu elastic --smoke > /dev/null

# multi-slice (DCN) trace gate: the flagship step on a 2-slice
# deployment must itemize DCN vs ICI bytes as separate tiers, place
# `data` across the slices (HSDP — hierarchical gradient reduction is
# the only cross-slice traffic), and audit clean of errors.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu trace llama3-8b \
    --topo 2xv5p-64 --json --fail-on error \
    | python -c '
import json, sys
r = json.load(sys.stdin)
assert r["ok"], "2xv5p-64 trace failed its own gate"
assert r["topology"]["n_slices"] == 2, "slice count not parsed"
assert r["dcn_bytes_per_step"] > 0, "no DCN tier itemized"
assert not any(f["rule"] == "RLT306" for f in r["findings"]), \
    "data-across-slices placement flagged RLT306"
gib = 1024 ** 3
ici, dcn = r["ici_bytes_per_step"] / gib, r["dcn_bytes_per_step"] / gib
print(f"dcn gate: ICI {ici:.1f} GiB/step + DCN {dcn:.3f} GiB/step, "
      "audits clean")'

# prefetch-overlap + collective-overlap smoke: a slow-loader CPU run
# must show pipeline occupancy > 0 (the device prefetcher demonstrably
# kept batches resident ahead of the step), the overlap jaxpr must
# carry the prefetch fingerprint with the off-trace flagging RLT305,
# and the throttled fake-collective interleave demo must beat the
# serial schedule — docs/PERFORMANCE.md. Exit 1 otherwise.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu perf --smoke --steps 25 \
    > /dev/null

# Total wall time of the gate suite. The non-slow pytest tier has a
# 10-minute budget (ROADMAP); this line keeps the format.sh gates on
# the same leash — a creeping gate shows up in every run's output
# instead of only in CI dashboards.
echo "format.sh: all gates passed in ${SECONDS}s"
