#!/usr/bin/env bash
# Lint/format entry point (reference analog: format.sh with yapf+flake8,
# reference format.sh:1-140). Two tools: ruff (style, both roles) and
# shardcheck (`python -m ray_lightning_tpu lint`, docs/STATIC_ANALYSIS.md)
# for the TPU/JAX-semantics rules ruff cannot know — host transfers in
# traced code, mesh-axis typos, unhashable static args.
#
#   ./format.sh           # fix in place (+ shardcheck)
#   ./format.sh --check   # CI mode: fail on violations
set -euo pipefail
cd "$(dirname "$0")"

RUFF_ARGS=(check ray_lightning_tpu tests examples bench.py __graft_entry__.py)

if [[ "${1:-}" == "--check" ]]; then
    ruff "${RUFF_ARGS[@]}"
else
    ruff "${RUFF_ARGS[@]}" --fix
fi

# shardcheck has no fix mode; it gates both invocations identically.
# examples/ ship user-facing step code, so they are held to the same bar.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu lint \
    ray_lightning_tpu examples bench.py __graft_entry__.py

# tracecheck gate: the flagship Llama-8B v5p-64 step must audit clean at
# the jaxpr level (no implicit resharding, no ring deadlocks, peak HBM
# within budget) — docs/STATIC_ANALYSIS.md "tracecheck". CPU-only.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu trace llama3-8b \
    --topo v5p-64 --json --fail-on error > /dev/null

# resilience gate, three supervised CPU-SPMD legs: (1) an injected
# worker kill must auto-resume from the step-cadence checkpoint and
# converge (kill -> classify -> relaunch -> resume, end to end); (2) an
# injected NaN batch must be SKIPPED IN-JIT by the trainguard (zero
# restarts) and converge; (3) an injected parameter bit-flip on rank 1
# must be caught by the SDC fingerprint probe within one cadence, rank
# 1 quarantined, and the rolled-back run must converge — all on a box
# with no accelerator. docs/RESILIENCE.md "trainguard" +
# "fault-injection cookbook".
JAX_PLATFORMS=cpu python -m ray_lightning_tpu supervise --smoke > /dev/null

# prefetch-overlap gate: a slow-loader CPU run must show pipeline
# occupancy > 0 (the device prefetcher demonstrably kept batches
# resident ahead of the step) — docs/PERFORMANCE.md. Exit 1 otherwise.
JAX_PLATFORMS=cpu python -m ray_lightning_tpu perf --smoke --steps 25 \
    > /dev/null
