#!/usr/bin/env bash
# Lint/format entry point (reference analog: format.sh with yapf+flake8,
# reference format.sh:1-140). One tool here: ruff handles both roles.
#
#   ./format.sh           # fix in place
#   ./format.sh --check   # CI mode: fail on violations
set -euo pipefail
cd "$(dirname "$0")"

RUFF_ARGS=(check ray_lightning_tpu tests examples bench.py __graft_entry__.py)

if [[ "${1:-}" == "--check" ]]; then
    ruff "${RUFF_ARGS[@]}"
else
    ruff "${RUFF_ARGS[@]}" --fix
fi
