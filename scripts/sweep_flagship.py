"""Sweep the flagship training path (remat=True + scan_layers=True +
fused CE at the Llama-3 vocabulary) on the real chip.

VERDICT r3 #1: this is the only configuration class that can hold at the
north-star Llama-3-8B (BASELINE.md config 4), and it had never been swept
on its own — remat shifts the optimum (recompute competes with the flash
kernel for VMEM; freed activation memory admits larger batches).

Dimensions: remat_policy (nothing|dots) x batch, then ce_chunk_tokens,
then flash block sizes (via RLT_FLASH_BLOCK_Q/K) at the incumbent best.
Appends one JSON line per config to scripts/sweep_flagship_results.jsonl
so a partial sweep is still a usable record.

Usage: python scripts/sweep_flagship.py [phase]
  phase in {1,...,7,all,retry} — 4 sweeps the inline-backward fused
  CE; 5 sweeps remat_policy="attn_out" (saved flash residuals); 6 sweeps
  bf16 Adam first moment (mu_dtype) at the memory-capped batches;
  7 crosses the candidate winners (inline x mu_bf16 x policy);
  "retry" re-runs the points that died on transient remote-compile 500s.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# RLT_SWEEP_RESULTS overrides the record path (CPU smoke runs of the
# harness itself must not pollute the real chip record)
RESULTS = os.environ.get(
    "RLT_SWEEP_RESULTS",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "sweep_flagship_results.jsonl"),
)


def run_one(tag: str, *, batch: int, policy: str, chunk: int,
            block_q: int | None = None, block_k: int | None = None,
            vocab: int = 128256, seq: int = 2048, inline: bool = False,
            mu_bf16: bool = False):
    import bench

    for key, val in (("RLT_FLASH_BLOCK_Q", block_q),
                     ("RLT_FLASH_BLOCK_K", block_k)):
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(val)
    rec = {"tag": tag, "batch": batch, "policy": policy, "chunk": chunk,
           "block_q": block_q, "block_k": block_k, "vocab": vocab,
           "seq": seq, "inline": inline, "mu_bf16": mu_bf16}
    t0 = time.time()
    try:
        import jax.numpy as jnp

        step, params, opt_state, tokens, tps_tokens, cfg = bench._make_step(
            use_flash=True, fused_ce=True, batch=batch, seq=seq,
            vocab=vocab, remat=True, scan=True,
            remat_policy=policy, ce_chunk_tokens=chunk, ce_inline=inline,
            mu_dtype=jnp.bfloat16 if mu_bf16 else None,
        )
        dt = bench._time_step(step, params, opt_state, tokens)
        tps = tps_tokens / dt
        import jax
        peak = bench._PEAK_TFLOPS.get(jax.devices()[0].device_kind,
                                      bench._DEFAULT_PEAK)
        mfu = tps * bench._flops_per_token(cfg, seq) / (peak * 1e12)
        rec.update(tokens_per_sec=round(tps, 1), mfu=round(mfu, 4),
                   step_ms=round(dt * 1e3, 2))
        del step, params, opt_state, tokens
    except Exception as exc:  # noqa: BLE001 — OOM/compile failures are data
        rec.update(error=f"{type(exc).__name__}: {str(exc)[:300]}")
    rec["wall_s"] = round(time.time() - t0, 1)
    with open(RESULTS, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return rec


def best_so_far():
    best = None
    try:
        with open(RESULTS) as f:
            for line in f:
                rec = json.loads(line)
                if "tokens_per_sec" in rec and (
                        best is None
                        or rec["tokens_per_sec"] > best["tokens_per_sec"]):
                    best = rec
    except FileNotFoundError:
        pass
    return best


def main():
    phase = sys.argv[1] if len(sys.argv) > 1 else "all"
    if phase in ("1", "all"):
        for policy in ("nothing", "dots"):
            for batch in (4, 8, 16):
                run_one(f"p1-{policy}-b{batch}", batch=batch, policy=policy,
                        chunk=2048)
    b = best_so_far()
    if b is None:
        print("BEST: none — no config completed; fix phase 1 first",
              flush=True)
        return
    # carry the incumbent's FULL configuration forward — a best record
    # that only fits because of bf16 mu (or only wins because of the
    # inline CE) must not be re-run without those flags in later phases
    def _carry(rec):
        return dict(inline=rec.get("inline", False),
                    mu_bf16=rec.get("mu_bf16", False))

    if phase in ("2", "all"):
        for chunk in (1024, 4096, 8192):
            run_one(f"p2-chunk{chunk}", batch=b["batch"], policy=b["policy"],
                    chunk=chunk, **_carry(b))
        b = best_so_far()
    if phase in ("3", "all"):
        for bq, bk in ((256, 1024), (512, 512), (1024, 1024), (512, 2048)):
            run_one(f"p3-q{bq}k{bk}", batch=b["batch"], policy=b["policy"],
                    chunk=b["chunk"], block_q=bq, block_k=bk, **_carry(b))
        b = best_so_far()
    if phase in ("4", "all"):
        # inline-backward fused CE (ops/fused_ce.py _ce_inline): removes
        # the lm_head tile recompute (~10% of executed FLOPs at this
        # shape) for a dW residual in the lm_head param dtype (f32 here:
        # ~1 GB at D=2048, V=128256); sweep batch x chunk around the
        # incumbent. Carry the incumbent's full configuration except the
        # forced inline=True (the carry invariant: a standalone phase-4
        # re-run after phase 6/7 records exist must keep the incumbent's
        # mu_bf16 — a batch that only fits with a bf16 mu would
        # otherwise re-run without it and record a spurious OOM).
        p4_carry = {**_carry(b), "inline": True}
        inline_recs = []
        for batch in (4, 8, 12, 16):
            inline_recs.append(
                run_one(f"p4-inline-b{batch}", batch=batch,
                        policy=b["policy"], chunk=b["chunk"], **p4_carry))
        done = [r for r in inline_recs if "tokens_per_sec" in r]
        if done:
            # chunk sweep continues from the best INLINE point (inline
            # stays True — an inline-loses-overall outcome must not
            # silently re-run non-inline configs under a p4 tag)
            bi = max(done, key=lambda r: r["tokens_per_sec"])
            for chunk in (2048, 8192, 16384):
                run_one(f"p4-inline-chunk{chunk}", batch=bi["batch"],
                        policy=bi["policy"], chunk=chunk,
                        **{**p4_carry,
                           "mu_bf16": bi.get("mu_bf16", False)})
    if phase in ("5", "all"):
        # remat_policy="attn_out" (save flash VJP residuals, skip the
        # attention share of the backward recompute — VERDICT r4 next #2's
        # "remat policies that save attention outputs"), with and without
        # the inline CE, around the incumbent batch/chunk
        for batch in (4, 8):
            for inline in (False, True):
                tag = f"p5-attnout-b{batch}" + ("-inline" if inline else "")
                run_one(tag, batch=batch, policy="attn_out", chunk=4096,
                        inline=inline)
    if phase in ("6", "all"):
        # bf16 Adam first moment: frees ~1.8 GB of optimizer HBM at this
        # scale — exactly what capped the flagship batch. Sweep the
        # batches that previously failed to compile/fit, with and
        # without the inline CE.
        for batch in (8, 12, 16):
            for inline in (False, True):
                tag = f"p6-mubf16-b{batch}" + ("-inline" if inline else "")
                run_one(tag, batch=batch, policy="nothing", chunk=4096,
                        inline=inline, mu_bf16=True)
    if phase in ("7", "all"):
        # cross of the candidate winners: inline CE (no logits-tile
        # recompute) x bf16 mu (frees HBM) x attn_out (no attention
        # recompute), at the incumbent batch and the next one up
        for policy in ("nothing", "attn_out"):
            for batch in (8, 12):
                run_one(f"p7-{policy}-b{batch}-inline-mubf16",
                        batch=batch, policy=policy, chunk=4096,
                        inline=True, mu_bf16=True)
    if phase == "retry":
        # re-run the points that died on transient remote-compile HTTP
        # 500s (VERDICT r4 weak #2) — unknowns, not losers
        run_one("p1-nothing-b16.r", batch=16, policy="nothing", chunk=2048)
        run_one("p1-dots-b8.r", batch=8, policy="dots", chunk=2048)
        run_one("p1-dots-b16.r", batch=16, policy="dots", chunk=2048)
        run_one("p2-chunk8192.r", batch=8, policy="nothing", chunk=8192)
    print("BEST:", json.dumps(best_so_far()), flush=True)


if __name__ == "__main__":
    main()
