#!/usr/bin/env python
"""Bench regression ratchet (ISSUE 6 satellite): a fresh bench JSON line
must not regress the best prior round.

Prior rounds are the checked-in ``BENCH_r0*.json`` recorder wrappers
(each holds the round's parsed bench line under ``"parsed"``; rounds the
backend skipped contribute nothing). For every ratcheted metric the best
prior value is the per-metric max — speed can only go up:

    value                    tokens/sec/chip (the headline metric)
    mfu                      model FLOPs utilization
    overlap_hidden_fraction  hidden share of prefetchable ICI time
                             (static, carried even on skip lines)
    goodput_fraction         productive share of the headline
                             measurement window (measured)
    slo_attainment_latency_critical
                             fraction of latency-critical completions
                             meeting the class TTFT target in the
                             bench's mixed-class SLO burst (ISSUE 20;
                             measured, waived on skip lines)

Bounded metrics (upper limits, not ratchets):

    telemetry_overhead_fraction  measured span-recorder cost relative
                                 to the step time — must stay < 1%
                                 (ISSUE 7: observability must not
                                 become the overhead it measures)
    ttft_warm_s                  warm single-request TTFT (ISSUE 8)
    ttft_p99_s                   steady-state warm TTFT p99 from the
                                 mergeable histogram buckets (ISSUE 12
                                 serving metrics; RLT_BENCH_TTFT_P99_MAX
                                 overrides, skip/null waives)
    reshard_restore_s            elastic cross-topology restore wall
                                 (ISSUE 9)
    scale_up_s                   autoscale add_replica actuation wall
                                 (ISSUE 13; RLT_BENCH_SCALE_UP_MAX
                                 overrides, skip/null waives)
    incidents                    watch-rule breaches fired against the
                                 bench's own serving drill (ISSUE 14:
                                 a healthy bench fires zero; any
                                 incident in the bench run itself is a
                                 regression — skip/null waived)

Gate semantics:

  * fresh line with ``"skipped"`` — an environmental skip (backend
    down, driver kill). The MEASURED metrics are waived: the ratchet
    gates merit, not machine availability. The STATIC metrics
    (overlap_hidden_fraction — computed without hardware and carried
    on the skip line) still ratchet when present. The BENCH_r05
    regression class (rc=124, no JSON) FAILS — there is no line to
    pass.
  * fresh success line — every ratcheted metric present in both the
    fresh line and some prior round must satisfy
    ``fresh >= best_prior * (1 - tolerance)`` (default 5%, --tolerance).
    A metric the priors track but the fresh line DROPPED also fails:
    deleting the field must not bypass the ratchet.

Usage:
    python scripts/bench_gate.py fresh.json          # wrapper or raw line
    ... | python scripts/bench_gate.py -             # last JSON line wins
    python scripts/bench_gate.py fresh.json --prior-glob 'BENCH_r0*.json'

Exit 0 pass, 1 regression, 2 invalid input (unparseable fresh line).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

#: metric name -> key in the bench JSON line. "value" is
#: tokens/sec/chip (see the line's "metric"/"unit" fields).
RATCHETED = {
    "tokens_per_sec_per_chip": "value",
    "mfu": "mfu",
    "overlap_hidden_fraction": "overlap_hidden_fraction",
    "goodput_fraction": "goodput_fraction",
    # serving leg (ISSUE 8): steady-state continuous-batching decode
    # throughput — measured, so waived on environmental skip lines
    "decode_tokens_per_s": "decode_tokens_per_s",
    # ISSUE 19: the prefix cache's measured sharing on the saturated
    # steady-state leg (fraction of mapped blocks that were shared —
    # may only grow), and tokens emitted per decoding slot-step
    # (exactly 1.0 without a draft, > 1.0 once speculative acceptance
    # lands — may only grow). Both measured: waived on skip lines.
    "shared_block_fraction": "shared_block_fraction",
    "accepted_tokens_per_step": "accepted_tokens_per_step",
    # ISSUE 20: fraction of latency-critical completions meeting the
    # class TTFT target in the bench's mixed-class SLO burst (1.0 when
    # every paying request held its SLO while best-effort shed).
    # Measured: waived on environmental skip lines.
    "slo_attainment_latency_critical": "slo_attainment_latency_critical",
}

#: keys computed by static analysis (no hardware needed) — carried on
#: backend-down skip lines and ratcheted there too, unlike measurements
STATIC = {"overlap_hidden_fraction"}

#: metric -> key for CEILING ratchets: lower is better, so the fresh
#: value must stay <= the best (minimum) prior * (1 + tolerance).
#: dcn_bytes_per_step is the static 2xv5p-64 trace's inter-slice bytes
#: (ISSUE 9): DCN is the slow tier, so its per-step traffic may only
#: shrink. serve_hbm_bytes_per_replica is the flagship serving
#: replica's static per-device HBM on its auto-selected attention
#: paths (ISSUE 11; re-anchored to the fused-PREFILL plan by ISSUE 15
#: — the prefill kernel retired the last dense gather, so the ceiling
#: now holds at the lower fused-both figure).
#: serve_prefill_gather_bytes is the prefill lane's surviving dense
#: per-group gather on the same plan (ISSUE 15): 0 once the fused
#: prefill kernel covers the flagship shape, and it may only shrink —
#: nothing may quietly re-materialize the gather. Static class:
#: ratchets on skip lines too; a line carrying the metric's waiver
#: error field instead waives (analysis bug != regression).
#: serve_decode_ici_bytes_per_tick is the flagship TP=2 sharded
#: replica's decode-step collective traffic (ISSUE 18,
#: serve/audit.py `audit_decode_step`): every byte rides the
#: latency-critical per-token path (the layer psums + the jit-boundary
#: logits gather), so the per-tick wire total may only shrink.
#: low_precision_reductions is numcheck's count of narrow-accumulation
#: findings on the flagship trace (RLT801 bf16 dot/reduce accumulations
#: + RLT804 bf16 gradient collectives, analysis/numcheck.py): 0 since
#: the f32-accumulation fixes, zero-anchored here — no future change
#: may quietly reintroduce a bf16 reduction into the flagship step.
CEILING = {"dcn_bytes_per_step": "dcn_bytes_per_step",
           "serve_hbm_bytes_per_replica": "serve_hbm_bytes_per_replica",
           "serve_prefill_gather_bytes": "serve_prefill_gather_bytes",
           "serve_decode_ici_bytes_per_tick":
               "serve_decode_ici_bytes_per_tick",
           "low_precision_reductions": "low_precision_reductions"}

#: ceiling metric -> error fields whose presence waives an ABSENT
#: value (the analysis that computes the static metric died and said
#: so); a present value always ratchets
CEILING_WAIVERS = {
    "dcn_bytes_per_step": ("multislice_error", "tracecheck_error"),
    "serve_hbm_bytes_per_replica": ("serving_error",
                                    "tracecheck_error"),
    "serve_prefill_gather_bytes": ("serving_error",
                                   "tracecheck_error"),
    "serve_decode_ici_bytes_per_tick": ("serving_error",
                                        "tracecheck_error"),
    "low_precision_reductions": ("numerics_error",),
}

#: ceiling metric -> short rationale for the failure message
CEILING_WHY = {
    "dcn_bytes_per_step": ("DCN is the slow tier; its per-step "
                           "traffic may only shrink"),
    "serve_hbm_bytes_per_replica": (
        "per-replica serving HBM may only shrink — the fused paged "
        "decode + prefill kernels retired the dense gathered views "
        "and nothing may quietly grow them back (the ceiling prices "
        "the full unshared pool: prefix sharing SAVES bytes inside "
        "it, so sharing can never excuse a bigger plan)"),
    "serve_prefill_gather_bytes": (
        "the prefill lane's dense per-group gather is retired by the "
        "fused paged-prefill kernel — its bytes may only shrink, and "
        "nothing may quietly re-materialize the gather"),
    "serve_decode_ici_bytes_per_tick": (
        "decode collectives ride the latency-critical per-token path "
        "(layer psums + the boundary logits gather) — the sharded "
        "replica's per-tick wire bytes may only shrink"),
    "low_precision_reductions": (
        "the flagship step accumulates every long reduction in f32 "
        "(numcheck RLT801/RLT804) — the count is zero-anchored and no "
        "change may quietly reintroduce a bf16 accumulation"),
}

#: metric -> max allowed value on a measured (non-skip) line; absent or
#: null waives (bench.py reports null when the probe itself failed) —
#: each bound exists to stop a latency/overhead class from growing, not
#: to demand the field on every historic line
BOUNDED = {
    "telemetry_overhead_fraction": float(
        os.environ.get("RLT_BENCH_TELEMETRY_OVERHEAD_MAX", 0.01)),
    # warm TTFT (serving leg, ISSUE 8): a request on the already-
    # compiled engine — queue + prefill only. A growth here means the
    # engine started recompiling (or prefill regressed) on the serving
    # hot path.
    "ttft_warm_s": float(
        os.environ.get("RLT_BENCH_TTFT_WARM_MAX", 2.0)),
    # warm TTFT p99 (serving metrics leg, ISSUE 12): the tail of the
    # steady-state admission->first-token latency, read from the
    # mergeable histogram BUCKETS (telemetry/metrics.py) — the SLO
    # number production serving is judged on. Looser than the warm
    # mean bound: the p99 request admitted behind a full slot set
    # waits out its predecessors' prefill chunks by design.
    "ttft_p99_s": float(
        os.environ.get("RLT_BENCH_TTFT_P99_MAX", 5.0)),
    # cross-topology restore (elastic leg, ISSUE 9): the wall seconds
    # one elastic shrink/grow pays to reshard its ~32 MiB probe state.
    # A growth here means the reshard path started gathering to host
    # (or the storage layer regressed) — the elastic story's hot path.
    "reshard_restore_s": float(
        os.environ.get("RLT_BENCH_RESHARD_MAX", 30.0)),
    # autoscale actuation (serving leg, ISSUE 13): the wall one
    # controller-driven add_replica pays — spawn + weight reload +
    # step compile/deserialize + warmup. This is how long a pressure
    # spike waits before capacity actually arrives; growth means the
    # respawn path regressed (e.g. the persistent compile cache
    # stopped hitting). Skip/null waived like every bound.
    "scale_up_s": float(
        os.environ.get("RLT_BENCH_SCALE_UP_MAX", 120.0)),
    # watch incidents (ISSUE 14): the bench arms the built-in SLO
    # rules over its own autoscale-drill run dir. The bound is ZERO:
    # any rule breach inside the bench's own controlled serving run is
    # a regression with a self-documenting incident record to read,
    # never acceptable noise. Skip lines and null/absent counts waive
    # (the drill degraded to autoscale_error and said so).
    "incidents": float(os.environ.get("RLT_BENCH_INCIDENTS_MAX", 0.0)),
}


def _extract_line(obj: dict) -> Optional[dict]:
    """A recorder wrapper ({"parsed": {...}}) or a raw bench line."""
    if not isinstance(obj, dict):
        return None
    if "parsed" in obj:
        parsed = obj["parsed"]
        return parsed if isinstance(parsed, dict) else None
    return obj if "metric" in obj else None


def _last_json_line(text: str) -> Optional[dict]:
    """The LAST parseable JSON object line — bench.py's contract is that
    its final stdout line is the structured one (watchdog/kill lines
    close any half-written line first)."""
    for raw in reversed(text.strip().splitlines()):
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            continue
    return None


def best_prior(prior_glob: str, repo_root: str) -> dict:
    """Per-metric max over all prior rounds that measured it. A skip
    round's static fields (e.g. overlap_hidden_fraction on a
    backend-down line) still ratchet: they were honestly computed."""
    best: dict = {}
    for path in sorted(glob.glob(os.path.join(repo_root, prior_glob))):
        try:
            with open(path) as f:
                line = _extract_line(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
        if line is None:
            continue
        try:
            measured = ("skipped" not in line
                        and float(line.get("value") or 0) > 0)
        except (TypeError, ValueError):  # "value": null / non-numeric
            measured = False
        for name, key in RATCHETED.items():
            # measurements count only from success lines; STATIC
            # metrics (computed without hardware) from any line
            v = line.get(key)
            if v is None or (key not in STATIC and not measured):
                continue
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            if name not in best or v > best[name][0]:
                best[name] = (v, os.path.basename(path))
    return best


def ceiling_prior(prior_glob: str, repo_root: str) -> dict:
    """Per-metric MIN over prior rounds for the CEILING metrics (lower
    is better — the fresh value must not grow past it). All current
    ceiling metrics are static, so every prior line that carries the
    field contributes."""
    best: dict = {}
    for path in sorted(glob.glob(os.path.join(repo_root, prior_glob))):
        try:
            with open(path) as f:
                line = _extract_line(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
        if line is None:
            continue
        for name, key in CEILING.items():
            v = line.get(key)
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            if name not in best or v < best[name][0]:
                best[name] = (v, os.path.basename(path))
    return best


def gate(fresh: dict, best: dict, tolerance: float,
         ceilings: Optional[dict] = None) -> list[str]:
    """Return the list of failure messages (empty = pass)."""
    skipped = "skipped" in fresh
    if skipped and "metric" not in fresh:
        return ["skip line is not the structured schema "
                "(missing 'metric')"]
    failures = []
    for name, key in RATCHETED.items():
        if skipped:
            # an environmental skip waives only the MEASURED metrics;
            # the static ones (overlap_hidden_fraction) are computed
            # without hardware, carried on the skip line, and still
            # ratchet when present. Absent on a skip line passes — an
            # analysis error (the line carries overlap_error instead)
            # must not masquerade as a regression.
            if key not in STATIC or fresh.get(key) is None:
                continue
        if name not in best:
            continue
        prior, source = best[name]
        if prior <= 0:
            continue
        v = fresh.get(key)
        if v is None:
            if key in STATIC and ("overlap_error" in fresh
                                  or "tracecheck_error" in fresh):
                # bench.py's contract: a static-analysis bug is reported
                # as overlap_error (or tracecheck_error when the whole
                # trace died) and must never cost perf evidence — that
                # is an analysis failure, not a deleted field
                continue
            failures.append(
                f"{name}: prior rounds track it ({prior:g} in {source}) "
                f"but the fresh line dropped the field '{key}'")
            continue
        try:
            v = float(v)
        except (TypeError, ValueError):
            failures.append(f"{name}: non-numeric value {v!r}")
            continue
        floor = prior * (1 - tolerance)
        if v < floor:
            failures.append(
                f"{name}: {v:g} regressed below {floor:g} "
                f"(best prior {prior:g} in {source}, "
                f"tolerance {tolerance:.0%})")
    for name, (prior, source) in (ceilings or {}).items():
        key = CEILING[name]
        v = fresh.get(key)
        if v is None:
            if any(w in fresh for w in CEILING_WAIVERS[name]):
                # the static analysis died — a failure is reported as
                # its own error field, never as a deleted metric (same
                # contract as the STATIC ratchet above)
                continue
            failures.append(
                f"{name}: prior rounds track it ({prior:g} in {source}) "
                f"but the fresh line dropped the field '{key}'")
            continue
        try:
            v = float(v)
        except (TypeError, ValueError):
            failures.append(f"{name}: non-numeric value {v!r}")
            continue
        cap = prior * (1 + tolerance)
        if v > cap:
            failures.append(
                f"{name}: {v:g} grew past {cap:g} (best prior {prior:g} "
                f"in {source}, tolerance {tolerance:.0%}) — "
                f"{CEILING_WHY[name]}")
    for key, bound in BOUNDED.items():
        if skipped:
            continue  # bounds apply to measured lines only
        v = fresh.get(key)
        if v is None:
            continue  # probe failed or pre-telemetry line: waived
        try:
            v = float(v)
        except (TypeError, ValueError):
            failures.append(f"{key}: non-numeric value {v!r}")
            continue
        if v > bound:
            whats = {
                "telemetry_overhead_fraction":
                    "telemetry is eating the step time it exists to "
                    "measure",
                "incidents":
                    "the bench's own serving drill breached a watch "
                    "rule — read the incident record(s) in the drill "
                    "run dir's incidents.jsonl excerpt for the "
                    "self-documented evidence",
                "ttft_p99_s":
                    "the steady-state TTFT tail blew its SLO bound — "
                    "queueing/prefill latency grew on the serving hot "
                    "path (see the histogram sketch in `report`)",
                "scale_up_s":
                    "autoscale actuation slowed — a pressure spike now "
                    "waits this long for capacity (the respawn path "
                    "or its compile-cache re-warm regressed)",
            }
            what = whats.get(
                key, "the serving warm path regressed (recompile "
                     "or prefill growth on the request hot path)")
            failures.append(
                f"{key}: {v:g} exceeds the {bound:g} upper bound — "
                f"{what}")
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "bench_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("fresh",
                   help="fresh bench JSON (wrapper or raw line); '-' "
                        "reads stdin and takes the last JSON line")
    p.add_argument("--prior-glob", default="BENCH_r0*.json",
                   help="prior-round files, relative to --repo-root")
    p.add_argument("--repo-root",
                   default=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
    p.add_argument("--tolerance", type=float,
                   default=float(os.environ.get("RLT_BENCH_GATE_TOL",
                                                0.05)),
                   help="allowed per-metric regression (default 0.05)")
    args = p.parse_args(argv)

    if args.fresh == "-":
        fresh = _last_json_line(sys.stdin.read())
    else:
        try:
            with open(args.fresh) as f:
                text = f.read()
        except OSError as exc:
            print(f"bench_gate: cannot read {args.fresh}: {exc}",
                  file=sys.stderr)
            return 2
        try:
            fresh = _extract_line(json.loads(text))
        except json.JSONDecodeError:
            fresh = _last_json_line(text)
    if fresh is None:
        print("bench_gate: no parseable bench JSON line in input — "
              "this is the BENCH_r05 failure class (unparseable round), "
              "failing", file=sys.stderr)
        return 2

    best = best_prior(args.prior_glob, args.repo_root)
    ceilings = ceiling_prior(args.prior_glob, args.repo_root)
    failures = gate(fresh, best, args.tolerance, ceilings)
    if failures:
        for msg in failures:
            print(f"bench_gate: REGRESSION — {msg}", file=sys.stderr)
        return 1
    if "skipped" in fresh:
        checked = ", ".join(
            f"{name}={float(fresh[key]):g} (best {best[name][0]:g})"
            for name, key in RATCHETED.items()
            if key in STATIC and name in best
            and fresh.get(key) is not None)
        print(f"bench_gate: pass (environmental skip: {fresh['skipped']}; "
              f"static ratchet: {checked or 'not exercised'})")
    else:
        checked = ", ".join(
            f"{name}={float(fresh[key]):g} (best {best[name][0]:g})"
            for name, key in RATCHETED.items()
            if name in best and fresh.get(key) is not None)
        print(f"bench_gate: pass — {checked or 'no prior metrics'}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
