"""Focused follow-up to sweep_flagship.py: batch fill-in around the
incumbent (b8 / nothing / chunk4096 / default flash blocks) plus the one
untried block shape. Appends to the same results file."""
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.sweep_flagship import run_one, best_so_far  # noqa: E402
import json  # noqa: E402

if __name__ == "__main__":
    run_one("p4-b10", batch=10, policy="nothing", chunk=4096)
    run_one("p4-b12", batch=12, policy="nothing", chunk=4096)
    run_one("p4-q512k2048", batch=8, policy="nothing", chunk=4096,
            block_q=512, block_k=2048)
    run_one("p4-chunk6144", batch=8, policy="nothing", chunk=6144)
    best = best_so_far()
    print("BEST:", json.dumps(best) if best else "none", flush=True)
