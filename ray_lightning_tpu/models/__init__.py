"""Model families shipped with the framework.

The reference ships only test MLPs and an MNIST example
(reference tests/utils.py:96-145, examples/ray_ddp_example.py); the
BASELINE.json configs additionally require ResNet/CIFAR, BERT fine-tune,
and Llama-3-8B FSDP — all provided here as TpuModules.
"""
from ray_lightning_tpu.models.bert import (
    BertClassifierModule,
    BertConfig,
    BertEncoder,
    BertForSequenceClassification,
)
from ray_lightning_tpu.models.hf_interop import (
    bert_classifier_params_from_hf,
    bert_params_from_hf,
    llama_params_from_hf,
)
from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
    generate,
    init_cache,
)
from ray_lightning_tpu.models.mlp import MLP, MLPClassifier, MNISTClassifier
from ray_lightning_tpu.models.moe import (
    MoEClassifierModule,
    MoEMLP,
    moe_param_specs,
)
from ray_lightning_tpu.models.resnet import (
    ResNet,
    ResNetModule,
    resnet18,
    resnet34,
    resnet50,
)

__all__ = [
    "BertClassifierModule",
    "BertConfig",
    "BertEncoder",
    "BertForSequenceClassification",
    "Llama",
    "LlamaConfig",
    "LlamaModule",
    "bert_classifier_params_from_hf",
    "bert_params_from_hf",
    "generate",
    "init_cache",
    "llama_params_from_hf",
    "MLP",
    "MLPClassifier",
    "MNISTClassifier",
    "MoEClassifierModule",
    "MoEMLP",
    "moe_param_specs",
    "ResNet",
    "ResNetModule",
    "resnet18",
    "resnet34",
    "resnet50",
]
