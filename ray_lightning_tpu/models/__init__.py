"""Model families shipped with the framework.

The reference ships only test MLPs and an MNIST example
(reference tests/utils.py:96-145, examples/ray_ddp_example.py); the
BASELINE.json configs additionally require ResNet/CIFAR, BERT fine-tune,
and Llama-3-8B FSDP — all provided here as TpuModules.
"""
from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
)
from ray_lightning_tpu.models.mlp import MLP, MLPClassifier, MNISTClassifier

__all__ = [
    "Llama",
    "LlamaConfig",
    "LlamaModule",
    "MLP",
    "MLPClassifier",
    "MNISTClassifier",
]
