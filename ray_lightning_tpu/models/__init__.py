"""Model families shipped with the framework.

The reference ships only test MLPs and an MNIST example
(reference tests/utils.py:96-145, examples/ray_ddp_example.py); the
BASELINE.json configs additionally require ResNet/CIFAR, BERT fine-tune,
and Llama-3-8B FSDP — all provided here as TpuModules.
"""
from ray_lightning_tpu.models.bert import (
    BertClassifierModule,
    BertConfig,
    BertEncoder,
    BertForSequenceClassification,
)
from ray_lightning_tpu.models.llama import (
    Llama,
    LlamaConfig,
    LlamaModule,
)
from ray_lightning_tpu.models.mlp import MLP, MLPClassifier, MNISTClassifier
from ray_lightning_tpu.models.resnet import (
    ResNet,
    ResNetModule,
    resnet18,
    resnet34,
    resnet50,
)

__all__ = [
    "BertClassifierModule",
    "BertConfig",
    "BertEncoder",
    "BertForSequenceClassification",
    "Llama",
    "LlamaConfig",
    "LlamaModule",
    "MLP",
    "MLPClassifier",
    "MNISTClassifier",
    "ResNet",
    "ResNetModule",
    "resnet18",
    "resnet34",
    "resnet50",
]
