"""Pipeline-parallel module: hidden layers run as a GPipe microbatch
pipeline over the mesh's `pipe` axis (ops/pipeline.py).

Beyond-parity capability — SURVEY §2.3 lists pipeline parallelism as
absent from the reference and out of its scope. This module is the
user-facing demonstration of the building block: the stacked layer
weights are stage-sharded (`param_specs` puts the layer axis on `pipe`),
the compute path is `gpipe_apply`, and everything else (optimizer,
checkpointing, sweeps, the distributed round-trip) is the ordinary
Trainer machinery — PP is a sharding + schedule choice, not a different
framework mode.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.ops.pipeline import gpipe_apply, pipeline_param_spec


def _stage_fn(lp, h):
    """One pipeline layer: tanh(h @ w + b)."""
    return jnp.tanh(h @ lp["w"] + lp["b"])


class PipelinedMLPModule(TpuModule):
    """Classifier with GPipe-pipelined hidden layers.

    Use with ``ShardedMesh(data=..., pipe=P)``: each of the P stage
    groups owns ``n_layers / P`` layers; microbatch activations flow
    stage→stage over ICI ppermutes inside one compiled step.
    """

    def __init__(self, d: int = 32, n_layers: int = 4, num_classes: int = 4,
                 microbatches: int = 2, lr: float = 5e-2):
        super().__init__()
        self.save_hyperparameters(d=d, n_layers=n_layers,
                                  num_classes=num_classes,
                                  microbatches=microbatches, lr=lr)
        self.d = d
        self.n_layers = n_layers
        self.num_classes = num_classes
        self.microbatches = microbatches
        self.lr = lr

    def configure_optimizers(self):
        return optax.adam(self.lr)

    def init_params(self, rng, batch):
        d, n = self.d, self.n_layers
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "inp": jax.random.normal(k1, (batch["x"].shape[-1], d)) * 0.3,
            "layers": {
                "w": jax.random.normal(k2, (n, d, d)) * 0.3,
                "b": jnp.zeros((n, d)),
            },
            "head": jax.random.normal(k3, (d, self.num_classes)) * 0.3,
        }

    def param_specs(self, params):
        return {"layers/w": pipeline_param_spec(),
                "layers/b": pipeline_param_spec(),
                "inp": P(), "head": P()}

    def _forward(self, params, x):
        h = x @ params["inp"]
        h = gpipe_apply(_stage_fn, params["layers"], h, self.mesh,
                        microbatches=self.microbatches)
        return h @ params["head"]

    def training_step(self, params, batch, rng):
        logits = self._forward(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        self.log("ptl/loss", loss)
        return loss

    def validation_step(self, params, batch):
        logits = self._forward(params, batch["x"])
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return {"val_acc": acc}

    def predict_step(self, params, batch):
        return self._forward(params, batch["x"]).argmax(-1)
