"""BERT-style bidirectional encoder for fine-tuning, TPU-first.

BASELINE.json config 3 ("BERT-base fine-tune, multi-host DP"). Net-new
capability (the reference ships no transformer). Same MXU-first shaping
as the Llama family: bf16 activations, fused QKV, flash attention (here
non-causal), param_specs for tensor parallelism; plus a pooled
classification head for GLUE-style fine-tunes and an optional MLM head.

HF-compatible in shape (bert-base: L=12, H=768, A=12, I=3072), so
weights exported from `transformers` can be mapped in by name; the
module itself has no transformers dependency.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.ops.attention import flash_attention
from ray_lightning_tpu.ops.precision import F32AccDense


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    use_flash: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        return cls(**{**dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                             hidden_dim=128, max_seq_len=128), **kw})


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool):
        cfg = self.cfg
        # f32-accumulating dense (ops/precision.py): bf16 operands at
        # full MXU rate, f32 dot accumulator AND f32 bias add, one
        # rounding — so the backward bias grad (a token-extent
        # reduce_sum) and the grad collectives run at f32 (numcheck
        # RLT801/RLT804); at dtype=f32 it is bitwise nn.Dense, so HF
        # parity is untouched
        dense = partial(F32AccDense, dtype=cfg.dtype)
        ln = partial(nn.LayerNorm, epsilon=cfg.norm_eps, dtype=cfg.dtype,
                     param_dtype=jnp.float32)
        B, S, _ = x.shape
        hd, H = cfg.head_dim, cfg.n_heads

        qkv = dense(3 * cfg.dim, name="wqkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, hd)
        k = k.reshape(B, S, H, hd)
        v = v.reshape(B, S, H, hd)
        attn = flash_attention(
            q, k, v, causal=False, mask=mask,
            use_pallas=None if cfg.use_flash else False,
        ).reshape(B, S, cfg.dim)
        attn = dense(cfg.dim, name="wo")(attn)
        attn = nn.Dropout(cfg.dropout)(attn, deterministic=deterministic)
        x = ln(name="attn_ln")(x + attn)

        # exact (erf) GELU — what HF BERT checkpoints were trained with.
        # Computed at f32: erf's backward is exp(-x^2), which numcheck
        # (RLT802) rightly refuses to see on a bf16 operand — and erf
        # itself lives on low-order bits bf16 has already rounded away
        h = nn.gelu(dense(cfg.hidden_dim, name="w_up")(x)
                    .astype(jnp.float32), approximate=False)
        h = dense(cfg.dim, name="w_down")(h.astype(cfg.dtype))
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        x = ln(name="mlp_ln")(x + h)
        return x, None


class BertEncoder(nn.Module):
    """[B, S] token ids (+ optional type ids / padding mask) -> [B, S, D]."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.cfg
        B, S = input_ids.shape
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="tok_embed")(input_ids)
        pos = jnp.arange(S)[None, :]
        x = x + nn.Embed(cfg.max_seq_len, cfg.dim, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="pos_embed")(pos)
        if cfg.type_vocab_size:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + nn.Embed(cfg.type_vocab_size, cfg.dim, dtype=cfg.dtype,
                             param_dtype=jnp.float32,
                             name="type_embed")(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed_ln")(x)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        mask = attention_mask.astype(bool) if attention_mask is not None else None
        if cfg.scan_layers:
            x, _ = nn.scan(
                BertLayer,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layers,
                in_axes=(nn.broadcast, nn.broadcast),
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")(x, mask, deterministic)
        else:
            for i in range(cfg.n_layers):
                x, _ = BertLayer(cfg, name=f"layer_{i}")(
                    x, mask, deterministic)
        return x


class BertForSequenceClassification(nn.Module):
    cfg: BertConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        x = BertEncoder(self.cfg, name="encoder")(
            input_ids, attention_mask, token_type_ids, deterministic)
        # BERT pooler: tanh-projected [CLS], consumed at the encoder's
        # activation dtype. Re-widening the final LayerNorm's rounded
        # bf16 output to f32 here would be a pure f32->bf16->f32 round
        # trip (numcheck RLT803) — instead the dense accumulates at f32
        # from bf16 operands (ops/precision.py) and only the bounded
        # tanh input is widened; at dtype=f32 this is bitwise nn.Dense
        pooled = nn.tanh(F32AccDense(self.cfg.dim, dtype=self.cfg.dtype,
                                     name="pooler")(x[:, 0])
                         .astype(jnp.float32))
        pooled = nn.Dropout(self.cfg.dropout)(pooled,
                                              deterministic=deterministic)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="classifier")(pooled)


def bert_param_specs(cfg: BertConfig) -> Dict[str, P]:
    """Megatron TP placement: column-split QKV/up, row-split O/down,
    vocab-split embeddings; norms replicated. FSDP overlays free axes."""
    def stacked(spec: P) -> P:
        return P(None, *spec) if cfg.scan_layers else spec

    specs: Dict[str, P] = {
        "encoder/tok_embed/embedding": P("tensor", None),
        "encoder/pos_embed/embedding": P(),
        "encoder/type_embed/embedding": P(),
        "encoder/embed_ln/scale": P(), "encoder/embed_ln/bias": P(),
    }
    per_layer = {
        "wqkv/kernel": P(None, "tensor"), "wqkv/bias": P("tensor"),
        "wo/kernel": P("tensor", None), "wo/bias": P(),
        "w_up/kernel": P(None, "tensor"), "w_up/bias": P("tensor"),
        "w_down/kernel": P("tensor", None), "w_down/bias": P(),
        "attn_ln/scale": P(), "attn_ln/bias": P(),
        "mlp_ln/scale": P(), "mlp_ln/bias": P(),
    }
    if cfg.scan_layers:
        for k, v in per_layer.items():
            specs[f"encoder/layers/{k}"] = stacked(v)
    else:
        for i in range(cfg.n_layers):
            for k, v in per_layer.items():
                specs[f"encoder/layer_{i}/{k}"] = v
    return specs


class BertClassifierModule(TpuModule):
    """Fine-tune BERT for sequence classification.

    Batch: {"input_ids": [B,S], "labels": [B]} + optional
    "attention_mask"/"token_type_ids".
    """

    def __init__(self, cfg: Optional[BertConfig] = None,
                 num_classes: int = 2, lr: float = 2e-5,
                 weight_decay: float = 0.01, warmup_steps: int = 100,
                 total_steps: int = 10_000, **cfg_overrides):
        super().__init__()
        if cfg is None:
            cfg = BertConfig(**cfg_overrides)
        elif cfg_overrides:
            cfg = dataclasses.replace(cfg, **cfg_overrides)
        self.cfg = cfg
        self.num_classes = num_classes
        self.lr = lr
        self.weight_decay = weight_decay
        self.warmup_steps = warmup_steps
        self.total_steps = total_steps
        self.save_hyperparameters(
            cfg=cfg, num_classes=num_classes, lr=lr,
            weight_decay=weight_decay, warmup_steps=warmup_steps,
            total_steps=total_steps,
        )

    def configure_model(self):
        return BertForSequenceClassification(self.cfg, self.num_classes)

    def configure_optimizers(self):
        sched = optax.warmup_cosine_decay_schedule(
            0.0, self.lr, self.warmup_steps, max(self.total_steps, 2))
        return optax.adamw(sched, weight_decay=self.weight_decay)

    def param_specs(self, params) -> Dict[str, P]:
        return bert_param_specs(self.cfg)

    def _forward(self, params, batch, deterministic, rng=None):
        rngs = {"dropout": rng} if rng is not None else None
        return self.model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
            deterministic=deterministic, rngs=rngs,
        )

    def training_step(self, params, batch, rng):
        logits = self._forward(params, batch, deterministic=False, rng=rng)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]).mean()
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        self.log("train_acc", acc)
        return loss

    def validation_step(self, params, batch):
        logits = self._forward(params, batch, deterministic=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]).mean()
        acc = (logits.argmax(-1) == batch["labels"]).mean()
        return {"val_loss": loss, "val_acc": acc}

    def predict_step(self, params, batch):
        return self._forward(params, batch, deterministic=True).argmax(-1)

    def init_params(self, rng, batch):
        return self.model.init(
            {"params": rng}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
        )["params"]

