"""MLP classifiers — parity with the reference's example/test models.

MNISTClassifier mirrors the reference's LightningMNISTClassifier
(reference tests/utils.py:96-145: 3-layer MLP 128→256→classes, Adam) and
the MNIST example model (reference examples/ray_ddp_example.py).
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import optax

from ray_lightning_tpu.core.module import TpuModule


class MLP(nn.Module):
    features: Sequence[int] = (128, 256)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.num_classes)(x)


class MLPClassifier(TpuModule):
    """Generic MLP classifier on {"x": [B, ...], "y": [B]} batches."""

    def __init__(self, features: Sequence[int] = (128, 256),
                 num_classes: int = 10, lr: float = 1e-3):
        super().__init__()
        self.save_hyperparameters(features=tuple(features),
                                  num_classes=num_classes, lr=lr)
        self.features = tuple(features)
        self.num_classes = num_classes
        self.lr = lr

    def configure_model(self):
        return MLP(self.features, self.num_classes)

    def configure_optimizers(self):
        return optax.adam(self.lr)

    def training_step(self, params, batch, rng):
        logits = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()
        acc = (logits.argmax(-1) == batch["y"]).mean()
        self.log("ptl/train_loss", loss)
        self.log("ptl/train_accuracy", acc)
        return loss

    def validation_step(self, params, batch):
        logits = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return {"ptl/val_loss": loss, "ptl/val_accuracy": acc}

    def predict_step(self, params, batch):
        return self.apply(params, batch["x"]).argmax(-1)


class MNISTClassifier(MLPClassifier):
    """Reference examples/ray_ddp_example.py MNISTClassifier analog."""

    def __init__(self, lr: float = 1e-3, layer_1: int = 128,
                 layer_2: int = 256):
        super().__init__(features=(layer_1, layer_2), num_classes=10, lr=lr)
        self.hparams.clear()  # ctor signature differs from parent's
        self.save_hyperparameters(lr=lr, layer_1=layer_1, layer_2=layer_2)
