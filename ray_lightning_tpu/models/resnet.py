"""ResNet family (CIFAR + ImageNet variants), TPU-first.

BASELINE.json config 2 ("ResNet-50 / CIFAR-10, 8-worker data-parallel").
The reference has no vision models (MLPs only, reference
tests/utils.py:96-145); this is net-new capability shaped for the MXU:

  * NHWC layout (TPU-native conv layout; channels innermost feeds the
    128-lane dimension);
  * bf16 activations / f32 params by default — convs hit the MXU at
    bf16 throughput;
  * GroupNorm instead of BatchNorm: stateless (pure-functional step, no
    mutable running stats to thread through the jitted train step) and
    batch-size independent — the standard choice for large-scale JAX
    vision stacks; sync-BN's cross-replica stats traffic is also exactly
    what you don't want riding ICI every layer.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_lightning_tpu.core.module import TpuModule


class ResNetBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.GroupNorm, num_groups=min(32, self.filters),
                       dtype=self.dtype, param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(name="proj_norm")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.GroupNorm, num_groups=min(32, self.filters),
                       dtype=self.dtype, param_dtype=jnp.float32)
        residual = x
        y = nn.relu(norm()(conv(self.filters, (1, 1))(x)))
        y = nn.relu(norm()(conv(self.filters, (3, 3),
                                (self.strides, self.strides))(y)))
        y = norm()(conv(4 * self.filters, (1, 1))(y))
        if residual.shape != y.shape:
            residual = conv(4 * self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(num_groups=min(32, 4 * self.filters),
                            name="proj_norm")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """stage_sizes/block pick the variant; NHWC [B, H, W, C] -> logits."""

    stage_sizes: Sequence[int]
    num_classes: int
    block_cls: Any = ResNetBlock
    num_filters: int = 64
    cifar_stem: bool = False   # 3x3/s1 stem, no maxpool (32x32 inputs)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="stem")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="stem")(x)
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = nn.relu(nn.GroupNorm(num_groups=min(32, self.num_filters),
                                 dtype=self.dtype,
                                 param_dtype=jnp.float32)(x))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, dtype=self.dtype)(x)
        # global average pool straight to f32: the head consumes f32
        # anyway, so rounding the pooled mean back to bf16 first would
        # be a pure f32->bf16->f32 round trip (numcheck RLT803)
        x = x.mean(axis=(1, 2), dtype=jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


def resnet18(num_classes=10, **kw):
    return ResNet([2, 2, 2, 2], num_classes, ResNetBlock, **kw)


def resnet34(num_classes=10, **kw):
    return ResNet([3, 4, 6, 3], num_classes, ResNetBlock, **kw)


def resnet50(num_classes=10, **kw):
    return ResNet([3, 4, 6, 3], num_classes, BottleneckBlock, **kw)


_VARIANTS = {"resnet18": resnet18, "resnet34": resnet34,
             "resnet50": resnet50}


class ResNetModule(TpuModule):
    """Image classification on {"x": NHWC images, "y": int labels}."""

    def __init__(self, variant: str = "resnet50", num_classes: int = 10,
                 lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 1e-4, total_steps: int = 10_000,
                 cifar_stem: bool = True):
        super().__init__()
        if variant not in _VARIANTS:
            raise ValueError(f"unknown variant {variant!r}; "
                             f"have {sorted(_VARIANTS)}")
        self.save_hyperparameters(
            variant=variant, num_classes=num_classes, lr=lr,
            momentum=momentum, weight_decay=weight_decay,
            total_steps=total_steps, cifar_stem=cifar_stem,
        )
        self.variant = variant
        self.num_classes = num_classes
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.total_steps = total_steps
        self.cifar_stem = cifar_stem

    def configure_model(self):
        return _VARIANTS[self.variant](
            num_classes=self.num_classes, cifar_stem=self.cifar_stem
        )

    def configure_optimizers(self):
        # linear warmup (5% of the run) prevents the early GN+SGD loss
        # spike, then cosine decay — the standard large-batch recipe.
        total = max(self.total_steps, 2)
        sched = optax.warmup_cosine_decay_schedule(
            0.0, self.lr, max(1, total // 20), total)
        return optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.add_decayed_weights(self.weight_decay),
            optax.sgd(sched, momentum=self.momentum, nesterov=True),
        )

    def _loss_acc(self, params, batch):
        logits = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss_acc(params, batch)
        self.log("train_acc", acc)
        return loss

    def validation_step(self, params, batch):
        loss, acc = self._loss_acc(params, batch)
        return {"val_loss": loss, "val_acc": acc}

    def predict_step(self, params, batch):
        return self.apply(params, batch["x"]).argmax(-1)

