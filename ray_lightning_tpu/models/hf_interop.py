"""HuggingFace weight interop: map `transformers` state dicts onto the
framework's flax param trees.

Capability rationale: "fine-tune BERT" (BASELINE config 3) and the Llama
family only matter in practice if pretrained weights can be loaded. The
converters are pure name/shape mapping — no transformers dependency at
runtime beyond the (optional) model you pass in; tensors arrive as numpy
via `.state_dict()` from the torch-cpu models baked into the image.

Conventions handled:
  * torch nn.Linear stores [out, in] — transposed to flax's [in, out];
  * per-layer HF tensors are stacked along the scan axis when the target
    config uses scan_layers (the framework default);
  * BERT's separate q/k/v projections are fused into the framework's
    single wqkv; Llama's separate q/k/v likewise, gate/up into w_gate_up.

Llama RoPE note: the framework rotates [x1, x2] half-split pairs — the
same "rotate_half" layout HF's LlamaModel uses, so HF checkpoints load
with no permutation. Meta-native (pre-HF-conversion) weights rotate
interleaved even/odd pairs and would need the standard q/k_proj
permutation first; these converters only accept the HF layout.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from ray_lightning_tpu.models.bert import BertConfig
from ray_lightning_tpu.models.llama import LlamaConfig


def _t(x) -> np.ndarray:
    """torch [out, in] linear weight -> flax [in, out] kernel."""
    return np.ascontiguousarray(np.asarray(x).T)


def _np(x) -> np.ndarray:
    return np.asarray(x)


def _get(sd: Mapping, key: str) -> np.ndarray:
    if key not in sd:
        raise KeyError(
            f"HF state dict is missing {key!r} — wrong architecture or an "
            f"unexpected transformers version (have e.g. "
            f"{list(sd)[:3]}...)"
        )
    v = sd[key]
    return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)


def _stack(per_layer: list) -> Any:
    """list of per-layer pytrees -> leaves stacked on a leading axis."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *per_layer)


# --------------------------------------------------------------------- bert


def bert_params_from_hf(hf_state_dict: Mapping, cfg: BertConfig,
                        prefix: str = "") -> Dict[str, Any]:
    """Map a `transformers.BertModel` state dict onto `BertEncoder` params.

    `prefix` handles wrappers ("bert." for BertForSequenceClassification's
    state dict, "" for a bare BertModel).
    """
    sd = hf_state_dict
    p = prefix

    def emb(name):
        return _get(sd, f"{p}embeddings.{name}")

    pos_table = emb("position_embeddings.weight")
    if pos_table.shape[0] < cfg.max_seq_len:
        raise ValueError(
            f"cfg.max_seq_len={cfg.max_seq_len} but the checkpoint has "
            f"only {pos_table.shape[0]} position embeddings — positions "
            "past the table would silently clamp; lower max_seq_len or "
            "extend the table explicitly"
        )
    encoder: Dict[str, Any] = {
        "tok_embed": {"embedding": emb("word_embeddings.weight")},
        "pos_embed": {"embedding": pos_table[: cfg.max_seq_len]},
        "type_embed": {"embedding": emb("token_type_embeddings.weight")},
        "embed_ln": {"scale": emb("LayerNorm.weight"),
                     "bias": emb("LayerNorm.bias")},
    }

    layers = []
    for i in range(cfg.n_layers):
        lp = f"{p}encoder.layer.{i}."
        q_w = _get(sd, lp + "attention.self.query.weight")
        k_w = _get(sd, lp + "attention.self.key.weight")
        v_w = _get(sd, lp + "attention.self.value.weight")
        q_b = _get(sd, lp + "attention.self.query.bias")
        k_b = _get(sd, lp + "attention.self.key.bias")
        v_b = _get(sd, lp + "attention.self.value.bias")
        layers.append({
            # fused qkv: concatenate along the OUTPUT dim (flax axis 1)
            "wqkv": {
                "kernel": np.concatenate([_t(q_w), _t(k_w), _t(v_w)], 1),
                "bias": np.concatenate([q_b, k_b, v_b]),
            },
            "wo": {
                "kernel": _t(_get(sd, lp + "attention.output.dense.weight")),
                "bias": _get(sd, lp + "attention.output.dense.bias"),
            },
            "attn_ln": {
                "scale": _get(sd, lp + "attention.output.LayerNorm.weight"),
                "bias": _get(sd, lp + "attention.output.LayerNorm.bias"),
            },
            "w_up": {
                "kernel": _t(_get(sd, lp + "intermediate.dense.weight")),
                "bias": _get(sd, lp + "intermediate.dense.bias"),
            },
            "w_down": {
                "kernel": _t(_get(sd, lp + "output.dense.weight")),
                "bias": _get(sd, lp + "output.dense.bias"),
            },
            "mlp_ln": {
                "scale": _get(sd, lp + "output.LayerNorm.weight"),
                "bias": _get(sd, lp + "output.LayerNorm.bias"),
            },
        })
    if cfg.scan_layers:
        encoder["layers"] = _stack(layers)
    else:
        for i, layer in enumerate(layers):
            encoder[f"layer_{i}"] = layer
    return encoder


def bert_classifier_params_from_hf(hf_state_dict: Mapping,
                                   cfg: BertConfig,
                                   num_classes: int,
                                   rng=None) -> Dict[str, Any]:
    """Full BertForSequenceClassification tree: pretrained encoder +
    pooler; classifier head fresh (or from HF when present)."""
    import jax

    sd = hf_state_dict
    prefix = "bert." if any(k.startswith("bert.") for k in sd) else ""
    params: Dict[str, Any] = {
        "encoder": bert_params_from_hf(sd, cfg, prefix=prefix),
        "pooler": {
            "kernel": _t(_get(sd, f"{prefix}pooler.dense.weight")),
            "bias": _get(sd, f"{prefix}pooler.dense.bias"),
        },
    }
    if "classifier.weight" in sd:
        params["classifier"] = {"kernel": _t(_get(sd, "classifier.weight")),
                                "bias": _get(sd, "classifier.bias")}
    else:
        rng = rng if rng is not None else jax.random.key(0)
        params["classifier"] = {
            "kernel": np.asarray(
                jax.random.normal(rng, (cfg.dim, num_classes)) * 0.02,
                dtype=np.float32),
            "bias": np.zeros((num_classes,), np.float32),
        }
    return params


# -------------------------------------------------------------------- llama


def llama_params_from_hf(hf_state_dict: Mapping,
                         cfg: LlamaConfig) -> Dict[str, Any]:
    """Map a `transformers.LlamaForCausalLM` (or LlamaModel) state dict
    onto the framework's `Llama` params."""
    sd = hf_state_dict
    p = "model." if any(k.startswith("model.") for k in sd) else ""

    params: Dict[str, Any] = {
        "tok_embed": {"embedding": _get(sd, f"{p}embed_tokens.weight")},
        "final_norm": _get(sd, f"{p}norm.weight"),
    }
    if cfg.tie_embeddings:
        # tied config: embed.attend serves as the lm_head. Guard against
        # an UNTIED checkpoint (distinct lm_head.weight, e.g. Llama-3)
        # being silently dropped.
        if "lm_head.weight" in sd and not np.array_equal(
            _get(sd, "lm_head.weight"),
            _np(params["tok_embed"]["embedding"]),
        ):
            raise ValueError(
                "checkpoint has a distinct lm_head.weight but "
                "cfg.tie_embeddings=True — its output head would be "
                "discarded; set tie_embeddings=False"
            )
    else:
        lm_key = "lm_head.weight"
        if lm_key in sd:
            params["lm_head"] = {"kernel": _t(_get(sd, lm_key))}
        else:  # tied checkpoints reuse the embedding
            params["lm_head"] = {
                "kernel": _np(params["tok_embed"]["embedding"]).T.copy()
            }

    layers = []
    for i in range(cfg.n_layers):
        lp = f"{p}layers.{i}."
        q = _t(_get(sd, lp + "self_attn.q_proj.weight"))
        k = _t(_get(sd, lp + "self_attn.k_proj.weight"))
        v = _t(_get(sd, lp + "self_attn.v_proj.weight"))
        gate = _t(_get(sd, lp + "mlp.gate_proj.weight"))
        up = _t(_get(sd, lp + "mlp.up_proj.weight"))
        layers.append({
            "wqkv": {"kernel": np.concatenate([q, k, v], axis=1)},
            "wo": {"kernel": _t(_get(sd, lp + "self_attn.o_proj.weight"))},
            "w_gate_up": {"kernel": np.concatenate([gate, up], axis=1)},
            "w_down": {"kernel": _t(_get(sd, lp + "mlp.down_proj.weight"))},
            "attn_norm": _get(sd, lp + "input_layernorm.weight"),
            "mlp_norm": _get(sd, lp + "post_attention_layernorm.weight"),
        })
    if cfg.scan_layers:
        params["layers"] = _stack(layers)
    else:
        for i, layer in enumerate(layers):
            params[f"layer_{i}"] = layer
    return params
