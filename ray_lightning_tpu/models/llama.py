"""Llama-3-style decoder-only transformer, TPU-first.

The flagship model (BASELINE.json config 4: Llama-3-8B FSDP on a v5p-64).
The reference has no transformer at all (its models are MLPs, reference
tests/utils.py:96-120) — this is net-new capability designed for the MXU:

  * bf16 activations, f32 RMSNorm reductions and softmax;
  * GQA attention through the pallas flash kernel (ops/pallas/flash.py);
  * SwiGLU MLP — two fused [D, 2F] projections keep matmuls large;
  * `lax.scan` over layers (one compiled layer body, L-step scan: compile
    time and HBM program size O(1) in depth) with optional
    `jax.checkpoint` rematerialization per layer;
  * sharding by annotation: `param_specs()` returns Megatron-style
    PartitionSpecs (column-split QKV/gate, row-split O/down) on the
    `tensor` axis, token-embedding sharded on `tensor`, everything
    FSDP-shardable on its largest free axis — the strategies compose
    these over the mesh;
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.ops.attention import flash_attention
from ray_lightning_tpu.ops.ring_attention import ring_attention
from ray_lightning_tpu.ops.norms import rms_norm
from ray_lightning_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True
    scan_layers: bool = True
    use_flash: bool = True
    #: shard attention over the mesh's `seq` axis (ring attention,
    #: ops/ring_attention.py) — long-context training where one device
    #: cannot hold the full sequence's KV. Takes effect when the strategy's
    #: mesh has seq > 1.
    seq_parallel: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336), **kw})

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test/debug config: same code path, laptop-sized."""
        return cls(**{**dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq_len=256, remat=False), **kw})


class LlamaBlock(nn.Module):
    cfg: LlamaConfig
    mesh: Optional[Any] = None  # jax.sharding.Mesh (static, hashable)

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.cfg
        d, hd = cfg.dim, cfg.head_dim
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)

        attn_norm_w = self.param("attn_norm", nn.initializers.ones, (d,))
        h = rms_norm(x, attn_norm_w, cfg.norm_eps)
        # fused QKV projection: one [D, (H + 2*Hkv) * hd] matmul
        n_q, n_kv = cfg.n_heads, cfg.n_kv_heads
        qkv = dense((n_q + 2 * n_kv) * hd, name="wqkv")(h)
        q, k, v = jnp.split(
            qkv, [n_q * hd, (n_q + n_kv) * hd], axis=-1)
        B, S = x.shape[0], x.shape[1]
        q = q.reshape(B, S, n_q, hd)
        k = k.reshape(B, S, n_kv, hd)
        v = v.reshape(B, S, n_kv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if (cfg.seq_parallel and self.mesh is not None
                and self.mesh.shape.get("seq", 1) > 1):
            # manual island: sequence sharded over `seq`, KV blocks rotate
            # the ring; everything outside stays compiler-sharded.
            attn = ring_attention(q, k, v, self.mesh, causal=True)
        else:
            # use_flash=True -> auto (pallas on TPU, XLA fallback
            # elsewhere); use_flash=False -> always the XLA reference path.
            attn = flash_attention(q, k, v, causal=True,
                                   use_pallas=None if cfg.use_flash else False)
        attn = attn.reshape(B, S, n_q * hd)
        x = x + dense(d, name="wo")(attn)

        mlp_norm_w = self.param("mlp_norm", nn.initializers.ones, (d,))
        h = rms_norm(x, mlp_norm_w, cfg.norm_eps)
        # fused gate+up: one [D, 2F] matmul
        gate_up = dense(2 * cfg.hidden_dim, name="w_gate_up")(h)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        x = x + dense(d, name="w_down")(nn.silu(gate) * up)
        return x, None  # (carry, out) pair so nn.scan can drive the block


class Llama(nn.Module):
    """Flax core model: token ids [B, S] -> logits [B, S, V]."""

    cfg: LlamaConfig
    mesh: Optional[Any] = None  # set by the strategy for seq/tensor islands

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="tok_embed",
        )
        x = embed(tokens)
        cos, sin = rope_frequencies(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, dtype=jnp.float32
        )
        cos, sin = cos[: tokens.shape[1]], sin[: tokens.shape[1]]

        block = LlamaBlock
        if cfg.remat:
            block = nn.remat(
                block, policy=jax.checkpoint_policies.nothing_saveable
            )
        if cfg.scan_layers:
            # one compiled block, scanned over a stacked-params layer axis
            x, _ = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                in_axes=nn.broadcast,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, self.mesh, name="layers")(x, cos, sin)
        else:
            for i in range(cfg.n_layers):
                x, _ = block(cfg, self.mesh, name=f"layer_{i}")(x, cos, sin)

        final_w = self.param("final_norm", nn.initializers.ones, (cfg.dim,))
        x = rms_norm(x, final_w, cfg.norm_eps)
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                param_dtype=jnp.float32, name="lm_head",
            )(x)
        return logits


def _stacked(spec: P, stacked: bool) -> P:
    """Prepend the scan layer axis (replicated) to a per-layer spec."""
    return P(None, *spec) if stacked else spec


def llama_param_specs(cfg: LlamaConfig) -> Dict[str, P]:
    """Megatron-style tensor-parallel placement for every weight.

    Keys are `/`-joined param paths as produced by utils.pytree._path_str.
    Column-parallel (output dim on `tensor`): wqkv, w_gate_up.
    Row-parallel (input dim on `tensor`): wo, w_down.
    Embedding: vocab on `tensor`. Norm gains: replicated (spec P()).
    The strategies overlay `fsdp` on whatever axis is still free.
    """
    st = cfg.scan_layers
    specs: Dict[str, P] = {
        "tok_embed/embedding": P("tensor", None),
        "final_norm": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head/kernel"] = P(None, "tensor")
    per_layer = {
        "wqkv/kernel": P(None, "tensor"),
        "wo/kernel": P("tensor", None),
        "w_gate_up/kernel": P(None, "tensor"),
        "w_down/kernel": P("tensor", None),
        "attn_norm": P(),
        "mlp_norm": P(),
    }
    if st:
        for k, v in per_layer.items():
            specs[f"layers/{k}"] = _stacked(v, True)
    else:
        for i in range(cfg.n_layers):
            for k, v in per_layer.items():
                specs[f"layer_{i}/{k}"] = v
    return specs


def cross_entropy_loss(
    logits: jnp.ndarray, targets: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Token-level CE in f32; `mask` (0/1) excludes padding."""
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    if mask is not None:
        return (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
    return losses.mean()


class LlamaModule(TpuModule):
    """TpuModule wrapper: next-token prediction on {"tokens": [B, S+1]}
    (or {"inputs","targets"} pairs)."""

    def __init__(self, cfg: Optional[LlamaConfig] = None,
                 lr: float = 3e-4, weight_decay: float = 0.1,
                 warmup_steps: int = 100, total_steps: int = 10000,
                 **cfg_overrides):
        super().__init__()
        if cfg is None:
            cfg = LlamaConfig(**cfg_overrides)
        elif cfg_overrides:
            cfg = dataclasses.replace(cfg, **cfg_overrides)
        self.cfg = cfg
        self.lr = lr
        self.weight_decay = weight_decay
        self.warmup_steps = warmup_steps
        self.total_steps = total_steps
        self.save_hyperparameters(
            cfg=cfg, lr=lr, weight_decay=weight_decay,
            warmup_steps=warmup_steps, total_steps=total_steps,
        )

    def configure_model(self):
        # `self.mesh` is bound by Strategy.setup before the model builds,
        # so seq/tensor manual islands (ring attention) see the live mesh.
        return Llama(self.cfg, mesh=self.mesh)

    def configure_optimizers(self):
        sched = optax.warmup_cosine_decay_schedule(
            0.0, self.lr, self.warmup_steps, max(self.total_steps, 2),
            end_value=self.lr * 0.1,
        )
        return optax.adamw(sched, b1=0.9, b2=0.95,
                           weight_decay=self.weight_decay)

    def param_specs(self, params) -> Dict[str, P]:
        return llama_param_specs(self.cfg)

    def _split(self, batch):
        if "tokens" in batch:
            toks = batch["tokens"]
            return toks[:, :-1], toks[:, 1:], batch.get("mask")
        return batch["inputs"], batch["targets"], batch.get("mask")

    def training_step(self, params, batch, rng):
        inputs, targets, mask = self._split(batch)
        logits = self.apply(params, inputs)
        loss = cross_entropy_loss(logits, targets, mask)
        self.log("train_loss", loss)
        return loss

    def validation_step(self, params, batch):
        inputs, targets, mask = self._split(batch)
        logits = self.apply(params, inputs)
        return {"val_loss": cross_entropy_loss(logits, targets, mask)}

    def predict_step(self, params, batch):
        inputs, _, _ = self._split(batch)
        return self.apply(params, inputs).argmax(-1)

    def init_params(self, rng, batch):
        inputs, _, _ = self._split(batch)
        return self.model.init(rng, inputs)["params"]

